"""Sharded serving plane (ISSUE 9): per-shard views, routed lookups,
distributed top-k.

The acceptance contract: every :class:`ShardedQueryEngine` response is
BIT-IDENTICAL to the single-device :class:`QueryEngine`'s and to the
pure-Python oracle's for every query kind on the virtual 8-device CPU
mesh — including leaderboard tie-breaks that span shard boundaries —
with zero steady-state retraces per shard, one monotone version number
across all shards (no torn cross-shard reads), and the mesh runner's
``view_publisher=`` wiring publishing per-shard patches at chunk
boundaries. The forced-host-device subprocess check rides the shared
``tests/hostmesh.py`` helpers.
"""

import threading

import jax
import numpy as np
import pytest

from analyzer_tpu.config import RatingConfig, ServiceConfig
from analyzer_tpu.core.state import MU_LO, SIGMA_LO, PlayerState
from analyzer_tpu.obs import get_registry, reset_registry
from analyzer_tpu.obs.retrace import retrace_counts
from analyzer_tpu.serve import (
    QueryEngine,
    ServePlane,
    ShardedQueryEngine,
    ShardedViewPublisher,
    UnknownPlayerError,
    ViewPublisher,
)
from analyzer_tpu.serve import oracle
from analyzer_tpu.serve.server import ServeServer
from analyzer_tpu.serve.view import (
    PATCH_BUCKET_FLOOR,
    _pow2_bucket,
    local_of_row,
    shard_of_row,
    shard_player_count,
)
from analyzer_tpu.service import InMemoryBroker, InMemoryStore, Worker
from tests.hostmesh import run_forced_host
from tests.test_serve import http_get, mk_match, rated_table

CFG = RatingConfig()

_NO_SHARD_MAP = not hasattr(jax, "shard_map")


@pytest.fixture(autouse=True)
def fresh_registry():
    reset_registry()
    yield
    reset_registry()


def publish_pair(n_players=60, n_rated=45, seed=0, n_shards=4, table=None):
    """The same rows published through BOTH planes — the comparison rig
    every parity test drives."""
    if table is None:
        table = rated_table(n_players, n_rated, seed)
    ids = [f"p{i}" for i in range(n_players)]
    pub1 = ViewPublisher()
    pubS = ShardedViewPublisher(n_shards)
    v1 = pub1.publish_rows(ids, table)
    vS = pubS.publish_rows(ids, table)
    return pub1, pubS, v1, vS, ids, table


def tied_table(n_players=40, n_shards=4, seed=5) -> np.ndarray:
    """A rated table with exact score ties pinned on rows owned by
    DIFFERENT shards (rows 3, 6, 9, 13 -> shards 3, 2, 1, 1 at S=4), so
    the merge's tie-break is exercised across shard boundaries."""
    table = rated_table(n_players, n_players, seed)
    for row in (3, 6, 9, 13):
        table[row, MU_LO] = np.float32(1987.5)
        table[row, SIGMA_LO] = np.float32(12.25)
    return table


class TestShardRouting:
    """The serve plane's routing MUST agree with the write mesh's
    interleaved ownership — these pins tie serve/view.py to
    parallel/mesh.py's layout helpers."""

    def test_matches_mesh_owner_helpers(self):
        from analyzer_tpu.parallel.mesh import _local_row, _owner

        rows = np.arange(1000, dtype=np.int64)
        for s in (1, 2, 3, 4, 8):
            np.testing.assert_array_equal(
                shard_of_row(rows, s), np.asarray(_owner(rows, s))
            )
            np.testing.assert_array_equal(
                local_of_row(rows, s), np.asarray(_local_row(rows, s))
            )

    def test_shard_player_count_partitions_exactly(self):
        for n in (0, 1, 7, 64, 100, 1001):
            for s in (1, 2, 4, 8):
                counts = [shard_player_count(n, d, s) for d in range(s)]
                assert sum(counts) == n
                for d in range(s):
                    assert counts[d] == sum(
                        1 for r in range(n) if shard_of_row(r, s) == d
                    )

    def test_locate_routes_by_ownership(self):
        _pub1, _pubS, _v1, vS, _ids, _table = publish_pair()
        for row in (0, 1, 7, 42, 59):
            shard, local = vS.locate(f"p{row}")
            assert shard == row % 4 and local == row // 4
        assert vS.locate("ghost") is None


class TestShardedViewPublisher:
    def test_one_version_spans_all_shards(self):
        _pub1, pubS, _v1, vS, ids, table = publish_pair()
        assert vS.version == 1
        assert all(s.version == 1 for s in vS.shards)
        v2 = pubS.publish_rows(ids[:3], table[:3])
        assert v2.version == 2
        assert all(s.version == 2 for s in v2.shards)

    def test_host_table_matches_single_plane(self):
        _pub1, _pubS, v1, vS, _ids, _table = publish_pair()
        np.testing.assert_array_equal(
            vS.host_table(), v1.host_table()[: v1.n_players]
        )

    def test_untouched_shards_carry_tables_forward(self):
        _pub1, pubS, _v1, vS, ids, table = publish_pair()
        # Rows owned by shard 0 only (row % 4 == 0).
        mine = [i for i in range(60) if i % 4 == 0][:5]
        v2 = pubS.publish_rows([f"p{i}" for i in mine], table[mine])
        assert v2.shards[0].table is not vS.shards[0].table
        for d in (1, 2, 3):
            # Zero transfer: the untouched shard's DEVICE table rides
            # into the next version by reference.
            assert v2.shards[d].table is vS.shards[d].table

    def test_shared_local_bucket_and_growth_rebuilds(self):
        pub1, pubS, v1, vS, ids, table = publish_pair()
        # 60 players / 4 shards = 15 local rows -> shared bucket 64.
        assert all(s.table.shape[0] == 65 for s in vS.shards)
        extra = rated_table(200, 200, seed=8)
        eids = [f"x{i}" for i in range(200)]
        v2 = pubS.publish_rows(eids, extra)
        # 260 players -> ceil(260/4)=65 local rows -> bucket 128.
        assert all(s.table.shape[0] == 129 for s in v2.shards)
        pub1.publish_rows(eids, extra)
        np.testing.assert_array_equal(
            v2.host_table(), pub1.current().host_table()[:260]
        )
        # The old version's shards are untouched by the growth.
        assert all(s.table.shape[0] == 65 for s in vS.shards)

    def test_mode_and_shape_validation(self):
        pub = ShardedViewPublisher(4)
        state = PlayerState.create(10, cfg=CFG)
        pub.publish_state(state)  # identity mode
        with pytest.raises(ValueError, match="table mode"):
            pub.publish_rows(["a"], rated_table(1, 1))
        with pytest.raises(ValueError):
            ShardedViewPublisher(0)
        with pytest.raises(ValueError):
            ShardedViewPublisher(4).publish_rows(
                ["a", "b"], np.zeros((1, 16), np.float32)
            )

    def test_publish_state_splits_by_interleaved_ownership(self):
        table = rated_table(30, 22, seed=3)
        state = PlayerState.create(30, cfg=CFG)
        host = np.asarray(state.table).copy()
        host[:30] = table
        stateish = type("S", (), {"table": host})()
        pubS = ShardedViewPublisher(4)
        vS = pubS.publish_state(stateish)
        for d, shard in enumerate(vS.shards):
            expect = table[d::4]
            np.testing.assert_array_equal(
                shard.host_table()[: expect.shape[0]], expect
            )
        np.testing.assert_array_equal(vS.host_table(), table)

    def test_publish_shard_patches_patch_equals_rebuild(self):
        table = rated_table(60, 60, seed=2)
        pubS = ShardedViewPublisher(4)

        def slices():
            return [table[d::4] for d in range(4)]

        v1 = pubS.publish_shard_patches(
            [(np.empty(0, np.int64), np.empty((0, 16), np.float32))] * 4,
            60,
            slices,
        )  # first publish: rebuild fallback
        np.testing.assert_array_equal(v1.host_table(), table)
        table2 = table.copy()
        table2[[5, 9, 17], MU_LO] += np.float32(3.0)
        patches = []
        for d in range(4):
            rows_idx = np.asarray(
                [r // 4 for r in (5, 9, 17) if r % 4 == d], np.int64
            )
            patches.append((rows_idx, table2[d::4][rows_idx]))
        v2 = pubS.publish_shard_patches(patches, 60, lambda: 1 / 0)
        assert v2.version == 2
        np.testing.assert_array_equal(v2.host_table(), table2)
        # v1 froze: the patch never mutated the previous version.
        np.testing.assert_array_equal(v1.host_table(), table)

    def test_shard_patch_transfer_bytes_are_per_shard_buckets(self):
        table = rated_table(60, 60, seed=2)
        pubS = ShardedViewPublisher(4)
        pubS.publish_shard_patches(
            [(np.empty(0, np.int64), np.empty((0, 16), np.float32))] * 4,
            60,
            lambda: [table[d::4] for d in range(4)],
        )
        counter = get_registry().counter("serve.view_publish_bytes_total")
        before = counter.value
        patches = []
        for d in range(4):
            rows_idx = np.asarray([0, 1], np.int64) if d < 2 else np.empty(
                0, np.int64
            )
            patches.append((rows_idx, table[d::4][rows_idx]))
        pubS.publish_shard_patches(patches, 60, lambda: 1 / 0)
        nb = _pow2_bucket(2, PATCH_BUCKET_FLOOR)
        per_shard = nb * 4 + nb * 16 * 4  # int32 idx + float32 rows
        # Two shards patched, two carried forward with ZERO transfer.
        assert counter.value - before == 2 * per_shard

    def test_torn_read_absence_under_concurrent_publishes(self):
        """mu encodes the version on every row; any reader-visible view
        mixing shard tables from two publishes would decode two
        different versions inside one ShardedRatingsView."""
        n = 48
        ids = [f"p{i}" for i in range(n)]
        base = np.asarray(PlayerState.create(n, cfg=CFG).table).copy()[:n]
        pubS = ShardedViewPublisher(4)

        def rows_for(v: int) -> np.ndarray:
            rows = base.copy()
            rows[:, MU_LO] = np.float32(1000.0 * v) + np.arange(
                n, dtype=np.float32
            )
            rows[:, SIGMA_LO] = np.float32(50.0)
            return rows

        pubS.publish_rows(ids, rows_for(1))
        stop = threading.Event()
        failures: list = []

        def writer():
            for v in range(2, 30):
                pubS.publish_rows(ids, rows_for(v))
            stop.set()

        def reader():
            try:
                while not stop.is_set():
                    view = pubS.current()
                    v = view.version
                    for d, shard in enumerate(view.shards):
                        host = shard.host_table()
                        for j in range(shard.n_players):
                            got = float(host[j, MU_LO])
                            expect = 1000.0 * v + (j * 4 + d)
                            assert got == expect, (
                                "torn cross-shard read", v, d, j, got
                            )
            except BaseException as err:  # noqa: BLE001 — surfaced below
                failures.append(err)

        readers = [threading.Thread(target=reader) for _ in range(3)]
        wt = threading.Thread(target=writer)
        for t in readers:
            t.start()
        wt.start()
        wt.join(timeout=60)
        stop.set()
        for t in readers:
            t.join(timeout=60)
        assert not failures, failures[0]
        assert pubS.version == 29

    def test_warm_patch_buckets_parity_with_single_plane(self):
        pub1, pubS, _v1, _vS, _ids, _table = publish_pair()
        n1 = pub1.warm_patch_buckets(512)
        nS = pubS.warm_patch_buckets(512)
        # Same ladder length -> same publish count -> same version
        # sequence for a soak, whatever the plane topology.
        assert n1 == nS > 0
        assert pub1.version == pubS.version


class TestShardedEngineParity:
    """The acceptance core: bit-identity across planes and vs oracle."""

    @pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
    def test_every_query_kind_bit_identical(self, n_shards):
        pub1, pubS, v1, vS, ids, table = publish_pair(n_shards=n_shards)
        e1 = QueryEngine(pub1, cfg=CFG)
        eS = ShardedQueryEngine(pubS, cfg=CFG)
        host = vS.host_table()
        assert e1.get_ratings(["p2", "p50", "ghost"]) == eS.get_ratings(
            ["p2", "p50", "ghost"]
        )
        rng = np.random.default_rng(7)
        for _ in range(10):
            na, nb = rng.integers(1, 6), rng.integers(1, 6)
            picks = rng.choice(60, na + nb, replace=False)
            a = [f"p{i}" for i in picks[:na]]
            b = [f"p{i}" for i in picks[na:]]
            r1 = e1.win_probability(a, b)
            rS = eS.win_probability(a, b)
            assert r1 == rS
            rows_a = [int(i) for i in picks[:na]]
            rows_b = [int(i) for i in picks[na:]]
            assert np.float32(rS["p_a"]) == oracle.win_probability(
                host, rows_a, rows_b, CFG.beta2
            )
            assert np.float32(rS["quality"]) == oracle.quality(
                host, rows_a, rows_b, CFG.beta2
            )
        for k in (1, 5, 44, 45, 60):
            l1 = e1.leaderboard(k)
            lS = eS.leaderboard(k)
            assert l1 == lS
            exp = oracle.leaderboard(host, vS.n_players, k)
            assert len(lS["leaders"]) == len(exp)
            for lead, (row, score) in zip(lS["leaders"], exp):
                assert lead["id"] == f"p{row}"
                assert np.float32(lead["conservative"]) == score
                assert np.float32(lead["mu"]) == np.float32(host[row, MU_LO])
        t1, tS = e1.tier_histogram(), eS.tier_histogram()
        assert t1 == tS
        counts, rated = oracle.tier_histogram(host, 60, eS.tier_edges)
        assert tS["counts"] == counts and tS["rated"] == rated
        for score in (-3000.0, 0.0, 612.25, 5000.0):
            p1, pS = e1.percentile(score), eS.percentile(score)
            assert p1 == pS
            below, rated = oracle.percentile(host, 60, score)
            assert pS["below"] == below and pS["rated"] == rated

    def test_cross_shard_tie_break_matches_topk_and_oracle(self):
        table = tied_table(n_players=40, n_shards=4)
        pub1, pubS, _v1, vS, _ids, _table = publish_pair(
            n_players=40, n_rated=40, n_shards=4, table=table
        )
        e1 = QueryEngine(pub1, cfg=CFG)
        eS = ShardedQueryEngine(pubS, cfg=CFG)
        l1 = e1.leaderboard(40)
        lS = eS.leaderboard(40)
        assert l1 == lS
        # The tied rows (3, 6, 9, 13) live on shards 3, 2, 1, 1 — the
        # merge must order them by GLOBAL row, exactly like lax.top_k on
        # the unsharded table and the oracle's stable sort.
        tied_ids = [e["id"] for e in lS["leaders"] if e["id"] in
                    ("p3", "p6", "p9", "p13")]
        assert tied_ids == ["p3", "p6", "p9", "p13"]
        exp = oracle.leaderboard(vS.host_table(), 40, 40)
        assert [e["id"] for e in lS["leaders"]] == [
            f"p{r}" for r, _ in exp
        ]

    def test_allgather_topk_variant_bit_identical(self):
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices for the all-gather serve mesh")
        table = tied_table(n_players=40, n_shards=4)
        pub1, pubS, _v1, _vS, _ids, _table = publish_pair(
            n_players=40, n_rated=40, n_shards=4, table=table
        )
        e1 = QueryEngine(pub1, cfg=CFG)
        eAG = ShardedQueryEngine(pubS, cfg=CFG, all_gather_topk=True)
        for k in (1, 7, 40):
            assert e1.leaderboard(k)["leaders"] == eAG.leaderboard(k)[
                "leaders"
            ]

    def test_unknown_ids_and_errors_match(self):
        pub1, pubS, _v1, _vS, _ids, _table = publish_pair()
        e1 = QueryEngine(pub1, cfg=CFG)
        eS = ShardedQueryEngine(pubS, cfg=CFG)
        with pytest.raises(UnknownPlayerError):
            eS.win_probability(["p0"], ["ghost"])
        assert e1.get_ratings(["ghost"]) == eS.get_ratings(["ghost"])

    def test_rolling_publishes_keep_parity(self):
        pub1, pubS, _v1, _vS, ids, table = publish_pair()
        e1 = QueryEngine(pub1, cfg=CFG)
        eS = ShardedQueryEngine(pubS, cfg=CFG)
        rng = np.random.default_rng(3)
        for step in range(6):
            picks = rng.choice(60, 9, replace=False)
            upd = table[picks].copy()
            upd[:, MU_LO] += np.float32(step + 1)
            pids = [f"p{i}" for i in picks]
            pub1.publish_rows(pids, upd)
            pubS.publish_rows(pids, upd)
            assert pub1.version == pubS.version
            assert e1.leaderboard(10) == eS.leaderboard(10)
            assert e1.get_ratings(pids[:4]) == eS.get_ratings(pids[:4])
            assert e1.tier_histogram() == eS.tier_histogram()

    def test_both_engines_satisfy_serve_plane(self):
        pub1, pubS, _v1, _vS, _ids, _table = publish_pair()
        assert isinstance(QueryEngine(pub1, cfg=CFG), ServePlane)
        assert isinstance(ShardedQueryEngine(pubS, cfg=CFG), ServePlane)


class TestShardedRetraceDiscipline:
    def test_zero_steady_state_retraces_per_shard(self):
        pub1, pubS, _v1, _vS, ids, table = publish_pair(n_shards=4)
        eS = ShardedQueryEngine(pubS, cfg=CFG, max_batch=32)
        eS.warmup()
        # One warm pass of the publish ladder, like the soak's prepare.
        pubS.warm_patch_buckets(64)
        baseline = {
            k: v for k, v in retrace_counts().items()
            if k.startswith("serve.")
        }
        rng = np.random.default_rng(0)
        for count in (1, 3, 8, 17):
            for _ in range(2):
                reqs = [
                    eS.submit("winprob", (("p0", "p1"), ("p2",)))
                    for _ in range(count)
                ]
                reqs.append(eS.submit("ratings", ("p0", "p4", "p9")))
                reqs.append(eS.submit("percentile", 100.0))
                reqs.append(eS.submit("leaderboard", int(rng.integers(1, 30))))
                reqs.append(eS.submit("tiers"))
                while eS.tick():
                    pass
                for r in reqs:
                    r.result(timeout=0)
                picks = rng.choice(60, 5, replace=False)
                pubS.publish_rows([f"p{i}" for i in picks], table[picks])
        after = {
            k: v for k, v in retrace_counts().items()
            if k.startswith("serve.")
        }
        assert after == baseline, "sharded steady state retraced a kernel"

    def test_per_shard_query_counters_move(self):
        _pub1, pubS, _v1, _vS, _ids, _table = publish_pair(n_shards=4)
        eS = ShardedQueryEngine(pubS, cfg=CFG)
        eS.get_ratings([f"p{i}" for i in range(8)])  # every shard owns 2
        reg = get_registry()
        for d in range(4):
            assert reg.counter(
                "serve.shard.queries_total", shard=str(d)
            ).value == 2
        eS.leaderboard(5)
        assert reg.counter("serve.shard.merges_total").value == 1
        assert reg.counter("serve.shard.merge_candidates_total").value > 0


class TestShardedServeServer:
    def test_http_plane_is_topology_blind(self):
        pub1, pubS, v1, _vS, _ids, _table = publish_pair()
        e1 = QueryEngine(pub1, cfg=CFG).start()
        eS = ShardedQueryEngine(pubS, cfg=CFG).start()
        s1 = ServeServer(e1, port=0)
        sS = ServeServer(eS, port=0)
        try:
            for path in (
                "/v1/ratings?ids=p0,p1,ghost",
                "/v1/leaderboard?k=5",
                "/v1/winprob?a=p0,p1&b=p2",
                "/v1/tiers?score=250",
            ):
                c1, b1 = http_get(s1.url + path)
                cS, bS = http_get(sS.url + path)
                assert (c1, b1) == (cS, bS), path
        finally:
            s1.close()
            sS.close()
            e1.close()
            eS.close()


class TestWorkerShardedIntegration:
    def _feed(self, broker, store, prefix: str, n=4, t0=0):
        for i in range(n):
            mid = f"{prefix}{i}"
            store.add_match(mk_match(mid, created_at=t0 + i))
            broker.publish("analyze", mid.encode())

    def test_worker_serves_through_the_sharded_plane(self):
        broker = InMemoryBroker()
        store = InMemoryStore()
        cfg = ServiceConfig(batch_size=4, idle_timeout=0.0)
        worker = Worker(broker, store, cfg, serve_port=0, serve_shards=4)
        try:
            assert isinstance(worker.query_engine, ShardedQueryEngine)
            assert isinstance(worker.view_publisher, ShardedViewPublisher)
            self._feed(broker, store, "a")
            assert worker.poll()
            assert worker.stats()["serve"]["view_version"] == 1
            pid = "a0_pl0"
            code, body = http_get(
                worker.serve_server.url + f"/v1/ratings?ids={pid}"
            )
            assert code == 200
            player = next(
                p for m in store.matches.values() for r in m.rosters
                for part in r.participants for p in part.player
                if p.api_id == pid
            )
            assert np.float32(body["ratings"][0]["mu"]) == np.float32(
                player.trueskill_mu
            )
            self._feed(broker, store, "b", t0=10)
            assert worker.poll()
            assert worker.stats()["serve"]["view_version"] == 2
        finally:
            worker.close()


@pytest.mark.skipif(
    _NO_SHARD_MAP, reason="jax.shard_map unavailable in this build"
)
class TestMeshRunnerPublish:
    """rate_history_sharded(view_publisher=) — per-shard views at chunk
    boundaries, one monotone cross-shard version, final unthrottled
    publish bit-identical to the finished state."""

    def _setup(self, n_matches=120, n_players=50, batch_size=16, seed=11):
        from analyzer_tpu.io.synthetic import (
            synthetic_players, synthetic_stream,
        )
        from analyzer_tpu.sched import pack_schedule

        players = synthetic_players(n_players, seed=seed)
        stream = synthetic_stream(n_matches, players, seed=seed)
        state = PlayerState.create(
            n_players,
            rank_points_ranked=players.rank_points_ranked,
            rank_points_blitz=players.rank_points_blitz,
            skill_tier=players.skill_tier,
        )
        sched = pack_schedule(
            stream, pad_row=state.pad_row, batch_size=batch_size
        )
        return state, sched

    def test_chunk_boundary_publishes_and_final_bit_identity(self):
        from analyzer_tpu.parallel import make_mesh, rate_history_sharded

        n_dev = min(4, len(jax.devices()))
        mesh = make_mesh(n_dev)
        state, sched = self._setup()
        pub = ShardedViewPublisher(n_dev, min_publish_interval_s=0.0)
        versions: list[int] = []

        def on_chunk(_snapshot, _stop):
            versions.append(pub.version)

        final = rate_history_sharded(
            state, sched, CFG, mesh=mesh, steps_per_chunk=7,
            view_publisher=pub, on_chunk=on_chunk,
        )
        view = pub.current()
        assert view is not None and view.n_players == 50
        # Per-shard views published AT chunk boundaries, not only at the
        # end: versions advanced while chunks were still flowing.
        assert versions and versions[-1] >= 2
        assert view.version == sorted(versions + [view.version])[-1]
        np.testing.assert_array_equal(
            view.host_table(), np.asarray(final.table)[:50]
        )
        # Routed lookups serve the finished ratings bit-for-bit.
        eng = ShardedQueryEngine(pub, cfg=CFG)
        resp = eng.get_ratings(["7"])
        got = np.float32(resp["ratings"][0]["mu"])
        assert got == np.float32(np.asarray(final.table)[7, MU_LO])

    def test_throttled_publisher_still_gets_final(self):
        from analyzer_tpu.parallel import make_mesh, rate_history_sharded

        n_dev = min(2, len(jax.devices()))
        mesh = make_mesh(n_dev)
        state, sched = self._setup(n_matches=40)
        pub = ShardedViewPublisher(n_dev, min_publish_interval_s=3600.0)
        final = rate_history_sharded(
            state, sched, CFG, mesh=mesh, view_publisher=pub
        )
        view = pub.current()
        # Throttle suppressed every chunk publish except the first-due
        # one; the FINAL publish is unthrottled and carries the result.
        assert view is not None
        np.testing.assert_array_equal(
            view.host_table(), np.asarray(final.table)[:50]
        )

    def test_shard_count_mismatch_rejected(self):
        from analyzer_tpu.parallel import make_mesh, rate_history_sharded

        mesh = make_mesh(min(2, len(jax.devices())))
        state, sched = self._setup(n_matches=20)
        with pytest.raises(ValueError, match="n_shards == mesh size"):
            rate_history_sharded(
                state, sched, CFG, mesh=mesh,
                view_publisher=ShardedViewPublisher(7),
            )

    def test_plain_publisher_gets_final_state_only(self):
        from analyzer_tpu.parallel import make_mesh, rate_history_sharded

        mesh = make_mesh(min(2, len(jax.devices())))
        state, sched = self._setup(n_matches=40)
        pub = ViewPublisher(min_publish_interval_s=0.0)
        final = rate_history_sharded(
            state, sched, CFG, mesh=mesh, view_publisher=pub
        )
        view = pub.current()
        assert view is not None and view.version == 1
        np.testing.assert_array_equal(
            view.host_table()[:50], np.asarray(final.table)[:50]
        )


class TestForcedHostSubprocess:
    """The reusable tests/hostmesh.py fixture end-to-end: a FRESH
    interpreter on an 8-way forced-host platform runs the sharded plane
    with shards spread one-per-device (the ``devices=`` rig shape) and
    checks bit-identity against the single-device engine there."""

    SNIPPET = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core.state import MU_LO, SIGMA_LO, PlayerState
from analyzer_tpu.serve import (
    QueryEngine, ShardedQueryEngine, ShardedViewPublisher, ViewPublisher,
)

devices = jax.devices()
assert len(devices) == 8, f"expected 8 forced host devices, got {len(devices)}"
cfg = RatingConfig()
rng = np.random.default_rng(0)
n = 96
state = PlayerState.create(n, skill_tier=rng.integers(1, 29, n), cfg=cfg)
table = np.asarray(state.table).copy()[:n]
table[:, MU_LO] = rng.normal(1500, 400, n).astype(np.float32)
table[:, SIGMA_LO] = rng.uniform(50, 600, n).astype(np.float32)
ids = [f"p{i}" for i in range(n)]
pub1 = ViewPublisher(); pub1.publish_rows(ids, table)
pubS = ShardedViewPublisher(8, devices=devices)
pubS.publish_rows(ids, table)
view = pubS.current()
# One shard table per device — the spread-plane rig shape.
assert sorted({s.table.device.id for s in view.shards}) == list(range(8))
e1 = QueryEngine(pub1, cfg=cfg)
eS = ShardedQueryEngine(pubS, cfg=cfg)
assert e1.leaderboard(20) == eS.leaderboard(20)
assert e1.get_ratings(ids[:10]) == eS.get_ratings(ids[:10])
assert e1.win_probability(ids[:3], ids[3:6]) == eS.win_probability(ids[:3], ids[3:6])
assert e1.tier_histogram() == eS.tier_histogram()
print("SHARDED-8DEV-OK")
"""

    @pytest.mark.slow
    def test_spread_shards_on_fresh_8_device_platform(self):
        proc = run_forced_host(self.SNIPPET, n_devices=8)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "SHARDED-8DEV-OK" in proc.stdout


class TestShardedBenchdiffFamily:
    def _artifact(self, qps, p99, sharded=True, ratio=1.5, stable=True):
        art = {
            "metric": "serve.queries_per_sec", "value": qps,
            "latency_ms": {"p50": p99 / 2, "p99": p99},
            "capture": {"degraded": False},
        }
        if sharded:
            art["sharded"] = {
                "shards": 8, "queries_per_sec": qps / 2,
                "min_over_single": ratio, "steady_retraces": 0,
                "bit_identical_to_single": True, "stable": stable,
            }
        return art

    def test_sharded_configs_parse_and_gate(self):
        from analyzer_tpu.obs.benchdiff import bench_configs, diff_configs

        a = bench_configs(self._artifact(10000.0, 20.0, ratio=1.5))
        names = [c.name for c in a]
        assert "sharded.min_over_single" in names
        assert "sharded.queries_per_sec" in names
        # Shard-plane tax regression (ratio UP) gates even when the
        # headline holds.
        b = bench_configs(self._artifact(10000.0, 20.0, ratio=2.5))
        rows = diff_configs(a, b, regress_pct=5.0)
        by = {r.name: r for r in rows}
        assert by["sharded.min_over_single"].regressed
        assert by["sharded.min_over_single"].gated
        assert not by["serve.queries_per_sec"].regressed
        # An unstable sharded capture is reported but not gated.
        b = bench_configs(
            self._artifact(10000.0, 20.0, ratio=2.5, stable=False)
        )
        rows = diff_configs(a, b, regress_pct=5.0)
        assert not {r.name: r for r in rows}["sharded.min_over_single"].gated

    def test_vanished_sharded_block_exits_1(self, tmp_path, capsys):
        import json as _json

        from analyzer_tpu import cli

        a = tmp_path / "SERVE_BENCH_r01.json"
        b = tmp_path / "SERVE_BENCH_r02.json"
        a.write_text(_json.dumps(self._artifact(10000.0, 20.0)))
        b.write_text(
            _json.dumps(self._artifact(10000.0, 20.0, sharded=False))
        )
        rc = cli.main([
            "benchdiff", "--family", "serve", str(a), str(b),
        ])
        err = capsys.readouterr().err
        assert rc == 1
        assert "no sharded capture" in err
        # Same artifact both sides: clean pass.
        assert cli.main([
            "benchdiff", "--family", "serve", str(a), str(a),
        ]) == 0


class TestShardSchema:
    def test_standard_schema_has_shard_series(self):
        from analyzer_tpu.obs.registry import (
            STANDARD_COUNTERS,
            STANDARD_GAUGES,
        )

        for name in (
            "serve.view_publish_bytes_total",
            "serve.shard.queries_total",
            "serve.shard.merges_total",
            "serve.shard.merge_candidates_total",
        ):
            assert name in STANDARD_COUNTERS, name
        assert "serve.shards" in STANDARD_GAUGES
