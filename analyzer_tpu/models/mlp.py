"""MLP match-outcome predictor (BASELINE.json config 4).

A small bfloat16-friendly MLP over match features (extensible to full
telemetry — items, gold, KDA — by widening the feature vector). Layers are
sized for MXU tiling (multiples of 8/128 would matter at telemetry scale;
at 10 features the model is VPU-bound and latency-trivial). Training: Adam,
jitted epoch scans, identical harness to the logistic head so the two are
drop-in comparable on log-loss.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analyzer_tpu.models.training import train_minibatch


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["w1", "b1", "w2", "b2", "w3", "b3"],
    meta_fields=[],
)
@dataclasses.dataclass
class MLPModel:
    w1: jnp.ndarray
    b1: jnp.ndarray
    w2: jnp.ndarray
    b2: jnp.ndarray
    w3: jnp.ndarray
    b3: jnp.ndarray

    def logits(self, x: jnp.ndarray) -> jnp.ndarray:
        h = jax.nn.relu(x @ self.w1 + self.b1)
        h = jax.nn.relu(h @ self.w2 + self.b2)
        return (h @ self.w3 + self.b3)[..., 0]

    def predict(self, x: jnp.ndarray) -> jnp.ndarray:
        """P(team 0 wins), ``[B]``."""
        return jax.nn.sigmoid(self.logits(x))


def init_mlp(n_features: int, hidden: int = 64, *, seed: int) -> MLPModel:
    # ``seed`` is required at the mint site: a defaulted seed here would
    # hand every caller that omits it the same weight stream (GL006).
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    s1 = (2.0 / n_features) ** 0.5
    s2 = (2.0 / hidden) ** 0.5
    return MLPModel(
        w1=jax.random.normal(k1, (n_features, hidden), jnp.float32) * s1,
        b1=jnp.zeros((hidden,), jnp.float32),
        w2=jax.random.normal(k2, (hidden, hidden), jnp.float32) * s2,
        b2=jnp.zeros((hidden,), jnp.float32),
        w3=jax.random.normal(k3, (hidden, 1), jnp.float32) * s2,
        b3=jnp.zeros((1,), jnp.float32),
    )


def _nll(model: MLPModel, x, y, mask):
    logits = model.logits(x)
    ll = -optax.sigmoid_binary_cross_entropy(logits, y)
    return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def train_mlp(
    features: np.ndarray,
    team0_won: np.ndarray,
    hidden: int = 64,
    epochs: int = 30,
    batch_size: int = 4096,
    lr: float = 1e-3,
    seed: int = 0,
    mesh=None,
) -> tuple[MLPModel, float]:
    """Trains on ``[N, F]`` features; returns (model, final mean NLL).
    ``mesh`` shards the minibatch axis (models.training)."""
    model = init_mlp(features.shape[1], hidden, seed=seed)
    return train_minibatch(
        model, _nll, features, team0_won, epochs, batch_size, lr, seed,
        mesh=mesh,
    )
