"""Rating and win-probability model zoo (BASELINE.json configs 1, 3, 4).

The reference's only model is the TrueSkill update in ``rater.py``; the
framework's north-star config list adds an Elo pairwise rater, a logistic
win-probability head over rating features, and an MLP outcome predictor.
All three follow the same TPU shape discipline as the TrueSkill core:
static-shape batches, jit-compiled pure functions, optax-free hand-rolled
SGD/Adam steps that scan over minibatches on device.
"""

from analyzer_tpu.models.elo import EloConfig, elo_history, elo_rate_batch
from analyzer_tpu.models.features import (
    N_FEATURES,
    N_TELEMETRY_FEATURES,
    history_features,
    match_features,
    telemetry_features,
)
from analyzer_tpu.models.calibration import apply_temperature, fit_temperature
from analyzer_tpu.models.logistic import LogisticModel, train_logistic
from analyzer_tpu.models.mlp import MLPModel, init_mlp, train_mlp

__all__ = [
    "EloConfig",
    "elo_history",
    "elo_rate_batch",
    "match_features",
    "history_features",
    "N_FEATURES",
    "N_TELEMETRY_FEATURES",
    "telemetry_features",
    "apply_temperature",
    "fit_temperature",
    "LogisticModel",
    "train_logistic",
    "MLPModel",
    "init_mlp",
    "train_mlp",
]
