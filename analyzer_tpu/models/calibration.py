"""Post-hoc temperature scaling for the sigmoid win-probability heads.

A single scalar T rescales the head's logits (``sigmoid(z / T)``) to
minimize NLL — the standard one-parameter calibration that fixes the
over/under-confidence an under-trained or over-trained head exhibits
without touching its ranking (accuracy and AUC are invariant under a
positive temperature; log-loss and calibration error improve). Fit T on
rows the head did NOT train on (the CLI reserves the chronological tail
of its train split): an overfit head's logits on its own training rows
look calibrated precisely when its eval logits are not.
"""

from __future__ import annotations

import math

import numpy as np


def fit_temperature(
    logits: np.ndarray,
    labels: np.ndarray,
    lo: float = 0.05,
    hi: float = 20.0,
    iters: int = 60,
) -> float:
    """Golden-section search for the NLL-minimizing temperature in
    ``[lo, hi]`` (log-domain; the NLL is smooth and unimodal in T).
    Deterministic, dependency-free, ~60 evaluations."""
    logits = np.asarray(logits, np.float64)
    labels = np.asarray(labels, np.float64)
    if logits.size == 0:
        return 1.0

    def nll(t: float) -> float:
        z = np.clip(logits / t, -30.0, 30.0)
        p = 1.0 / (1.0 + np.exp(-z))
        eps = 1e-12
        return float(
            -np.mean(
                labels * np.log(p + eps) + (1.0 - labels) * np.log(1.0 - p + eps)
            )
        )

    a, b = math.log(lo), math.log(hi)
    gr = (math.sqrt(5.0) - 1.0) / 2.0
    c, d = b - gr * (b - a), a + gr * (b - a)
    fc, fd = nll(math.exp(c)), nll(math.exp(d))
    for _ in range(iters):
        if fc < fd:
            b, d, fd = d, c, fc
            c = b - gr * (b - a)
            fc = nll(math.exp(c))
        else:
            a, c, fc = c, d, fd
            d = a + gr * (b - a)
            fd = nll(math.exp(d))
    return math.exp((a + b) / 2.0)


def apply_temperature(logits: np.ndarray, temperature: float) -> np.ndarray:
    """``sigmoid(logits / T)`` as float64 probabilities."""
    z = np.clip(np.asarray(logits, np.float64) / temperature, -30.0, 30.0)
    return 1.0 / (1.0 + np.exp(-z))
