"""Elo pairwise/team rater (BASELINE.json config 1).

Closed-form like the TrueSkill kernel but with a single scalar per player:
team rating = mean of members, expected score from the logistic curve, and
every member of a team moves by the same K-scaled surprise. Runs over the
SAME conflict-free superstep schedule as TrueSkill (sched.pack_schedule),
so chronology and scatter-safety come for free, and the state is a packed
``[P+1, 1]``-style row table for the fast row-gather path.

The reference has no Elo implementation; this is the harness-validation
model from BASELINE.json ("Elo pairwise rater on 1k-match CSV") — simple
enough to check the scheduler/scan machinery end-to-end by hand.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from analyzer_tpu.sched.superstep import PackedSchedule, expand_step


@dataclasses.dataclass(frozen=True)
class EloConfig:
    initial: float = 1500.0
    k: float = 32.0
    scale: float = 400.0


def create_elo_table(n_players: int, cfg: EloConfig = EloConfig()) -> jnp.ndarray:
    """``[P+1]`` ratings, all at the initial value (padding row included)."""
    return jnp.full((n_players + 1,), cfg.initial, jnp.float32)


def elo_rate_batch(
    table: jnp.ndarray,
    player_idx: jnp.ndarray,
    slot_mask: jnp.ndarray,
    winner: jnp.ndarray,
    ratable: jnp.ndarray,
    pad_row: int,
    cfg: EloConfig,
):
    """One conflict-free batch of team-Elo updates.

    Returns (new_table, expected0) where expected0 is P(team 0 wins) under
    the logistic curve — the pairwise-prediction output.
    """
    maskf = slot_mask.astype(table.dtype)
    r = table[player_idx]  # [B,2,T] — row gather
    n = jnp.maximum(maskf.sum(-1), 1.0)  # [B,2]
    team_r = (r * maskf).sum(-1) / n  # [B,2] mean rating
    diff = (team_r[:, 0] - team_r[:, 1]) / cfg.scale
    expected0 = 1.0 / (1.0 + jnp.power(10.0, -diff))  # [B]

    score0 = (winner == 0).astype(table.dtype)
    delta0 = cfg.k * (score0 - expected0)  # team 0 members; team 1 gets -delta0
    delta = jnp.stack([delta0, -delta0], axis=1)[:, :, None]  # [B,2,1]

    do = ratable[:, None, None] & slot_mask
    idx = jnp.where(do, player_idx, pad_row)
    new_table = table.at[idx].add(jnp.where(do, delta, 0.0))
    return new_table, expected0


def elo_history(
    sched: PackedSchedule,
    n_players: int,
    cfg: EloConfig = EloConfig(),
    steps_per_chunk: int = 8192,
) -> tuple[np.ndarray, np.ndarray]:
    """Full-history Elo re-rate over a packed schedule.

    Returns (ratings [P], expected0 [N] in stream order) — the latter is the
    model's win prediction for every match, made from pre-match ratings.
    """
    pad_row = n_players  # the elo table's own parking row for padding writes
    if sched.pad_row < n_players:
        # expand_step derives slot_mask from sched.pad_row; a schedule
        # packed against a SMALLER table would alias a real player's row.
        # (A larger sched.pad_row is fine: masks derive from it, writes
        # park at the elo table's own pad row.)
        raise ValueError(
            f"schedule packed with pad_row={sched.pad_row} < "
            f"n_players={n_players}"
        )

    @partial(jax.jit, donate_argnums=(0,))
    def run_chunk(table, arrays):
        def step(tb, xs):
            p, m, w, mo, a = expand_step(xs, sched.pad_row)
            ratable = (mo >= 0) & ~a
            tb, exp0 = elo_rate_batch(tb, p, m, w, ratable, pad_row, cfg)
            return tb, exp0

        return jax.lax.scan(step, table, arrays)

    table = create_elo_table(n_players, cfg)
    exps = []
    for start in range(0, sched.n_steps, steps_per_chunk):
        stop = min(start + steps_per_chunk, sched.n_steps)
        table, exp0 = run_chunk(table, sched.device_arrays(start, stop))
        exps.append(np.asarray(exp0))

    flat = np.concatenate(exps, axis=0).reshape(-1)  # [S*B]
    src = sched.match_idx.reshape(-1)
    sel = src >= 0
    expected = np.zeros(sched.n_matches, np.float32)
    expected[src[sel]] = flat[sel]
    return np.asarray(table)[:n_players], expected
