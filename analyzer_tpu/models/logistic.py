"""Logistic win-probability head (BASELINE.json config 3).

A single sigmoid over the match features — trained with Adam (optax) via a
jitted epoch scan over static-shape minibatches. The label is "team 0 won";
the model calibrates the TrueSkill-derived features against observed
outcomes (e.g. learning how much rating gap actually predicts a win per
mode). Everything runs on device; the training loop is one lax.scan per
epoch, not a Python-per-batch loop.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analyzer_tpu.models.training import train_minibatch


@partial(
    jax.tree_util.register_dataclass, data_fields=["w", "b"], meta_fields=[]
)
@dataclasses.dataclass
class LogisticModel:
    w: jnp.ndarray  # [F]
    b: jnp.ndarray  # []

    def logits(self, x: jnp.ndarray) -> jnp.ndarray:
        return x @ self.w + self.b

    def predict(self, x: jnp.ndarray) -> jnp.ndarray:
        """P(team 0 wins), ``[B]`` for ``x [B, F]``."""
        return jax.nn.sigmoid(self.logits(x))


def _nll(model: LogisticModel, x, y, mask):
    p = jnp.clip(model.predict(x), 1e-7, 1 - 1e-7)
    ll = y * jnp.log(p) + (1 - y) * jnp.log1p(-p)
    return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def train_logistic(
    features: np.ndarray,
    team0_won: np.ndarray,
    epochs: int = 30,
    batch_size: int = 4096,
    lr: float = 0.05,
    seed: int = 0,
    mesh=None,
) -> tuple[LogisticModel, float]:
    """Trains on ``[N, F]`` features; returns (model, final mean NLL).
    ``mesh`` shards the minibatch axis (models.training)."""
    f = features.shape[1]
    model = LogisticModel(w=jnp.zeros((f,), jnp.float32), b=jnp.zeros((), jnp.float32))
    return train_minibatch(
        model, _nll, features, team0_won, epochs, batch_size, lr, seed,
        mesh=mesh,
    )
