"""Match feature extraction for the win-probability heads.

Features per match, built from the *pre-match* rating state (no leakage:
the history runner's collected outputs are posteriors, so features here are
reconstructed from a separate forward pass or from prior snapshots):

    0    shared-mu sum difference (team0 - team1), mu0-normalized
    1    mean shared sigma over the match's real players,
         sigma0-normalized (uncertainty) — per-player mean, not a sum, so
         the scale is comparable between 3v3 (6 players) and 5v5 (10)
    2    TrueSkill win probability Phi(diff / c)  (ops.trueskill)
    3    match quality (draw probability proxy)
    4..9 one-hot game mode (6 modes)

10 features total — the "player-rating features" of BASELINE config 3. The
reference exposes no such head; hero-draft features would concatenate here
when draft data exists in the stream.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core import constants
from analyzer_tpu.core.state import MU_HI, MU_LO, SIGMA_HI, SIGMA_LO, COL_SEED_MU, COL_SEED_SIGMA, PlayerState
from analyzer_tpu.ops import trueskill as ts

N_FEATURES = 4 + constants.N_MODES


def match_features(
    state: PlayerState,
    player_idx: jnp.ndarray,
    slot_mask: jnp.ndarray,
    mode_id: jnp.ndarray,
    cfg: RatingConfig,
) -> jnp.ndarray:
    """``[B, N_FEATURES]`` from the current state (prior to these matches)."""
    rows = state.table[player_idx]  # [B,2,T,W]
    maskf = slot_mask.astype(rows.dtype)

    mu_sh = rows[..., MU_LO]
    sg_sh = rows[..., SIGMA_LO]
    seed_mu = rows[..., COL_SEED_MU]
    seed_sg = rows[..., COL_SEED_SIGMA]
    has = ~jnp.isnan(mu_sh)
    mu = jnp.where(has, mu_sh, seed_mu)
    sg = jnp.where(has, sg_sh, seed_sg)

    team_mu = (mu * maskf).sum(-1)  # [B,2]
    mu_diff = (team_mu[:, 0] - team_mu[:, 1]) / cfg.mu0
    n_active = jnp.maximum(maskf.sum((-2, -1)), 1.0)  # [B] real players
    sg_sum = (sg * maskf).sum((-2, -1)) / (cfg.sigma0 * n_active)

    p_win = ts.win_probability(mu, sg, slot_mask, cfg)
    quality = ts.quality(mu, sg, slot_mask, cfg)

    onehot = (
        jnp.clip(mode_id, 0, None)[:, None] == jnp.arange(constants.N_MODES)[None, :]
    ).astype(rows.dtype)

    return jnp.concatenate(
        [mu_diff[:, None], sg_sum[:, None], p_win[:, None], quality[:, None], onehot],
        axis=1,
    )


from analyzer_tpu.io.synthetic import N_ITEM_BUILDS, TELEMETRY_STATS  # noqa: E402

# Per numeric stat a ratio + a log-total, plus the item-build histogram.
N_TELEMETRY_FEATURES = 2 * (len(TELEMETRY_STATS) - 1) + N_ITEM_BUILDS


def telemetry_features(telemetry, player_idx) -> "np.ndarray":
    """``[N, 18]`` from POST-GAME telemetry ``[N, 2, T, 6]`` (kills,
    deaths, assists, gold, cs, item_build — io/synthetic.py
    TELEMETRY_STATS): per numeric stat, the bounded team ratio
    ``(t0 - t1) / (t0 + t1 + 1)`` and the log1p match total (scale);
    plus the per-build team HISTOGRAM difference over the categorical
    item channel (the "items" of config 4), team-size normalized. These
    describe a FINISHED match — the telemetry head analyzes outcomes
    from game stats; it does not forecast. Forecasting features are
    :func:`match_features` (pre-match state only)."""
    import numpy as np

    tele = np.asarray(telemetry, np.float32)
    if tele.ndim != 4 or tele.shape[-1] != len(TELEMETRY_STATS):
        # A stat-width mismatch (e.g. an npz from an older schema) would
        # silently misread the categorical channel as a stat — reject.
        raise ValueError(
            f"telemetry must be [N, 2, T, {len(TELEMETRY_STATS)}] "
            f"({', '.join(TELEMETRY_STATS)}), got shape {tele.shape}"
        )
    maskb = player_idx >= 0
    mask = maskb.astype(np.float32)[..., None]
    stats = tele[..., :-1]
    team = (stats * mask).sum(axis=2)  # [N,2,5]
    total = team.sum(axis=1)  # [N,5]
    diff = (team[:, 0] - team[:, 1]) / (total + 1.0)

    n, _, t = player_idx.shape
    build = np.clip(tele[..., -1].astype(np.int64), 0, N_ITEM_BUILDS - 1)
    rows = np.repeat(np.arange(n * 2), t).reshape(n, 2, t)
    key = (rows * N_ITEM_BUILDS + build)[maskb]
    hist = np.bincount(key, minlength=n * 2 * N_ITEM_BUILDS).reshape(
        n, 2, N_ITEM_BUILDS
    )
    n_team = np.maximum(maskb.sum(axis=2), 1)[:, :, None]  # [N,2,1]
    hdiff = hist[:, 0] / n_team[:, 0] - hist[:, 1] / n_team[:, 1]

    return np.concatenate(
        [diff, np.log1p(total), hdiff], axis=1
    ).astype(np.float32)


def composition_features(archetype, player_idx) -> "np.ndarray":
    """``[N, A*(A+1)/2]`` PRE-MATCH composition features: the difference
    (team0 - team1) of unordered teammate-archetype-PAIR counts.

    A team's synergy under any symmetric pairwise model is
    ``sum_{i<j} S[a_i, a_j]`` — a LINEAR function of these pair counts,
    so even the logistic head can represent the generator's hidden
    synergy matrix exactly (io/synthetic.py synergy_matrix) and recover
    it from outcomes. Archetypes are static player attributes (playstyle
    buckets, known before the match like a draft), so these features are
    leak-free forecasting inputs, unlike ``telemetry_features``.
    Diagonal entries count same-archetype pairs C(c_a, 2)."""
    import numpy as np

    from analyzer_tpu.io.synthetic import N_ARCHETYPES

    arch = np.asarray(archetype, np.int64)
    if arch.ndim != 1:
        raise ValueError(f"archetype must be [P], got shape {arch.shape}")
    mask = player_idx >= 0
    a = np.where(mask, arch[np.clip(player_idx, 0, None)], -1)  # [N,2,T]
    counts = (a[..., None] == np.arange(N_ARCHETYPES)).sum(axis=2)  # [N,2,A]
    iu, ju = np.triu_indices(N_ARCHETYPES)
    ci = counts[:, :, iu]
    cj = counts[:, :, ju]
    pairs = np.where(iu == ju, ci * (ci - 1) // 2, ci * cj)  # [N,2,#pairs]
    return (pairs[:, 0] - pairs[:, 1]).astype(np.float32)


def history_features(state, sched, cfg: RatingConfig, steps_per_chunk: int = 8192):
    """Leak-free training data for the win-prob heads: one scan over the
    packed schedule that computes each match's features from the PRE-match
    state, then applies the rating update.

    Returns ``(features [N, F], ratable [N] bool, final_state)`` in stream
    order. Train only on ``ratable`` rows: non-ratable matches (unsupported
    mode / AFK) still get feature rows for shape-stability, but their mode
    one-hot is a clamped placeholder and their winner label is meaningless."""
    import dataclasses
    from functools import partial

    import numpy as np

    from analyzer_tpu.core.state import MatchBatch
    from analyzer_tpu.core.update import rate_and_apply
    from analyzer_tpu.sched.superstep import expand_step

    @partial(jax.jit, static_argnames=("cfg", "pad_row"), donate_argnums=(0,))
    def run_chunk(st, arrays, cfg, pad_row):
        def step(s, xs):
            pidx, mask, win, mode, afk = expand_step(xs, pad_row)
            batch = MatchBatch(
                player_idx=pidx, slot_mask=mask, winner=win, mode_id=mode, afk=afk
            )
            feats = match_features(s, pidx, mask, mode, cfg)
            s, _ = rate_and_apply(s, batch, cfg)
            return s, feats

        return jax.lax.scan(step, st, arrays)

    state = jax.tree.map(jnp.copy, state)
    chunks = []
    for start in range(0, sched.n_steps, steps_per_chunk):
        stop = min(start + steps_per_chunk, sched.n_steps)
        state, feats = run_chunk(
            state, sched.device_arrays(start, stop), cfg, sched.pad_row
        )
        chunks.append(np.asarray(feats))

    flat = np.concatenate(chunks, axis=0).reshape(-1, N_FEATURES)
    src = sched.match_idx.reshape(-1)
    sel = src >= 0
    out = np.zeros((sched.n_matches, N_FEATURES), np.float32)
    out[src[sel]] = flat[sel]
    ratable = np.zeros((sched.n_matches,), bool)
    flat_ratable = ((sched.mode_id >= 0) & ~sched.afk).reshape(-1)
    ratable[src[sel]] = flat_ratable[sel]
    return out, ratable, state
