"""Shared minibatch training harness for the win-probability heads.

One implementation of the pad-to-static-shape, permute, and jitted
epoch/step ``lax.scan`` loop, parameterized by model and loss — this is
what makes the logistic and MLP heads genuinely drop-in comparable (same
batching, same masking, same optimizer step structure).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax


def train_minibatch(
    model,
    loss_fn,
    features: np.ndarray,
    labels: np.ndarray,
    epochs: int,
    batch_size: int,
    lr: float,
    seed: int,
    mesh=None,
):
    """Adam over jitted epoch scans. ``loss_fn(model, x, y, mask)`` must be
    a masked mean so the static-shape padding rows contribute nothing.
    Returns (trained model, final epoch mean loss).

    ``mesh`` turns on DATA-PARALLEL training the TPU way: the minibatch
    axis is sharded over the mesh's ``data`` axis and the model/optimizer
    state replicated — under ``jit``, GSPMD partitions the forward/
    backward and inserts the gradient all-reduce itself (the psum a
    NCCL-era trainer would hand-write). The batch size is rounded up to
    a mesh multiple; results match single-device training up to f32
    reduction order."""
    if mesh is not None:
        batch_size = -(-batch_size // int(mesh.devices.size)) * int(
            mesh.devices.size
        )
    n, f = features.shape
    n_batches = max(1, -(-n // batch_size))
    padded = n_batches * batch_size
    x = np.zeros((padded, f), np.float32)
    y = np.zeros((padded,), np.float32)
    m = np.zeros((padded,), np.float32)
    x[:n] = features
    y[:n] = labels
    m[:n] = 1.0

    rng = np.random.default_rng(seed)
    perm = rng.permutation(padded)
    xb = jnp.asarray(x[perm].reshape(n_batches, batch_size, f))
    yb = jnp.asarray(y[perm].reshape(n_batches, batch_size))
    mb = jnp.asarray(m[perm].reshape(n_batches, batch_size))
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from analyzer_tpu.parallel.mesh import DATA_AXIS

        xb = jax.device_put(xb, NamedSharding(mesh, P(None, DATA_AXIS, None)))
        yb = jax.device_put(yb, NamedSharding(mesh, P(None, DATA_AXIS)))
        mb = jax.device_put(mb, NamedSharding(mesh, P(None, DATA_AXIS)))
        model = jax.device_put(model, NamedSharding(mesh, P()))

    opt = optax.adam(lr)
    opt_state = opt.init(model)

    @jax.jit
    def epoch(carry, _):
        mdl, ost = carry

        def step(c, batch):
            mdl, ost = c
            bx, by, bm = batch
            loss, grads = jax.value_and_grad(loss_fn)(mdl, bx, by, bm)
            updates, ost = opt.update(grads, ost)
            mdl = optax.apply_updates(mdl, updates)
            return (mdl, ost), loss

        (mdl, ost), losses = jax.lax.scan(step, (mdl, ost), (xb, yb, mb))
        return (mdl, ost), losses.mean()

    (model, _), losses = jax.lax.scan(epoch, (model, opt_state), None, length=epochs)
    return model, float(np.asarray(losses)[-1])
