"""Reference-compatible object API: ``get_trueskill_seed`` and ``rate_match``.

This is the drop-in surface of the reference's ``rater.py`` — same function
names, same duck-typed object graph (anything with the right attributes:
match -> rosters -> participants -> player / participant_items[0]), same
side effects and logging events — but the rating math runs through the
jit-compiled closed-form kernels in :mod:`analyzer_tpu.ops.trueskill` instead
of the trueskill/mpmath factor graph. The four reference parity tests
(``worker_test.py:66-189``) pass against this module unchanged in spirit:
see ``tests/test_rater_parity.py``.

Behavioral contracts preserved deliberately (from SURVEY.md section 2.1):
  * unsupported game modes mutate nothing (``rater.py:83-85``);
  * ``len(rosters) != 2`` or any ``went_afk == 1`` => quality=0 and
    ``any_afk=True`` on every participant_items[0], no rating update
    (``rater.py:90-106``);
  * quality is computed from the queue-specific matchup even though the
    reference comment says "shared" (``rater.py:140-141``);
  * ``trueskill_delta`` compares conservative estimates against the player's
    *current* attribute value at write time — which, for the test fixtures
    that alias one Participant object three times per roster
    (``worker_test.py:130``), reproduces the reference's sequential-write
    semantics exactly (``rater.py:147-157``);
  * seeding from a skill tier outside -1..29 raises KeyError, as the
    reference's dict lookup does (``rater.py:60``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core import constants
from analyzer_tpu.core.state import MAX_TEAM_SIZE
from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.ops import trueskill as ts

logger = get_logger(__name__)

_default_cfg: RatingConfig | None = None


def _cfg() -> RatingConfig:
    global _default_cfg
    if _default_cfg is None:
        _default_cfg = RatingConfig.from_env()
    return _default_cfg


def get_trueskill_seed(player, cfg: RatingConfig | None = None) -> tuple[float, float]:
    """(mu, sigma) prior for a player with no shared rating yet.

    Fallback 1: max of ranked/blitz rank points (None and 0 both mean
    missing), sigma = UNKNOWN_PLAYER_SIGMA * 2/3, mu = points + sigma so that
    mu - sigma reproduces the points exactly. Fallback 2: the skill-tier
    table with sigma = UNKNOWN_PLAYER_SIGMA. (``rater.py:42-62``.)
    Host-side float64 — seeding is feature preparation, not the TPU hot loop.
    """
    cfg = cfg or _cfg()
    points = [
        p
        for p in (player.rank_points_ranked, player.rank_points_blitz)
        if p is not None and p != 0
    ]
    if points:
        sigma = cfg.unknown_player_sigma * (2.0 / 3.0)
        return float(max(points)) + sigma, sigma
    sigma = cfg.unknown_player_sigma
    return constants.VST_POINTS[player.skill_tier] + sigma, sigma


@partial(jax.jit, static_argnames=("cfg",))
def _rate_arrays(mu_sh, sigma_sh, mu_q, sigma_q, mask, winner, cfg: RatingConfig):
    quality = ts.quality(mu_q, sigma_q, mask, cfg)
    sh_mu, sh_sigma = ts.two_team_update(mu_sh, sigma_sh, mask, winner, cfg)
    q_mu, q_sigma = ts.two_team_update(mu_q, sigma_q, mask, winner, cfg)
    return quality, sh_mu, sh_sigma, q_mu, q_sigma


def rate_match(match, cfg: RatingConfig | None = None):
    """Rates one match object graph in place (reference ``rater.py:69-169``)."""
    cfg = cfg or _cfg()

    # Mode names are normalized by the upstream processor service.
    mode_id = constants.MODE_TO_ID.get(match.game_mode, constants.UNSUPPORTED_MODE_ID)
    if mode_id == constants.UNSUPPORTED_MODE_ID:
        logger.info("got unsupported game mode %s", match.game_mode)
        return
    col = "trueskill_" + match.game_mode

    any_afk = False
    if len(match.rosters) != 2:
        logger.error("got an invalid matchup %s", match.api_id)
        any_afk = True
    for participant in match.participants:
        participant.participant_items[0].any_afk = False
        if participant.went_afk == 1:
            logger.info("got an afk matchup %s", match.api_id)
            any_afk = True
            break
    if any_afk:
        match.trueskill_quality = 0
        for participant in match.participants:
            participant.participant_items[0].any_afk = True
        return

    # --- host -> tensor: pack the two rosters into padded [1, 2, T] arrays.
    team_size = max(
        MAX_TEAM_SIZE, *(len(r.participants) for r in match.rosters)
    )
    shape = (1, 2, team_size)
    mu_sh = np.zeros(shape, np.float32)
    sigma_sh = np.ones(shape, np.float32)
    mu_q = np.zeros(shape, np.float32)
    sigma_q = np.ones(shape, np.float32)
    mask = np.zeros(shape, bool)

    for ti, roster in enumerate(match.rosters):
        for si, participant in enumerate(roster.participants):
            player = participant.player[0]
            if player.trueskill_mu is not None:
                m_sh, s_sh = float(player.trueskill_mu), float(player.trueskill_sigma)
            else:
                m_sh, s_sh = get_trueskill_seed(player, cfg)
            q_prior_mu = getattr(player, col + "_mu")
            if q_prior_mu is not None:
                m_q, s_q = float(q_prior_mu), float(getattr(player, col + "_sigma"))
            else:
                m_q, s_q = m_sh, s_sh  # fall back to the shared prior
            mu_sh[0, ti, si] = m_sh
            sigma_sh[0, ti, si] = s_sh
            mu_q[0, ti, si] = m_q
            sigma_q[0, ti, si] = s_q
            mask[0, ti, si] = True

    logger.info("got a valid matchup %s", match.api_id)
    # The reference encodes ranks as [int(not r.winner) for r in rosters]
    # (rater.py:144); with draw_probability=0 exactly one roster must win.
    # Corrupt flags (both or neither marked winner) would silently produce a
    # bogus update — fail loudly instead so the service's failure policy
    # (dead-letter the batch, worker.py:110-120) handles the bad record.
    w0, w1 = bool(match.rosters[0].winner), bool(match.rosters[1].winner)
    if w0 == w1:
        raise ValueError(
            f"match {match.api_id!r}: rosters have inconsistent winner flags "
            f"({w0}, {w1}); exactly one team must win"
        )
    winner = np.asarray([0 if w0 else 1], np.int32)

    quality, sh_mu, sh_sigma, q_mu, q_sigma = jax.device_get(
        _rate_arrays(
            jnp.asarray(mu_sh), jnp.asarray(sigma_sh),
            jnp.asarray(mu_q), jnp.asarray(sigma_q),
            jnp.asarray(mask), jnp.asarray(winner), cfg,
        )
    )

    # --- tensor -> host write-back, in the reference's traversal order.
    match.trueskill_quality = float(quality[0])

    for ti, roster in enumerate(match.rosters):
        for si, participant in enumerate(roster.participants):
            player = participant.player[0]
            new_mu = float(sh_mu[0, ti, si])
            new_sigma = float(sh_sigma[0, ti, si])
            if player.trueskill_mu is not None:
                participant.trueskill_delta = (new_mu - new_sigma) - (
                    float(player.trueskill_mu) - float(player.trueskill_sigma)
                )
            else:
                participant.trueskill_delta = 0
            player.trueskill_mu = new_mu
            participant.trueskill_mu = new_mu
            player.trueskill_sigma = new_sigma
            participant.trueskill_sigma = new_sigma

    for ti, roster in enumerate(match.rosters):
        for si, participant in enumerate(roster.participants):
            player = participant.player[0]
            items = participant.participant_items[0]
            new_mu = float(q_mu[0, ti, si])
            new_sigma = float(q_sigma[0, ti, si])
            setattr(player, col + "_mu", new_mu)
            setattr(items, col + "_mu", new_mu)
            setattr(player, col + "_sigma", new_sigma)
            setattr(items, col + "_sigma", new_sigma)
