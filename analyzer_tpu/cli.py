"""Command-line interface: ``python -m analyzer_tpu.cli <cmd>``.

The reference's only entry point is ``python3 worker.py`` (env-var config,
``worker.py:219-221``). The CLI keeps that (``worker`` subcommand) and adds
the offline paths the reference delegates to its database for: full-history
re-rates from CSV streams with checkpoint/resume, the Elo harness
(BASELINE.json config 1: "Elo pairwise rater on 1k-match CSV"), synthetic
stream generation, and the benchmark.

Subcommands:
  synth   generate a synthetic match history (.csv or .npz by extension)
  rate    TrueSkill full-history re-rate of a stream (checkpoint/resume)
  train   win-probability heads (logistic/MLP) on leak-free rating features
  elo     Elo re-rate of a stream + prediction accuracy
  bench   the headline throughput benchmark (one JSON line)
  benchdiff  per-config throughput delta between two BENCH_*.json
          artifacts; non-zero exit past --regress-pct (CI trajectory gate)
  worker  the broker-consuming service loop (needs pika)
  serve   ratesrv: the standalone query-serving plane over a checkpoint
          or database table (/v1/ratings /v1/leaderboard /v1/winprob
          /v1/tiers — docs/serving.md)
  soak    closed-loop matchmaking soak: matchmake from the served
          ratings, rate through the worker, query /v1/* concurrently,
          gate SLOs; emits SOAK_*.json for benchdiff --family soak
          (deterministic per seed — docs/OPERATIONS.md); --migrate
          runs a full re-rate under the live load as the judge;
          --hosts N runs the soak over a real multi-process fabric
          (FABRIC_BENCH_*.json for benchdiff --family fabric)
  fabric  launch a standing multi-host rate fabric: shard-owning host
          processes, partitioned ingest, per-host serve planes and
          /fabric/* control surfaces (docs/fabric.md)
  migrate zero-downtime global re-rate: streamed decode->assign->scan
          backfill into a staging view lineage while the live lineage
          serves, atomic cutover, checkpoint/resume (docs/migration.md)
  query   one query against a running serve endpoint (HTTP client)
  lint    graftlint static analysis (JAX hazards + native ABI, docs/lint.md)
  metrics runtime telemetry snapshots (docs/observability.md): render a
          --metrics-out artifact (or this process) as JSON/Prometheus/text

Live introspection: rate/bench/worker take ``--obs-port`` (obsd —
/metrics, /healthz, /readyz, /statusz, /debug/snapshot on localhost);
the worker also takes ``--flight-dir`` to arm flight-recorder dumps and
``--serve-port`` to co-host the ratesrv read plane.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np


def _load_stream(path: str):
    from analyzer_tpu.io.csv_codec import load_stream

    stream = load_stream(path)
    n_players = int(stream.player_idx.max()) + 1 if stream.n_matches else 0
    return stream, n_players


def _load_inputs(args, cfg, timer):
    """The rate paths' input loader: a CSV/npz stream file (--csv) or a
    columnar full-history DB ingest (--db, sql_store.load_stream).
    Returns (stream, n_players, db_state, db_store, player_ids) — the
    last three None on the file path; db_state carries the players' DB
    rating priors, which a fresh file run does not have."""
    if getattr(args, "db", None):
        from analyzer_tpu.service.sql_store import SqlStore

        with timer.phase("load"):
            store = SqlStore(args.db)
            hist = store.load_stream(cfg)
        return (
            hist.stream, hist.state.n_players, hist.state, store,
            hist.player_ids,
        )
    with timer.phase("load"):
        stream, n_players = _load_stream(args.csv)
    return stream, n_players, None, None, None


def _require_one_source(args) -> bool:
    """Validates that EXACTLY one of --csv / --db names a source,
    normalizing empty strings to missing (``--db ""`` must not slip
    past the xor and crash in the loader). Shared by rate/elo/train."""
    args.csv = getattr(args, "csv", None) or None
    args.db = getattr(args, "db", None) or None
    if (args.csv is None) == (args.db is None):
        print("error: exactly one of --csv / --db is required",
              file=sys.stderr)
        return False
    return True


def _maybe_db_write(args, timer, db_store, state, player_ids) -> dict:
    """Final-table write-back for --db --db-write runs; returns a stats
    extra ({} when not writing)."""
    if db_store is None or not getattr(args, "db_write", False):
        return {}
    with timer.phase("db_write"):
        n = db_store.write_players(state, player_ids)
    return {"players_written": n}


def cmd_synth(args) -> int:
    from analyzer_tpu.io.csv_codec import save_stream
    from analyzer_tpu.io.synthetic import (
        synthetic_players,
        synthetic_stream,
        synthetic_telemetry,
    )

    if args.synergy and not args.out.endswith(".npz"):
        # Archetypes ride only in the npz block; a synergy-driven stream
        # whose composition channel can't be saved would silently train
        # heads against unexplainable outcomes.
        print("error: --synergy requires an .npz output", file=sys.stderr)
        return 2
    players = synthetic_players(args.players, seed=args.seed)
    stream = synthetic_stream(
        args.matches, players, seed=args.seed,
        activity_concentration=args.concentration,
        max_activity_share=args.max_share or None,
        synergy_strength=args.synergy,
    )
    telemetry = None
    if args.telemetry:
        if not args.out.endswith(".npz"):
            print("error: --telemetry requires an .npz output", file=sys.stderr)
            return 2
        telemetry = synthetic_telemetry(stream, players, seed=args.seed)
    if args.out.endswith(".db"):
        # Reference-schema sqlite: exercises the whole DB lane (service
        # worker, rate/elo/train --db) without production data.
        from analyzer_tpu.io.dbgen import write_history_db

        write_history_db(args.out, stream, players)
    else:
        save_stream(
            args.out, stream, telemetry=telemetry,
            # npz streams always carry the composition channel so a
            # synergy=0 control trains with the SAME feature set as the
            # synergy run — a clean signal-vs-no-signal comparison.
            archetype=players.archetype if args.out.endswith(".npz") else None,
        )
    print(
        f"wrote {stream.n_matches} matches / {args.players} players to "
        f"{args.out}" + (" (+telemetry)" if telemetry is not None else "")
    )
    return 0


def _checkpoint_hook(args, sched, cursor, start_step, finished, lead=True):
    """The shared periodic/bounded-run snapshot closure of the
    single-device and --mesh rate paths. Returns ``(on_chunk, close)``
    — on_chunk is None when no saves can be due; close drains the async
    writer (call it in a finally). Periodic saves honor
    --checkpoint-every; a bounded run always snapshots at its stop
    boundary; the finished branch's final save is never duplicated.

    Snapshots are ASYNC (io.checkpoint.CheckpointWriter): the hook pays
    only the device fetch; the ~100 MB serialize+rename at north-star
    scale runs on a writer thread instead of stalling the scan (the
    reference pays durability synchronously per 500-match commit,
    worker.py:194 — bounded blast radius without the per-batch stall).

    Multi-host discipline: the hook must run on EVERY process — the mesh
    runner hands the state as a lazy thunk whose evaluation is a
    cross-process collective (the unshard gather), and the cadence
    decision is a pure function of ``next_step``, so all processes make
    the same call and the SPMD program never diverges. Only the lead
    process has a writer. The thunk is evaluated strictly AFTER the
    cadence decision, so skipped chunks never pay the cross-mesh gather."""
    from analyzer_tpu.io.checkpoint import CheckpointWriter

    if not args.checkpoint or (not args.checkpoint_every and finished):
        return None, lambda: None
    every = args.checkpoint_every or sched.n_steps + 1
    fingerprint = sched.fingerprint
    effective_stop = (
        sched.n_steps if finished else min(args.stop_after_steps, sched.n_steps)
    )
    last_saved = start_step
    writer = CheckpointWriter(args.checkpoint) if lead else None

    def on_chunk(st, next_step):
        nonlocal last_saved
        due = next_step - last_saved >= every
        at_bound = not finished and next_step >= effective_stop
        if (not due and not at_bound) or (
            finished and next_step >= sched.n_steps
        ):
            return
        last_saved = next_step
        if callable(st):  # mesh path: collective snapshot, all processes
            st = st()
        if writer is not None:
            writer.save(
                st, cursor=cursor,
                step_cursor=next_step, schedule_fingerprint=fingerprint,
            )

    return on_chunk, (writer.close if writer is not None else lambda: None)


def _rate_streamed(
    args, cfg, timer, state, stream, cursor, n_players,
    mesh=None, finalize=None, **extra,
) -> int:
    """The fully-streamed rate path shared by cmd_rate and _rate_mesh:
    concurrent assignment feeding the device (sched.rate_stream), stats
    reconstructed from the runner's observables (the schedule never
    exists as one object here). ``finalize(state) -> dict`` runs after
    the rate (DB write-back) and its stats merge into the output line."""
    import types

    from analyzer_tpu.sched import rate_stream
    from analyzer_tpu.utils import trace

    stats: dict = {}
    with timer.phase("rate"), trace(args.trace):
        state, _ = rate_stream(
            state, stream.slice(cursor, stream.n_matches), cfg,
            stats_out=stats, mesh=mesh,
            prefetch_depth=getattr(args, "prefetch_depth", None),
            kernel=getattr(args, "kernel", "reference") if mesh is None
            else "reference",
            fuse_window=getattr(args, "fuse_window", None),
            hot_rows=getattr(args, "hot_rows", 0) if mesh is None else 0,
        )
        np.asarray(state.table[:1])  # force completion for honest timing
    if finalize is not None:
        extra.update(finalize(state))
    sched_view = types.SimpleNamespace(
        n_steps=stats["n_steps"], occupancy=stats["occupancy"]
    )
    extra.setdefault(
        "choose_batch_size_s", round(stats["choose_batch_size_s"], 3)
    )
    print(
        _rate_stats(stream, cursor, n_players, state, sched_view, timer, **extra)
    )
    return 0


def _rate_stats(stream, cursor, n_players, state, sched, timer, **extra) -> str:
    """The shared stats line of the single-device and --mesh rate paths."""
    mu = np.asarray(state.mu)[:n_players, 0]
    rated = ~np.isnan(mu)
    stats = {
        "matches": stream.n_matches - cursor,
        "players_rated": int(rated.sum()),
        "mean_mu": round(float(mu[rated].mean()), 2) if rated.any() else None,
        "supersteps": sched.n_steps,
        "occupancy": round(sched.occupancy, 3),
        **extra,
        "phases": {k: round(v, 3) for k, v in timer.report().items()},
    }
    return json.dumps(stats)


def _auc(p: np.ndarray, y: np.ndarray) -> float | None:
    """ROC AUC via the Mann-Whitney U statistic, tie-averaged ranks."""
    pos = y == 1.0
    n1, n0 = int(pos.sum()), int((~pos).sum())
    if n1 == 0 or n0 == 0:
        return None
    order = np.argsort(p, kind="mergesort")
    sp = p[order]
    first = np.r_[True, sp[1:] != sp[:-1]]
    grp = np.cumsum(first) - 1
    counts = np.bincount(grp)
    starts = np.cumsum(counts) - counts
    avg = starts + (counts - 1) / 2.0 + 1.0
    ranks = np.empty(p.size)
    ranks[order] = avg[grp]
    return float((ranks[pos].sum() - n1 * (n1 + 1) / 2.0) / (n1 * n0))


def _ece(p: np.ndarray, y: np.ndarray, bins: int = 10) -> float:
    """Expected calibration error over equal-width probability bins."""
    idx = np.clip((p * bins).astype(int), 0, bins - 1)
    err = 0.0
    for b in range(bins):
        sel = idx == b
        if sel.any():
            err += abs(p[sel].mean() - y[sel].mean()) * sel.mean()
    return float(err)


def _half_credit_accuracy(p: np.ndarray, team0_won: np.ndarray) -> float:
    """Prediction accuracy with exact ties (p == 0.5, e.g. two fresh
    teams) scoring half credit instead of silently counting as "team 0
    predicted" — shared by the elo and train evals."""
    hit = np.where(p == 0.5, 0.5, (p > 0.5) == (team0_won == 1.0))
    return float(hit.mean())


def _obs_begin(args) -> None:
    """Arms the telemetry surface for a ``--metrics-out``/``--trace-events``
    /``--obs-port`` run: the jax.monitoring compile listeners make
    retraces countable from the first compile."""
    if (
        getattr(args, "metrics_out", None)
        or getattr(args, "trace_events", None)
        or getattr(args, "obs_port", None) is not None
    ):
        from analyzer_tpu.obs import install_jax_hooks

        install_jax_hooks()


def _obs_serve(args):
    """Starts obsd for the duration of a CLI run when ``--obs-port`` was
    given (0 = ephemeral; the bound port prints to stderr). Returns the
    server (caller closes) or None."""
    port = getattr(args, "obs_port", None)
    if port is None:
        return None
    from analyzer_tpu.obs.server import ObsServer

    server = ObsServer(port=port)
    print(f"obsd listening on {server.url}", file=sys.stderr)
    return server


def _obs_write(args) -> None:
    """Writes the snapshot/trace artifacts a run asked for."""
    if getattr(args, "metrics_out", None):
        from analyzer_tpu.obs import write_snapshot

        write_snapshot(args.metrics_out)
        print(f"wrote metrics snapshot to {args.metrics_out}", file=sys.stderr)
    if getattr(args, "trace_events", None):
        from analyzer_tpu.obs import write_chrome_trace

        n = write_chrome_trace(args.trace_events)
        print(
            f"wrote {n} Chrome trace events to {args.trace_events} "
            "(open in Perfetto)", file=sys.stderr,
        )


def cmd_rate(args) -> int:
    _obs_begin(args)
    server = _obs_serve(args)
    try:
        rc = _cmd_rate_impl(args)
        if rc == 0:
            _obs_write(args)
    finally:
        if server is not None:
            server.close()
    return rc


def _cmd_rate_impl(args) -> int:
    from analyzer_tpu.config import RatingConfig
    from analyzer_tpu.core.state import PlayerState
    from analyzer_tpu.io.checkpoint import load_checkpoint, save_checkpoint
    from analyzer_tpu.sched import pack_schedule, rate_history
    from analyzer_tpu.utils import PhaseTimer, trace

    cfg = RatingConfig.from_env()
    if args.resume and not args.checkpoint:
        print("error: --resume requires --checkpoint", file=sys.stderr)
        return 2
    for flag in ("checkpoint_every", "stop_after_steps", "prefetch_depth"):
        val = getattr(args, flag)
        if val is not None and val <= 0:
            print(f"error: --{flag.replace('_', '-')} must be positive",
                  file=sys.stderr)
            return 2
    if args.checkpoint_every and not args.checkpoint:
        # Silently writing nothing would defeat the flag's whole purpose
        # (crash blast radius); --stop-after-steps alone stays legal as a
        # bounded smoke run (stats only, state discarded).
        print("error: --checkpoint-every requires --checkpoint", file=sys.stderr)
        return 2
    if args.mesh is not None and args.mesh < 0:
        print("error: --mesh must be >= 0 (0 = all devices)", file=sys.stderr)
        return 2
    if args.mesh is not None and args.kernel == "fused":
        # The sharded scatter is already per-shard compacted; a per-shard
        # fused working set is future work (parallel/mesh.py tracks its
        # value via mesh.writebacks_avoidable_total). Refuse rather than
        # silently rating with a different kernel than asked.
        print(
            "error: --kernel fused is not supported with --mesh yet; "
            "drop --mesh or use --kernel reference", file=sys.stderr,
        )
        return 2
    if args.fuse_window is not None and args.fuse_window <= 0:
        print("error: --fuse-window must be positive", file=sys.stderr)
        return 2
    if args.hot_rows < 0:
        print("error: --hot-rows must be >= 0 (0 = untiered)", file=sys.stderr)
        return 2
    if args.mesh is not None and args.hot_rows:
        # Each shard tiering its table slice independently is the
        # ROADMAP item 2 composition; refuse rather than silently
        # running untiered on the mesh.
        print(
            "error: --hot-rows is not supported with --mesh yet; "
            "drop --mesh or --hot-rows", file=sys.stderr,
        )
        return 2
    if not _require_one_source(args):
        return 2
    if args.db_write and not args.db:
        print("error: --db-write requires --db", file=sys.stderr)
        return 2
    if args.db_write and args.stop_after_steps is not None:
        # A bounded run never reaches the write-back; silently skipping
        # it would let a user believe partial ratings were persisted.
        print(
            "error: --db-write requires a finished run "
            "(drop --stop-after-steps, or resume to completion and "
            "write then)", file=sys.stderr,
        )
        return 2
    timer = PhaseTimer()
    if args.mesh is not None:
        return _rate_mesh(args, cfg, timer)
    stream, n_players, db_state, db_store, player_ids = _load_inputs(
        args, cfg, timer
    )
    cursor, start_step = 0, 0
    ck = None
    if args.resume:
        with timer.phase("restore"):
            ck = load_checkpoint(args.checkpoint)
        state, cursor, start_step = ck.state, ck.cursor, ck.step_cursor
        print(
            f"resumed at match {cursor}/{stream.n_matches}"
            + (f", superstep {start_step}" if start_step else ""),
            file=sys.stderr,
        )
    elif db_state is not None:
        state = db_state  # DB rating priors, seeds baked by load_stream
    else:
        state = PlayerState.create(n_players, cfg=cfg)
    if not args.checkpoint and args.stop_after_steps is None:
        # No snapshots to coordinate: take the fully-streamed path —
        # schedule assignment runs on a worker thread and overlaps the
        # device scan (sched.rate_stream).
        return _rate_streamed(
            args, cfg, timer, state, stream, cursor, n_players,
            finalize=lambda st: _maybe_db_write(
                args, timer, db_store, st, player_ids
            ),
        )
    with timer.phase("pack"):
        # Windowed: the big gather tensors materialize inside the runner's
        # prefetch loop, overlapped with the device scan.
        sched = pack_schedule(
            stream.slice(cursor, stream.n_matches),
            pad_row=state.pad_row,
            windowed=True,
        )
    if start_step:
        # A mid-schedule cursor is only meaningful against the identical
        # schedule: packing is deterministic, so a fingerprint mismatch
        # means the stream file or packing policy changed — resuming would
        # double-apply updates. Fail loudly (io/checkpoint.py).
        if sched.fingerprint != ck.schedule_fingerprint:
            print(
                "error: checkpoint was taken mid-schedule but the packed "
                "schedule no longer matches (stream file or packing policy "
                "changed); re-rate from scratch or from a full-run checkpoint",
                file=sys.stderr,
            )
            return 2
    finished = args.stop_after_steps is None or args.stop_after_steps >= sched.n_steps
    on_chunk, ck_close = _checkpoint_hook(args, sched, cursor, start_step, finished)
    try:
        with timer.phase("rate"), trace(args.trace):
            state, _ = rate_history(
                state, sched, cfg,
                start_step=start_step,
                stop_after=args.stop_after_steps,
                steps_per_chunk=(
                    min(8192, args.checkpoint_every) if args.checkpoint_every else None
                ),
                on_chunk=on_chunk,
                prefetch_depth=args.prefetch_depth,
                kernel=args.kernel,
                fuse_window=args.fuse_window,
                hot_rows=args.hot_rows,
            )
            np.asarray(state.table[:1])  # force completion for honest timing
    finally:
        ck_close()  # drain async snapshot writes (raises on write error)
    if args.checkpoint and finished:
        with timer.phase("checkpoint"):
            save_checkpoint(args.checkpoint, state, cursor=stream.n_matches)
    extra = (
        _maybe_db_write(args, timer, db_store, state, player_ids)
        if finished else {}
    )
    print(_rate_stats(stream, cursor, n_players, state, sched, timer, **extra))
    return 0


def _rate_mesh(args, cfg, timer) -> int:
    """The ``--mesh`` re-rate: data-parallel over an ICI/DCN device mesh.

    Single host: ``--mesh N`` shards over the first N local devices.
    Multi-host: set the ``jax.distributed`` env (COORDINATOR_ADDRESS,
    NUM_PROCESSES, PROCESS_ID), run the same command on every host with
    ``--mesh 0`` (= all global devices); each process feeds only its
    addressable shards of the identical deterministic schedule, the psum
    rides ICI within a slice and DCN across (parallel/mesh.py), and
    process 0 writes the checkpoint and stats."""
    import math

    from analyzer_tpu.core.state import PlayerState
    from analyzer_tpu.io.checkpoint import load_checkpoint, save_checkpoint
    from analyzer_tpu.parallel import (
        assert_processes_agree,
        initialize_distributed,
        make_mesh,
        rate_history_sharded,
    )
    from analyzer_tpu.sched import choose_batch_size, pack_schedule
    from analyzer_tpu.utils import trace

    import jax

    distributed = initialize_distributed()
    lead = not distributed or jax.process_index() == 0
    stream, n_players, db_state, db_store, player_ids = _load_inputs(
        args, cfg, timer
    )
    cursor, start_step = 0, 0
    ck = None
    if args.resume:
        with timer.phase("restore"):
            ck = load_checkpoint(args.checkpoint)
        state, cursor, start_step = ck.state, ck.cursor, ck.step_cursor
    elif db_state is not None:
        state = db_state
    else:
        state = PlayerState.create(n_players, cfg=cfg)
    # Every process must hold identical inputs before any is fed into the
    # sharded table — a stale checkpoint copy or divergent stream file on
    # one host would be silently wrong, not crash.
    assert_processes_agree(
        "rate --mesh inputs", state.table, stream.player_idx,
        stream.winner, stream.mode_id, stream.afk, np.int64(cursor),
        np.int64(start_step),
    )
    mesh = make_mesh(args.mesh or None)  # 0 = all (global) devices
    n_dev = int(mesh.devices.size)
    if (
        not args.checkpoint
        and args.stop_after_steps is None
        and not distributed
    ):
        # No snapshots to coordinate: the fully-streamed sharded path —
        # worker-thread assignment + per-window routing feeding the mesh
        # (sched.rate_stream(mesh=...)). Multi-host keeps the windowed
        # schedule below: emission timing differs per process and the
        # deterministic schedule is what keeps hosts in lockstep there.
        return _rate_streamed(
            args, cfg, timer, state, stream, cursor, n_players,
            mesh=mesh, mesh_devices=n_dev, processes=1,
            finalize=lambda st: _maybe_db_write(
                args, timer, db_store, st, player_ids
            ),
        )
    with timer.phase("pack"):
        work = stream.slice(cursor, stream.n_matches)
        # The cost model may pick a width below the mesh size on deep
        # chain-bound ladders; the sharded batch axis needs B % D == 0
        # and lane alignment wants B % 8 == 0 — round up to the lcm.
        m = math.lcm(8, n_dev)
        b = choose_batch_size(work, batch_multiple=m)
        b = -(-b // m) * m
        # Windowed: gather tensors AND scatter routing materialize per
        # chunk inside the sharded feed loop (O(window) host memory).
        sched = pack_schedule(
            work, pad_row=state.pad_row, batch_size=b, windowed=True
        )
    if start_step and sched.fingerprint != ck.schedule_fingerprint:
        # Same rule as the single-device path — a mid-schedule cursor is
        # only valid against the identical schedule. Note the two paths
        # pack with different batch widths, so their mid-schedule
        # checkpoints are deliberately not interchangeable.
        print(
            "error: checkpoint was taken mid-schedule but the packed "
            "schedule no longer matches (stream file, packing policy, or "
            "mesh size changed); re-rate from scratch or from a "
            "finished-run checkpoint",
            file=sys.stderr,
        )
        return 2
    finished = args.stop_after_steps is None or args.stop_after_steps >= sched.n_steps
    on_chunk, ck_close = _checkpoint_hook(
        args, sched, cursor, start_step, finished, lead
    )
    try:
        with timer.phase("rate"), trace(args.trace):
            state = rate_history_sharded(
                state, sched, cfg, mesh=mesh,
                start_step=start_step, stop_after=args.stop_after_steps,
                on_chunk=on_chunk,
                steps_per_chunk=(
                    min(1024, args.checkpoint_every) if args.checkpoint_every else 1024
                ),
                prefetch_depth=args.prefetch_depth,
            )
            np.asarray(state.table[:1])
    finally:
        ck_close()  # drain async snapshot writes (raises on write error)
    if args.checkpoint and lead and finished:
        with timer.phase("checkpoint"):
            save_checkpoint(args.checkpoint, state, cursor=stream.n_matches)
    extra = (
        _maybe_db_write(args, timer, db_store, state, player_ids)
        if finished and lead else {}
    )
    if lead:
        print(
            _rate_stats(
                stream, cursor, n_players, state, sched, timer,
                mesh_devices=n_dev, processes=jax.process_count(),
                **extra,
            )
        )
    return 0


def cmd_elo(args) -> int:
    from analyzer_tpu.config import RatingConfig
    from analyzer_tpu.models import elo_history
    from analyzer_tpu.sched import pack_schedule
    from analyzer_tpu.utils import PhaseTimer

    if not _require_one_source(args):
        return 2
    timer = PhaseTimer()
    stream, n_players, _, _, _ = _load_inputs(
        args, RatingConfig.from_env(), timer
    )
    # Windowed: elo_history consumes device_arrays/match_idx only, so the
    # gather tensors materialize lazily here too.
    with timer.phase("pack"):
        sched = pack_schedule(stream, pad_row=n_players, windowed=True)
    with timer.phase("rate"):
        ratings, expected = elo_history(sched, n_players)
    ratable = stream.ratable
    if ratable.any():
        acc = _half_credit_accuracy(
            expected[ratable], (stream.winner[ratable] == 0).astype(np.float32)
        )
    else:
        acc = None
    if args.out:
        np.savez(args.out, ratings=ratings, expected=expected)
    print(
        json.dumps(
            {
                "matches": stream.n_matches,
                "players": n_players,
                "mean_rating": round(float(ratings.mean()), 2),
                "prediction_accuracy": round(acc, 4) if acc is not None else None,
                "phases": {
                    k: round(v, 3) for k, v in timer.report().items()
                },
            }
        )
    )
    return 0


def cmd_train(args) -> int:
    """BASELINE configs 3-4: win-probability heads over rating features.

    Features are leak-free (each match's row is computed from the
    PRE-match rating state during one scan — models/features.py), and the
    evaluation split is CHRONOLOGICAL: train on the first (1 - eval_frac)
    of ratable matches, evaluate on the tail, matching how a deployed
    predictor sees time. Exact-tie predictions score half credit, like
    cmd_elo."""
    from analyzer_tpu.config import RatingConfig
    from analyzer_tpu.core.state import PlayerState
    from analyzer_tpu.models import history_features, train_logistic, train_mlp
    from analyzer_tpu.sched import pack_schedule
    from analyzer_tpu.utils import PhaseTimer

    if not (0.0 <= args.eval_frac < 1.0):
        print("error: --eval-frac must be in [0, 1)", file=sys.stderr)
        return 2
    if not _require_one_source(args):
        return 2
    if args.telemetry and args.db:
        print(
            "error: --telemetry needs an .npz stream (databases carry no "
            "telemetry block); use --csv", file=sys.stderr,
        )
        return 2
    cfg = RatingConfig.from_env()
    timer = PhaseTimer()
    stream, n_players, _, _, _ = _load_inputs(args, cfg, timer)
    # ALWAYS cold-start, even on the DB lane: a production database's
    # stored ratings are usually the END state of rating this very
    # history (e.g. after `rate --db --db-write`), so seeding features
    # from them would leak every match's own outcome into its
    # "pre-match" features and inflate the chronological holdout. The
    # one scan below re-derives honest pre-match state either way.
    state = PlayerState.create(n_players, cfg=cfg)
    with timer.phase("features"):
        sched = pack_schedule(stream, pad_row=state.pad_row, windowed=True)
        feats, ratable, _ = history_features(state, sched, cfg)
        composition = False
        if args.csv:
            # PRE-MATCH composition features (teammate archetype-pair
            # count differences) when the stream carries the archetype
            # block — the channel through which a learned head can beat
            # the rating-only baseline (synth --synergy; with synergy 0
            # these columns are outcome-independent and the heads tie
            # the baseline, the correct control).
            from analyzer_tpu.io.csv_codec import load_archetypes
            from analyzer_tpu.models.features import composition_features

            arch = load_archetypes(args.csv)
            if arch is not None:
                feats = np.concatenate(
                    [feats, composition_features(arch, stream.player_idx)],
                    axis=1,
                )
                composition = True
        if args.telemetry:
            # Config 4's full-telemetry head: POST-GAME stats, so this
            # trains an analysis model (outcome from game stats), not a
            # forecast — models/features.py documents the distinction.
            from analyzer_tpu.io.csv_codec import load_telemetry
            from analyzer_tpu.models.features import telemetry_features

            tel = load_telemetry(args.csv)
            if tel is None:
                print(
                    "error: --telemetry needs an .npz stream with a "
                    "telemetry block (synth --telemetry)", file=sys.stderr,
                )
                return 2
            try:
                tfeat = telemetry_features(tel, stream.player_idx)
            except ValueError as err:  # e.g. an older-schema npz
                print(f"error: {err}", file=sys.stderr)
                return 2
            feats = np.concatenate([feats, tfeat], axis=1)
    y = (stream.winner == 0).astype(np.float32)
    rows = np.flatnonzero(ratable)  # stream order
    if rows.size < 10:
        print("error: too few ratable matches to train on", file=sys.stderr)
        return 2
    mesh = None
    if args.mesh is not None:
        from analyzer_tpu.parallel import make_mesh

        mesh = make_mesh(args.mesh or None)
    cut = max(1, int(rows.size * (1.0 - args.eval_frac)))
    tr, ev = rows[:cut], rows[cut:]
    # Reserve the chronological tail of the train split for temperature
    # calibration — rows the model never fits, or the overfit case would
    # hide exactly the miscalibration being corrected. Too-small splits
    # fall back to fitting on (and calibrating from) everything.
    cal_cut = int(tr.size * 0.8)
    if tr.size - cal_cut >= 50:
        fit, cal = tr[:cal_cut], tr[cal_cut:]
    else:
        fit, cal = tr, tr
    with timer.phase("train"):
        if args.model == "logistic":
            model, nll = train_logistic(
                feats[fit], y[fit], epochs=args.epochs, seed=args.seed,
                mesh=mesh,
            )
        else:
            model, nll = train_mlp(
                feats[fit], y[fit], hidden=args.hidden,
                epochs=args.epochs, seed=args.seed, mesh=mesh,
            )
    # Temperature-scale on the calibration slice (held out from the fit
    # above): fixes the head's raw over/under-confidence (log-loss, ECE)
    # without touching its ranking (accuracy/AUC are invariant under a
    # positive temperature).
    from analyzer_tpu.models.calibration import apply_temperature, fit_temperature

    temperature = fit_temperature(np.asarray(model.logits(feats[cal])), y[cal])
    def _metrics(p, yy):
        eps = 1e-7
        auc = _auc(p, yy)  # None on a single-class eval slice
        return {
            "accuracy": round(_half_credit_accuracy(p, yy), 4),
            "logloss": round(float(-np.mean(
                yy * np.log(p + eps) + (1 - yy) * np.log(1 - p + eps)
            )), 4),
            "auc": round(auc, 4) if auc is not None else None,
            "ece": round(_ece(p, yy), 4),
        }

    if ev.size:
        p = apply_temperature(np.asarray(model.logits(feats[ev])), temperature)
        m = _metrics(p, y[ev])
        acc, logloss = m["accuracy"], m["logloss"]
        auc, ece = m["auc"], m["ece"]
        # The trivial rating-only baseline every head must beat to earn
        # its keep: the closed-form TrueSkill win probability computed
        # from the same pre-match state (feature column 2,
        # models/features.py) with NO learned parameters. Reported on
        # the same eval split so BASELINE.md rows carry the comparison.
        baseline = _metrics(feats[ev, 2].astype(np.float64), y[ev])
    else:
        acc = logloss = auc = ece = baseline = None
    if args.out:
        # temperature rides along so artifact consumers reproduce the
        # reported (calibrated) probabilities, not the raw head.
        np.savez(
            args.out,
            model=args.model,
            temperature=temperature,
            **{k: np.asarray(v) for k, v in vars(model).items()},
        )
    print(
        json.dumps(
            {
                "model": args.model,
                "matches": stream.n_matches,
                "composition_features": composition,
                "trained_on": int(fit.size),
                "calibrated_on": int(cal.size) if cal is not fit else 0,
                "eval_on": int(ev.size),
                "train_nll": round(float(nll), 4),
                "eval_accuracy": acc,
                "eval_logloss": logloss,
                "eval_auc": auc,
                "eval_ece": ece,
                "baseline_rating_only": baseline,
                "temperature": round(temperature, 3),
                "phases": {k: round(v, 3) for k, v in timer.report().items()},
            }
        )
    )
    return 0


def cmd_bench(args) -> int:
    # bench.py lives at the repo root (the driver's benchmark contract),
    # not inside the package — load it by path so the subcommand works
    # from any working directory.
    import importlib.util
    import os

    import analyzer_tpu

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(analyzer_tpu.__file__))),
        "bench.py",
    )
    if not os.path.exists(path):
        print(f"error: bench.py not found at {path}", file=sys.stderr)
        return 2
    spec = importlib.util.spec_from_file_location("bench", path)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    # The kernel knobs ride the env (bench.py's config surface) so
    # `cli bench --kernel ...` and a bare BENCH_KERNEL=... bench.py run
    # stay one code path.
    if getattr(args, "kernel", None):
        os.environ["BENCH_KERNEL"] = args.kernel
    if getattr(args, "fuse_window", None):
        os.environ["BENCH_FUSE_WINDOW"] = str(args.fuse_window)
    if getattr(args, "hot_rows", None):
        os.environ["BENCH_HOT_ROWS"] = str(args.hot_rows)
    if getattr(args, "ingest", False):
        os.environ["BENCH_INGEST"] = "1"
    if getattr(args, "migrate", False):
        os.environ["BENCH_MIGRATE"] = "1"
    if getattr(args, "profile", False):
        os.environ["BENCH_PROFILE"] = "1"
    if getattr(args, "profile_dir", None):
        os.environ["BENCH_PROFILE_DIR"] = args.profile_dir
    bench.main(
        metrics_out=getattr(args, "metrics_out", None),
        obs_port=getattr(args, "obs_port", None),
    )
    return 0


def cmd_benchdiff(args) -> int:
    """Bench trajectory gate: per-config deltas between two BENCH_*.json
    artifacts; non-zero exit past ``--regress-pct`` (obs/benchdiff.py)."""
    from analyzer_tpu.obs.benchdiff import (
        bench_configs,
        diff_configs,
        family_configs,
        find_bench_artifacts,
        latest_artifact,
        load_bench,
        render_diff,
    )

    paths = args.artifacts
    if args.against_latest:
        if len(paths) > 1:
            print(
                "error: --against-latest takes at most one artifact (the "
                "candidate)", file=sys.stderr,
            )
            return 2
        if paths:
            b_path = paths[0]
            a_path = latest_artifact(
                args.dir, exclude=b_path, family=args.family
            )
        else:
            arts = find_bench_artifacts(args.dir, family=args.family)
            a_path, b_path = (arts[-2], arts[-1]) if len(arts) >= 2 else (None, None)
        if a_path is None or b_path is None:
            from analyzer_tpu.obs.benchdiff import FAMILIES

            print(
                f"error: not enough {FAMILIES[args.family]}_*.json "
                f"artifacts under {args.dir}",
                file=sys.stderr,
            )
            return 2
    elif len(paths) == 2:
        a_path, b_path = paths
    else:
        print(
            "error: benchdiff needs two artifacts (baseline candidate) or "
            "--against-latest", file=sys.stderr,
        )
        return 2
    try:
        a_raw = load_bench(a_path)
        b_raw = load_bench(b_path)
        a = family_configs(bench_configs(a_raw), args.family)
        b = family_configs(bench_configs(b_raw), args.family)
    except (OSError, ValueError) as err:
        print(f"error: {err}", file=sys.stderr)
        return 2
    rc = 0
    if args.family == "soak":
        # The soak family's ABSOLUTE half: SLOs re-derived from the
        # candidate's deterministic block (zero dead-letters, flat
        # steady-state retraces, bounded view staleness, drained
        # backlog), gated on the candidate alone — a regression-free
        # delta must not mask a violated SLO.
        from analyzer_tpu.obs.benchdiff import soak_slo_violations

        violations = soak_slo_violations(b_raw)
        for v in violations:
            print(f"SLO VIOLATION: {v}")
        if violations:
            print(
                f"error: {os.path.basename(b_path)} violates "
                f"{len(violations)} soak SLO(s)", file=sys.stderr,
            )
            rc = 1
        # The vanished-block contract for the rating-quality plane: a
        # baseline whose artifact carried a calibration `quality` block
        # and a candidate without one means the ledger silently
        # disengaged (quality=False leaked into CI, or the scoring
        # site was dropped) — a delta gate would just diff fewer
        # configs and report "no regressions".
        a_quality = isinstance(a_raw.get("quality"), dict)
        b_quality = isinstance(b_raw.get("quality"), dict)
        if a_quality and not b_quality:
            print(
                f"error: {os.path.basename(b_path)} has no rating-quality "
                f"block but {os.path.basename(a_path)} does (calibration "
                "ledger silently disengaged?)",
                file=sys.stderr,
            )
            return 1
    if args.family == "fabric":
        # The fabric family's ABSOLUTE half, gated on the candidate
        # alone: lost work, dead letters, view staleness past the
        # configured tick bound, per-host steady-state retraces,
        # burning fleet objectives.
        from analyzer_tpu.obs.benchdiff import fabric_slo_violations

        violations = fabric_slo_violations(b_raw)
        for v in violations:
            print(f"SLO VIOLATION: {v}")
        if violations:
            print(
                f"error: {os.path.basename(b_path)} violates "
                f"{len(violations)} fabric SLO(s)", file=sys.stderr,
            )
            rc = 1
        # The vanished-block contract for the fabric: a baseline
        # captured over a real multi-host topology and a candidate
        # whose fleet block reports a single process means the soak
        # silently fell back to one host — the exact regression this
        # family exists to catch (a single-process capture flatters
        # every remote-path number), and one a delta gate would merely
        # call "faster".
        a_hosts = int((a_raw.get("fleet") or {}).get("n_hosts") or 1)
        b_hosts = int((b_raw.get("fleet") or {}).get("n_hosts") or 1)
        if a_hosts > 1 and b_hosts <= 1:
            print(
                f"error: {os.path.basename(b_path)} captured a "
                f"single-process topology but {os.path.basename(a_path)} "
                f"ran {a_hosts} hosts (silent fall-back to "
                "single-process?)", file=sys.stderr,
            )
            return 1
    if args.family == "tiered" and a and not b:
        # The baseline captured a tiered block but the candidate has
        # none: the run silently fell back to untiered — exactly the
        # regression this family exists to catch.
        print(
            f"error: {os.path.basename(b_path)} has no tiered capture "
            f"but {os.path.basename(a_path)} does (silent fall-back to "
            "untiered?)", file=sys.stderr,
        )
        return 1
    if args.family == "ingest":
        # The vanished-block contract for the ingest plane: a baseline
        # captured with the native columnar decoder and a candidate
        # without it means the decode silently fell back to the python
        # codec — the exact regression this family exists to catch, and
        # one a delta gate would merely report as "slower".
        a_native = bool((a_raw.get("ingest") or {}).get("native"))
        b_native = bool((b_raw.get("ingest") or {}).get("native"))
        if a_native and not b_native:
            print(
                f"error: {os.path.basename(b_path)} has no native "
                f"columnar-decode capture but {os.path.basename(a_path)} "
                "does (silent fallback to the python codec?)",
                file=sys.stderr,
            )
            return 1
    if args.family == "migrate":
        # The vanished-block contract for the migration engine: a
        # baseline captured with the STREAMED backfill (decode->assign->
        # scan overlapped) and a candidate whose capture fell back to
        # the offline re-rate shape means the streaming front half
        # silently disengaged — the exact regression this family exists
        # to catch, and one a delta gate would merely call "slower".
        a_streamed = bool((a_raw.get("migrate") or {}).get("streamed"))
        b_streamed = bool((b_raw.get("migrate") or {}).get("streamed"))
        if a_streamed and not b_streamed:
            print(
                f"error: {os.path.basename(b_path)} has no streamed "
                f"backfill capture but {os.path.basename(a_path)} does "
                "(silent fall-back to the offline re-rate?)",
                file=sys.stderr,
            )
            return 1
        # And the assign-native contract (same pattern as the ingest
        # family's python-codec gate): a baseline whose front half ran
        # the GIL-released native windowed first-fit and a candidate
        # whose assign block reports native: false means the assigner
        # silently fell back to the python recurrence — a ~two-orders
        # front-half slowdown a delta gate would merely call "slower".
        a_native = bool((a_raw.get("assign") or {}).get("native"))
        b_native = bool((b_raw.get("assign") or {}).get("native"))
        if a_native and not b_native:
            print(
                f"error: {os.path.basename(b_path)} has no native "
                f"windowed-assigner capture but {os.path.basename(a_path)} "
                "does (silent fall-back to the python first-fit "
                "recurrence?)",
                file=sys.stderr,
            )
            return 1
    if args.family == "serve":
        # Same vanished-block contract for the shard plane: a baseline
        # with sharded.* configs and a candidate without them means the
        # bench silently fell back to the single-device engine.
        a_sharded = any(c.name.startswith("sharded.") for c in a)
        b_sharded = any(c.name.startswith("sharded.") for c in b)
        if a_sharded and not b_sharded:
            print(
                f"error: {os.path.basename(b_path)} has no sharded "
                f"capture but {os.path.basename(a_path)} does (silent "
                "fall-back to the single-device serve plane?)",
                file=sys.stderr,
            )
            return 1
        # And the front door's native-codec contract (same pattern as
        # the migrate family's assign-native gate): a baseline whose
        # socket plane rendered every response through the zero-copy
        # native codec and a candidate reporting native: false means the
        # codec silently fell back to python json.dumps — a route flip a
        # delta gate would merely call "slower".
        a_native = bool((a_raw.get("frontdoor") or {}).get("native"))
        b_native = bool((b_raw.get("frontdoor") or {}).get("native"))
        if a_native and not b_native:
            print(
                f"error: {os.path.basename(b_path)} has no native-codec "
                f"front door capture but {os.path.basename(a_path)} does "
                "(silent fall-back to the python json encoder?)",
                file=sys.stderr,
            )
            return 1
    # The vanished-block contract for profile intelligence (any family —
    # bench --profile stamps the block wherever a capture was armed): a
    # baseline whose device profile parsed and a candidate whose profile
    # block is missing or reports parsed:false means the candidate
    # silently stopped attributing its captures — its roofline rides
    # wall time again, and a delta gate would never notice.
    a_parsed = bool((a_raw.get("profile") or {}).get("parsed"))
    b_parsed = bool((b_raw.get("profile") or {}).get("parsed"))
    if a_parsed and not b_parsed:
        print(
            f"error: {os.path.basename(b_path)} has no parsed device "
            f"profile but {os.path.basename(a_path)} does (capture "
            "attribution silently broke?)", file=sys.stderr,
        )
        return 1
    if args.family in ("bench", "tiered"):
        # Absolute tracing-tax gate on the candidate alone: the bench's
        # trace_overhead block (tracing-on vs tracing-off on the same
        # config) must stay <= TRACE_OVERHEAD_MAX_PCT — causal tracing
        # that stops being ~free would silently tax every traced run.
        from analyzer_tpu.obs.benchdiff import trace_overhead_violations

        overhead = trace_overhead_violations(b_raw)
        for v in overhead:
            print(f"TRACE OVERHEAD VIOLATION: {v}")
        if overhead:
            print(
                f"error: {os.path.basename(b_path)} fails the tracing "
                "overhead gate", file=sys.stderr,
            )
            rc = 1
        # Same absolute contract for the live SLO plane: the bench's
        # watchdog_overhead block (sampler+watchdog+audit on vs off on
        # the same e2e line) must stay <= WATCHDOG_OVERHEAD_MAX_PCT.
        from analyzer_tpu.obs.benchdiff import watchdog_overhead_violations

        wd_overhead = watchdog_overhead_violations(b_raw)
        for v in wd_overhead:
            print(f"WATCHDOG OVERHEAD VIOLATION: {v}")
        if wd_overhead:
            print(
                f"error: {os.path.basename(b_path)} fails the SLO-plane "
                "overhead gate", file=sys.stderr,
            )
            rc = 1
        # And for the fleet plane: the bench's federate_overhead block
        # (a Collector scraping obsd under load vs unscraped on the
        # same e2e line) must stay <= FEDERATE_OVERHEAD_MAX_PCT.
        from analyzer_tpu.obs.benchdiff import federate_overhead_violations

        fed_overhead = federate_overhead_violations(b_raw)
        for v in fed_overhead:
            print(f"FEDERATE OVERHEAD VIOLATION: {v}")
        if fed_overhead:
            print(
                f"error: {os.path.basename(b_path)} fails the "
                "federation overhead gate", file=sys.stderr,
            )
            rc = 1
    rows = diff_configs(a, b, args.regress_pct)
    sys.stdout.write(render_diff(a_path, b_path, rows))
    if any(r.regressed and r.gated for r in rows):
        print(
            f"error: throughput regressed more than {args.regress_pct:g}%",
            file=sys.stderr,
        )
        return 1
    return rc


def cmd_metrics(args) -> int:
    """Renders a telemetry snapshot: a saved ``--metrics-out`` artifact
    when a path is given, else the live registry of THIS process (mostly
    the declared schema — useful to list the metric catalog)."""
    from analyzer_tpu.obs import prometheus_text, render_summary, snapshot

    if args.snapshot:
        try:
            with open(args.snapshot, encoding="utf-8") as f:
                snap = json.load(f)
        except (OSError, ValueError) as err:
            print(f"error: cannot read snapshot: {err}", file=sys.stderr)
            return 2
    else:
        snap = snapshot()
    if args.format == "prom":
        sys.stdout.write(prometheus_text(snap))
    elif args.format == "summary":
        sys.stdout.write(render_summary(snap))
    else:
        json.dump(snap, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    return 0


def cmd_history(args) -> int:
    """Telemetry history rings (obs/history.py): trend-render or dump
    the tiered time series — from a live worker's ``/historyz``
    (``--url``), from a saved ``history.json`` / flight-dump directory,
    or from this process's own sampler (mostly empty outside a run —
    useful to see the series list)."""
    import os

    payload = None
    if args.url:
        import urllib.request

        url = args.url.rstrip("/") + "/historyz"
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                payload = json.load(resp)
        except OSError as err:
            print(f"error: cannot fetch {url}: {err}", file=sys.stderr)
            return 2
    elif args.artifact:
        path = args.artifact
        if os.path.isdir(path):
            path = os.path.join(path, "history.json")
        try:
            with open(path, encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError) as err:
            print(f"error: cannot read history: {err}", file=sys.stderr)
            return 2
    else:
        from analyzer_tpu.obs.history import get_history

        payload = get_history().to_json()
    series = payload.get("series", {})
    if args.series:
        series = {
            name: s for name, s in series.items()
            if any(name.startswith(p) for p in args.series)
        }
        payload = dict(payload, series=series)
    if args.json:
        json.dump(payload, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
        return 0
    from analyzer_tpu.obs.history import render_history

    last_t = payload.get("last_sample_t")
    print(
        f"history: {len(series)} series, {payload.get('samples', 0)} "
        f"samples, last_t={last_t}"
    )
    sys.stdout.write(render_history(payload, tier=args.tier))
    return 0


def cmd_quality(args) -> int:
    """Rating-quality report (docs/observability.md "Rating quality"):
    the calibration ledger's reliability table, streaming Brier /
    log-loss / ECE, and the population-drift verdict — from a live
    worker's ``/qualityz`` (``--url``), from a saved soak artifact's
    ``quality`` block (``--artifact``), or from this process's own
    ledger (mostly empty outside a run). ``--fit-temperature`` fits a
    post-hoc temperature over the live ledger's retained (logit,
    outcome) prefix (models/calibration.py) — a fitted T far from 1.0
    quantifies HOW miscalibrated the predictor is, not merely that the
    ECE floor tripped."""
    summary = None
    if args.url:
        import urllib.request

        url = args.url.rstrip("/") + "/qualityz"
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                summary = json.load(resp)
        except OSError as err:
            print(f"error: cannot fetch {url}: {err}", file=sys.stderr)
            return 2
        if not summary.get("enabled", True):
            print(
                "error: worker runs with the quality ledger disabled",
                file=sys.stderr,
            )
            return 2
    elif args.artifact:
        try:
            with open(args.artifact, encoding="utf-8") as f:
                artifact = json.load(f)
        except (OSError, ValueError) as err:
            print(f"error: cannot read artifact: {err}", file=sys.stderr)
            return 2
        summary = artifact.get("quality")
        if not isinstance(summary, dict):
            print(
                "error: artifact has no quality block (soak ran with "
                "--no-quality?)", file=sys.stderr,
            )
            return 2
    ledger = None
    if summary is None:
        from analyzer_tpu.obs.quality import get_quality_ledger

        ledger = get_quality_ledger()
        if ledger is None:
            from analyzer_tpu.config import RatingConfig
            from analyzer_tpu.obs.quality import CalibrationLedger

            ledger = CalibrationLedger(RatingConfig(), mirror=False)
        summary = ledger.summary()
    if args.fit_temperature:
        if ledger is None:
            print(
                "error: --fit-temperature needs the live ledger's "
                "retained (logit, outcome) pairs — /qualityz and the "
                "artifact carry only their count", file=sys.stderr,
            )
            return 2
        import numpy as np

        from analyzer_tpu.models.calibration import fit_temperature

        z, y = ledger.retained()
        if not z.size:
            print(
                "error: no retained (logit, outcome) pairs to fit",
                file=sys.stderr,
            )
            return 2

        def _nll(t: float) -> float:
            zz = np.clip(z / t, -30.0, 30.0)
            p = 1.0 / (1.0 + np.exp(-zz))
            eps = 1e-12
            return float(-np.mean(
                y * np.log(p + eps) + (1.0 - y) * np.log(1.0 - p + eps)
            ))

        t = fit_temperature(z, y)
        summary["temperature"] = {
            "t": round(float(t), 4),
            "nll_before": round(_nll(1.0), 6),
            "nll_after": round(_nll(float(t)), 6),
            "n": int(z.size),
        }
    if args.json:
        json.dump(summary, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
        return 0
    from analyzer_tpu.obs.quality import render_quality

    sys.stdout.write(render_quality(summary))
    return 0


def cmd_trace(args) -> int:
    """Trace analyzer (obs/traceview.py): reconstruct per-match /
    per-batch timelines from a trace-events JSONL (``--trace-events``)
    or a flight-recorder dump directory, with the stage decomposition
    (queue wait -> encode -> pack -> feed staging -> H2D -> dispatch ->
    fetch -> commit -> publish lag) and a critical-path report naming
    the dominant stage. MULTIPLE artifacts stitch into one cross-process
    trace forest (clock-aligned via each export's trace_epoch metadata):
    a match enqueued in one process and rated in another reconstructs
    end to end, its handoff gap reported as the ``broker_transit`` stage
    and each stage attributed to its host. Needs traces captured with
    causal tracing ON (``cli soak --trace``, ``ANALYZER_TPU_TRACE=1``)."""
    from analyzer_tpu.obs.traceview import (
        batch_report,
        build_model,
        critical_path,
        load_events,
        load_forest,
        match_report,
        render_batch,
        render_critical_path,
        render_match,
        verify_chain,
    )

    try:
        if len(args.artifact) == 1:
            events = load_events(args.artifact[0])
        else:
            events = load_forest(args.artifact)
    except (OSError, ValueError) as err:
        print(f"error: {err}", file=sys.stderr)
        return 2
    model = build_model(events)
    if not model.batches and not model.enqueue_ts:
        print(
            "error: no causal-trace events in the artifact — was the "
            "capture taken with tracing enabled (cli soak --trace / "
            "ANALYZER_TPU_TRACE=1)?", file=sys.stderr,
        )
        return 2
    if args.match:
        report = match_report(model, args.match)
        if report is None:
            print(f"error: match {args.match!r} not in this trace",
                  file=sys.stderr)
            return 1
        problems = verify_chain(model, args.match)
        if args.json:
            report = dict(report, problems=problems)
            json.dump(report, sys.stdout, indent=1, sort_keys=True)
            sys.stdout.write("\n")
        else:
            sys.stdout.write(render_match(report))
            for p in problems:
                print(f"  incomplete: {p}")
        return 0
    if args.batch:
        bt = model.batches.get(args.batch)
        if bt is None:
            print(f"error: batch {args.batch!r} not in this trace",
                  file=sys.stderr)
            return 1
        report = batch_report(bt)
        if args.json:
            json.dump(report, sys.stdout, indent=1, sort_keys=True)
            sys.stdout.write("\n")
        else:
            sys.stdout.write(render_batch(report))
        return 0
    cp = critical_path(model, window=args.window or None)
    decomp = None
    if getattr(args, "profile", None):
        # Join a capture dir's device trace against this host-side
        # forest: the critical path's `dispatch` stage decomposes into
        # device-execute / device-idle / host-overhead (obs/profview).
        from analyzer_tpu.obs.profview import (
            analyze_capture,
            decompose_dispatch,
            render_decomposition,
        )

        att = analyze_capture(args.profile, update_metrics=False)
        decomp = decompose_dispatch(model, att)
        if decomp is None:
            print(
                f"note: profile {args.profile} did not join this trace "
                f"(parsed={str(bool(att.get('parsed'))).lower()})",
                file=sys.stderr,
            )
    if args.json:
        if decomp is not None:
            cp = dict(cp, dispatch_decomposition=decomp)
        json.dump(cp, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_critical_path(cp))
        if decomp is not None:
            sys.stdout.write(render_decomposition(decomp))
    return 0


def cmd_profile(args) -> int:
    """Profile attribution (obs/profview.py): read a device-profiler
    capture dir (obs/prof.py's ``profile-<ts>-<reason>-<pid>/``), bin
    its Chrome-format device trace into a per-kernel device-time table,
    and report the busy/idle and compile/execute splits. A torn or
    missing trace reports ``parsed: false`` (exit 1) rather than
    crashing. With ``--trace-events``, additionally joins the capture
    against the host-side causal-trace forest and decomposes the
    ``dispatch`` stage into device-execute / device-idle /
    host-overhead."""
    from analyzer_tpu.obs.profview import (
        analyze_capture,
        decompose_dispatch,
        render_attribution,
        render_decomposition,
    )

    att = analyze_capture(args.capture_dir, update_metrics=False)
    decomp = None
    if args.trace_events:
        from analyzer_tpu.obs.traceview import build_model, load_forest

        try:
            model = build_model(load_forest(args.trace_events))
        except (OSError, ValueError) as err:
            print(f"error: {err}", file=sys.stderr)
            return 2
        decomp = decompose_dispatch(model, att)
    if args.json:
        out = dict(att)
        if decomp is not None:
            out["dispatch_decomposition"] = decomp
        json.dump(out, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_attribution(att))
        if decomp is not None:
            sys.stdout.write(render_decomposition(decomp))
    return 0 if att["parsed"] else 1


def cmd_tune(args) -> int:
    """Tuning advisor (obs/advisor.py): a deterministic rule table over
    the artifacts the repo already emits (BENCH/SOAK/INGEST/MIGRATE
    JSON, history rings, profile attribution) that names the bottleneck
    and recommends concrete knob changes, each citing its evidence.
    Same inputs produce a byte-identical report — pipe it into a file
    and diff across runs. Exit 0 with findings or without; exit 2 only
    when no artifact loads at all."""
    from analyzer_tpu.obs.advisor import advise, gather_inputs, render_report

    inputs = gather_inputs(
        paths=args.artifacts,
        scan_dir=args.dir if not args.artifacts else None,
        profile_dir=args.profile,
    )
    if not inputs["artifacts"] and not inputs["history"] \
            and inputs["profile"] is None:
        print(
            f"error: no artifacts loaded (looked at "
            f"{args.artifacts or args.dir})", file=sys.stderr,
        )
        return 2
    report = advise(inputs)
    if args.json:
        json.dump(report, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_report(report))
    return 0


def cmd_fleet(args) -> int:
    """Fleet observability plane (obs/federate.py, docs/observability.md
    "Fleet plane"): scrape N workers' obsd endpoints, merge their
    registries under the reserved ``host=`` label, evaluate the
    STANDARD objectives at fleet scope with per-host attribution, and
    serve /fleetz, aggregated /metrics, a fleet /sloz and the fleet
    history rings. ``--check`` is the CI one-shot: scrape once,
    evaluate, exit 1 on any burn — the multi-process topology's
    benchdiff."""
    import time

    from analyzer_tpu.obs.federate import Collector, FleetServer

    targets = list(args.targets_pos)
    if args.targets:
        targets.extend(
            t.strip() for t in args.targets.split(",") if t.strip()
        )
    if not targets:
        print(
            "error: no targets (positional host:port... or "
            "--targets host:port,...)", file=sys.stderr,
        )
        return 2
    collector = Collector(
        targets,
        flight_token=args.flight_token,
        request_flight_dumps=not args.no_flight_requests,
    )
    if args.check:
        burns = collector.check(time.monotonic())
        down = [
            t for t, row in collector.fleetz()["hosts"].items()
            if not row["up"]
        ]
        for target in down:
            print(f"DOWN: {target}")
        for burn, hosts in burns:
            where = ", ".join(hosts) if hosts else "fleet-wide"
            print(f"FLEET BURN: {burn.objective} [{where}] — {burn.detail}")
        if args.json:
            json.dump(
                collector.sloz(), sys.stdout, indent=1, sort_keys=True
            )
            sys.stdout.write("\n")
        if burns or (down and args.require_all_up):
            return 1
        up = collector.fleetz()["up"]
        print(f"fleet ok: {up}/{len(targets)} host(s) up, no burns")
        return 0
    server = FleetServer(collector, port=args.port)
    print(f"fleetd serving /fleetz /metrics /sloz /historyz at {server.url}")
    scrapes = 0
    try:
        while args.scrapes <= 0 or scrapes < args.scrapes:
            collector.scrape(time.monotonic())
            scrapes += 1
            burning = collector.burning
            if burning:
                attribution = collector.attribution()
                for name in burning:
                    hosts = attribution.get(name)
                    print(
                        f"FLEET BURNING: {name} "
                        f"[{', '.join(hosts) if hosts else 'fleet-wide'}]"
                    )
            if args.scrapes > 0 and scrapes >= args.scrapes:
                break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
    return 1 if collector.burning else 0


def cmd_lint(args) -> int:
    """graftlint: the JAX-hazard + native-ABI static analysis pass.

    Deliberately a thin delegate — the lint package is jax- and
    numpy-free so CI can gate on it in milliseconds; everything heavy in
    this module stays behind the other subcommands' lazy imports."""
    from analyzer_tpu.lint.__main__ import main as lint_main

    argv = list(args.paths)
    if args.json:
        argv.append("--json")
    if args.rules:
        argv.append("--rules")
    if not args.project:
        argv.append("--no-project")
    if args.baseline:
        argv.extend(["--baseline", args.baseline])
    if args.write_baseline:
        argv.extend(["--write-baseline", args.write_baseline])
    return lint_main(argv)


def cmd_serve(args) -> int:
    """ratesrv standalone: publish a rating table (checkpoint or DB) as
    version 1 and serve queries against it. The co-hosted flavor — the
    view tracking a live worker's commits — is ``cli worker
    --serve-port`` / ``Worker(serve_port=)``; this one is for serving a
    finished re-rate or a warm standby next to the write plane."""
    import time

    from analyzer_tpu.config import RatingConfig
    from analyzer_tpu.serve import (
        QueryEngine,
        ShardedQueryEngine,
        ShardedViewPublisher,
        ViewPublisher,
    )
    from analyzer_tpu.serve.server import ServeServer

    if not _require_one_source_serve(args):
        return 2
    if args.shards < 1:
        print("error: --shards must be >= 1", file=sys.stderr)
        return 2
    cfg = RatingConfig.from_env()
    _obs_begin(args)
    obs = _obs_serve(args)
    try:
        # Topology-blind bootstrap (ServePlane): publish_state splits the
        # table by interleaved row ownership when sharded; everything
        # below — warmup, /v1/* — is the same code either way.
        sharded = args.shards > 1
        publisher = (
            ShardedViewPublisher(args.shards) if sharded else ViewPublisher()
        )
        if args.checkpoint:
            from analyzer_tpu.io.checkpoint import load_checkpoint

            ck = load_checkpoint(args.checkpoint)
            # Checkpoints carry no id column: rows serve by index.
            view = publisher.publish_state(ck.state)
        else:
            from analyzer_tpu.service.sql_store import SqlStore

            store = SqlStore(args.db)
            hist = store.load_stream(cfg)
            view = publisher.publish_state(hist.state, ids=hist.player_ids)
        if sharded:
            engine = ShardedQueryEngine(
                publisher, cfg=cfg, max_batch=args.max_batch,
                all_gather_topk=args.all_gather_topk,
            )
        else:
            engine = QueryEngine(
                publisher, cfg=cfg, max_batch=args.max_batch
            )
        engine.warmup(view)  # no first-query XLA stall
        engine.start()
        server = ServeServer(engine, port=args.port)
        print(json.dumps({
            "serving": server.url,
            "players": view.n_players,
            "version": view.version,
            "shards": args.shards,
            "source": args.checkpoint or args.db,
        }))
        sys.stdout.flush()
        try:
            deadline = (
                None if args.max_seconds is None
                else time.monotonic() + args.max_seconds
            )
            while deadline is None or time.monotonic() < deadline:
                time.sleep(0.2)
        except KeyboardInterrupt:
            pass
        finally:
            server.close()
            engine.close()
    finally:
        if obs is not None:
            obs.close()
    return 0


def _require_one_source_serve(args) -> bool:
    """serve's source xor: exactly one of --checkpoint / --db."""
    args.checkpoint = getattr(args, "checkpoint", None) or None
    args.db = getattr(args, "db", None) or None
    if (args.checkpoint is None) == (args.db is None):
        print("error: exactly one of --checkpoint / --db is required",
              file=sys.stderr)
        return False
    return True


def cmd_query(args) -> int:
    """One query against a running serve endpoint — the operator's curl
    with the URL assembly done for them (an HTTP CLIENT: the listening
    sockets stay in obs/ + serve/, graftlint GL024)."""
    import urllib.error
    import urllib.parse
    import urllib.request

    params = {}
    if args.kind == "ratings":
        if not args.ids:
            print("error: ratings needs --ids a,b,c", file=sys.stderr)
            return 2
        params["ids"] = args.ids
    elif args.kind == "leaderboard":
        params["k"] = str(args.k)
    elif args.kind == "winprob":
        if not (args.a and args.b):
            print("error: winprob needs --a ids and --b ids", file=sys.stderr)
            return 2
        params["a"] = args.a
        params["b"] = args.b
    elif args.kind == "tiers" and args.score is not None:
        params["score"] = str(args.score)
    url = (
        args.url.rstrip("/") + "/v1/" + args.kind
        + ("?" + urllib.parse.urlencode(params) if params else "")
    )
    try:
        with urllib.request.urlopen(url, timeout=args.timeout) as resp:
            body = resp.read().decode("utf-8")
    except urllib.error.HTTPError as err:
        print(err.read().decode("utf-8"), end="")
        print(f"error: {url} -> HTTP {err.code}", file=sys.stderr)
        return 1
    except (urllib.error.URLError, ValueError) as err:
        # URLError: nothing listening; ValueError: a malformed --url
        reason = getattr(err, "reason", err)
        print(f"error: {url}: {reason}", file=sys.stderr)
        return 1
    print(body, end="")
    return 0


def cmd_soak(args) -> int:
    """The closed-loop matchmaking soak (analyzer_tpu/loadgen,
    ROADMAP item 3): matchmaker -> broker -> worker -> commit -> view
    publish, with concurrent /v1/* query traffic, SLO sampling per
    virtual tick, and a SOAK_*.json artifact for
    ``benchdiff --family soak``. Deterministic per (seed, config);
    exit 1 when any SLO is violated."""
    from analyzer_tpu.loadgen import SoakConfig, SoakDriver
    from analyzer_tpu.loadgen.driver import write_artifact

    if args.hosts is not None:
        return _cmd_soak_fabric(args)
    for flag in ("duration", "qps", "tick", "players", "batch_size",
                 "polls_per_tick", "serve_shards", "broker_partitions",
                 "audit_sample_denom", "migrate_matches"):
        if getattr(args, flag) <= 0:
            print(f"error: --{flag.replace('_', '-')} must be positive",
                  file=sys.stderr)
            return 2
    if args.query_qps < 0:
        print("error: --query-qps must be >= 0 (0 = no read traffic)",
              file=sys.stderr)
        return 2
    if args.backfill_qps < 0:
        print("error: --backfill-qps must be >= 0", file=sys.stderr)
        return 2
    if args.serve_http and args.in_process:
        print("error: --serve-http drives the HTTP socket path; it cannot "
              "combine with --in-process", file=sys.stderr)
        return 2
    if args.backfill_qps > 0 and not args.priority_lanes:
        print("error: --backfill-qps needs --priority-lanes (backfill "
              "traffic rides the backfill lane)", file=sys.stderr)
        return 2
    if args.forbid_dominant_stages and not (args.trace or args.trace_events):
        print("error: --forbid-dominant-stage needs --trace (the check "
              "reads the trace block's critical path)", file=sys.stderr)
        return 2
    _obs_begin(args)
    # The soak's obsd rides the WORKER (SoakConfig.obs_port), not the
    # generic CLI server: the endpoints then carry worker stats()/
    # readiness and the /debug/flight trigger, so a fleet Collector
    # (cli fleet) can scrape/judge the soak like any production worker.
    cfg = SoakConfig(
        seed=args.seed,
        obs_port=args.obs_port,
        trace=bool(args.trace or args.trace_events),
        duration_s=args.duration,
        tick_s=args.tick,
        qps=args.qps,
        query_qps=args.query_qps,
        n_players=args.players,
        batch_size=args.batch_size,
        polls_per_tick=args.polls_per_tick,
        team5_frac=args.team5_frac,
        afk_rate=args.afk_rate,
        warmup=not args.no_warmup,
        use_http=not args.in_process,
        serve_http=args.serve_http,
        serve_shards=args.serve_shards,
        broker_partitions=args.broker_partitions,
        priority_lanes=args.priority_lanes,
        backfill_qps=args.backfill_qps,
        realtime=args.realtime,
        max_view_lag_ticks=args.max_view_lag_ticks,
        min_matches_per_sec=args.min_matches_per_sec,
        max_p99_ms=args.max_p99_ms,
        forbid_dominant_stages=tuple(args.forbid_dominant_stages),
        slo_plane=not args.no_slo_plane,
        audit=args.audit,
        audit_sample_denom=args.audit_sample_denom,
        migrate=args.migrate,
        migrate_matches=args.migrate_matches,
        quality=not args.no_quality,
    )
    driver = SoakDriver(cfg)
    try:
        artifact = driver.run()
    finally:
        driver.close()
    # _obs_write exports --trace-events (the ring still carries the
    # causal ids after close — only the enable flag is restored); the
    # export is the `cli trace` input.
    _obs_write(args)
    # The headline line mirrors bench.py's contract (one JSON line on
    # stdout); the full artifact — the benchdiff input — goes to --out.
    line = {
        k: artifact[k]
        for k in ("metric", "value", "latency_ms", "measured", "slo")
    }
    line["deterministic"] = {
        k: v for k, v in artifact["deterministic"].items()
        if k != "trajectory"
    }
    print(json.dumps(line))
    if args.out:
        write_artifact(artifact, args.out)
        print(f"wrote soak artifact to {args.out}", file=sys.stderr)
    if not artifact["slo"]["pass"]:
        for v in artifact["slo"]["violations"]:
            print(f"SLO VIOLATION: {v}", file=sys.stderr)
        return 1
    return 0


def _cmd_soak_fabric(args) -> int:
    """``cli soak --hosts N``: the closed-loop soak over a REAL
    multi-process fabric (analyzer_tpu/fabric) — N shard-owning host
    subprocesses, broker-partitioned ingest, routed /v1/* queries, and
    a fleet Collector judging STANDARD_OBJECTIVES across the hosts'
    obsd planes. The artifact's deterministic block is bit-identical
    per (seed, config) at any --hosts count (FABRIC_BENCH_*.json, the
    ``benchdiff --family fabric`` input)."""
    from analyzer_tpu.fabric.driver import FabricSoakConfig, FabricSoakDriver
    from analyzer_tpu.loadgen.driver import write_artifact

    for flag in ("hosts", "duration", "qps", "tick", "players",
                 "batch_size", "fabric_shards"):
        if getattr(args, flag) <= 0:
            print(f"error: --{flag.replace('_', '-')} must be positive",
                  file=sys.stderr)
            return 2
    if args.query_qps < 0:
        print("error: --query-qps must be >= 0 (0 = no read traffic)",
              file=sys.stderr)
        return 2
    if args.fabric_shards < args.hosts:
        print(
            "error: --fabric-shards must be >= --hosts (every host "
            "must own at least one shard)", file=sys.stderr,
        )
        return 2
    cfg = FabricSoakConfig(
        seed=args.seed,
        duration_s=args.duration,
        tick_s=args.tick,
        qps=args.qps,
        query_qps=args.query_qps,
        n_players=args.players,
        batch_size=args.batch_size,
        n_shards=args.fabric_shards,
        n_hosts=args.hosts,
        team5_frac=args.team5_frac,
        afk_rate=args.afk_rate,
        warmup=not args.no_warmup,
        trace=bool(args.trace or args.trace_events),
        quality=not args.no_quality,
        slo_plane=not args.no_slo_plane,
        max_view_lag_ticks=args.max_view_lag_ticks,
    )
    driver = FabricSoakDriver(cfg)
    try:
        artifact = driver.run()
    finally:
        driver.close()
    line = {
        k: artifact[k]
        for k in ("metric", "value", "latency_ms", "measured", "slo")
    }
    line["deterministic"] = artifact["deterministic"]
    print(json.dumps(line))
    if args.out:
        write_artifact(artifact, args.out)
        print(f"wrote fabric artifact to {args.out}", file=sys.stderr)
    if not artifact["slo"]["pass"]:
        for v in artifact["slo"]["violations"]:
            print(f"SLO VIOLATION: {v}", file=sys.stderr)
        return 1
    return 0


def cmd_fabric(args) -> int:
    """Bring up a standing fabric: N shard-owning host processes
    (analyzer_tpu/fabric/process), each with its own partitioned
    ingest, serve plane, obsd, and /fabric/* control surface. Prints
    one JSON line per host once its listeners are bound (the
    serve_url/control_url/obs_port a router or fleet Collector needs),
    then runs until --duration wall seconds elapse or Ctrl-C, and
    signals every host down on the way out."""
    import tempfile
    import time as _time

    if args.hosts <= 0 or args.shards <= 0:
        print("error: --hosts and --shards must be positive",
              file=sys.stderr)
        return 2
    if args.shards < args.hosts:
        print(
            "error: --shards must be >= --hosts (every host must own "
            "at least one shard)", file=sys.stderr,
        )
        return 2
    from analyzer_tpu.fabric.driver import spawn_fabric_hosts

    rc = 0
    with tempfile.TemporaryDirectory(prefix="fabric-cli-") as tmp:
        exit_file = os.path.join(tmp, "exit")
        base_spec = {
            "n_shards": args.shards,
            "n_hosts": args.hosts,
            "seed": args.seed,
            "n_players": args.players,
            "batch_size": args.batch_size,
            "max_wall_s": args.duration + 60.0,
        }
        hosts: list = []
        try:
            hosts = spawn_fabric_hosts(base_spec, tmp, exit_file)
            for h in hosts:
                print(json.dumps({
                    "host": h["host"],
                    "shards": list(range(h["host"], args.shards,
                                         args.hosts)),
                    "serve_url": h["serve_url"],
                    "control_url": h["control_url"],
                    "obs_port": h["obs_port"],
                    "pid": h["pid"],
                }))
            sys.stdout.flush()
            deadline = _time.monotonic() + args.duration
            try:
                while _time.monotonic() < deadline:
                    for h in hosts:
                        if h["proc"].poll() is not None:
                            print(
                                f"error: fabric host {h['host']} exited "
                                f"rc={h['proc'].returncode}; see "
                                f"{h['log_path']}", file=sys.stderr,
                            )
                            rc = 1
                    if rc:
                        break
                    _time.sleep(0.2)
            except KeyboardInterrupt:
                print("interrupt: signalling fabric down", file=sys.stderr)
        except RuntimeError as err:
            print(f"error: {err}", file=sys.stderr)
            rc = 1
        finally:
            with open(exit_file, "w", encoding="utf-8") as f:
                f.write("exit\n")
            for h in hosts:
                try:
                    h["proc"].wait(timeout=30)
                except Exception:
                    h["proc"].kill()
                h["log"].close()
    return rc


def _migrate_quality(data: bytes, report, pre_live_view, cfg):
    """The staging-vs-live replay judge (obs/quality.py
    :func:`score_table`): scores the migrated table AND the
    pre-migration live table over the SAME replay window with the
    identical serve-plane Phi link — did the re-rate produce a
    better-fitting table than the lineage it replaced? Advisory (the
    migrated table saw these matches, the live one may not have — a
    fit gap is expected; the alarm is a MIGRATED table that fits
    worse)."""
    import io as _io

    import numpy as np

    from analyzer_tpu.io.csv_codec import load_stream_csv
    from analyzer_tpu.obs.quality import score_table

    stream = load_stream_csv(_io.StringIO(data.decode("utf-8")))
    keys = ("matches_scored", "brier", "logloss", "ece")
    migrated = score_table(np.asarray(report.state.table), stream, cfg)
    out = {"migrated": {k: migrated[k] for k in keys}}
    if pre_live_view is not None:
        live_q = score_table(
            np.asarray(pre_live_view.host_table()), stream, cfg
        )
        out["live_pre_cutover"] = {k: live_q[k] for k in keys}
    return out


def cmd_migrate(args) -> int:
    """Zero-downtime global re-rate (docs/migration.md): the streamed
    decode->assign->scan backfill engine rates a CSV history while a
    live lineage keeps serving, publishes into a staging view lineage,
    and cuts traffic over atomically at the end. Checkpointed and
    resumable: a killed backfill restarts from its last window-boundary
    watermark and produces a bit-identical final table."""
    from analyzer_tpu.config import RatingConfig
    from analyzer_tpu.core.state import PlayerState
    from analyzer_tpu.migrate import LineageManager, run_migration
    from analyzer_tpu.serve import ViewPublisher
    from analyzer_tpu.utils import PhaseTimer

    if args.resume and not args.checkpoint:
        print("error: --resume requires --checkpoint", file=sys.stderr)
        return 2
    if args.checkpoint_every and not args.checkpoint:
        print("error: --checkpoint-every requires --checkpoint",
              file=sys.stderr)
        return 2
    for flag in ("checkpoint_every", "stop_after_steps", "prefetch_depth",
                 "window_rows", "batch_size", "plan_windows"):
        val = getattr(args, flag)
        if val is not None and val <= 0:
            print(f"error: --{flag.replace('_', '-')} must be positive",
                  file=sys.stderr)
            return 2
    if args.hot_rows < 0:
        print("error: --hot-rows must be >= 0 (0 = untiered)", file=sys.stderr)
        return 2
    _obs_begin(args)
    server = _obs_serve(args)
    timer = PhaseTimer()
    try:
        cfg = RatingConfig.from_env()
        with timer.phase("load"):
            with open(args.csv, "rb") as f:
                data = f.read()
        state = None
        if not args.resume:
            n_players = args.players
            if n_players is None:
                # No --players: probe the stream for its row ceiling
                # (one decode pass — pass --players to skip it).
                from analyzer_tpu.io.ingest import decode_stream_csv

                with timer.phase("probe"):
                    probe = decode_stream_csv(data)
                    if probe is None:
                        import io as _io

                        from analyzer_tpu.io.csv_codec import load_stream_csv

                        probe = load_stream_csv(
                            _io.StringIO(data.decode("utf-8"))
                        )
                    n_players = (
                        int(probe.player_idx.max()) + 1
                        if probe.n_matches else 0
                    )
                    del probe
                print(
                    f"probed {n_players} players (pass --players to skip "
                    "the probe)", file=sys.stderr,
                )
            state = PlayerState.create(n_players, cfg=cfg)
        else:
            n_players = None  # the checkpoint carries the table
        # The in-process live lineage: primed from --from-checkpoint
        # when serving continuity from an existing table matters, else
        # empty (the cutover publishes version 1).
        live = ViewPublisher()
        if args.from_checkpoint:
            from analyzer_tpu.io.checkpoint import load_checkpoint

            live.publish_state(load_checkpoint(args.from_checkpoint).state)
        lineage = LineageManager(live)
        engine_kw = {}
        if args.window_rows:
            engine_kw["window_rows"] = args.window_rows
        if args.plan_windows:
            engine_kw["plan_windows"] = args.plan_windows
        # Snapshot the pre-migration live view NOW — the cutover inside
        # run_migration repoints `live` at the migrated table, and the
        # replay judge needs the table being REPLACED.
        pre_live_view = live.current()
        with timer.phase("migrate"):
            report = run_migration(
                state, data, cfg,
                lineage=lineage,
                checkpoint=args.checkpoint,
                resume=args.resume,
                checkpoint_every=args.checkpoint_every,
                stop_after=args.stop_after_steps,
                do_cutover=not args.no_cutover,
                batch_size=args.batch_size,
                prefetch_depth=args.prefetch_depth,
                kernel=args.kernel,
                fuse_window=args.fuse_window,
                hot_rows=args.hot_rows,
                **engine_kw,
            )
        if report.finished:
            _obs_write(args)
        quality = None
        if report.finished and not args.no_quality:
            with timer.phase("quality"):
                try:
                    quality = _migrate_quality(
                        data, report, pre_live_view, cfg
                    )
                except Exception as e:  # noqa: BLE001 — advisory evidence
                    quality = {"error": repr(e)}
        stats = report.stats
        print(json.dumps({
            "matches": stats.get("matches"),
            "supersteps": stats.get("n_steps"),
            "batch_size": stats.get("batch_size"),
            "occupancy": round(stats.get("occupancy", 0.0), 3),
            "streamed": stats.get("streamed"),
            "assign_native": stats.get("assign_native"),
            "plan_windows": stats.get("plan_windows"),
            "stopped": stats.get("stopped", False),
            "ttfd_s": (
                round(stats["ttfd_s"], 4)
                if stats.get("ttfd_s") is not None else None
            ),
            "cutover_pause_ms": report.cutover_pause_ms,
            "lineage_live_version": live.version,
            "quality": quality,
            "phases": {k: round(v, 3) for k, v in timer.report().items()},
        }))
        return 0
    finally:
        if server is not None:
            server.close()


def cmd_worker(args) -> int:
    if args.requeue_failed:
        # Dead-letter redrive: move <QUEUE>_failed back onto the main
        # queue and exit — run after fixing whatever poisoned them.
        from analyzer_tpu.config import ServiceConfig
        from analyzer_tpu.service.broker import make_pika_broker
        from analyzer_tpu.service.worker import requeue_failed

        config = ServiceConfig.from_env()
        # Deliberately NOT config.prefetch_count: the redrive acks each
        # message right after republish (no deferred-ack window to
        # cover), and the prefetch bound is also the worst-case
        # duplicate window on a mid-drain crash — keep it one batch.
        broker = make_pika_broker(
            config.rabbitmq_uri, prefetch=config.batch_size
        )
        n = requeue_failed(broker, config)
        print(json.dumps({"requeued": n, "queue": config.queue}))
        return 0
    from analyzer_tpu.service.worker import main as worker_main

    worker_main(
        obs_port=args.obs_port, flight_dir=args.flight_dir,
        serve_port=args.serve_port, serve_shards=args.serve_shards,
        profile_dir=args.profile_dir,
        audit=True if args.audit else None,
        slo_plane=not args.no_slo_plane,
    )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="analyzer_tpu", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("synth", help="generate a synthetic match history (.csv/.npz)")
    s.add_argument("--matches", type=int, default=1000)
    s.add_argument("--players", type=int, default=300)
    s.add_argument("--seed", type=int, default=0)
    s.add_argument("--concentration", type=float, default=0.8)
    s.add_argument(
        "--max-share", type=float, default=0.0, metavar="FRAC",
        help="cap any player's expected share of match slots (bench.py "
        "uses 1e-4: a physically plausible ladder; 0 = uncapped Zipf, "
        "whose top grinder chains the whole schedule — io/synthetic.py)",
    )
    s.add_argument(
        "--out", required=True,
        help=".csv (native parser), .npz (binary), or .db "
        "(reference-schema sqlite for the --db lanes)",
    )
    s.add_argument(
        "--telemetry", action="store_true",
        help="also generate post-game telemetry (K/D/A, gold, cs) for the "
        "config-4 analysis head (.npz only)",
    )
    s.add_argument(
        "--synergy", type=float, default=0.0, metavar="STRENGTH",
        help="composition-dependent outcome term: teams gain "
        "STRENGTH*400 skill points per unit of mean archetype-pair "
        "synergy (io/synthetic.py synergy_matrix) — signal a per-player "
        "rating system cannot represent, so learned heads with "
        "composition features can beat the rating baseline (.npz only)",
    )
    s.set_defaults(fn=cmd_synth)

    s = sub.add_parser("rate", help="TrueSkill full-history re-rate of a stream")
    s.add_argument("--csv", help="match stream, .csv or .npz")
    s.add_argument(
        "--db", metavar="URI",
        help="full-history columnar ingest straight from a database "
        "(sqlite:///... or mysql://...; the reference's actual data "
        "source, worker.py:176-191) — player rating priors come from "
        "the player table",
    )
    s.add_argument(
        "--db-write", action="store_true",
        help="with --db: bulk-write the final player ratings back",
    )
    s.add_argument("--checkpoint", help="state snapshot path (.npz)")
    s.add_argument("--resume", action="store_true", help="resume from --checkpoint")
    s.add_argument(
        "--checkpoint-every", type=int, metavar="STEPS",
        help="also snapshot every N supersteps mid-run (crash blast radius; "
        "the reference commits every 500-match batch, worker.py:194)",
    )
    s.add_argument(
        "--stop-after-steps", type=int, metavar="STEPS",
        help="stop after this superstep (bounded runs; a snapshot is always "
        "written at the stop boundary when --checkpoint is set)",
    )
    s.add_argument("--trace", help="jax.profiler trace output dir")
    s.add_argument(
        "--metrics-out", metavar="PATH",
        help="write the runtime telemetry snapshot (counters/gauges/"
        "histograms, batch spans, retrace counts — docs/observability.md) "
        "as JSON after a successful run",
    )
    s.add_argument(
        "--trace-events", metavar="PATH",
        help="write the span ring as Chrome trace-event JSONL "
        "(Perfetto-loadable, alongside --trace's XLA capture)",
    )
    s.add_argument(
        "--mesh", type=int, metavar="N",
        help="data-parallel re-rate over a device mesh: N devices, or 0 for "
        "all (global under jax.distributed — set COORDINATOR_ADDRESS/"
        "NUM_PROCESSES/PROCESS_ID and run on every host)",
    )
    s.add_argument(
        "--obs-port", type=int, metavar="PORT",
        help="serve live introspection endpoints (/metrics /healthz "
        "/readyz /statusz /debug/snapshot) on localhost:PORT for the "
        "duration of the run (0 = ephemeral; docs/observability.md)",
    )
    s.add_argument(
        "--prefetch-depth", type=int, metavar="N",
        help="device-feed slab ring depth (default 2): how many windows "
        "ahead the feed thread materializes + transfers while the scan "
        "runs; results are depth-invariant, HBM cost is N slabs "
        "(docs/observability.md, 'Prefetching device feed')",
    )
    s.add_argument(
        "--kernel", choices=("reference", "fused"),
        default=os.environ.get("BENCH_KERNEL", "reference"),
        help="device kernel: 'reference' = per-superstep gather/update/"
        "scatter scan; 'fused' = VMEM-resident window kernel (each "
        "touched player row gathered once and written back once per "
        "--fuse-window supersteps; bit-identical results — "
        "docs/kernels.md). Default from BENCH_KERNEL, else reference. "
        "Not composable with --mesh yet",
    )
    s.add_argument(
        "--fuse-window", type=int, metavar="K",
        default=int(os.environ.get("BENCH_FUSE_WINDOW", 0)) or None,
        help="supersteps per fused window dispatch (default 16; env "
        "BENCH_FUSE_WINDOW). Larger K amortizes the per-window gather/"
        "writeback further but grows the VMEM working set; overflow "
        "splits the window (a counted spill)",
    )
    s.add_argument(
        "--hot-rows", type=int, metavar="N",
        default=int(os.environ.get("BENCH_HOT_ROWS", 0)),
        help="tiered ratings table (default 0 = untiered): keep only an "
        "N-row hot set (pow2-bucketed) of the player table in device "
        "memory, spilling cold rows to a host tier promoted ahead of "
        "need on the feed thread; results bit-identical at every size "
        "(sched/tier.py, docs/kernels.md). Not composable with --mesh",
    )
    s.set_defaults(fn=cmd_rate)

    s = sub.add_parser(
        "train",
        help="win-probability heads (logistic/MLP) on leak-free rating "
        "features, chronological holdout eval",
    )
    s.add_argument("--csv", help="match stream, .csv or .npz")
    s.add_argument(
        "--db", metavar="URI",
        help="train on a full history ingested straight from a database "
        "(columnar load_stream; features COLD-START even if the DB holds "
        "ratings — stored ratings are usually this history's own end "
        "state, and seeding from them would leak outcomes into the eval)",
    )
    s.add_argument("--model", choices=("logistic", "mlp"), default="logistic")
    s.add_argument("--epochs", type=int, default=30)
    s.add_argument("--hidden", type=int, default=64, help="MLP width")
    s.add_argument("--eval-frac", type=float, default=0.2,
                   help="chronological tail fraction held out for eval")
    s.add_argument("--seed", type=int, default=0)
    s.add_argument("--out", help="npz output for the trained weights")
    s.add_argument(
        "--telemetry", action="store_true",
        help="append post-game telemetry features (analysis head, "
        "BASELINE config 4; needs an .npz stream from synth --telemetry)",
    )
    s.add_argument(
        "--mesh", type=int, metavar="N",
        help="data-parallel training: shard the minibatch axis over N "
        "devices (0 = all)",
    )
    s.set_defaults(fn=cmd_train)

    s = sub.add_parser("elo", help="Elo re-rate of a stream + accuracy")
    s.add_argument("--csv", help="match stream, .csv or .npz")
    s.add_argument(
        "--db", metavar="URI",
        help="Elo re-rate a full history straight from a database",
    )
    s.add_argument("--out", help="npz output for ratings/predictions")
    s.set_defaults(fn=cmd_elo)

    s = sub.add_parser("bench", help="headline throughput benchmark")
    s.add_argument(
        "--metrics-out", metavar="PATH",
        help="also write the full telemetry snapshot as JSON (the BENCH "
        "line embeds the phase/retrace breakdown either way)",
    )
    s.add_argument(
        "--obs-port", type=int, metavar="PORT",
        help="serve the live introspection endpoints while the benchmark "
        "runs (watch /metrics mid-capture; 0 = ephemeral)",
    )
    s.add_argument(
        "--kernel", choices=("reference", "fused"),
        help="headline kernel (default: BENCH_KERNEL env, else fused). "
        "'fused' times BOTH kernels and embeds a `fused` telemetry "
        "block with min_over_reference in the BENCH line",
    )
    s.add_argument(
        "--fuse-window", type=int, metavar="K",
        help="fused window size (default: BENCH_FUSE_WINDOW env, else 16)",
    )
    s.add_argument(
        "--hot-rows", type=int, metavar="N",
        help="also capture the tiered-table line with an N-row hot set "
        "(BENCH_HOT_ROWS env): the BENCH line gains a `tiered` block — "
        "hit rate, promotion bytes, min_over_resident — that "
        "`cli benchdiff --family tiered` gates",
    )
    s.add_argument(
        "--ingest", action="store_true",
        help="capture the wire-speed ingest line instead (BENCH_INGEST "
        "env): columnar windowed decode into pinned arena slabs + "
        "per-window H2D through the prefetch ring; emits the "
        "INGEST_BENCH_*.json artifact `cli benchdiff --family ingest` "
        "gates (bytes/s, queue-to-H2D p99, arena hit rate — "
        "docs/ingest.md)",
    )
    s.add_argument(
        "--migrate", action="store_true",
        help="capture the zero-downtime migration line instead "
        "(BENCH_MIGRATE env): streamed backfill matches/s, live serve "
        "p99 under the concurrent migration, cutover pause ms; emits "
        "the MIGRATE_BENCH_*.json artifact `cli benchdiff --family "
        "migrate` gates (docs/migration.md)",
    )
    s.add_argument(
        "--profile", action="store_true",
        help="auto-arm a one-window device-profiler capture per config "
        "(BENCH_PROFILE env): the artifact's `roofline` block is then "
        "computed from MEASURED device-busy time instead of wall time, "
        "and gains the device_idle_frac `cli benchdiff` gates",
    )
    s.add_argument(
        "--profile-dir", metavar="DIR",
        help="where --profile writes its capture dirs "
        "(BENCH_PROFILE_DIR env; default: a temp directory)",
    )
    s.set_defaults(fn=cmd_bench)

    s = sub.add_parser(
        "benchdiff",
        help="diff two BENCH_*.json artifacts; non-zero exit on a "
        "throughput regression past --regress-pct",
    )
    s.add_argument(
        "artifacts", nargs="*",
        help="baseline and candidate artifacts (raw bench lines or the "
        "driver's {parsed: ...} captures); with --against-latest, at most "
        "the candidate",
    )
    s.add_argument(
        "--against-latest", action="store_true",
        help="compare the candidate (or the newest artifact) against the "
        "latest other BENCH_*.json under --dir",
    )
    s.add_argument(
        "--dir", default=".",
        help="directory scanned for BENCH_*.json (default: .)",
    )
    s.add_argument(
        "--regress-pct", type=float, default=5.0, metavar="PCT",
        help="fail (exit 1) when a non-degraded config is worse by more "
        "than PCT percent (default: 5)",
    )
    s.add_argument(
        "--family",
        choices=(
            "bench", "serve", "tiered", "soak", "ingest", "migrate",
            "fabric",
        ),
        default="bench",
        help="artifact family for --against-latest scans: bench "
        "(BENCH_*.json, the write path), serve (SERVE_BENCH_*.json — "
        "queries/sec + p99 latency, experiments/serve_bench.py), "
        "tiered (the same BENCH_*.json artifacts, gating only the "
        "tiered-table configs — min_over_resident + hit rate; a "
        "candidate that silently dropped its tiered block fails), or "
        "soak (SOAK_*.json from `cli soak` — throughput/p99 regression "
        "PLUS the absolute SLOs: zero dead-letters, flat steady-state "
        "retraces, bounded view staleness, drained backlog), or ingest "
        "(INGEST_BENCH_*.json from `cli bench --ingest` — decoded "
        "bytes/s, queue-to-H2D p99, arena hit rate; a candidate whose "
        "decode silently fell back to the python codec fails), or "
        "migrate (MIGRATE_BENCH_*.json from `cli bench --migrate` — "
        "backfill matches/s, live serve p99 under concurrent migration, "
        "cutover pause ms; a candidate whose backfill silently fell "
        "back to the offline re-rate fails), or fabric "
        "(FABRIC_BENCH_*.json from `cli soak --hosts N` — per-host "
        "ingest matches/s, routed-query p99, worst per-host view "
        "staleness, plus the fleet-scope absolute SLOs; a candidate "
        "that silently fell back to a single-process topology fails); "
        "explicit two-path diffs auto-detect from the metric name",
    )
    s.set_defaults(fn=cmd_benchdiff)

    s = sub.add_parser(
        "lint",
        help="graftlint: JAX-hazard + native-ABI static analysis "
        "(docs/lint.md; exit 1 on findings)",
    )
    s.add_argument(
        "paths", nargs="*", default=["analyzer_tpu"],
        help="files or directories to lint (default: analyzer_tpu)",
    )
    s.add_argument("--json", action="store_true", help="JSON output")
    s.add_argument(
        "--rules", action="store_true", help="print the rule catalog"
    )
    s.add_argument(
        "--project", action=argparse.BooleanOptionalAction, default=True,
        help="cross-module thread rules GL040-GL045 (default on)",
    )
    s.add_argument(
        "--baseline", metavar="FILE",
        help="JSON suppression snapshot (stale entries fail loudly)",
    )
    s.add_argument(
        "--write-baseline", metavar="FILE",
        help="snapshot current findings as a baseline and exit 0",
    )
    s.set_defaults(fn=cmd_lint)

    s = sub.add_parser(
        "trace",
        help="reconstruct per-match/per-batch causal timelines from a "
        "trace-events JSONL or a flight-recorder dump "
        "(docs/observability.md \"Causal tracing\")",
    )
    s.add_argument(
        "artifact", nargs="+",
        help="a --trace-events JSONL export, or a flight-recorder dump "
        "directory (its trace.jsonl is used); several stitch into one "
        "cross-process trace forest",
    )
    s.add_argument(
        "--match", metavar="ID",
        help="one match's journey: queue wait + its batch's stage "
        "decomposition + the view version that served it",
    )
    s.add_argument(
        "--batch", metavar="ID",
        help="one batch's stage decomposition (ids look like b17; "
        "`--match` prints the owning batch id)",
    )
    s.add_argument(
        "--window", type=int, default=0, metavar="N",
        help="restrict the critical-path report to the last N batches "
        "(default: all)",
    )
    s.add_argument(
        "--profile", metavar="DIR",
        help="a device-profiler capture dir (obs/prof.py): joins its "
        "attribution against this host trace and decomposes the "
        "`dispatch` stage into device-execute / device-idle / "
        "host-overhead",
    )
    s.add_argument("--json", action="store_true", help="JSON output")
    s.set_defaults(fn=cmd_trace)

    s = sub.add_parser(
        "profile",
        help="attribute a device-profiler capture dir: per-kernel "
        "device time, busy/idle and compile/execute splits "
        "(docs/observability.md \"Profile intelligence\")",
    )
    s.add_argument(
        "capture_dir",
        help="a profile-<ts>-<reason>-<pid>/ capture directory "
        "(--profile-dir / ANALYZER_TPU_PROFILE_DIR arms them)",
    )
    s.add_argument(
        "--trace-events", nargs="+", metavar="ARTIFACT", default=[],
        help="host-side trace artifacts (JSONL exports or flight-dump "
        "dirs): join the capture against the causal-trace forest and "
        "decompose the dispatch stage",
    )
    s.add_argument("--json", action="store_true", help="JSON output")
    s.set_defaults(fn=cmd_profile)

    s = sub.add_parser(
        "tune",
        help="telemetry-driven tuning advisor: name the bottleneck and "
        "the knob from bench/soak/migrate artifacts, history rings and "
        "profile captures (deterministic; docs/observability.md "
        "\"Profile intelligence\")",
    )
    s.add_argument(
        "artifacts", nargs="*",
        help="artifact paths (BENCH/SOAK/INGEST/MIGRATE_BENCH JSON, a "
        "history.json or flight-dump dir); none = scan --dir",
    )
    s.add_argument(
        "--dir", default=".",
        help="directory scanned for artifacts when none are named "
        "(default: .)",
    )
    s.add_argument(
        "--profile", metavar="DIR",
        help="also attribute a device-profiler capture dir and feed its "
        "busy/idle split to the rules",
    )
    s.add_argument("--json", action="store_true", help="JSON output")
    s.set_defaults(fn=cmd_tune)

    s = sub.add_parser(
        "metrics",
        help="render a runtime telemetry snapshot (docs/observability.md)",
    )
    s.add_argument(
        "snapshot", nargs="?",
        help="a --metrics-out JSON artifact; omitted = this process's "
        "live registry (the declared metric catalog)",
    )
    s.add_argument(
        "--format", choices=("json", "prom", "summary"), default="json",
        help="json (default), prom (Prometheus text exposition), or "
        "summary (human digest)",
    )
    s.set_defaults(fn=cmd_metrics)

    s = sub.add_parser(
        "history",
        help="render telemetry history rings (live /historyz, a saved "
        "history.json / flight dump, or this process)",
    )
    s.add_argument(
        "artifact", nargs="?",
        help="a history.json file or a flight-dump directory "
        "(default: this process's sampler)",
    )
    s.add_argument(
        "--url", metavar="URL",
        help="fetch from a live worker's obsd endpoint "
        "(e.g. http://127.0.0.1:9100 — /historyz is appended)",
    )
    s.add_argument(
        "--series", action="append", default=[], metavar="PREFIX",
        help="only series whose name starts with PREFIX (repeatable)",
    )
    s.add_argument(
        "--tier", choices=["raw", "10s", "1m"], default="raw",
        help="downsampling tier to render (default: raw)",
    )
    s.add_argument(
        "--json", action="store_true",
        help="dump the (filtered) payload as JSON instead of trends",
    )
    s.set_defaults(fn=cmd_history)

    s = sub.add_parser(
        "quality",
        help="rating-quality report: calibration reliability table, "
        "Brier/log-loss/ECE, population drift (live /qualityz, a saved "
        "soak artifact, or this process's ledger) "
        "(docs/observability.md \"Rating quality\")",
    )
    s.add_argument(
        "--url", metavar="URL",
        help="fetch from a live worker's obsd endpoint "
        "(e.g. http://127.0.0.1:9100 — /qualityz is appended)",
    )
    s.add_argument(
        "--artifact", metavar="PATH",
        help="read the quality block of a saved SOAK_*.json artifact",
    )
    s.add_argument(
        "--fit-temperature", action="store_true",
        help="fit a post-hoc temperature over the live ledger's "
        "retained (logit, outcome) prefix (models/calibration.py) and "
        "report NLL before/after — quantifies over/under-confidence",
    )
    s.add_argument(
        "--json", action="store_true",
        help="dump the summary as JSON instead of the rendered report",
    )
    s.set_defaults(fn=cmd_quality)

    s = sub.add_parser(
        "fleet",
        help="fleet observability plane: scrape N workers' obsd "
        "endpoints, merge registries under host=, evaluate fleet-scope "
        "SLO burns with per-host attribution, serve /fleetz "
        "(docs/observability.md \"Fleet plane\")",
    )
    s.add_argument(
        "targets_pos", nargs="*", metavar="HOST:PORT",
        help="worker obsd endpoints to scrape",
    )
    s.add_argument(
        "--targets", metavar="HOST:PORT,...",
        help="comma-separated target list (merged with positionals)",
    )
    s.add_argument(
        "--port", type=int, default=0,
        help="fleetd serving port (default: ephemeral, printed)",
    )
    s.add_argument(
        "--interval", type=float, default=2.0, metavar="S",
        help="scrape cadence in seconds (default: 2)",
    )
    s.add_argument(
        "--scrapes", type=int, default=0, metavar="N",
        help="stop after N scrape rounds (default: run until ^C); the "
        "exit code reports whether anything was burning at the end",
    )
    s.add_argument(
        "--check", action="store_true",
        help="one-shot CI gate: scrape once, evaluate the objectives a "
        "single sample can judge (absolute counter_zero + worst-host "
        "gauge_max), exit 1 on any burn",
    )
    s.add_argument(
        "--require-all-up", action="store_true",
        help="--check also fails when any target is unreachable",
    )
    s.add_argument(
        "--flight-token", metavar="TOKEN",
        help="shared secret for the burning host's /debug/flight "
        "trigger (workers read ANALYZER_TPU_FLIGHT_TOKEN)",
    )
    s.add_argument(
        "--no-flight-requests", action="store_true",
        help="never ask burning hosts for flight dumps",
    )
    s.add_argument("--json", action="store_true",
                   help="--check prints the fleet /sloz payload as JSON")
    s.set_defaults(fn=cmd_fleet)

    s = sub.add_parser(
        "fabric",
        help="launch a standing multi-host rate fabric: N shard-owning "
        "host processes with partitioned ingest, per-host serve "
        "planes, and /fabric/* control surfaces (docs/fabric.md)",
    )
    s.add_argument(
        "--hosts", type=int, default=2, metavar="N",
        help="host process count (default: 2)",
    )
    s.add_argument(
        "--shards", type=int, default=4, metavar="S",
        help="shard count; ownership is shard s -> host s %% N, so S "
        "must be >= --hosts (default: 4)",
    )
    s.add_argument("--seed", type=int, default=0)
    s.add_argument("--players", type=int, default=400)
    s.add_argument(
        "--batch-size", type=int, default=64,
        help="per-host worker micro-batch size (default: 64)",
    )
    s.add_argument(
        "--duration", type=float, default=600.0, metavar="S",
        help="wall seconds to keep the fabric up (default: 600; ^C "
        "exits early and signals the hosts down)",
    )
    s.set_defaults(fn=cmd_fabric)

    s = sub.add_parser(
        "soak",
        help="closed-loop matchmaking soak with SLO gates "
        "(analyzer_tpu/loadgen; artifact for benchdiff --family soak)",
    )
    s.add_argument("--seed", type=int, default=0)
    s.add_argument(
        "--duration", type=float, default=8.0, metavar="S",
        help="VIRTUAL seconds to soak (ticks = duration/tick; wall time "
        "only matters with --realtime). Default: 8",
    )
    s.add_argument(
        "--qps", type=float, default=24.0,
        help="matches formed per virtual second (default: 24)",
    )
    s.add_argument(
        "--query-qps", type=float, default=10.0, metavar="QPS",
        help="serve queries per virtual second against /v1/* "
        "(default: 10; mix: ratings/winprob/leaderboard/tiers)",
    )
    s.add_argument(
        "--tick", type=float, default=1.0, metavar="S",
        help="virtual tick length (default: 1.0)",
    )
    s.add_argument("--players", type=int, default=400)
    s.add_argument(
        "--batch-size", type=int, default=64,
        help="worker micro-batch size (default: 64)",
    )
    s.add_argument(
        "--polls-per-tick", type=int, default=4,
        help="worker poll budget per tick — overload shows up as queue "
        "depth instead of stretching the tick (default: 4)",
    )
    s.add_argument("--team5-frac", type=float, default=0.3,
                   help="fraction of 5v5 matches (default: 0.3)")
    s.add_argument("--afk-rate", type=float, default=0.0,
                   help="fraction of matches with an AFK participant")
    s.add_argument(
        "--max-view-lag-ticks", type=int, default=2, metavar="N",
        help="SLO: ticks the served view may stay stale while commits "
        "are pending (default: 2)",
    )
    s.add_argument(
        "--min-matches-per-sec", type=float, metavar="N",
        help="SLO: absolute wall-throughput floor (default: ungated — "
        "regressions gate via benchdiff)",
    )
    s.add_argument(
        "--max-p99-ms", type=float, metavar="MS",
        help="SLO: absolute serve-query p99 cap (default: ungated)",
    )
    s.add_argument(
        "--no-warmup", action="store_true",
        help="skip the worker/serve/publish compile warmup (the retrace "
        "SLO then measures warmup compiles too)",
    )
    s.add_argument(
        "--in-process", action="store_true",
        help="query the engine in-process instead of over HTTP /v1/*",
    )
    s.add_argument(
        "--serve-http", action="store_true",
        help="drive the HTTP query workload through the concurrent serve "
        "front door (serve/frontdoor.py: keep-alive socket plane + native "
        "codec) instead of the stdlib RoutedHTTPServer plane; the "
        "deterministic block is bit-identical either way "
        "(docs/serving.md \"Front door\")",
    )
    s.add_argument(
        "--serve-shards", type=int, default=1, metavar="S",
        help="serve the soak's read plane through S shards "
        "(ShardedViewPublisher + ShardedQueryEngine); the deterministic "
        "block is bit-identical to --serve-shards 1 for the same seed "
        "(docs/serving.md \"Sharded plane\")",
    )
    s.add_argument(
        "--broker-partitions", type=int, default=1, metavar="S",
        help="partition the analyze queue by player-shard (row %% S, the "
        "serve plane's mesh layout invariant): per-partition depth/"
        "dead-letter accounting, global delivery order preserved — the "
        "deterministic block is bit-identical to the single-queue run "
        "(docs/ingest.md \"Partition math\")",
    )
    s.add_argument(
        "--priority-lanes", action="store_true",
        help="live-vs-backfill priority lanes on the broker, with the "
        "admission controller arbitrating backfill behind live traffic "
        "on feed-starvation + tier-promotion telemetry "
        "(docs/ingest.md \"Lane arbitration\")",
    )
    s.add_argument(
        "--backfill-qps", type=float, default=0.0, metavar="QPS",
        help="re-publish already-rated matches on the backfill lane at "
        "this rate (requires --priority-lanes) — the re-rate/replay "
        "ingest shape of ROADMAP item 4",
    )
    s.add_argument(
        "--forbid-dominant-stage", action="append", default=[],
        metavar="STAGE", dest="forbid_dominant_stages",
        help="SLO: fail when the trace block's critical-path dominant "
        "stage is STAGE (repeatable; e.g. queue_wait encode — the "
        "ingest-edge gate; needs --trace)",
    )
    s.add_argument(
        "--realtime", action="store_true",
        help="pace ticks against the wall clock (rig soaks); decisions "
        "still run on the virtual clock, so results stay deterministic",
    )
    s.add_argument(
        "--out", metavar="PATH",
        help="write the SOAK_*.json artifact (the benchdiff --family "
        "soak input; stdout always carries the one-line summary)",
    )
    s.add_argument(
        "--metrics-out", metavar="PATH",
        help="also write the full telemetry snapshot as JSON",
    )
    s.add_argument(
        "--obs-port", type=int, metavar="PORT",
        help="serve the soak worker's obsd introspection endpoints "
        "(watch soak.* and broker.queue_depth live, or point a "
        "`cli fleet` Collector at it; 0 = ephemeral)",
    )
    s.add_argument(
        "--trace", action="store_true",
        help="causal tracing: every match carries a TraceContext from "
        "broker enqueue to view publish, and the artifact gains a "
        "`trace` block (stage decomposition + dominant stage); the "
        "deterministic block stays bit-identical "
        "(docs/observability.md \"Causal tracing\")",
    )
    s.add_argument(
        "--trace-events", metavar="PATH",
        help="write the span ring as Chrome trace-event JSONL after the "
        "soak (implies --trace; the `cli trace` input)",
    )
    s.add_argument(
        "--audit", action="store_true",
        help="continuous shadow audit: a seeded-hash sample of the "
        "soak's served queries replays through the bit-exact oracle off "
        "the hot path; one mismatch fails the soak's SLO gate "
        "(docs/observability.md \"Shadow audit\")",
    )
    s.add_argument(
        "--audit-sample-denom", type=int, default=4, metavar="N",
        help="audit 1-in-N served queries (default: 4; 1 = every query)",
    )
    s.add_argument(
        "--no-slo-plane", action="store_true",
        help="disable the history sampler + SLO watchdog (the "
        "bit-identity AB knob; the deterministic block is identical "
        "either way)",
    )
    s.add_argument(
        "--no-quality", action="store_true",
        help="disable the calibration ledger (the rating-quality "
        "bit-identity AB knob; the artifact loses its `quality` block "
        "and the deterministic block is identical either way)",
    )
    s.add_argument(
        "--migrate", action="store_true",
        help="run a full zero-downtime re-rate UNDER the live soak "
        "load: the streamed backfill engine rates a seeded synthetic "
        "history into a staging lineage (admission-arbitrated against "
        "live traffic) while the soak serves, then cuts over "
        "atomically after the measured window; the artifact gains a "
        "`migration` block and the deterministic block is unchanged "
        "per (seed, config) (docs/migration.md)",
    )
    s.add_argument(
        "--migrate-matches", type=int, default=400, metavar="N",
        help="matches in the migrated synthetic history (default: 400)",
    )
    s.add_argument(
        "--hosts", type=int, metavar="N",
        help="run the soak over a REAL multi-process fabric of N "
        "shard-owning host subprocesses (analyzer_tpu/fabric): "
        "broker-partitioned ingest, routed /v1/* queries, fleet-scope "
        "SLOs; the deterministic block is bit-identical per (seed, "
        "config) at any N, and the artifact is FABRIC_BENCH-shaped "
        "(`benchdiff --family fabric`). Flags that configure the "
        "single-process pipeline shape (--serve-shards, "
        "--broker-partitions, --migrate, --audit, ...) do not apply",
    )
    s.add_argument(
        "--fabric-shards", type=int, default=4, metavar="S",
        help="fabric shard count for --hosts (ownership: shard s -> "
        "host s %% N; must be >= --hosts; default: 4)",
    )
    s.set_defaults(fn=cmd_soak)

    s = sub.add_parser(
        "migrate",
        help="zero-downtime streamed re-rate: decode->assign->scan "
        "overlapped, dual-lineage serve cutover, checkpoint/resume "
        "(docs/migration.md)",
    )
    s.add_argument("--csv", required=True, help="match history CSV")
    s.add_argument(
        "--players", type=int, metavar="N",
        help="player-table rows (default: probed from the stream with "
        "one extra decode pass)",
    )
    s.add_argument(
        "--checkpoint", metavar="PATH",
        help="migration snapshot path (.npz; written at window "
        "boundaries with the schedule fingerprint)",
    )
    s.add_argument(
        "--resume", action="store_true",
        help="resume from --checkpoint's watermark (the front half "
        "re-derives the identical schedule from the bytes and skips "
        "device work below it; final table bit-identical)",
    )
    s.add_argument(
        "--checkpoint-every", type=int, metavar="STEPS",
        help="snapshot every N supersteps mid-backfill",
    )
    s.add_argument(
        "--stop-after-steps", type=int, metavar="STEPS",
        help="stop at the window boundary at/after this superstep "
        "(bounded runs; a snapshot is written there when --checkpoint "
        "is set; no cutover happens)",
    )
    s.add_argument(
        "--from-checkpoint", metavar="PATH",
        help="prime the live lineage from this snapshot (serving "
        "continuity while the backfill runs); default: empty live "
        "lineage",
    )
    s.add_argument(
        "--no-cutover", action="store_true",
        help="skip the final atomic cutover (inspect the staging "
        "lineage only)",
    )
    s.add_argument("--batch-size", type=int, metavar="B")
    s.add_argument(
        "--window-rows", type=int, metavar="N",
        help="decode window rows (default 4096; io/ingest.py)",
    )
    s.add_argument(
        "--plan-windows", type=int, metavar="K",
        help="decode windows in the batch-size planning prefix (default "
        "4; deterministic — the policy folds into the resume "
        "fingerprint, so resume with the value the run was started with)",
    )
    s.add_argument("--prefetch-depth", type=int, metavar="N")
    s.add_argument(
        "--kernel", choices=("reference", "fused"),
        default=os.environ.get("BENCH_KERNEL", "reference"),
    )
    s.add_argument("--fuse-window", type=int, metavar="K",
                   default=int(os.environ.get("BENCH_FUSE_WINDOW", 0)) or None)
    s.add_argument("--hot-rows", type=int, metavar="N",
                   default=int(os.environ.get("BENCH_HOT_ROWS", 0)))
    s.add_argument("--obs-port", type=int, metavar="PORT")
    s.add_argument("--metrics-out", metavar="PATH")
    s.add_argument("--trace-events", metavar="PATH")
    s.add_argument(
        "--no-quality", action="store_true",
        help="skip the staging-vs-live calibration replay judge "
        "(obs/quality.py score_table; it re-reads the stream once per "
        "lineage, so very large histories may want this)",
    )
    s.set_defaults(fn=cmd_migrate)

    s = sub.add_parser("worker", help="broker-consuming service loop")
    s.add_argument(
        "--requeue-failed", action="store_true",
        help="redrive <QUEUE>_failed back onto the main queue and exit "
        "(run after fixing what dead-lettered them)",
    )
    s.add_argument(
        "--obs-port", type=int, metavar="PORT",
        help="obsd: /metrics /healthz /readyz /statusz /debug/snapshot on "
        "localhost:PORT (also ANALYZER_TPU_OBS_PORT); /readyz 503s while "
        "the pipelined lane is degraded",
    )
    s.add_argument(
        "--flight-dir", metavar="DIR",
        help="arm flight-recorder dumps into DIR (also "
        "ANALYZER_TPU_FLIGHT_DIR): dead-letters, pipeline degradation "
        "and SIGUSR1 leave a timestamped artifact directory",
    )
    s.add_argument(
        "--serve-port", type=int, metavar="PORT",
        help="co-host the ratesrv query plane (/v1/ratings /v1/leaderboard "
        "/v1/winprob /v1/tiers on localhost:PORT, also "
        "ANALYZER_TPU_SERVE_PORT): a new view version publishes at every "
        "batch commit (docs/serving.md)",
    )
    s.add_argument(
        "--serve-shards", type=int, metavar="S",
        help="serve through the sharded plane: S per-shard views + "
        "routed lookups + distributed top-k (also "
        "ANALYZER_TPU_SERVE_SHARDS; bit-identical results, "
        "docs/serving.md \"Sharded plane\")",
    )
    s.add_argument(
        "--profile-dir", metavar="DIR",
        help="arm on-demand jax.profiler capture windows into DIR (also "
        "ANALYZER_TPU_PROFILE_DIR): SIGUSR2 captures the next batch's "
        "dispatch; dead-letters/degradation capture automatically "
        "(throttled) and the flight dump names the capture directory "
        "(docs/observability.md \"Device-time attribution\")",
    )
    s.add_argument(
        "--audit", action="store_true",
        help="continuous shadow audit of served queries against the "
        "bit-exact oracle (needs --serve-port; also ANALYZER_TPU_AUDIT; "
        "audit.mismatches_total is a zero-tolerance SLO — "
        "docs/observability.md \"Shadow audit\")",
    )
    s.add_argument(
        "--no-slo-plane", action="store_true",
        help="disable the live SLO plane (history rings + burn-rate "
        "watchdog + audit) — on by default; /historyz and /sloz then "
        "serve empty",
    )
    s.set_defaults(fn=cmd_worker)

    s = sub.add_parser(
        "serve",
        help="ratesrv: serve lookups/leaderboards/win-probability over a "
        "rating table (docs/serving.md)",
    )
    s.add_argument("--checkpoint", help="rating-state snapshot (.npz)")
    s.add_argument(
        "--db", metavar="URI",
        help="serve the player table of a reference-schema database "
        "(sqlite:///... or mysql://...)",
    )
    s.add_argument(
        "--port", type=int, default=0, metavar="PORT",
        help="bind port (default 0 = ephemeral; the bound URL prints as "
        "one JSON line on stdout)",
    )
    s.add_argument(
        "--max-batch", type=int, default=256, metavar="N",
        help="microbatch coalescing cap per tick (default: 256)",
    )
    s.add_argument(
        "--max-seconds", type=float, metavar="S",
        help="serve for S seconds then exit (default: forever; smoke "
        "tests and drills)",
    )
    s.add_argument(
        "--shards", type=int, default=1, metavar="S",
        help="serve through the sharded plane: the table splits into S "
        "per-shard views (interleaved by row), lookups route by "
        "player-id shard, leaderboards merge per-shard top-k — "
        "bit-identical to --shards 1 (docs/serving.md \"Sharded "
        "plane\")",
    )
    s.add_argument(
        "--all-gather-topk", action="store_true",
        help="with --shards > 1: one shard_map'd all-gather top-k "
        "dispatch over a serve mesh instead of S per-shard dispatches "
        "(the rig flag; needs one device per shard)",
    )
    s.add_argument(
        "--obs-port", type=int, metavar="PORT",
        help="also serve the obsd introspection endpoints (serve.* "
        "metrics land in /metrics)",
    )
    s.set_defaults(fn=cmd_serve)

    s = sub.add_parser(
        "query",
        help="one query against a running serve endpoint",
    )
    s.add_argument(
        "kind", choices=("ratings", "leaderboard", "winprob", "tiers"),
    )
    s.add_argument(
        "--url", required=True, metavar="URL",
        help="serve endpoint base, e.g. http://127.0.0.1:8391",
    )
    s.add_argument("--ids", metavar="A,B,C", help="ratings: player ids")
    s.add_argument("--k", type=int, default=10, help="leaderboard depth")
    s.add_argument("--a", metavar="IDS", help="winprob: team A ids")
    s.add_argument("--b", metavar="IDS", help="winprob: team B ids")
    s.add_argument(
        "--score", type=float,
        help="tiers: also report this conservative score's percentile",
    )
    s.add_argument("--timeout", type=float, default=10.0)
    s.set_defaults(fn=cmd_query)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
