"""Fabric ownership math — pure functions, no state, no clock.

The serve plane's mesh layout invariant (``serve/view.py``: global row
``r`` lives in shard ``r % S`` at local index ``r // S``) extends one
level to hosts: shard ``s`` is owned by host ``s % H``. Everything the
fabric routes — point lookups, match ingest partitions, view patches —
derives from these two modular maps, so ownership needs no lookup
service and no rebalance protocol: any process that knows ``(S, H)``
computes the same answer.

The companion invariant is ``partition_of == shard ownership``: the
partitioned broker routes a match by its first team-A row's shard
(``x-partition`` header, ``loadgen/driver.py``), so a host that
consumes exactly its owned partitions receives exactly its owned
players' matches. The fabric's matchmaking keeps matches SHARD-PURE
(every participant in one shard — :mod:`analyzer_tpu.fabric.matchmaker`),
which is what makes that routing loss-free: no match ever needs rows
two hosts own.
"""

from __future__ import annotations

import dataclasses

# THE layout invariant, reused verbatim — fabric ownership must agree
# with the serve plane's shard math or routed lookups read the wrong
# host (same contract as serve <-> mesh, pinned by tests/test_fabric.py).
from analyzer_tpu.serve.view import (  # noqa: F401  (re-exported)
    local_of_row,
    shard_of_row,
    shard_player_count,
)


def host_of_shard(shard: int, n_hosts: int) -> int:
    """Owner host for ``shard`` — the interleaved map one level up
    (shard ``s`` lives on host ``s % H``)."""
    return shard % n_hosts


def host_of_row(row: int, n_shards: int, n_hosts: int) -> int:
    """Owner host for a global row: ``host_of_shard(shard_of_row(r))``."""
    return host_of_shard(shard_of_row(row, n_shards), n_hosts)


def owned_shards(host: int, n_shards: int, n_hosts: int) -> tuple[int, ...]:
    """The shards ``host`` owns, ascending (``s % H == host``)."""
    return tuple(range(host, n_shards, n_hosts))


def owned_partitions(host: int, n_shards: int, n_hosts: int) -> tuple[int, ...]:
    """The broker partitions ``host`` consumes. Partition == shard by
    the ingest invariant (``x-partition`` carries the first team-A
    row's shard), so this IS :func:`owned_shards` — spelled separately
    because the two travel to different subsystems (broker vs view)."""
    return owned_shards(host, n_shards, n_hosts)


def owned_rows(host: int, n_players: int, n_shards: int, n_hosts: int) -> list[int]:
    """Global rows ``host`` owns among the first ``n_players``,
    ascending — the host's authoritative player set (seed publishes,
    table exports)."""
    return [
        r for r in range(n_players)
        if host_of_row(r, n_shards, n_hosts) == host
    ]


def row_of_id(player_id: str) -> int:
    """Global row for a soak-population api id (``p%06d`` —
    ``loadgen/matchmaker.player_id``). The parse is the routing
    primitive: id -> row -> shard -> host, all pure functions.
    Raises ``ValueError`` for ids outside the scheme."""
    if not player_id or player_id[0] != "p" or not player_id[1:].isdigit():
        raise ValueError(
            f"player id {player_id!r} is not in the fabric's p<row> "
            "scheme; cannot derive an owner host"
        )
    return int(player_id[1:])


@dataclasses.dataclass(frozen=True)
class FabricTopology:
    """One fleet's shape: ``n_shards`` fixed by config (the
    determinism key), ``n_hosts`` fixed by deployment. Shards must be a
    multiple of hosts is NOT required — ownership interleaves — but
    every host must own at least one shard, or it would idle forever."""

    n_shards: int
    n_hosts: int

    def __post_init__(self) -> None:
        if self.n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {self.n_shards}")
        if self.n_hosts < 1:
            raise ValueError(f"n_hosts must be >= 1, got {self.n_hosts}")
        if self.n_hosts > self.n_shards:
            raise ValueError(
                f"{self.n_hosts} hosts but only {self.n_shards} shards — "
                f"host {self.n_shards} would own nothing; raise n_shards "
                "or lower n_hosts"
            )

    def host_of_shard(self, shard: int) -> int:
        return host_of_shard(shard, self.n_hosts)

    def host_of_row(self, row: int) -> int:
        return host_of_row(row, self.n_shards, self.n_hosts)

    def host_of_id(self, player_id: str) -> int:
        return self.host_of_row(row_of_id(player_id))

    def owned_shards(self, host: int) -> tuple[int, ...]:
        return owned_shards(host, self.n_shards, self.n_hosts)

    def owned_partitions(self, host: int) -> tuple[int, ...]:
        return owned_partitions(host, self.n_shards, self.n_hosts)

    def owned_rows(self, host: int, n_players: int) -> list[int]:
        return owned_rows(host, n_players, self.n_shards, self.n_hosts)
