"""Shard-pure matchmaking: the fabric's determinism keystone.

The broker partitions by the FIRST team-A row's shard (``x-partition``);
a host consumes only its owned partitions. If a match could mix rows
from two shards, the ingest routing would have to pick ONE owner and the
other host's rows would be rated remotely — cross-host write traffic,
ordering hazards, topology-dependent bits. The fabric forbids the case
at formation time instead: every match is SHARD-PURE (all ``2t``
participants drawn from one shard), so ``partition_of == shard
ownership`` routes every match to the one host that owns every row it
touches.

Shard-purity is also what makes the deterministic block bit-identical
across host counts: the parent soak driver runs ONE
:class:`ShardMatchmaker` per shard with a per-shard seeded substream
(``SeedSequence(entropy=seed, spawn_key=(3, shard))``) and iterates
shards in a fixed order — the (tick, shard) -> matches map is a pure
function of (seed, config), independent of how many hosts the shards
land on. Within a shard the sampling math is the base
:class:`~analyzer_tpu.loadgen.matchmaker.Matchmaker`'s, applied to the
shard's own Zipf activity ladder over its ``r % S == shard`` rows.
"""

from __future__ import annotations

import numpy as np

from analyzer_tpu.io.synthetic import AliasSampler
from analyzer_tpu.loadgen.matchmaker import RATINGS_PAGE, Matchmaker


class ShardMatchmaker(Matchmaker):
    """A matchmaker whose candidate pool is ONE shard's rows.

    ``sample_rows`` returns GLOBAL row indices (all satisfying
    ``row % n_shards == shard``), so everything downstream — id
    formation, the served-rating sweep, split scoring through the
    routed winprob path — is the base class unchanged. The formation
    stream is the per-shard substream ``spawn_key=(3, shard)``; two
    fabrics with the same (seed, shard) draw identical candidates no
    matter the host count.
    """

    def __init__(
        self,
        players,
        client,
        shard: int,
        n_shards: int,
        seed: int = 0,
        cfg=None,
        activity_concentration: float = 1.2,
        team5_frac: float = 0.3,
        ratings_page: int = RATINGS_PAGE,
    ) -> None:
        super().__init__(
            players,
            client,
            seed=seed,
            cfg=cfg,
            activity_concentration=activity_concentration,
            team5_frac=team5_frac,
            ratings_page=ratings_page,
        )
        if not 0 <= shard < n_shards:
            raise ValueError(f"shard {shard} outside 0..{n_shards - 1}")
        self.shard = int(shard)
        self.n_shards = int(n_shards)
        # The shard's global rows, ascending — the candidate universe.
        self.shard_rows = np.arange(
            shard, players.n_players, n_shards, dtype=np.int64
        )
        if len(self.shard_rows) < 2 * 5:
            raise ValueError(
                f"shard {shard} holds {len(self.shard_rows)} of "
                f"{players.n_players} players; need at least 10 to form a "
                "5v5 — raise n_players or lower n_shards"
            )
        # Replace the base formation stream and sampler with the
        # per-shard substream + the shard's own Zipf activity ladder
        # (shuffled by THIS stream, so "who is the shard's grinder" is a
        # pure function of (seed, shard)).
        self.rng = np.random.default_rng(
            np.random.SeedSequence(entropy=seed, spawn_key=(3, shard))
        )
        ranks = np.arange(1, len(self.shard_rows) + 1, dtype=np.float64)
        weights = 1.0 / ranks**activity_concentration
        self.rng.shuffle(weights)
        self.sampler = AliasSampler(weights / weights.sum())

    def sample_rows(self, k: int, rng=None) -> list[int]:
        """``k`` DISTINCT global rows of THIS shard by activity weight,
        in draw order — the base redraw loop over shard-local draws,
        mapped through ``shard_rows`` to global indices."""
        rng = self.rng if rng is None else rng
        out: dict[int, None] = {}
        while len(out) < k:
            for c in self.sampler.draw(rng, (k,)).tolist():
                if len(out) == k:
                    break
                out.setdefault(int(self.shard_rows[int(c)]), None)
        return list(out)
