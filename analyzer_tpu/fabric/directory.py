"""FabricDirectory: the host-local view of the fleet's version vector.

Each fabric host publishes its owned shards' rows under ONE monotone
per-host version (its ``ViewPublisher`` — every owned shard advances
together, so a reader of that host can never see shard A at version v
and shard B at v-1: the torn cross-shard pair the protocol forbids).
The directory is each process's bookkeeping of the fleet:

    host -> (owned shards, base urls, last observed version, last seen)

It is deliberately NOT a consensus service. Ownership is a pure
function (:mod:`.topology`), so the directory never arbitrates who owns
what — it only tracks which hosts are reachable and how fresh each
host's published version is. A host whose version has not been observed
to advance within ``down_after_s`` is reported down and leaves the read
merge (:mod:`.route`) without wedging readers; it re-enters on its next
observed publish.

Clock discipline (graftlint GL048): the directory is CLOCK-INJECTED
like every obs plane — ``observe``/``lag``/``down_hosts`` take ``now``
from the caller (the worker's clock; under the soak the VirtualClock),
so fabric bookkeeping is exactly as deterministic as its driver.

Thread contract: one writer lock inside; ``vector()``/``snapshot()``
return fresh copies, safe from any thread.
"""

from __future__ import annotations

import dataclasses
import threading

from analyzer_tpu.fabric.topology import FabricTopology
from analyzer_tpu.obs import get_registry


@dataclasses.dataclass
class HostEntry:
    """One host's directory row. ``serve_url``/``control_url`` are None
    for in-process hosts (the follower-adoption read path)."""

    host: int
    shards: tuple[int, ...]
    serve_url: str | None = None
    control_url: str | None = None
    version: int = 0
    last_seen: float | None = None
    down: bool = False


class FabricDirectory:
    """Tracks the fleet's ``(host, shards, version)`` vector.

    ``register`` adds a host (idempotent; shards come from the
    topology, not the caller — ownership is not negotiable).
    ``observe`` records a published version at ``now`` and enforces
    per-host monotonicity: a version that moves backwards is a protocol
    violation (a restarted host must re-register, which resets the
    floor) and raises rather than silently serving a rewound view.
    """

    def __init__(
        self, topology: FabricTopology, down_after_s: float = 10.0
    ) -> None:
        self.topology = topology
        self.down_after_s = float(down_after_s)
        self._lock = threading.Lock()
        self._hosts: dict[int, HostEntry] = {}
        reg = get_registry()
        reg.gauge("fabric.hosts").set(topology.n_hosts)
        self._observe_count = reg.counter("fabric.version_observations_total")

    # -- membership -------------------------------------------------------
    def register(
        self,
        host: int,
        serve_url: str | None = None,
        control_url: str | None = None,
        now: float | None = None,
    ) -> HostEntry:
        """Adds (or re-adds) ``host``. Re-registration resets the
        version floor to 0 — the restart path: a rebuilt host starts a
        fresh monotone sequence."""
        if not 0 <= host < self.topology.n_hosts:
            raise ValueError(
                f"host {host} outside the topology's 0..{self.topology.n_hosts - 1}"
            )
        entry = HostEntry(
            host=host,
            shards=self.topology.owned_shards(host),
            serve_url=serve_url,
            control_url=control_url,
            version=0,
            last_seen=now,
        )
        with self._lock:
            self._hosts[host] = entry
        return entry

    def entry(self, host: int) -> HostEntry:
        with self._lock:
            e = self._hosts.get(host)
        if e is None:
            raise KeyError(f"host {host} is not registered in the directory")
        return e

    def hosts(self) -> list[HostEntry]:
        with self._lock:
            return sorted(self._hosts.values(), key=lambda e: e.host)

    # -- the version vector ------------------------------------------------
    def observe(self, host: int, version: int, now: float) -> None:
        """Records that ``host`` has published ``version`` (observed at
        ``now``, the caller's clock). Monotone per host: a rewind flags
        a protocol violation loudly."""
        with self._lock:
            e = self._hosts.get(host)
            if e is None:
                raise KeyError(
                    f"host {host} observed before register(); the fabric "
                    "registers membership before it routes"
                )
            if version < e.version:
                raise ValueError(
                    f"host {host} version rewound {e.version} -> {version}; "
                    "a restarted host must re-register (directory."
                    "register resets its floor)"
                )
            e.version = int(version)
            e.last_seen = float(now)
            e.down = False
        self._observe_count.add(1)

    def mark_down(self, host: int) -> None:
        """Explicitly removes ``host`` from the read merge (probe
        failure, operator action). It re-enters on the next observe."""
        with self._lock:
            e = self._hosts.get(host)
            if e is not None:
                e.down = True

    def vector(self) -> dict[int, int]:
        """The fleet version vector — one monotone version per host."""
        with self._lock:
            return {h: e.version for h, e in sorted(self._hosts.items())}

    # -- health -----------------------------------------------------------
    def down_hosts(self, now: float) -> list[int]:
        """Hosts currently out of the merge: explicitly marked down, or
        not observed within ``down_after_s`` of ``now``."""
        with self._lock:
            out = []
            for h, e in sorted(self._hosts.items()):
                stale = (
                    e.last_seen is None
                    or now - e.last_seen > self.down_after_s
                )
                if e.down or stale:
                    out.append(h)
            return out

    def alive_hosts(self, now: float) -> list[HostEntry]:
        down = set(self.down_hosts(now))
        return [e for e in self.hosts() if e.host not in down]

    def lag_s(self, now: float) -> dict[int, float | None]:
        """Per-host staleness in caller-clock seconds (None = never
        observed) — what /fleetz renders when one host lags."""
        with self._lock:
            return {
                h: (None if e.last_seen is None else max(0.0, now - e.last_seen))
                for h, e in sorted(self._hosts.items())
            }

    # -- routing ----------------------------------------------------------
    def route_shard(self, shard: int) -> HostEntry:
        return self.entry(self.topology.host_of_shard(shard))

    def route_row(self, row: int) -> HostEntry:
        return self.entry(self.topology.host_of_row(row))

    def route_id(self, player_id: str) -> HostEntry:
        return self.entry(self.topology.host_of_id(player_id))

    # -- introspection -----------------------------------------------------
    def snapshot(self, now: float | None = None) -> dict:
        """The /statusz ``fabric.directory`` block: topology, the
        version vector, per-host freshness and down-ness."""
        down = set(self.down_hosts(now)) if now is not None else set()
        with self._lock:
            return {
                "n_shards": self.topology.n_shards,
                "n_hosts": self.topology.n_hosts,
                "hosts": [
                    {
                        "host": h,
                        "shards": list(e.shards),
                        "version": e.version,
                        "serve_url": e.serve_url,
                        "down": e.down or h in down,
                    }
                    for h, e in sorted(self._hosts.items())
                ],
            }
