"""Multi-host rate fabric: shard-owning worker processes, a
version-consistent cross-host view protocol, and broker-partitioned
ingest (docs/fabric.md).

The single-process analyzer already owns every layer — rating scan,
serve plane, partitioned broker, SLO plane. The fabric is the refactor
that takes "millions of users" from a table size to an actual fleet:

  * **ownership** (:mod:`.topology`) — the serve plane's ``row % S``
    interleaved layout extends one level: shard ``s`` is owned by host
    ``s % H``. Ownership is a pure function of (row, S, H); no lookup
    service, no rebalance protocol, no state.
  * **version vector** (:mod:`.directory`) — each host publishes its
    owned shards' rows under ONE monotone per-host version (its
    ``ViewPublisher``); a host-local :class:`FabricDirectory` tracks
    the fleet's ``(host, shards, version)`` vector. Clock-injected
    (graftlint GL048): every observation takes ``now`` from the caller.
  * **routing** (:mod:`.route`) — point lookups go to the owning host
    over the existing ``/v1/*`` ServePlane surface; leaderboards merge
    per-host top-k candidates with the serve plane's shard-boundary-
    safe ``(-score, global_row)`` tie-break; tier counts sum exactly.
    In-process readers follow a host's lineage by REFERENCE
    (``ViewPublisher.adopt_view`` — the ``cutover_from`` mechanism
    without consuming the source), so a reader never observes a torn
    cross-shard version pair.
  * **ingest** (:class:`~analyzer_tpu.service.broker.PartitionSubscription`)
    — the partitioned broker's ``<queue>.p<k>.{live,backfill}`` layout
    is the transport; each worker consumes ONLY its owned partitions,
    and ``partition_of == shard ownership`` by construction.

``cli fabric`` launches the host processes (:mod:`.process`);
``cli soak --hosts N`` runs the closed-loop soak over the real
subprocess topology (:mod:`.driver`) with a deterministic block that is
bit-identical per (seed, config) across host counts.
"""

from analyzer_tpu.fabric.directory import FabricDirectory, HostEntry
from analyzer_tpu.fabric.driver import FabricSoakConfig, FabricSoakDriver
from analyzer_tpu.fabric.host import FabricHost, FabricHostConfig
from analyzer_tpu.fabric.matchmaker import ShardMatchmaker
from analyzer_tpu.fabric.publish import FabricShardPublisher
from analyzer_tpu.fabric.route import FabricRouter, FollowerPlane
from analyzer_tpu.fabric.topology import (
    FabricTopology,
    host_of_row,
    host_of_shard,
    owned_partitions,
    owned_rows,
    owned_shards,
    row_of_id,
)

__all__ = [
    "FabricDirectory",
    "FabricHost",
    "FabricHostConfig",
    "FabricRouter",
    "FabricShardPublisher",
    "FabricSoakConfig",
    "FabricSoakDriver",
    "FabricTopology",
    "FollowerPlane",
    "HostEntry",
    "ShardMatchmaker",
    "host_of_row",
    "host_of_shard",
    "owned_partitions",
    "owned_rows",
    "owned_shards",
    "row_of_id",
]
