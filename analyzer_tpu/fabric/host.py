"""FabricHost: one shard-owning worker process of the rate fabric.

A fabric host is the full single-worker rig — partitioned broker,
store, sequential :class:`~analyzer_tpu.service.worker.Worker` with the
serve plane and obsd attached — scoped to the shards it owns
(``shard % n_hosts == host``, :mod:`.topology`):

  * its broker is a ``PartitionedBroker`` with one partition per shard,
    consumed through a :class:`~analyzer_tpu.service.broker.
    PartitionSubscription` over the OWNED partitions only — the worker
    never sees another host's traffic (``partition_of == shard
    ownership``);
  * its served view covers exactly the owned population: the host is
    seeded with only its owned players' rows and rates only shard-pure
    matches of its owned shards, so every version it publishes is a
    complete, untorn snapshot of "my players";
  * a control plane (``/fabric/*`` POST routes on the shared
    ``obs/httpd.py`` plumbing — no ad-hoc server, GL024) lets the fabric
    driver seed, warm, feed per-(tick, shard) match groups, and read the
    final table; the existing ``/v1/*`` serve surface answers routed
    queries and obsd feeds the fleet Collector.

Determinism: the host runs on a :class:`~analyzer_tpu.loadgen.shaper.
VirtualClock` the driver advances through ``/fabric/rate`` — a group is
enqueued whole and drained to empty before the call returns, so batch
composition is a pure function of (group, batch_size), identical across
host counts (docs/fabric.md "Bit-identity across topologies").

Clock discipline (graftlint GL048): no wall-clock reads — every ``now``
is the virtual clock.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from analyzer_tpu.config import RatingConfig, ServiceConfig
from analyzer_tpu.fabric.directory import FabricDirectory
from analyzer_tpu.fabric.topology import FabricTopology
from analyzer_tpu.loadgen.shaper import VirtualClock
from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs import get_registry
from analyzer_tpu.obs.httpd import HttpError, RoutedHTTPServer, json_body

logger = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class FabricHostConfig:
    """One host's full parameterization (the subprocess spec,
    ``fabric/process.py``, is this plus file-handshake paths)."""

    host: int
    n_shards: int
    n_hosts: int
    seed: int = 0
    n_players: int = 400
    batch_size: int = 64
    quality: bool = True
    slo_plane: bool = True
    down_after_s: float = 10.0


class FabricHost:
    """The in-process composition: build it directly in tests, or let
    ``fabric/process.py`` wrap it in a subprocess with the ready-file
    handshake. ``close()`` tears down both planes (idempotent)."""

    def __init__(self, config: FabricHostConfig) -> None:
        from analyzer_tpu.io.synthetic import synthetic_players
        from analyzer_tpu.service.broker import (
            PartitionedBroker,
            PartitionSubscription,
        )
        from analyzer_tpu.service.store import InMemoryStore
        from analyzer_tpu.service.worker import Worker

        self.cfg = config
        self.topology = FabricTopology(config.n_shards, config.n_hosts)
        if not 0 <= config.host < config.n_hosts:
            raise ValueError(
                f"host {config.host} outside the topology's "
                f"0..{config.n_hosts - 1}"
            )
        self.host = int(config.host)
        self.owned = self.topology.owned_shards(self.host)
        self.vclock = VirtualClock()
        # One partition per shard — THE fabric layout. The subscription
        # is what the worker consumes: owned partitions only.
        self.broker = PartitionedBroker(partitions=config.n_shards)
        self.subscription = PartitionSubscription(
            self.broker, self.topology.owned_partitions(self.host)
        )
        self.store = InMemoryStore()
        self.rating_config = RatingConfig()
        self.worker = Worker(
            self.subscription,
            self.store,
            ServiceConfig(
                batch_size=config.batch_size, idle_timeout=0.0,
                pipeline=False,
            ),
            self.rating_config,
            clock=self.vclock.monotonic,
            pipeline=False,
            serve_port=0,
            obs_port=0,
            slo_plane=config.slo_plane,
            audit=False,
            quality=config.quality,
        )
        self.players = synthetic_players(config.n_players, seed=config.seed)
        self.directory = FabricDirectory(
            self.topology, down_after_s=config.down_after_s
        )
        self.directory.register(
            self.host, serve_url=self.worker.serve_server.url,
            now=self.vclock.now,
        )
        reg = get_registry()
        reg.gauge("fabric.host_index").set(self.host)
        reg.gauge("fabric.owned_shards").set(len(self.owned))
        self.worker.fabric_info = self._fabric_stats
        self._player_cache: dict[int, object] = {}
        self._retrace_base: float | None = None
        self._closed = False
        # The control plane: POST verbs on the shared httpd plumbing.
        self.control = RoutedHTTPServer(
            routes={
                "/fabric/status": lambda _p: json_body(self.status()),
                "/fabric/table": lambda _p: json_body(self.table()),
            },
            post_routes={
                "/fabric/seed": lambda b: json_body(self.seed_rows(**b)),
                "/fabric/warmup": lambda b: json_body(
                    self.warm(**(b or {}))
                ),
                "/fabric/rate": lambda b: json_body(self.rate_group(**b)),
                "/fabric/finish": lambda _b: json_body(self.finish()),
                "/fabric/burn": lambda b: json_body(self.burn(**b)),
            },
            name=f"fabric-host-{self.host}",
            json_errors=True,
        )

    # -- introspection -----------------------------------------------------
    def _fabric_stats(self) -> dict:
        """The worker's ``stats()['fabric']`` block (and /statusz's):
        membership + the fleet version vector as this host knows it."""
        return {
            "host": self.host,
            "n_hosts": self.topology.n_hosts,
            "n_shards": self.topology.n_shards,
            "shards": list(self.owned),
            "vector": {
                str(h): v for h, v in self.directory.vector().items()
            },
        }

    @property
    def serve_url(self) -> str:
        return self.worker.serve_server.url

    @property
    def control_url(self) -> str:
        return self.control.url

    @property
    def obs_port(self) -> int:
        return self.worker.obs_server.port

    def status(self) -> dict:
        queue = self.worker.config.queue
        return {
            "host": self.host,
            "owned_shards": list(self.owned),
            "version": self.worker.view_publisher.version,
            "matches_rated": self.worker.matches_rated,
            "batches_ok": self.worker.batches_ok,
            "dead_letters": self.worker.dead_letters,
            "queue_depth": (
                self.subscription.qsize(queue) + len(self.worker.queue)
            ),
            "virtual_now": self.vclock.now,
            "directory": self.directory.snapshot(self.vclock.now),
        }

    def table(self) -> dict:
        """The owned population's final rows — ids + packed float32 rows
        (exact through JSON: every float32 is representable as a
        double). The driver reassembles per-host tables into global row
        order for the topology-invariant final-table digest."""
        view = self.worker.view_publisher.current()
        if view is None:
            return {"version": 0, "ids": [], "rows": []}
        host_rows = view.host_table()[: view.n_players]
        return {
            "version": view.version,
            "ids": [view.id_of(r) for r in range(view.n_players)],
            "rows": [
                [float(x) for x in row] for row in np.asarray(host_rows)
            ],
        }

    # -- the driver's verbs ------------------------------------------------
    def seed_rows(self, ids, rows) -> dict:
        """Publishes version 1 over the OWNED seed population. ``ids``
        must all be owned — a foreign id here means the driver sliced
        the population wrong, which would silently tear ownership."""
        for pid in ids:
            owner = self.topology.host_of_id(pid)
            if owner != self.host:
                raise HttpError(
                    400,
                    f"id {pid} belongs to host {owner}, not {self.host}",
                )
        table = np.asarray(rows, np.float32)
        view = self.worker.view_publisher.publish_rows(list(ids), table)
        self.directory.observe(self.host, view.version, self.vclock.now)
        return {"host": self.host, "version": view.version, "n": len(ids)}

    def warm(self, cap_ids: int | None = None) -> dict:
        """The production precompile discipline (SoakDriver.prepare):
        worker + engine warmup, the publisher's patch-bucket ladder, and
        the retrace base the steady-state SLO is measured from."""
        from analyzer_tpu.core.state import MAX_TEAM_SIZE

        self.worker.warmup()
        self.worker.query_engine.warmup()
        self.worker.view_publisher.warm_patch_buckets(
            int(cap_ids)
            if cap_ids is not None
            else self.cfg.batch_size * 2 * MAX_TEAM_SIZE
        )
        self._retrace_base = float(
            get_registry().counter("jax.retraces_total").value
        )
        return {
            "host": self.host,
            "version": self.worker.view_publisher.version,
            "retrace_base": self._retrace_base,
        }

    def _player_obj(self, row: int):
        """One shared duck-typed player object per owned row — the
        worker's write-back updates the priors the next batch loads
        (the same closed loop as SoakDriver._player_obj)."""
        obj = self._player_cache.get(row)
        if obj is None:
            from analyzer_tpu.fixtures import fake_player
            from analyzer_tpu.loadgen.matchmaker import player_id

            p = self.players

            def _opt(x):
                return None if np.isnan(x) else float(x)

            obj = fake_player(
                skill_tier=int(p.skill_tier[row]),
                rank_points_ranked=_opt(p.rank_points_ranked[row]),
                rank_points_blitz=_opt(p.rank_points_blitz[row]),
            )
            obj.api_id = player_id(row)
            self._player_cache[row] = obj
        return obj

    def _build_match(self, spec: dict):
        from analyzer_tpu.fixtures import (
            fake_match,
            fake_participant,
            fake_roster,
        )

        winner = int(spec["winner"])
        afk = bool(spec["afk"])
        rosters = []
        for t, rows in enumerate((spec["a_rows"], spec["b_rows"])):
            parts = [
                fake_participant(
                    player=self._player_obj(int(r)),
                    skill_tier=int(self.players.skill_tier[int(r)]),
                    went_afk=bool(afk and t == 0 and s == 0),
                )
                for s, r in enumerate(rows)
            ]
            rosters.append(
                fake_roster(winner=int(t == winner), participants=parts)
            )
        match = fake_match(spec["mode"], rosters, api_id=spec["id"])
        match.created_at = int(spec["created_at"])
        return match

    def rate_group(self, now, matches, peer_versions=None) -> dict:
        """One (tick, shard) match group: advance the virtual clock to
        the driver's ``now``, enqueue every match (original headers —
        the trace chain's broker hop and the ``x-partition`` routing
        ride them), then poll until the backlog is EMPTY. The drain
        barrier is the bit-identity keystone: batch composition becomes
        a pure function of (group, batch_size), so the rating bits
        cannot depend on how many hosts the shards landed on."""
        if now > self.vclock.now:
            self.vclock.advance(now - self.vclock.now)
        for spec in matches:
            for r in list(spec["a_rows"]) + list(spec["b_rows"]):
                shard = int(r) % self.topology.n_shards
                if self.topology.host_of_shard(shard) != self.host:
                    raise HttpError(
                        400,
                        f"match {spec['id']} touches row {r} of shard "
                        f"{shard}, owned by host "
                        f"{self.topology.host_of_shard(shard)} — the "
                        "fabric only routes shard-pure matches to their "
                        "owner",
                    )
            match = self._build_match(spec)
            self.store.add_match(match)
            self.broker.publish(
                self.worker.config.queue,
                match.api_id.encode(),
                headers=spec.get("headers") or None,
            )
        queue = self.worker.config.queue
        budget = 2 * len(matches) + 50
        while (
            self.subscription.qsize(queue) or self.worker.queue
        ) and budget > 0:
            self.worker.poll()
            budget -= 1
        if self.subscription.qsize(queue) or self.worker.queue:
            raise HttpError(
                503,
                f"host {self.host} could not drain a {len(matches)}-match "
                "group; the fabric's per-group barrier is stuck",
            )
        self.directory.observe(
            self.host, self.worker.view_publisher.version, self.vclock.now
        )
        for h, v in (peer_versions or {}).items():
            h = int(h)
            if h == self.host:
                continue
            try:
                self.directory.entry(h)
            except KeyError:
                self.directory.register(h, now=self.vclock.now)
            self.directory.observe(h, int(v), self.vclock.now)
        return {
            "host": self.host,
            "version": self.worker.view_publisher.version,
            "matches_rated": self.worker.matches_rated,
            "batches_ok": self.worker.batches_ok,
            "dead_letters": self.worker.dead_letters,
        }

    def burn(self, count: int = 1) -> dict:
        """The injected-burn hook (fleet SLO attribution tests): dead
        letters appear on THIS host only, strictly between two of the
        parent Collector's scrapes."""
        get_registry().counter("worker.dead_letters_total").add(int(count))
        return {"host": self.host, "burned": int(count)}

    def finish(self) -> dict:
        """End-of-run accounting: flushes the audit backlog (when armed)
        and reports the per-host deterministic counters plus the
        steady-state retrace delta the fleet SLO gates on."""
        if self.worker.auditor is not None:
            self.worker.auditor.drain()
        retraces = float(
            get_registry().counter("jax.retraces_total").value
        )
        return {
            "host": self.host,
            "version": self.worker.view_publisher.version,
            "matches_rated": self.worker.matches_rated,
            "batches_ok": self.worker.batches_ok,
            "dead_letters": self.worker.dead_letters,
            "retraces_steady": (
                retraces - self._retrace_base
                if self._retrace_base is not None else 0.0
            ),
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.control.close()
        self.worker.close()
