"""Fabric host subprocess entry: ``python -m analyzer_tpu.fabric.process
spec.json``.

One invocation = one shard-owning host of the fabric. The spec (JSON,
argv[1]) is a :class:`~analyzer_tpu.fabric.host.FabricHostConfig` plus
the file handshake the fleet tests established (tests/fleet_worker.py):

  * ``ready_file`` — written atomically (tmp + rename) once the host's
    three listeners are up, carrying the bound ports/urls the parent
    needs: ``{"host", "serve_url", "control_url", "obs_port", "pid"}``;
  * ``exit_file`` — the parent touches it to end the process; until
    then the host keeps serving ``/v1/*``, ``/fabric/*`` and obsd;
  * ``trace`` — arms causal tracing before the worker builds (both the
    env var and the live flag: a ``-m`` launch imports the package —
    and the obs modules — before the spec is read);
  * ``trace_out`` — dump the host's chrome trace there on exit, so the
    parent can ``load_forest`` it with its own (host label = basename);
  * ``platform`` — ``"cpu"`` (default) re-pins jax onto CPU in the
    child, mirroring conftest.py's harness discipline.

The lifetime loop below reads the wall clock: a subprocess's liveness
deadline is inherently wall-shaped (the parent that feeds it virtual
time may have died), exactly like the fleet worker template. Every
DECISION inside the host stays on the virtual clock (GL048).
"""

import json
import os
import sys
import time


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    with open(args[0], encoding="utf-8") as f:
        spec = json.load(f)
    if spec.get("trace"):
        # ``-m`` runs import the fabric package (and with it the obs
        # modules) before this line — the env var alone is too late, so
        # flip the process-wide flag through the API as well.
        os.environ["ANALYZER_TPU_TRACE"] = "1"
        from analyzer_tpu.obs import tracectx

        tracectx.enable_tracing(True)
    if spec.get("platform", "cpu") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    from analyzer_tpu.fabric.host import FabricHost, FabricHostConfig

    cfg = FabricHostConfig(
        host=spec["host"],
        n_shards=spec["n_shards"],
        n_hosts=spec["n_hosts"],
        seed=spec.get("seed", 0),
        n_players=spec.get("n_players", 400),
        batch_size=spec.get("batch_size", 64),
        quality=spec.get("quality", True),
        slo_plane=spec.get("slo_plane", True),
    )
    host = FabricHost(cfg)
    tmp = spec["ready_file"] + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(
            {
                "host": host.host,
                "serve_url": host.serve_url,
                "control_url": host.control_url,
                "obs_port": host.obs_port,
                "pid": os.getpid(),
            },
            f,
        )
    os.replace(tmp, spec["ready_file"])
    deadline = time.time() + float(spec.get("max_wall_s", 600.0))  # graftlint: disable=GL048 — subprocess liveness deadline, wall-shaped by nature
    while time.time() < deadline and not os.path.exists(spec["exit_file"]):  # graftlint: disable=GL048 — subprocess liveness poll, wall-shaped by nature
        time.sleep(0.05)  # graftlint: disable=GL048 — idle wait for the parent's exit signal
    if spec.get("trace_out"):
        from analyzer_tpu.obs.snapshot import write_chrome_trace

        write_chrome_trace(spec["trace_out"])
    host.close()


if __name__ == "__main__":
    main()
