"""Cross-host query routing: the fabric's read plane.

:class:`FabricRouter` gives callers the same query surface as a
``ServePlane`` — ratings, winprob, leaderboard, tiers, percentile —
over a FLEET of shard-owning hosts:

  * **point lookups** route to the owning host (pure function of the
    id — :mod:`.topology`) over the existing ``/v1/*`` ServePlane HTTP
    surface; nothing new to operate, nothing the single-host plane
    doesn't already serve.
  * **leaderboards** merge per-host top-k candidates with THE serving
    plane's boundary-safe tie-break
    (:func:`analyzer_tpu.serve.engine.merge_topk_candidates` —
    ``(-score, global_row)``), so ties spanning host boundaries land
    exactly where the single-plane engine puts them: merged responses
    are bit-identical to a single plane over the union table.
  * **tier histograms / percentiles** sum per-host INTEGER partial
    counts — exact, order-free.
  * each host's response is computed against ONE of its published
    versions (its ``ViewPublisher``'s atomic snapshot), so a reader
    never observes a torn cross-shard pair within a host; the merged
    response reports the per-host versions it combined (``versions``),
    and :meth:`FabricRouter.strip_versions` removes version keys for
    topology-invariant digests (per-host version counters depend on H;
    the rating bits do not).

A host the directory reports down LEAVES the merge — leaderboards and
tiers keep answering from the live hosts — while point lookups to it
fail loudly (the owner is the only process with the rows; a made-up
answer would be worse than an error).

:class:`FollowerPlane` is the in-process read replica: a private
``ViewPublisher`` that ADOPTS a leader lineage's published views by
reference (``ViewPublisher.adopt_view`` — the ``cutover_from``
mechanism without consuming the source) plus a ``QueryEngine`` ticking
over it. Same-process readers scale without re-keying or copying a
table.

Clock discipline (graftlint GL048): this module never reads a wall
clock — latency observation and down-host staleness take the caller's
injected ``clock``/``now``.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse

import numpy as np

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.obs import get_registry
from analyzer_tpu.obs.httpd import PooledHTTPClient
from analyzer_tpu.serve.engine import (
    QueryEngine,
    UnknownPlayerError,
    _finish_quality,
    _finish_winprob,
    merge_topk_candidates,
)
from analyzer_tpu.serve.view import ViewPublisher

from analyzer_tpu.fabric.directory import FabricDirectory
from analyzer_tpu.fabric.topology import row_of_id


class HttpHostClient:
    """One host's ``/v1/*`` surface as a client (an HTTP *client* — the
    listening sockets stay in serve/ + obs/, graftlint GL024). Rides
    one pooled keep-alive connection
    (:class:`~analyzer_tpu.obs.httpd.PooledHTTPClient`) instead of a
    TCP handshake per lookup; the pool's urlopen-compatible errors keep
    the router's mark-down semantics unchanged."""

    def __init__(self, base_url: str, timeout_s: float = 30.0) -> None:
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.pool = PooledHTTPClient(self.base_url, timeout_s=timeout_s)

    def _get(self, path: str, params: dict | None = None) -> dict:
        if params:
            path += "?" + urllib.parse.urlencode(params)
        return json.loads(self.pool.get(path).decode("utf-8"))

    def get_ratings(self, ids) -> dict:
        return self._get("/v1/ratings", {"ids": ",".join(ids)})

    def win_probability(self, team_a, team_b) -> dict:
        return self._get(
            "/v1/winprob", {"a": ",".join(team_a), "b": ",".join(team_b)}
        )

    def leaderboard(self, k: int) -> dict:
        return self._get("/v1/leaderboard", {"k": str(k)})

    def tier_histogram(self) -> dict:
        return self._get("/v1/tiers")

    def percentile(self, score: float) -> dict:
        # /v1/tiers?score folds the percentile keys into the tiers body.
        out = self._get("/v1/tiers", {"score": repr(float(score))})
        return {
            "version": out["version"],
            "score": out["score"],
            "below": out["below"],
            "rated": out["rated"],
            "percentile": out["percentile"],
        }


class EngineHostClient:
    """One host's plane called in-process (unit tests, follower
    planes) — same method surface as :class:`HttpHostClient`."""

    def __init__(self, plane) -> None:
        self.plane = plane

    def get_ratings(self, ids) -> dict:
        return self.plane.get_ratings(ids)

    def win_probability(self, team_a, team_b) -> dict:
        return self.plane.win_probability(team_a, team_b)

    def leaderboard(self, k: int) -> dict:
        return self.plane.leaderboard(k)

    def tier_histogram(self) -> dict:
        return self.plane.tier_histogram()

    def percentile(self, score: float) -> dict:
        return self.plane.percentile(score)


class HostDownError(RuntimeError):
    """The owning host for a point lookup is out of the fleet — only it
    has the rows, so the router fails loudly instead of guessing."""


class FabricRouter:
    """Fans queries across the fleet and merges bit-identically.

    ``clients`` maps host index -> a host client; hosts registered in
    the directory with a ``serve_url`` get an :class:`HttpHostClient`
    built lazily when not supplied. ``clock`` (injected — GL048) is
    used for down-host staleness (``directory.down_hosts(now)``) and
    remote-lookup latency observation; with ``clock=None`` only hosts
    explicitly marked down leave the merge and latency goes unobserved.
    """

    def __init__(
        self,
        directory: FabricDirectory,
        clients: dict[int, object] | None = None,
        cfg: RatingConfig | None = None,
        clock=None,
    ) -> None:
        self.directory = directory
        self.cfg = cfg or RatingConfig()
        self.clock = clock
        self.calls: dict[str, int] = {}
        self._clients: dict[int, object] = dict(clients or {})
        reg = get_registry()
        self._lookups = reg.counter("fabric.remote_lookups_total")
        self._errors = reg.counter("fabric.remote_errors_total")

    # -- plumbing ---------------------------------------------------------
    def client_of(self, host: int):
        c = self._clients.get(host)
        if c is None:
            entry = self.directory.entry(host)
            if entry.serve_url is None:
                raise KeyError(
                    f"host {host} has no client and no serve_url in the "
                    "directory"
                )
            c = HttpHostClient(entry.serve_url)
            self._clients[host] = c
        return c

    def _now(self):
        return self.clock() if self.clock is not None else None

    def _down(self) -> set[int]:
        now = self._now()
        if now is None:
            return {e.host for e in self.directory.hosts() if e.down}
        return set(self.directory.down_hosts(now))

    def _call(self, host: int, kind: str, fn):
        """One routed call: counts it, observes latency on the injected
        clock, converts transport failures into a down mark + error."""
        self.calls[kind] = self.calls.get(kind, 0) + 1
        self._lookups.add(1)
        t0 = self._now()
        try:
            out = fn()
        except (OSError, urllib.error.URLError) as err:
            self._errors.add(1)
            self.directory.mark_down(host)
            raise HostDownError(
                f"host {host} failed a {kind} call: {err}"
            ) from err
        if t0 is not None:
            get_registry().histogram(
                "fabric.remote_lookup_ms", peer=str(host)
            ).observe((self.clock() - t0) * 1e3)
        return out

    @staticmethod
    def strip_versions(resp: dict) -> dict:
        """The topology-invariant body: version counters depend on the
        host count (each host runs its own monotone sequence), the
        rating bits do not — deterministic-block digests hash THIS."""
        return {
            k: v for k, v in resp.items() if k not in ("version", "versions")
        }

    def version_vector(self) -> dict[int, int]:
        return self.directory.vector()

    # -- point lookups ----------------------------------------------------
    def get_ratings(self, player_ids) -> dict:
        """Splits the ids by owning host (input order preserved in the
        merged response), one routed ``/v1/ratings`` call per owner.
        Ids outside the fabric's ``p<row>`` scheme are unknown by
        construction — no host can own them."""
        topo = self.directory.topology
        per: dict[int, list[str]] = {}
        owner: list[int | None] = []
        for pid in player_ids:
            try:
                h = topo.host_of_id(pid)
            except ValueError:
                owner.append(None)
                continue
            owner.append(h)
            per.setdefault(h, []).append(pid)
        versions: dict[str, int] = {}
        ratings_iter: dict[int, object] = {}
        unknown_of: dict[int, set] = {}
        for h, ids in sorted(per.items()):
            resp = self._call(
                h, "ratings", lambda c=self.client_of(h), i=ids: c.get_ratings(i)
            )
            versions[str(h)] = resp["version"]
            ratings_iter[h] = iter(resp["ratings"])
            unknown_of[h] = set(resp["unknown"])
        out, unknown = [], []
        for pid, h in zip(player_ids, owner):
            if h is None or pid in unknown_of[h]:
                unknown.append(pid)
            else:
                out.append(next(ratings_iter[h]))
        return {"versions": versions, "ratings": out, "unknown": unknown}

    def win_probability(self, team_a, team_b) -> dict:
        """Shard-pure matchups (every participant one host — the fabric
        matchmaker's invariant) route WHOLE to the owner: one call, one
        version. A cross-host matchup gathers each side's rows from the
        owners and replays the kernel's fixed-order float32 reduction on
        host (the sharded engine's own mechanism, one level up) — same
        bits as a single plane holding the union table."""
        owners = set()
        for pid in list(team_a) + list(team_b):
            try:
                owners.add(self.directory.topology.host_of_id(pid))
            except ValueError as err:
                raise UnknownPlayerError([pid]) from err
        if len(owners) == 1:
            h = owners.pop()
            resp = self._call(
                h, "winprob",
                lambda c=self.client_of(h): c.win_probability(team_a, team_b),
            )
            return {
                "versions": {str(h): resp["version"]},
                "p_a": resp["p_a"],
                "quality": resp["quality"],
            }
        merged = self.get_ratings(list(team_a) + list(team_b))
        if merged["unknown"]:
            raise UnknownPlayerError(merged["unknown"])
        rows = merged["ratings"]
        ra, rb = rows[: len(team_a)], rows[len(team_a):]
        one = np.float32(1.0)
        acc_n = np.float32(0.0)
        acc_s2 = np.float32(0.0)
        team_mu = [np.float32(0.0), np.float32(0.0)]
        for t, team in enumerate((ra, rb)):
            for r in team:
                if r["rated"]:
                    mu, sg = np.float32(r["mu"]), np.float32(r["sigma"])
                else:
                    mu = np.float32(r["seed_mu"])
                    sg = np.float32(r["seed_sigma"])
                acc_n = np.float32(acc_n + one)
                acc_s2 = np.float32(acc_s2 + np.float32(sg * sg))
                team_mu[t] = np.float32(team_mu[t] + mu)
        n = np.array([acc_n], np.float32)
        s2 = np.array([acc_s2], np.float32)
        mu_diff = np.array([np.float32(team_mu[0] - team_mu[1])], np.float32)
        beta2 = self.cfg.beta2
        return {
            "versions": merged["versions"],
            "p_a": float(_finish_winprob(n, s2, mu_diff, beta2)[0]),
            "quality": float(_finish_quality(n, s2, mu_diff, beta2)[0]),
        }

    # -- fleet merges -----------------------------------------------------
    def _alive(self) -> list[int]:
        down = self._down()
        hosts = [e.host for e in self.directory.hosts() if e.host not in down]
        if not hosts:
            raise HostDownError("every fabric host is down; nothing to merge")
        return hosts

    def leaderboard(self, k: int = 10) -> dict:
        """Per-host top-k + the plane's pinned ``(-score, global_row)``
        merge. Each host's list covers exactly its owned population (a
        host publishes only its owned players), so the union of per-host
        top-k always contains the global top-k — the merged response is
        bit-identical to a single plane over the whole table."""
        versions: dict[str, int] = {}
        entries = []
        for h in self._alive():
            try:
                resp = self._call(
                    h, "leaderboard",
                    lambda c=self.client_of(h): c.leaderboard(k),
                )
            except HostDownError:
                continue  # dropped mid-merge: serve from the rest
            versions[str(h)] = resp["version"]
            for row in resp["leaders"]:
                entries.append(
                    (row["conservative"], row_of_id(row["id"]), row)
                )
        leaders = []
        for rank, (_s, _r, row) in enumerate(merge_topk_candidates(entries, k)):
            leaders.append({**row, "rank": rank + 1})
        return {"versions": versions, "leaders": leaders}

    def tier_histogram(self) -> dict:
        versions: dict[str, int] = {}
        counts = None
        edges = None
        rated = 0
        for h in self._alive():
            try:
                resp = self._call(
                    h, "tiers",
                    lambda c=self.client_of(h): c.tier_histogram(),
                )
            except HostDownError:
                continue
            versions[str(h)] = resp["version"]
            if edges is None:
                edges = resp["edges"]
                counts = list(resp["counts"])
            else:
                if resp["edges"] != edges:
                    raise ValueError(
                        f"host {h} tiers on different edges; the fleet "
                        "must share one tier ladder to merge counts"
                    )
                counts = [a + b for a, b in zip(counts, resp["counts"])]
            rated += resp["rated"]
        if edges is None:
            raise HostDownError("no host answered the tiers merge")
        return {
            "versions": versions, "edges": edges, "counts": counts,
            "rated": rated,
        }

    def percentile(self, score: float) -> dict:
        versions: dict[str, int] = {}
        below = 0
        rated = 0
        value = None
        for h in self._alive():
            try:
                resp = self._call(
                    h, "percentile",
                    lambda c=self.client_of(h): c.percentile(score),
                )
            except HostDownError:
                continue
            versions[str(h)] = resp["version"]
            below += resp["below"]
            rated += resp["rated"]
            value = resp["score"]
        if value is None:
            raise HostDownError("no host answered the percentile merge")
        return {
            "versions": versions,
            "score": value,
            "below": below,
            "rated": rated,
            "percentile": (below / rated) if rated else None,
        }


class FollowerPlane:
    """An in-process read replica of one host's serve lineage.

    The follower's private ``ViewPublisher`` adopts the leader's
    published views BY REFERENCE (:meth:`ViewPublisher.adopt_view`) —
    zero copy, zero re-keying, version numbers tracking the leader's
    monotone sequence — and a standard ``QueryEngine`` microbatches over
    it. ``refresh()`` is the poll point; callers decide the cadence
    (the staleness bound is the refresh interval plus the leader's
    publish throttle — docs/fabric.md)."""

    def __init__(
        self,
        leader,
        cfg: RatingConfig | None = None,
        max_batch: int = 256,
        clock=None,
    ) -> None:
        self.leader = leader
        self.publisher = ViewPublisher(min_publish_interval_s=0.0)
        kw = {} if clock is None else {"clock": clock}
        self.engine = QueryEngine(
            self.publisher, cfg=cfg, max_batch=max_batch, **kw
        )

    def refresh(self) -> bool:
        """Adopts the leader's current view when it is new. Returns
        True when the follower advanced."""
        view = self.leader.current()
        if view is None:
            return False
        return self.publisher.adopt_view(view)

    @property
    def version(self) -> int:
        return self.publisher.version

    def start(self) -> "FollowerPlane":
        self.refresh()
        self.engine.start()
        return self

    def close(self) -> None:
        self.engine.close()
