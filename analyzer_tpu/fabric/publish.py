"""FabricShardPublisher: per-host shard publishing over the fabric
protocol.

The mesh runner (``parallel/mesh.py rate_history_sharded``) publishes
every shard's dirty rows through one ``ShardedViewPublisher``. That is
correct single-process; on a multi-host mesh each process only sees its
own shards' blocks, so a raw publisher would tear the view. The fabric
answer: wrap the publisher so each host publishes ONLY its owned
shards' patches (``shard % H`` — :mod:`.topology`) under its own
monotone version, and record every publish in the
:class:`~analyzer_tpu.fabric.directory.FabricDirectory` so readers
route around staleness instead of reading torn state.

The wrapper is also the per-owner staging seam for the sharded
backfill: ``migrate.LineageManager.begin_fabric`` wraps its staging
lineage in one of these, so a fabric host's re-rate publishes a
staging lineage scoped to the rows it owns (docs/fabric.md).

Clock discipline (GL048): version observations take ``now`` from the
caller's clock, injected at construction — this module never reads a
wall clock.
"""

from __future__ import annotations


class FabricShardPublisher:
    """Owned-shard filter + directory recording around a
    ``ShardedViewPublisher`` (or anything with its publish surface).

    ``clock`` is the owning worker's injected clock (the soak's
    VirtualClock, ``time.monotonic`` in production workers) — passed in
    so directory observations stay on the caller's timeline.
    """

    def __init__(self, directory, host: int, inner, clock=None) -> None:
        topo = directory.topology
        if inner.n_shards != topo.n_shards:
            raise ValueError(
                f"publisher has {inner.n_shards} shards but the fabric "
                f"topology says {topo.n_shards}; the two must agree or "
                "ownership filtering drops real patches"
            )
        self.directory = directory
        self.host = int(host)
        self.inner = inner
        self.clock = clock
        self.owned = frozenset(topo.owned_shards(self.host))

    # -- the publish surface the mesh runner drives -----------------------
    @property
    def n_shards(self) -> int:
        return self.inner.n_shards

    @property
    def version(self) -> int:
        return self.inner.version

    def current(self):
        return self.inner.current()

    def due(self) -> bool:
        return self.inner.due()

    def warm_patch_buckets(self, cap_ids: int) -> int:
        return self.inner.warm_patch_buckets(cap_ids)

    def publish_shard_patches(self, patches, n_players, blocks_thunk):
        """The fabric filter: non-owned shards' patches are emptied (an
        empty rows_idx is the publisher's own no-op encoding), owned
        shards pass through untouched, and the resulting version lands
        in the directory. The inner publisher still advances ONE
        version for all its shards — per-host atomicity is exactly what
        keeps cross-shard reads untorn on this host."""
        import numpy as np

        filtered = []
        for shard, (rows_idx, rows) in enumerate(patches):
            if shard in self.owned:
                filtered.append((rows_idx, rows))
            else:
                filtered.append((
                    np.empty(0, np.int64),
                    np.empty((0, rows.shape[1] if rows.ndim == 2 else 16),
                             np.float32),
                ))
        view = self.inner.publish_shard_patches(
            filtered, n_players, blocks_thunk
        )
        self._record()
        return view

    def _record(self) -> None:
        now = self.clock() if self.clock is not None else 0.0
        try:
            self.directory.entry(self.host)
        except KeyError:
            self.directory.register(self.host, now=now)
        self.directory.observe(self.host, self.inner.version, now)
