"""FabricSoakDriver: the multi-host closed-loop soak over a real
subprocess fabric.

The single-process :class:`~analyzer_tpu.loadgen.driver.SoakDriver`
re-shaped around shard-owning worker PROCESSES:

  * the parent owns formation: one :class:`~analyzer_tpu.fabric.
    matchmaker.ShardMatchmaker` per shard (per-shard seeded substreams,
    shard-pure matches) plus ONE outcome model and ONE driver stream,
    consumed in a fixed shard order — the (tick, shard) -> matches map
    is a pure function of (seed, config), independent of the host
    count;
  * each host is a :mod:`~analyzer_tpu.fabric.process` subprocess: a
    ``PartitionedBroker`` consumed through its owned partitions, a
    sequential worker on a virtual clock the parent advances through
    ``/fabric/rate``, the ``/v1/*`` serve plane, and obsd for the
    fleet Collector;
  * each (tick, shard) group is posted to the owning host and DRAINED
    before the next group — the barrier that makes batch composition
    (and therefore every rating bit) topology-invariant;
  * the query workload runs through the :class:`~analyzer_tpu.fabric.
    route.FabricRouter` (point lookups to owners, merged leaderboards/
    tiers), digesting version-stripped responses;
  * a fleet :class:`~analyzer_tpu.obs.federate.Collector` scrapes every
    host's obsd each tick (on the VIRTUAL clock) and evaluates
    ``STANDARD_OBJECTIVES`` at fleet scope with per-host attribution.

Headline contract (docs/fabric.md, pinned by tests/test_fabric_fleet.
py): the artifact's ``deterministic`` block — match digest, query
digest, final-table digest, counters — is BIT-IDENTICAL per (seed,
config) across ``n_hosts`` ∈ {1, 2, 4}; ``n_hosts=1`` is the
single-plane oracle topology.

Wall-clock reads below are each explicitly disabled for GL048: they
are subprocess liveness (a child that never writes its ready file) or
the measured block (latencies, wall throughput) — never decision
inputs on the deterministic path, exactly the loadgen discipline.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

import numpy as np

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.fabric.directory import FabricDirectory
from analyzer_tpu.fabric.matchmaker import ShardMatchmaker
from analyzer_tpu.fabric.route import FabricRouter
from analyzer_tpu.fabric.topology import FabricTopology, row_of_id
from analyzer_tpu.loadgen.driver import LEADERBOARD_K, QUERY_RATINGS_IDS
from analyzer_tpu.loadgen.matchmaker import HttpServeClient, player_id
from analyzer_tpu.loadgen.outcomes import OutcomeModel
from analyzer_tpu.loadgen.shaper import (
    DEFAULT_QUERY_MIX,
    TrafficShaper,
    VirtualClock,
    choose_kind,
)
from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs import get_registry
from analyzer_tpu.obs.tracectx import (
    enable_tracing,
    headers as trace_headers,
    mint as trace_mint,
    tracing_enabled,
)

logger = get_logger(__name__)

#: Wall budget for a child to come up and write its ready file.
READY_TIMEOUT_S = 180.0


@dataclasses.dataclass(frozen=True)
class FabricSoakConfig:
    """One fabric soak's full parameterization. Defaults are a CPU
    smoke fabric — a few seconds, tier-1 safe. The deterministic block
    is bit-identical per (seed, config-minus-n_hosts): ``n_hosts`` is
    the topology knob the contract quantifies over."""

    seed: int = 0
    duration_s: float = 6.0
    tick_s: float = 1.0
    qps: float = 16.0
    query_qps: float = 8.0
    n_players: int = 240
    batch_size: int = 32
    n_shards: int = 4
    n_hosts: int = 2
    team5_frac: float = 0.3
    afk_rate: float = 0.0
    activity_concentration: float = 1.2
    warmup: bool = True
    trace: bool = False
    quality: bool = True
    slo_plane: bool = True
    scrape: bool = True  # fleet Collector over the hosts' obsd planes
    down_after_s: float = 10.0  # virtual seconds before a host is down
    max_view_lag_ticks: int = 2
    child_max_wall_s: float = 900.0

    @property
    def n_ticks(self) -> int:
        return max(1, int(round(self.duration_s / self.tick_s)))


def _post_json(url: str, obj, timeout_s: float = 300.0) -> dict:
    """One control-plane POST (JSON in, JSON out). Raises on non-200 —
    a failed control verb is a broken fabric, never a silent skip."""
    req = urllib.request.Request(
        url,
        data=json.dumps(obj).encode("utf-8"),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _get_json(url: str, timeout_s: float = 300.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def spawn_fabric_hosts(
    base_spec: dict, tmpdir: str, exit_file: str
) -> list[dict]:
    """Spawns ``base_spec["n_hosts"]`` :mod:`~analyzer_tpu.fabric.
    process` children with the ready/exit file handshake and blocks
    until every child published its bound urls. Shared by the soak
    driver and ``cli fabric``. Each returned host dict carries the
    child's ready info (``serve_url``/``control_url``/``obs_port``)
    plus ``proc``/``log``/``log_path`` for reaping.

    Raises ``RuntimeError`` when a child dies or stalls during
    bring-up — the caller still owns the SURVIVING children, so it
    must signal ``exit_file`` and reap on the way out."""
    import analyzer_tpu

    repo = os.path.dirname(
        os.path.dirname(os.path.abspath(analyzer_tpu.__file__))
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    hosts: list[dict] = []
    for h in range(int(base_spec["n_hosts"])):
        ready = os.path.join(tmpdir, f"ready-{h}.json")
        spec = dict(
            base_spec, host=h, ready_file=ready, exit_file=exit_file
        )
        spec_path = os.path.join(tmpdir, f"spec-{h}.json")
        with open(spec_path, "w", encoding="utf-8") as f:
            json.dump(spec, f)
        log_path = os.path.join(tmpdir, f"host-{h}.log")
        log = open(log_path, "w", encoding="utf-8")  # noqa: SIM115 — lives with the child
        proc = subprocess.Popen(
            [sys.executable, "-m", "analyzer_tpu.fabric.process",
             spec_path],
            env=env,
            stdout=log,
            stderr=subprocess.STDOUT,
        )
        hosts.append({
            "host": h, "proc": proc, "ready_file": ready,
            "log_path": log_path, "log": log,
        })
    deadline = time.monotonic() + READY_TIMEOUT_S  # graftlint: disable=GL048 — subprocess bring-up deadline, wall-shaped by nature
    for h in hosts:
        while not os.path.exists(h["ready_file"]):
            if h["proc"].poll() is not None:
                raise RuntimeError(
                    f"fabric host {h['host']} exited rc="
                    f"{h['proc'].returncode} before ready; see "
                    f"{h['log_path']}"
                )
            if time.monotonic() > deadline:  # graftlint: disable=GL048 — subprocess bring-up deadline, wall-shaped by nature
                raise RuntimeError(
                    f"fabric host {h['host']} not ready within "
                    f"{READY_TIMEOUT_S}s; see {h['log_path']}"
                )
            time.sleep(0.05)  # graftlint: disable=GL048 — bring-up poll interval, wall-shaped by nature
        with open(h["ready_file"], encoding="utf-8") as f:
            h.update(json.load(f))
    return hosts


class FabricSoakDriver:
    """Spawns the host topology, runs the soak, returns the artifact.

    ``close()`` signals the children to exit and reaps them
    (idempotent; ``run`` does not close, so a test can query the live
    fabric afterwards)."""

    def __init__(self, config: FabricSoakConfig | None = None) -> None:
        from analyzer_tpu.io.synthetic import synthetic_players

        self.cfg = cfg = config or FabricSoakConfig()
        self.topology = FabricTopology(cfg.n_shards, cfg.n_hosts)
        self._trace_prev: bool | None = None
        if cfg.trace and not tracing_enabled():
            self._trace_prev = False
            enable_tracing(True)
        self.vclock = VirtualClock()
        self.rating_config = RatingConfig()
        self.players = synthetic_players(cfg.n_players, seed=cfg.seed)
        self.outcomes = OutcomeModel(
            self.players, self.rating_config, seed=cfg.seed
        )
        # Streams: (2,) drives afk flags + query draws (the SoakDriver
        # convention), (4,) assigns each formed match's shard — all
        # consumed in fixed orders, so every draw sequence is
        # topology-invariant.
        self.qrng = np.random.default_rng(
            np.random.SeedSequence(entropy=cfg.seed, spawn_key=(2,))
        )
        self.frng = np.random.default_rng(
            np.random.SeedSequence(entropy=cfg.seed, spawn_key=(4,))
        )
        self._seq = 0
        self._match_digest = hashlib.sha256()
        self._query_digest = hashlib.sha256()
        self._closed = False
        self._tmp = tempfile.TemporaryDirectory(prefix="fabric-soak-")
        self._exit_file = os.path.join(self._tmp.name, "exit")
        self.hosts: list[dict] = []
        self._spawn_hosts()
        self.directory = FabricDirectory(
            self.topology, down_after_s=cfg.down_after_s
        )
        for h in self.hosts:
            self.directory.register(
                h["host"], serve_url=h["serve_url"],
                control_url=h["control_url"], now=self.vclock.now,
            )
        self.router = FabricRouter(
            self.directory, cfg=self.rating_config,
            clock=self.vclock.monotonic,
        )
        self.matchmakers = [
            ShardMatchmaker(
                self.players,
                HttpServeClient(
                    self.hosts[self.topology.host_of_shard(s)]["serve_url"]
                ),
                s,
                cfg.n_shards,
                seed=cfg.seed,
                cfg=self.rating_config,
                activity_concentration=cfg.activity_concentration,
                team5_frac=cfg.team5_frac,
            )
            for s in range(cfg.n_shards)
        ]
        self.collector = None
        if cfg.scrape:
            from analyzer_tpu.obs.federate import Collector

            self.collector = Collector(
                targets=[f"127.0.0.1:{h['obs_port']}" for h in self.hosts],
            )

    # -- topology bring-up -------------------------------------------------
    def _spawn_hosts(self) -> None:
        cfg = self.cfg
        base_spec = {
            "n_shards": cfg.n_shards,
            "n_hosts": cfg.n_hosts,
            "seed": cfg.seed,
            "n_players": cfg.n_players,
            "batch_size": cfg.batch_size,
            "quality": cfg.quality,
            "slo_plane": cfg.slo_plane,
            "trace": cfg.trace,
            "max_wall_s": cfg.child_max_wall_s,
        }
        self.hosts.extend(
            spawn_fabric_hosts(base_spec, self._tmp.name, self._exit_file)
        )

    # -- rig preparation ---------------------------------------------------
    def prepare(self) -> None:
        """Seeds every host with its OWNED slice of the version-1
        population (global-row order within each host — on the 1-host
        oracle the view's local rows ARE the global rows) and runs the
        per-host precompile discipline."""
        from analyzer_tpu.core.state import MAX_TEAM_SIZE, PlayerState

        cfg = self.cfg
        state = PlayerState.create(
            cfg.n_players,
            rank_points_ranked=self.players.rank_points_ranked,
            rank_points_blitz=self.players.rank_points_blitz,
            skill_tier=self.players.skill_tier,
            cfg=self.rating_config,
        )
        rows = np.asarray(state.table)[: cfg.n_players]
        for h in self.hosts:
            owned = [
                r for r in range(cfg.n_players)
                if self.topology.host_of_row(r) == h["host"]
            ]
            resp = _post_json(
                h["control_url"] + "/fabric/seed",
                {
                    "ids": [player_id(r) for r in owned],
                    "rows": [[float(x) for x in rows[r]] for r in owned],
                },
            )
            self.directory.observe(
                h["host"], resp["version"], self.vclock.now
            )
        if cfg.warmup:
            for h in self.hosts:
                _post_json(
                    h["control_url"] + "/fabric/warmup",
                    {"cap_ids": cfg.batch_size * 2 * MAX_TEAM_SIZE},
                )

    # -- formation ---------------------------------------------------------
    def _form_specs(self, shard: int, k: int) -> list[dict]:
        """``k`` shard-pure match specs for ``shard``: formation off the
        shard's own substream, outcomes + afk off the shared streams in
        this fixed call order, digest folded exactly like the
        single-process soak."""
        if k <= 0:
            return []
        specs = []
        for m in self.matchmakers[shard].form(k):
            winner, p_model = self.outcomes.resolve(
                m.team_a_rows, m.team_b_rows
            )
            afk = bool(self.qrng.random() < self.cfg.afk_rate)
            mid = f"fab-{self._seq:08d}"
            ctx = trace_mint(mid)
            headers = dict(trace_headers(ctx) or {})
            headers["x-partition"] = shard
            specs.append({
                "id": mid,
                "mode": m.mode,
                "a_rows": [int(r) for r in m.team_a_rows],
                "b_rows": [int(r) for r in m.team_b_rows],
                "winner": int(winner),
                "afk": afk,
                "created_at": self._seq,
                "headers": headers,
            })
            self._match_digest.update(
                json.dumps(
                    {
                        "id": mid,
                        "mode": m.mode,
                        "a": m.team_a_ids,
                        "b": m.team_b_ids,
                        "split": m.split,
                        "p_served": m.p_a,
                        "quality": m.quality,
                        "p_model": p_model,
                        "winner": winner,
                        "afk": afk,
                    },
                    sort_keys=True,
                ).encode()
            )
            self._seq += 1
        get_registry().counter("soak.matches_published_total").add(len(specs))
        return specs

    # -- query workload ----------------------------------------------------
    def _issue_queries(self, n: int, latencies_ms: list, counts: dict) -> None:
        """``n`` routed queries with the soak's deterministic kind mix.
        Payloads draw a shard first, then that shard's rows — every
        draw and therefore every response body (version-stripped) is
        topology-invariant."""
        cfg = self.cfg
        for _ in range(n):
            kind = choose_kind(self.qrng, DEFAULT_QUERY_MIX)
            shard = int(self.qrng.integers(cfg.n_shards))
            if kind == "ratings":
                rows = self.matchmakers[shard].sample_rows(
                    QUERY_RATINGS_IDS, rng=self.qrng
                )
                call = (
                    self.router.get_ratings,
                    ([player_id(r) for r in rows],),
                )
            elif kind == "winprob":
                rows = self.matchmakers[shard].sample_rows(6, rng=self.qrng)
                call = (
                    self.router.win_probability,
                    (
                        [player_id(r) for r in rows[:3]],
                        [player_id(r) for r in rows[3:]],
                    ),
                )
            elif kind == "leaderboard":
                call = (self.router.leaderboard, (LEADERBOARD_K,))
            else:
                call = (self.router.tier_histogram, ())
            t0 = time.perf_counter()  # graftlint: disable=GL048 — measured-block latency, not a decision input
            resp = call[0](*call[1])
            dt = time.perf_counter() - t0  # graftlint: disable=GL048 — measured-block latency, not a decision input
            latencies_ms.append(dt * 1e3)
            counts[kind] = counts.get(kind, 0) + 1
            self._query_digest.update(
                (
                    kind + "\n"
                    + json.dumps(
                        FabricRouter.strip_versions(resp), sort_keys=True
                    )
                ).encode()
            )
        get_registry().counter("soak.queries_sent_total").add(n)

    # -- the loop ----------------------------------------------------------
    def run(self) -> dict:
        cfg = self.cfg
        reg = get_registry()
        self.prepare()
        match_shaper = TrafficShaper(cfg.qps, cfg.tick_s)
        query_shaper = TrafficShaper(cfg.query_qps, cfg.tick_s)
        published = 0
        query_counts: dict[str, int] = {}
        latencies_ms: list[float] = []
        per_host_rated = {h["host"]: 0 for h in self.hosts}
        per_host_version = {h["host"]: 0 for h in self.hosts}
        staleness = {h["host"]: 0 for h in self.hosts}
        staleness_max = 0
        wall_t0 = time.perf_counter()  # graftlint: disable=GL048 — measured-block wall anchor, not a decision input
        for tick in range(cfg.n_ticks):
            self.vclock.advance(cfg.tick_s)
            due = match_shaper.due()
            # Shard assignment off its own stream, then a fixed-order
            # walk: (tick, shard) -> match specs is topology-invariant.
            drawn = (
                self.frng.integers(0, cfg.n_shards, size=due)
                if due else np.empty(0, np.int64)
            )
            per_shard = [int((drawn == s).sum()) for s in range(cfg.n_shards)]
            tick_load = {h["host"]: 0 for h in self.hosts}
            for shard in range(cfg.n_shards):
                owner = self.topology.host_of_shard(shard)
                specs = self._form_specs(shard, per_shard[shard])
                published += len(specs)
                tick_load[owner] += len(specs)
                # Always posted — the empty group is the heartbeat that
                # advances the child's virtual clock and refreshes the
                # directory's freshness bookkeeping.
                resp = _post_json(
                    self.hosts[owner]["control_url"] + "/fabric/rate",
                    {
                        "now": self.vclock.now,
                        "matches": specs,
                        "peer_versions": {
                            str(k): v
                            for k, v in self.directory.vector().items()
                        },
                    },
                )
                self.directory.observe(
                    owner, resp["version"], self.vclock.now
                )
                per_host_rated[owner] = resp["matches_rated"]
            for h in self.hosts:
                idx = h["host"]
                version = self.directory.entry(idx).version
                if version != per_host_version[idx] or tick_load[idx] == 0:
                    staleness[idx] = 0
                else:
                    staleness[idx] += 1
                per_host_version[idx] = version
                staleness_max = max(staleness_max, staleness[idx])
            self._issue_queries(
                query_shaper.due(), latencies_ms, query_counts
            )
            if self.collector is not None:
                self.collector.scrape(self.vclock.now)
            reg.counter("soak.ticks_total").add(1)
            reg.gauge("soak.virtual_seconds").set(self.vclock.now)
        wall_s = time.perf_counter() - wall_t0  # graftlint: disable=GL048 — measured-block wall clock, not a decision input

        finals = [
            _post_json(h["control_url"] + "/fabric/finish", {})
            for h in self.hosts
        ]
        rated = sum(f["matches_rated"] for f in finals)
        table_digest = self._table_digest()
        burning: list[str] = []
        attribution: dict = {}
        if self.collector is not None:
            self.collector.scrape(self.vclock.now)
            burning = list(self.collector.burning)
            attribution = self.collector.attribution()
        lat = np.asarray(latencies_ms, np.float64)
        pct = lambda q: (  # noqa: E731 — three-use one-liner
            round(float(np.percentile(lat, q)), 3) if lat.size else None
        )
        artifact = {
            "metric": "fabric.matches_per_sec_per_host",
            "value": (
                round(rated / wall_s / cfg.n_hosts, 2) if wall_s > 0 else 0.0
            ),
            "config": dataclasses.asdict(cfg),
            "deterministic": {
                "seed": cfg.seed,
                "ticks": cfg.n_ticks,
                "virtual_s": round(cfg.n_ticks * cfg.tick_s, 6),
                "matches_published": published,
                "matches_rated": rated,
                "matches_digest": self._match_digest.hexdigest(),
                "queries_digest": self._query_digest.hexdigest(),
                "table_digest": table_digest,
                "queries": dict(sorted(query_counts.items())),
                "batches_ok": sum(f["batches_ok"] for f in finals),
                "dead_letters": sum(f["dead_letters"] for f in finals),
                "view_staleness_ticks_max": staleness_max,
                "drained": True,  # the per-group barrier drains or 503s
            },
            "fleet": {
                "n_hosts": cfg.n_hosts,
                "n_shards": cfg.n_shards,
                # Per-kind routed-call counts: fan-out kinds scale with
                # the host count, so these live OUTSIDE deterministic.
                "route_calls": dict(sorted(self.router.calls.items())),
                "hosts": [
                    {
                        "host": f["host"],
                        "matches_rated": f["matches_rated"],
                        "batches_ok": f["batches_ok"],
                        "dead_letters": f["dead_letters"],
                        "retraces_steady": f["retraces_steady"],
                        "view_version_final": f["version"],
                    }
                    for f in finals
                ],
                "burning": burning,
                "attribution": attribution,
                "scrapes": (
                    self.collector.scrapes
                    if self.collector is not None else 0
                ),
            },
            "latency_ms": {"p50": pct(50), "p90": pct(90), "p99": pct(99)},
            "measured": {
                "wall_s": round(wall_s, 3),
                "queries_per_sec": (
                    round(len(latencies_ms) / wall_s, 2)
                    if wall_s > 0 else 0.0
                ),
                "remote_lookup_p99_ms": pct(99),
            },
            "capture": {"degraded": False},
        }
        violations = self._violations(artifact, finals)
        artifact["slo"] = {
            "pass": not violations,
            "violations": violations,
            "thresholds": {
                "max_view_lag_ticks": cfg.max_view_lag_ticks,
            },
        }
        if violations:
            reg.counter("soak.slo_violations_total").add(len(violations))
            logger.warning(
                "fabric soak SLO violations: %s", "; ".join(violations)
            )
        logger.info(
            "fabric soak done: %d matches over %d ticks x %d hosts "
            "(%.1f wall s), slo=%s",
            rated, cfg.n_ticks, cfg.n_hosts, wall_s,
            "pass" if not violations else "FAIL",
        )
        return artifact

    def _violations(self, artifact: dict, finals: list[dict]) -> list[str]:
        cfg = self.cfg
        det = artifact["deterministic"]
        out = []
        if det["matches_rated"] < det["matches_published"]:
            out.append(
                f"lost work: {det['matches_published']} published, "
                f"{det['matches_rated']} rated"
            )
        if det["dead_letters"]:
            out.append(f"dead letters: {det['dead_letters']}")
        if det["view_staleness_ticks_max"] > cfg.max_view_lag_ticks:
            out.append(
                "view staleness "
                f"{det['view_staleness_ticks_max']} ticks exceeds "
                f"{cfg.max_view_lag_ticks}"
            )
        if cfg.warmup:
            for f in finals:
                if f["retraces_steady"] > 0:
                    out.append(
                        f"host {f['host']}: {f['retraces_steady']:.0f} "
                        "steady-state retraces (unwarmed shape reached "
                        "the fabric)"
                    )
        for name in artifact["fleet"]["burning"]:
            out.append(f"fleet objective burning: {name}")
        return out

    def _table_digest(self) -> str:
        """The final-table digest: every host's owned rows reassembled
        into GLOBAL row order, hashed as packed float32 — THE
        topology-invariance witness (same bits at any host count)."""
        table = None
        seen = 0
        for h in self.hosts:
            resp = _get_json(h["control_url"] + "/fabric/table")
            for pid, row in zip(resp["ids"], resp["rows"]):
                r = row_of_id(pid)
                if table is None:
                    table = np.full(
                        (self.cfg.n_players, len(row)), np.nan, np.float32
                    )
                table[r] = np.asarray(row, np.float32)
                seen += 1
        if table is None or seen != self.cfg.n_players:
            raise RuntimeError(
                f"final table incomplete: {seen} of "
                f"{self.cfg.n_players} rows published"
            )
        return hashlib.sha256(
            np.ascontiguousarray(table).tobytes()
        ).hexdigest()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            with open(self._exit_file, "w", encoding="utf-8") as f:
                f.write("exit\n")
        except OSError:
            pass
        for h in self.hosts:
            try:
                h["proc"].wait(timeout=30)
            except subprocess.TimeoutExpired:
                h["proc"].kill()
                h["proc"].wait(timeout=10)
            h["log"].close()
        self._tmp.cleanup()
        if self._trace_prev is not None:
            enable_tracing(self._trace_prev)
