"""Shared logging setup: INFO/DEBUG to stdout, WARNING+ to stderr.

The reference duplicates this block in both files and marks it
``# TODO share this between the two classes`` (``rater.py:172-188``,
``worker.py:202-217``); this module is that TODO done. It also fixes the
reference's quirk of naming the logger with the literal string ``"__name__"``
(``rater.py:178``) — loggers here are namespaced per module.
"""

from __future__ import annotations

import logging
import sys


class InfoFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        return record.levelno in (logging.DEBUG, logging.INFO)


_configured: set[str] = set()


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(name)
    if name not in _configured:
        logger.setLevel(logging.INFO)
        h1 = logging.StreamHandler(sys.stdout)
        h1.setLevel(logging.INFO)
        h1.addFilter(InfoFilter())
        logger.addHandler(h1)
        h2 = logging.StreamHandler(sys.stderr)
        h2.setLevel(logging.WARNING)
        logger.addHandler(h2)
        logger.propagate = False
        _configured.add(name)
    return logger
