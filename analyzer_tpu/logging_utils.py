"""Shared logging setup: INFO/DEBUG to stdout, WARNING+ to stderr.

The reference duplicates this block in both files and marks it
``# TODO share this between the two classes`` (``rater.py:172-188``,
``worker.py:202-217``); this module is that TODO done. It also fixes the
reference's quirk of naming the logger with the literal string ``"__name__"``
(``rater.py:178``) — loggers here are namespaced per module.

Two operator affordances:

  * ``ANALYZER_TPU_LOG_LEVEL`` (DEBUG/INFO/WARNING/ERROR/CRITICAL) sets
    the level for every logger this module hands out — read per
    ``get_logger`` call, so an env change before a late import applies.
  * Records render as ONE structured key=value line
    (``ts=... level=... logger=... msg="..."``), the same shape the obs
    layer uses for event output (:func:`kv_line`), so worker logs and
    metric-event lines grep and parse with the same tooling.
"""

from __future__ import annotations

import logging
import os
import sys
import time

_ENV_LEVEL = "ANALYZER_TPU_LOG_LEVEL"


def kv_line(**fields) -> str:
    """``k=v`` pairs joined by spaces, values quoted when they contain
    whitespace or quotes — the shared structured-line vocabulary of the
    log formatter and the obs layer's event output."""
    parts = []
    for k, v in fields.items():
        s = str(v)
        if s == "" or any(c.isspace() for c in s) or '"' in s or "=" in s:
            s = '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'
        parts.append(f"{k}={s}")
    return " ".join(parts)


class KVFormatter(logging.Formatter):
    """One structured line per record: ``ts=<iso8601> level=<level>
    logger=<name> msg="..."`` (plus ``exc`` when an exception rides
    along)."""

    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime(
            "%Y-%m-%dT%H:%M:%S", time.localtime(record.created)
        ) + f".{int(record.msecs):03d}"
        fields = {
            "ts": ts,
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            fields["exc"] = self.formatException(record.exc_info)
        return kv_line(**fields)


class InfoFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        return record.levelno in (logging.DEBUG, logging.INFO)


def _env_level() -> int:
    name = os.environ.get(_ENV_LEVEL, "INFO").strip().upper()
    level = getattr(logging, name, None)
    return level if isinstance(level, int) else logging.INFO


_configured: set[str] = set()
_shared_handlers: list[logging.Handler] = []


def add_shared_handler(handler: logging.Handler) -> None:
    """Attaches ``handler`` to every logger this module configured and to
    all future ones. The loggers here deliberately do not propagate (the
    stream handlers would double-print under a configured root), so a
    root-level handler sees nothing — this is the sanctioned tap for
    whole-package capture (the flight recorder's event ring)."""
    if handler in _shared_handlers:
        return
    _shared_handlers.append(handler)
    for name in _configured:
        logging.getLogger(name).addHandler(handler)


def remove_shared_handler(handler: logging.Handler) -> None:
    if handler in _shared_handlers:
        _shared_handlers.remove(handler)
    for name in _configured:
        logging.getLogger(name).removeHandler(handler)


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(name)
    if name not in _configured:
        formatter = KVFormatter()
        h1 = logging.StreamHandler(sys.stdout)
        h1.setLevel(logging.DEBUG)  # the logger level is the one gate
        h1.addFilter(InfoFilter())
        h1.setFormatter(formatter)
        logger.addHandler(h1)
        h2 = logging.StreamHandler(sys.stderr)
        h2.setLevel(logging.WARNING)
        h2.setFormatter(formatter)
        logger.addHandler(h2)
        for shared in _shared_handlers:
            logger.addHandler(shared)
        logger.propagate = False
        _configured.add(name)
    logger.setLevel(_env_level())
    return logger
