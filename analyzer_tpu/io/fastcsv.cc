// Native CSV match-stream parser — the host-side data loader.
//
// The python csv module parses the 10M-match interchange file in minutes
// (~13 s per 1M rows); this single-pass scanner does it in under a second
// per million. Format is csv_codec.py's writer output:
//
//   match_id,mode,winner,afk,team0,team1\r?\n
//
// with team columns ';'-joined player ids, an optional header line, and
// rows already in chronological order (the reference's ORDER BY
// created_at ASC contract, worker.py:176). Mode names arrive as a
// '\n'-joined candidate list so the mapping stays owned by
// core/constants.py — unknown names map to -1 (UNSUPPORTED_MODE_ID),
// which the python side carries through like the reference's
// log-and-skip (rater.py:83-85).
//
// Built on demand by _native_csv.py (g++ -O3 -shared, ctypes), same
// pattern as sched/_native.py. Returns rows parsed, or -(1+row) on a
// malformed row so the caller can fall back to the permissive python
// parser (quoted fields etc.).

#include <cstdint>
#include <cstring>

namespace {

// Parses a non-negative integer, advancing *p. Returns -1 if no digits
// or the value exceeds INT32_MAX — ids wrap to negative in the int32
// output and would silently read as empty padding slots downstream,
// where the python parser raises OverflowError; rejecting here routes
// corrupt data to that loud path.
inline int64_t parse_uint(const char** p, const char* end) {
  const char* s = *p;
  int64_t v = 0;
  bool any = false;
  while (s < end && *s >= '0' && *s <= '9') {
    v = v * 10 + (*s - '0');
    if (v > INT32_MAX) {  // also bounds the digit run before int64 overflow
      while (s < end && *s >= '0' && *s <= '9') ++s;
      *p = s;
      return -1;
    }
    ++s;
    any = true;
  }
  *p = s;
  return any ? v : -1;
}

}  // namespace

extern "C" {

// player_idx [cap_rows, 2, max_team] must arrive prefilled with -1.
// out_tmax receives the widest team seen. Returns rows parsed (>= 0) or
// -(row + 1) of the first malformed row.
//
// PROBE MODE: passing NULL output arrays (player_idx/winner/mode_id/afk)
// runs the same grammar scan without writing — callers use it as a first
// pass to learn (rows, tmax) and allocate exactly, instead of paying a
// worst-case-width buffer (e.g. ~1.3 GB at 10M rows x 16 team slots).
int64_t parse_stream_csv(const char* buf, int64_t len, const char* modes,
                         int64_t n_modes, int64_t max_team, int64_t cap_rows,
                         int32_t* player_idx, int32_t* winner,
                         int32_t* mode_id, uint8_t* afk, int64_t* out_tmax) {
  // Pre-split the candidate mode names.
  const char* mode_ptr[64];
  int64_t mode_len[64];
  {
    const char* m = modes;
    const char* mend = modes + std::strlen(modes);
    int64_t k = 0;
    while (m < mend && k < n_modes && k < 64) {
      const char* nl = static_cast<const char*>(
          std::memchr(m, '\n', static_cast<size_t>(mend - m)));
      if (!nl) nl = mend;
      mode_ptr[k] = m;
      mode_len[k] = nl - m;
      ++k;
      m = nl + 1;
    }
    n_modes = k;
  }

  const char* p = buf;
  const char* end = buf + len;
  // Optional header.
  if (len >= 8 && std::strncmp(p, "match_id", 8) == 0) {
    const char* nl =
        static_cast<const char*>(std::memchr(p, '\n', static_cast<size_t>(len)));
    if (!nl) return 0;
    p = nl + 1;
  }

  int64_t row = 0;
  int64_t tmax = 1;
  while (p < end) {
    if (*p == '\n' || *p == '\r') {  // blank/trailing line
      ++p;
      continue;
    }
    if (row >= cap_rows) return -(row + 1);
    // field 0: match_id (ignored)
    const char* c = static_cast<const char*>(
        std::memchr(p, ',', static_cast<size_t>(end - p)));
    if (!c) return -(row + 1);
    p = c + 1;
    // field 1: mode name
    c = static_cast<const char*>(
        std::memchr(p, ',', static_cast<size_t>(end - p)));
    if (!c) return -(row + 1);
    int32_t mid = -1;
    for (int64_t k = 0; k < n_modes; ++k) {
      if (mode_len[k] == c - p && std::memcmp(mode_ptr[k], p, mode_len[k]) == 0) {
        mid = static_cast<int32_t>(k);
        break;
      }
    }
    if (mode_id) mode_id[row] = mid;
    p = c + 1;
    // field 2: winner (0/1)
    int64_t w = parse_uint(&p, end);
    if (w < 0 || p >= end || *p != ',') return -(row + 1);
    if (winner) winner[row] = static_cast<int32_t>(w);
    ++p;
    // field 3: afk (0/1)
    int64_t a = parse_uint(&p, end);
    if (a < 0 || p >= end || *p != ',') return -(row + 1);
    if (afk) afk[row] = static_cast<uint8_t>(a != 0);
    ++p;
    // fields 4-5: team id lists
    for (int team = 0; team < 2; ++team) {
      int32_t* out =
          player_idx ? player_idx + (row * 2 + team) * max_team : nullptr;
      int64_t slot = 0;
      const char sep_end = team == 0 ? ',' : '\n';
      if (p < end && *p != sep_end && *p != '\r') {
        while (true) {
          int64_t id = parse_uint(&p, end);
          if (id < 0) return -(row + 1);
          if (slot >= max_team) return -(row + 1);
          if (out) out[slot] = static_cast<int32_t>(id);
          ++slot;
          if (p < end && *p == ';') {
            ++p;
            continue;
          }
          break;
        }
      }
      if (slot > tmax) tmax = slot;
      if (team == 0) {
        if (p >= end || *p != ',') return -(row + 1);
        ++p;
      } else {
        if (p < end && *p == '\r') ++p;
        if (p < end) {
          if (*p != '\n') return -(row + 1);
          ++p;
        }
      }
    }
    ++row;
  }
  *out_tmax = tmax;
  return row;
}

}  // extern "C"
