// Native CSV match-stream parser — the host-side data loader.
//
// The python csv module parses the 10M-match interchange file in minutes
// (~13 s per 1M rows); this single-pass scanner does it in under a second
// per million. Format is csv_codec.py's writer output:
//
//   match_id,mode,winner,afk,team0,team1\r?\n
//
// with team columns ';'-joined player ids, an optional header line, and
// rows already in chronological order (the reference's ORDER BY
// created_at ASC contract, worker.py:176). Mode names arrive as a
// '\n'-joined candidate list so the mapping stays owned by
// core/constants.py — unknown names map to -1 (UNSUPPORTED_MODE_ID),
// which the python side carries through like the reference's
// log-and-skip (rater.py:83-85).
//
// Two entry points share one row grammar:
//
//   * parse_stream_csv — the whole-file two-pass loader (probe then
//     exact-width decode), unchanged ABI since it landed;
//   * parse_csv_window — the WIRE-SPEED INGEST entry (docs/ingest.md):
//     decodes up to cap_rows rows starting at *cursor into
//     caller-provided fixed-width column slabs (the pinned staging
//     arena's reusable buffers, sched/feed.py) and advances *cursor,
//     so a stream decodes window by window through a few slabs instead
//     of one giant allocation, and each window can H2D while the next
//     decodes.
//
// Built on demand by _native_csv.py (g++ -O3 -shared, ctypes), same
// pattern as sched/_native.py. Returns rows parsed, or -(1+row) on a
// malformed row so the caller can fall back to the permissive python
// parser (quoted fields etc.).

#include <cstdint>
#include <cstring>

namespace {

// Parses a non-negative integer, advancing *p. Returns -1 if no digits
// or the value exceeds INT32_MAX — ids wrap to negative in the int32
// output and would silently read as empty padding slots downstream,
// where the python parser raises OverflowError; rejecting here routes
// corrupt data to that loud path.
inline int64_t parse_uint(const char** p, const char* end) {
  const char* s = *p;
  int64_t v = 0;
  bool any = false;
  while (s < end && *s >= '0' && *s <= '9') {
    v = v * 10 + (*s - '0');
    if (v > INT32_MAX) {  // also bounds the digit run before int64 overflow
      while (s < end && *s >= '0' && *s <= '9') ++s;
      *p = s;
      return -1;
    }
    ++s;
    any = true;
  }
  *p = s;
  return any ? v : -1;
}

struct ModeTable {
  const char* ptr[64];
  int64_t len[64];
  int64_t n;
};

inline ModeTable split_modes(const char* modes, int64_t n_modes) {
  ModeTable mt;
  const char* m = modes;
  const char* mend = modes + std::strlen(modes);
  int64_t k = 0;
  while (m < mend && k < n_modes && k < 64) {
    const char* nl = static_cast<const char*>(
        std::memchr(m, '\n', static_cast<size_t>(mend - m)));
    if (!nl) nl = mend;
    mt.ptr[k] = m;
    mt.len[k] = nl - m;
    ++k;
    m = nl + 1;
  }
  mt.n = k;
  return mt;
}

// One row of the writer's grammar. Advances *pp past the row's newline;
// returns 0 on success, -1 malformed (*pp position is undefined then —
// callers report the row index and stop). Output pointers may be null
// (probe mode). `out` is the row's [2, max_team] player block; unused
// slots are filled with -1 so a reused slab needs no host-side reset.
inline int parse_row(const char** pp, const char* end, const ModeTable& mt,
                     int64_t max_team, int32_t* out, int32_t* w_out,
                     int32_t* m_out, uint8_t* a_out, int64_t* tmax) {
  const char* p = *pp;
  // field 0: match_id (ignored)
  const char* c = static_cast<const char*>(
      std::memchr(p, ',', static_cast<size_t>(end - p)));
  if (!c) return -1;
  p = c + 1;
  // field 1: mode name
  c = static_cast<const char*>(
      std::memchr(p, ',', static_cast<size_t>(end - p)));
  if (!c) return -1;
  int32_t mid = -1;
  for (int64_t k = 0; k < mt.n; ++k) {
    if (mt.len[k] == c - p && std::memcmp(mt.ptr[k], p, mt.len[k]) == 0) {
      mid = static_cast<int32_t>(k);
      break;
    }
  }
  if (m_out) *m_out = mid;
  p = c + 1;
  // field 2: winner (0/1)
  int64_t w = parse_uint(&p, end);
  if (w < 0 || p >= end || *p != ',') return -1;
  if (w_out) *w_out = static_cast<int32_t>(w);
  ++p;
  // field 3: afk (0/1)
  int64_t a = parse_uint(&p, end);
  if (a < 0 || p >= end || *p != ',') return -1;
  if (a_out) *a_out = static_cast<uint8_t>(a != 0);
  ++p;
  // fields 4-5: team id lists
  for (int team = 0; team < 2; ++team) {
    int32_t* slots = out ? out + team * max_team : nullptr;
    int64_t slot = 0;
    const char sep_end = team == 0 ? ',' : '\n';
    if (p < end && *p != sep_end && *p != '\r') {
      while (true) {
        int64_t id = parse_uint(&p, end);
        if (id < 0) return -1;
        if (slot >= max_team) return -1;
        if (slots) slots[slot] = static_cast<int32_t>(id);
        ++slot;
        if (p < end && *p == ';') {
          ++p;
          continue;
        }
        break;
      }
    }
    if (slots) {
      for (int64_t s = slot; s < max_team; ++s) slots[s] = -1;
    }
    if (slot > *tmax) *tmax = slot;
    if (team == 0) {
      if (p >= end || *p != ',') return -1;
      ++p;
    } else {
      if (p < end && *p == '\r') ++p;
      if (p < end) {
        if (*p != '\n') return -1;
        ++p;
      }
    }
  }
  *pp = p;
  return 0;
}

inline const char* skip_header(const char* p, const char* end) {
  if (end - p >= 8 && std::strncmp(p, "match_id", 8) == 0) {
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(end - p)));
    if (!nl) return end;
    return nl + 1;
  }
  return p;
}

}  // namespace

extern "C" {

// player_idx [cap_rows, 2, max_team] need not arrive prefilled: unused
// team slots are written -1 by the scanner. out_tmax receives the widest
// team seen. Returns rows parsed (>= 0) or -(row + 1) of the first
// malformed row.
//
// PROBE MODE: passing NULL output arrays (player_idx/winner/mode_id/afk)
// runs the same grammar scan without writing — callers use it as a first
// pass to learn (rows, tmax) and allocate exactly, instead of paying a
// worst-case-width buffer (e.g. ~1.3 GB at 10M rows x 16 team slots).
int64_t parse_stream_csv(const char* buf, int64_t len, const char* modes,
                         int64_t n_modes, int64_t max_team, int64_t cap_rows,
                         int32_t* player_idx, int32_t* winner,
                         int32_t* mode_id, uint8_t* afk, int64_t* out_tmax) {
  const ModeTable mt = split_modes(modes, n_modes);
  const char* p = buf;
  const char* end = buf + len;
  if (len >= 8) p = skip_header(p, end);

  int64_t row = 0;
  int64_t tmax = 1;
  while (p < end) {
    if (*p == '\n' || *p == '\r') {  // blank/trailing line
      ++p;
      continue;
    }
    if (row >= cap_rows) return -(row + 1);
    int32_t* out = player_idx ? player_idx + row * 2 * max_team : nullptr;
    if (parse_row(&p, end, mt, max_team, out,
                  winner ? winner + row : nullptr,
                  mode_id ? mode_id + row : nullptr,
                  afk ? afk + row : nullptr, &tmax) != 0) {
      return -(row + 1);
    }
    ++row;
  }
  *out_tmax = tmax;
  return row;
}

// Windowed streaming decode — the ingest plane's entry (docs/ingest.md).
// Parses up to cap_rows rows starting at byte *cursor into the caller's
// FIXED-WIDTH column slabs (player_idx [cap_rows, 2, max_team], winner/
// mode_id [cap_rows], afk [cap_rows] — the reusable pinned staging
// buffers), writes -1 into unused team slots itself (a reused slab needs
// no reset), advances *cursor to the first unconsumed byte, and returns
// the rows decoded. 0 means end of stream. A malformed row ENDS the
// window early: the valid prefix is returned (those rows are real work)
// with *cursor left at the offending row's first byte, so the next call
// sees the bad row first and returns -1 — the caller attributes the
// poison to an absolute row index and routes the remaining bytes to the
// permissive python parser without losing the prefix.
// The optional header line is consumed only when *cursor == 0.
// out_tmax receives the widest team seen IN THIS WINDOW (floor 0).
int64_t parse_csv_window(const char* buf, int64_t len, const char* modes,
                         int64_t n_modes, int64_t max_team, int64_t cap_rows,
                         int64_t* cursor, int32_t* player_idx,
                         int32_t* winner, int32_t* mode_id, uint8_t* afk,
                         int64_t* out_tmax) {
  const ModeTable mt = split_modes(modes, n_modes);
  const char* end = buf + len;
  const char* p = buf + *cursor;
  if (*cursor == 0 && len >= 8) p = skip_header(p, end);

  int64_t row = 0;
  int64_t tmax = 0;
  while (p < end && row < cap_rows) {
    if (*p == '\n' || *p == '\r') {  // blank/trailing line
      ++p;
      continue;
    }
    const char* row_start = p;
    if (parse_row(&p, end, mt, max_team,
                  player_idx + row * 2 * max_team, winner + row,
                  mode_id + row, afk + row, &tmax) != 0) {
      *cursor = row_start - buf;
      if (row == 0) return -1;  // the bad row leads: the caller's turn
      *out_tmax = tmax;
      return row;  // valid prefix; the next call reports the poison
    }
    ++row;
  }
  *cursor = p - buf;
  *out_tmax = tmax;
  return row;
}

}  // extern "C"
