"""ctypes loader for the native CSV parser (fastcsv.cc).

Compiled/loaded via the shared helper (``analyzer_tpu.native_build``):
ImportError on ANY build or load failure so the caller's pure-python
parser engages instead.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from analyzer_tpu.native_build import build_and_load

_DIR = os.path.dirname(os.path.abspath(__file__))
_lib = build_and_load(
    os.path.join(_DIR, "fastcsv.cc"), os.path.join(_DIR, "_fastcsv.so")
)
_lib.parse_stream_csv.argtypes = [
    ctypes.c_char_p,
    ctypes.c_int64,
    ctypes.c_char_p,
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.POINTER(ctypes.c_int64),
]
_lib.parse_stream_csv.restype = ctypes.c_int64


def parse_stream_csv(data: bytes, mode_names: list[str], max_team: int):
    """Parses the writer's CSV format. Returns (player_idx [N,2,tmax],
    winner, mode_id, afk) numpy arrays, or None when the data doesn't
    match the fast path (caller falls back to the python parser).

    Two passes: a write-free probe learns (rows, widest team) so the
    arrays are allocated at exactly the data's width — a worst-case
    ``max_team``-wide buffer would be ~1.3 GB of mostly padding at the
    10M-row scale this parser exists for."""
    if b'"' in data:
        # Quoting is csv-module territory; the scanner would compare a
        # quoted mode name literally and mis-map it. Rare -> python path.
        return None
    modes = "\n".join(mode_names).encode()
    null_i32 = ctypes.POINTER(ctypes.c_int32)()
    null_u8 = ctypes.POINTER(ctypes.c_uint8)()
    tmax = np.zeros(1, np.int64)
    tmax_ptr = tmax.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    n = _lib.parse_stream_csv(
        data, len(data), modes, len(mode_names), max_team,
        np.iinfo(np.int64).max,
        null_i32, null_i32, null_i32, null_u8, tmax_ptr,
    )
    if n < 0:
        return None  # malformed for the fast path; python parser decides
    t = max(int(tmax[0]), 1)
    player_idx = np.full((n, 2, t), -1, np.int32)
    winner = np.zeros(n, np.int32)
    mode_id = np.zeros(n, np.int32)
    afk = np.zeros(n, np.uint8)
    n2 = _lib.parse_stream_csv(
        data, len(data), modes, len(mode_names), t, n,
        player_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        winner.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        mode_id.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        afk.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        tmax_ptr,
    )
    assert n2 == n, (n2, n)  # same bytes, same grammar — cannot differ
    return player_idx, winner, mode_id, afk.astype(bool)
