"""ctypes loader for the native CSV parser (fastcsv.cc).

Compiled/loaded via the shared helper (``analyzer_tpu.native_build``):
ImportError on ANY build or load failure so the caller's pure-python
parser engages instead.

Two surfaces: :func:`parse_stream_csv`, the whole-file two-pass loader,
and :func:`parse_csv_window`, the wire-speed ingest entry that decodes
up to a slab's worth of rows into caller-provided (reusable, pinned)
column buffers and resumes from a byte cursor (docs/ingest.md).
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from analyzer_tpu.native_build import build_and_load

_DIR = os.path.dirname(os.path.abspath(__file__))
_lib = build_and_load(
    os.path.join(_DIR, "fastcsv.cc"), os.path.join(_DIR, "_fastcsv.so")
)
_lib.parse_stream_csv.argtypes = [
    ctypes.c_char_p,
    ctypes.c_int64,
    ctypes.c_char_p,
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.POINTER(ctypes.c_int64),
]
_lib.parse_stream_csv.restype = ctypes.c_int64
_lib.parse_csv_window.argtypes = [
    ctypes.c_char_p,
    ctypes.c_int64,
    ctypes.c_char_p,
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_int32),
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.POINTER(ctypes.c_int64),
]
_lib.parse_csv_window.restype = ctypes.c_int64


def _i32(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def parse_stream_csv(data: bytes, mode_names: list[str], max_team: int):
    """Parses the writer's CSV format. Returns (player_idx [N,2,tmax],
    winner, mode_id, afk) numpy arrays, or None when the data doesn't
    match the fast path (caller falls back to the python parser).

    Two passes: a write-free probe learns (rows, widest team) so the
    arrays are allocated at exactly the data's width — a worst-case
    ``max_team``-wide buffer would be ~1.3 GB of mostly padding at the
    10M-row scale this parser exists for."""
    if b'"' in data:
        # Quoting is csv-module territory; the scanner would compare a
        # quoted mode name literally and mis-map it. Rare -> python path.
        return None
    modes = "\n".join(mode_names).encode()
    null_i32 = ctypes.POINTER(ctypes.c_int32)()
    null_u8 = ctypes.POINTER(ctypes.c_uint8)()
    tmax = np.zeros(1, np.int64)
    tmax_ptr = tmax.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    n = _lib.parse_stream_csv(
        data, len(data), modes, len(mode_names), max_team,
        np.iinfo(np.int64).max,
        null_i32, null_i32, null_i32, null_u8, tmax_ptr,
    )
    if n < 0:
        return None  # malformed for the fast path; python parser decides
    t = max(int(tmax[0]), 1)
    player_idx = np.full((n, 2, t), -1, np.int32)
    winner = np.zeros(n, np.int32)
    mode_id = np.zeros(n, np.int32)
    afk = np.zeros(n, np.uint8)
    n2 = _lib.parse_stream_csv(
        data, len(data), modes, len(mode_names), t, n,
        _i32(player_idx), _i32(winner), _i32(mode_id),
        afk.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        tmax_ptr,
    )
    assert n2 == n, (n2, n)  # same bytes, same grammar — cannot differ
    return player_idx, winner, mode_id, afk.astype(bool)


class WindowDecodeError(ValueError):
    """A malformed row inside :func:`parse_csv_window`'s grammar,
    attributed to the WINDOW-RELATIVE row index (the caller adds its
    stream offset for the absolute poison row) and the byte offset of
    the offending row."""

    def __init__(self, row: int, byte_offset: int) -> None:
        super().__init__(
            f"malformed CSV row at window row {row} (byte {byte_offset})"
        )
        self.row = row
        self.byte_offset = byte_offset


def parse_csv_window(
    data: bytes,
    modes_blob: bytes,
    n_modes: int,
    max_team: int,
    cursor: np.ndarray,
    player_idx: np.ndarray,
    winner: np.ndarray,
    mode_id: np.ndarray,
    afk: np.ndarray,
) -> int:
    """Decodes up to ``player_idx.shape[0]`` rows of ``data`` starting at
    byte ``cursor[0]`` into the caller's column slabs (C-contiguous
    int32 [W, 2, max_team] / int32 [W] / int32 [W] / uint8 [W] — the
    pinned staging arena's reusable buffers; unused team slots are
    written -1 by the scanner, so slabs need NO reset between windows).
    Advances ``cursor`` in place and returns rows decoded (0 = end of
    stream). Raises :class:`WindowDecodeError` on a malformed row, with
    ``cursor`` left at the offending row's first byte.

    ``modes_blob`` is the pre-encoded '\\n'-joined mode-name list —
    encoded ONCE per stream by the caller, not per window (the whole
    point of this entry is no per-window python staging work)."""
    cap = int(player_idx.shape[0])
    tmax = np.zeros(1, np.int64)
    n = _lib.parse_csv_window(
        data, len(data), modes_blob, n_modes, max_team, cap,
        cursor.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        _i32(player_idx), _i32(winner), _i32(mode_id),
        afk.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        tmax.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if n < 0:
        raise WindowDecodeError(int(-n - 1), int(cursor[0]))
    return int(n)
