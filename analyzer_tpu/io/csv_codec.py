"""CSV match-stream codec (BASELINE config 1: "Elo pairwise rater on
1k-match CSV").

One row per match: ``match_id,mode,winner,afk,team0,team1`` where the team
columns are ``;``-separated player ids. Mode is the reference's game-mode
string (``rater.py:70-82``) — unknown strings map to UNSUPPORTED_MODE_ID and
are carried through (the reference logs-and-skips them, ``rater.py:83-85``).
Rows must already be in chronological order, mirroring the reference's
``ORDER BY created_at ASC`` contract (``worker.py:176``).
"""

from __future__ import annotations

import csv
import io as _io

import numpy as np

from analyzer_tpu.core import constants
from analyzer_tpu.sched.superstep import MatchStream

HEADER = ("match_id", "mode", "winner", "afk", "team0", "team1")


def save_stream_csv(path: str, stream: MatchStream) -> None:
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(HEADER)
        for i in range(stream.n_matches):
            mode = (
                constants.MODES[stream.mode_id[i]]
                if stream.mode_id[i] >= 0
                else "unsupported"
            )
            teams = []
            for team in range(2):
                ids = stream.player_idx[i, team]
                teams.append(";".join(str(x) for x in ids[ids >= 0]))
            w.writerow([i, mode, int(stream.winner[i]), int(stream.afk[i])] + teams)


def save_stream_npz(
    path: str, stream: MatchStream, telemetry: np.ndarray | None = None,
    archetype: np.ndarray | None = None,
) -> None:
    """Binary stream format — the bulk-interchange fast path. A 10M-match
    history is ~3 min each way as CSV text; as npz it is seconds. Same
    chronological-order contract as the CSV. ``telemetry`` optionally
    rides along (``[N, 2, T, 6]`` post-game stats, io/synthetic.py) for
    the config-4 analysis head — npz only, the CSV schema has no column
    for it. ``archetype`` (``[P]`` int32 playstyle buckets, a PRE-match
    observable) likewise rides along for the composition features of the
    forecasting heads (models/features.py composition_features)."""
    arrays = dict(
        player_idx=stream.player_idx,
        winner=stream.winner,
        mode_id=stream.mode_id,
        afk=stream.afk,
    )
    if archetype is not None:
        arrays["archetype"] = np.asarray(archetype, np.int32)
    if telemetry is not None:
        from analyzer_tpu.io.synthetic import TELEMETRY_STATS

        want = stream.player_idx.shape + (len(TELEMETRY_STATS),)
        if telemetry.ndim != 4 or telemetry.shape != want:
            raise ValueError(
                f"telemetry shape {telemetry.shape} does not match the "
                f"stream's {want} ([N, 2, T, {len(TELEMETRY_STATS)}])"
            )
        arrays["telemetry"] = telemetry
    np.savez(path, **arrays)


def load_stream_npz(path: str) -> MatchStream:
    with np.load(path) as z:
        return MatchStream(
            player_idx=z["player_idx"],
            winner=z["winner"],
            mode_id=z["mode_id"],
            afk=z["afk"],
        )


def load_telemetry(path: str) -> np.ndarray | None:
    """The telemetry block of an ``.npz`` stream, or None (absent /
    CSV stream)."""
    if not path.endswith(".npz"):
        return None
    with np.load(path) as z:
        return z["telemetry"] if "telemetry" in z else None


def load_archetypes(path: str) -> np.ndarray | None:
    """The per-player archetype block of an ``.npz`` stream, or None
    (absent / CSV stream)."""
    if not path.endswith(".npz"):
        return None
    with np.load(path) as z:
        return z["archetype"] if "archetype" in z else None


def save_stream(
    path: str, stream: MatchStream, telemetry: np.ndarray | None = None,
    archetype: np.ndarray | None = None,
) -> None:
    """Extension-dispatched save: ``.npz`` binary, anything else CSV."""
    if path.endswith(".npz"):
        save_stream_npz(path, stream, telemetry, archetype)
    elif telemetry is not None:
        raise ValueError("telemetry requires the .npz stream format")
    else:
        save_stream_csv(path, stream)


def load_stream(path: str) -> MatchStream:
    """Extension-dispatched load: ``.npz`` binary, anything else CSV."""
    if path.endswith(".npz"):
        return load_stream_npz(path)
    return load_stream_csv(path)


def load_stream_csv(path_or_file) -> MatchStream:
    if isinstance(path_or_file, str):
        # Fast path: the native single-pass scanner (fastcsv.cc) parses
        # the writer's exact format ~20x faster than the csv module; any
        # deviation (quoted fields, stray columns) falls back to python.
        try:
            from analyzer_tpu.io import _native_csv

            with open(path_or_file, "rb") as f:
                parsed = _native_csv.parse_stream_csv(
                    f.read(), list(constants.MODES), max_team=16
                )
            if parsed is not None:
                player_idx, winner, mode_id, afk = parsed
                return MatchStream(
                    player_idx=player_idx, winner=winner, mode_id=mode_id, afk=afk
                )
        except ImportError:
            pass
        with open(path_or_file, newline="") as f:
            return _parse(f)
    return _parse(path_or_file)


def _parse(f) -> MatchStream:
    rows = list(csv.reader(f))
    if rows and tuple(rows[0]) == HEADER:
        rows = rows[1:]
    n = len(rows)
    teams = [[r[4].split(";") if r[4] else [], r[5].split(";") if r[5] else []] for r in rows]
    t_max = max((max(len(t[0]), len(t[1])) for t in teams), default=1)
    player_idx = np.full((n, 2, t_max), -1, dtype=np.int32)
    winner = np.zeros(n, dtype=np.int32)
    mode_id = np.zeros(n, dtype=np.int32)
    afk = np.zeros(n, dtype=bool)
    # graftlint: disable=GL031 — permissive csv-module fallback, not the hot path (that is io/ingest.py)
    for i, r in enumerate(rows):
        mode_id[i] = constants.MODE_TO_ID.get(r[1], constants.UNSUPPORTED_MODE_ID)
        winner[i] = int(r[2])
        afk[i] = bool(int(r[3]))
        for team in range(2):
            ids = teams[i][team]
            player_idx[i, team, : len(ids)] = [int(x) for x in ids]
    return MatchStream(player_idx=player_idx, winner=winner, mode_id=mode_id, afk=afk)
