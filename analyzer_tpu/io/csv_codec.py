"""CSV match-stream codec (BASELINE config 1: "Elo pairwise rater on
1k-match CSV").

One row per match: ``match_id,mode,winner,afk,team0,team1`` where the team
columns are ``;``-separated player ids. Mode is the reference's game-mode
string (``rater.py:70-82``) — unknown strings map to UNSUPPORTED_MODE_ID and
are carried through (the reference logs-and-skips them, ``rater.py:83-85``).
Rows must already be in chronological order, mirroring the reference's
``ORDER BY created_at ASC`` contract (``worker.py:176``).
"""

from __future__ import annotations

import csv
import io as _io

import numpy as np

from analyzer_tpu.core import constants
from analyzer_tpu.sched.superstep import MatchStream

HEADER = ("match_id", "mode", "winner", "afk", "team0", "team1")


def save_stream_csv(path: str, stream: MatchStream) -> None:
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(HEADER)
        for i in range(stream.n_matches):
            mode = (
                constants.MODES[stream.mode_id[i]]
                if stream.mode_id[i] >= 0
                else "unsupported"
            )
            teams = []
            for team in range(2):
                ids = stream.player_idx[i, team]
                teams.append(";".join(str(x) for x in ids[ids >= 0]))
            w.writerow([i, mode, int(stream.winner[i]), int(stream.afk[i])] + teams)


def load_stream_csv(path_or_file) -> MatchStream:
    if isinstance(path_or_file, str):
        with open(path_or_file, newline="") as f:
            return _parse(f)
    return _parse(path_or_file)


def _parse(f) -> MatchStream:
    rows = list(csv.reader(f))
    if rows and tuple(rows[0]) == HEADER:
        rows = rows[1:]
    n = len(rows)
    teams = [[r[4].split(";") if r[4] else [], r[5].split(";") if r[5] else []] for r in rows]
    t_max = max((max(len(t[0]), len(t[1])) for t in teams), default=1)
    player_idx = np.full((n, 2, t_max), -1, dtype=np.int32)
    winner = np.zeros(n, dtype=np.int32)
    mode_id = np.zeros(n, dtype=np.int32)
    afk = np.zeros(n, dtype=bool)
    for i, r in enumerate(rows):
        mode_id[i] = constants.MODE_TO_ID.get(r[1], constants.UNSUPPORTED_MODE_ID)
        winner[i] = int(r[2])
        afk[i] = bool(int(r[3]))
        for team in range(2):
            ids = teams[i][team]
            player_idx[i, team, : len(ids)] = [int(x) for x in ids]
    return MatchStream(player_idx=player_idx, winner=winner, mode_id=mode_id, afk=afk)
