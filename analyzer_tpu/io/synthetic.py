"""Synthetic match-history generation for tests and benchmarks.

Produces chronologically ordered streams with the reference's real-world
shape: a heavy-tailed player-activity distribution (a few very active
players — the worst case for superstep depth), a mix of 3v3 and 5v5 modes,
occasional AFK/invalid matches, and seed features (rank points / skill
tiers) distributed like the reference's fallback paths expect
(``rater.py:42-62``). Outcomes are sampled from latent skills so the
win-probability models (BASELINE configs 3-4) have signal to learn.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from analyzer_tpu.core import constants
from analyzer_tpu.sched.superstep import MatchStream

# 3v3 modes per MODES order: casual, ranked, blitz, br are 3v3; 5v5_* are 5.
_MODE_TEAM_SIZE = np.array([3, 3, 3, 3, 5, 5], dtype=np.int32)


class AliasSampler:
    """Walker alias method over a fixed weight vector: O(P) build, O(1)
    per draw.

    ``rng.choice(p=weights)`` costs a ~20-probe binary search per draw
    (log2 of the population) — ~37 s for the 100M draws of a 10M-match
    generation. The alias table replaces that with two table reads per
    draw (~5x faster end to end). Build is the standard Vose two-stack
    pairing; exactness: every draw is distributed exactly per ``weights``.

    Public API (the loadgen matchmaker reuses this for activity-weighted
    player sampling — ``analyzer_tpu/loadgen/matchmaker.py`` — instead of
    rebuilding the alias construction):

      * ``AliasSampler(weights)`` — ``weights`` is a 1-D positive float
        array; it is normalized internally (callers need not sum to 1).
      * ``draw(rng, size)`` — samples indices ``[0, len(weights))`` with
        probability proportional to ``weights``, shaped ``size``, using
        exactly two ``rng`` streams (cell + keep) per call, so a given
        ``rng`` state yields a deterministic draw sequence.
    """

    def __init__(self, weights: np.ndarray) -> None:
        p = weights.shape[0]
        scaled = weights * (p / weights.sum())
        self.alias = np.arange(p, dtype=np.int64)
        self.prob = scaled.copy()
        prob, alias = self.prob, self.alias
        # Bulk-pairing Vose: each round pairs m smalls with m distinct
        # larges elementwise (a different processing order than the
        # classic one-at-a-time stacks, but the same invariant: a paired
        # small cell is finalized, the large keeps its residual). Queues
        # are flat ring buffers so a round is pure numpy with no
        # reslicing copies; every cell is enqueued at most twice, so the
        # build is O(P) with a handful of vector ops per round.
        # Capacity: qs sees each cell at most twice (initial + one
        # large-turned-small); ql sees initial larges plus one re-enqueue
        # per pairing, and pairings = finalized smalls <= 2p.
        qs = np.empty(2 * p + 1, np.int64)
        ql = np.empty(3 * p + 1, np.int64)
        init_s = np.flatnonzero(scaled < 1.0)
        init_l = np.flatnonzero(scaled >= 1.0)
        qs[: init_s.size] = init_s
        ql[: init_l.size] = init_l
        sh, st = 0, init_s.size  # small queue head/tail
        lh, lt = 0, init_l.size  # large queue head/tail
        while sh < st and lh < lt:
            m = min(st - sh, lt - lh)
            s = qs[sh : sh + m]
            l = ql[lh : lh + m]
            sh += m
            lh += m
            alias[s] = l
            prob[l] -= 1.0 - prob[s]
            lp = prob[l]
            new_small = l[lp < 1.0]
            new_large = l[lp >= 1.0]
            qs[st : st + new_small.size] = new_small
            st += new_small.size
            ql[lt : lt + new_large.size] = new_large
            lt += new_large.size
        # Numerical leftovers on either queue have prob ~= 1.
        prob[qs[sh:st]] = 1.0
        prob[ql[lh:lt]] = 1.0

    def draw(self, rng: np.random.Generator, size) -> np.ndarray:
        n = int(np.prod(size))
        cell = rng.integers(0, self.prob.shape[0], size=n)
        keep = rng.random(n) < self.prob[cell]
        return np.where(keep, cell, self.alias[cell]).reshape(size)


# Hidden player archetypes (playstyle / preferred-role buckets): the
# composition channel. Small on purpose — 8 archetypes give 36 unordered
# teammate pairs, enough for a learnable synergy structure while every
# pair is seen often even in a 10k-match test stream.
N_ARCHETYPES = 8


@dataclasses.dataclass
class SyntheticPlayers:
    """Latent skills + observable seed features for a synthetic population."""

    latent_skill: np.ndarray  # [P] float64, the "true" skill driving outcomes
    rank_points_ranked: np.ndarray  # [P] float64, NaN = missing
    rank_points_blitz: np.ndarray  # [P] float64, NaN = missing
    skill_tier: np.ndarray  # [P] int32 in [-1, 29]
    # [P] int32 in [0, N_ARCHETYPES): the player's playstyle bucket — a
    # PRE-MATCH observable (like a draft pick), orthogonal to skill. Only
    # influences outcomes when synthetic_stream's synergy_strength > 0.
    archetype: np.ndarray = None

    @property
    def n_players(self) -> int:
        return self.latent_skill.shape[0]


def synthetic_players(n_players: int, seed: int = 0) -> SyntheticPlayers:
    rng = np.random.default_rng(seed)
    latent = rng.normal(1500.0, 400.0, n_players)
    # ~40% of players have rank points (fallback 1); the rest seed from tier.
    has_ranked = rng.random(n_players) < 0.35
    has_blitz = rng.random(n_players) < 0.15
    rp_ranked = np.where(has_ranked, np.clip(latent + rng.normal(0, 150, n_players), 1, None), np.nan)
    rp_blitz = np.where(has_blitz, np.clip(latent + rng.normal(0, 200, n_players), 1, None), np.nan)
    # Skill tier loosely tracks latent skill, clipped to the table range.
    tier = np.clip(((latent - 600.0) / 85.0).astype(np.int32), -1, 29)
    return SyntheticPlayers(
        latent_skill=latent,
        rank_points_ranked=rp_ranked,
        rank_points_blitz=rp_blitz,
        skill_tier=tier.astype(np.int32),
        # Drawn LAST so adding the archetype channel left every earlier
        # draw (and thus every historical stream/test fixture) unchanged.
        archetype=rng.integers(0, N_ARCHETYPES, n_players).astype(np.int32),
    )


def synergy_matrix(seed: int = 0) -> np.ndarray:
    """The hidden symmetric archetype-pair synergy matrix ``[A, A]``.

    Entries ~ N(0, 1); S[a, b] is the bonus (in units later scaled to
    skill points) each unordered {a, b} teammate pair contributes to its
    team's effective strength. Deterministic per stream seed — the
    generator and a test oracle can both reconstruct it; the learned
    heads never see it (they must recover it from outcomes)."""
    rng = np.random.default_rng(seed + 101)
    s = rng.normal(0.0, 1.0, (N_ARCHETYPES, N_ARCHETYPES))
    return (s + s.T) / np.sqrt(2.0)


def _team_synergy(
    archetype: np.ndarray, player_idx: np.ndarray, seed: int,
    chunk: int = 1_000_000,
) -> np.ndarray:
    """Mean unordered-teammate-pair synergy per team, ``[N, 2]`` float64.

    Chunked over matches: the [n, 2, T, T] pairwise gather at 10M
    matches would otherwise materialize ~4 GB at once."""
    s = synergy_matrix(seed)
    n, _, t = player_idx.shape
    out = np.zeros((n, 2), np.float64)
    off_diag = ~np.eye(t, dtype=bool)
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        idx = player_idx[lo:hi]
        mask = idx >= 0
        a = np.where(mask, archetype[np.clip(idx, 0, None)], 0)
        pair_mask = (mask[:, :, :, None] & mask[:, :, None, :]) & off_diag
        pair_s = s[a[:, :, :, None], a[:, :, None, :]]
        # Each unordered pair appears twice in the [T, T] grid.
        tot = (pair_s * pair_mask).sum((-1, -2)) / 2.0
        n_pairs = pair_mask.sum((-1, -2)) / 2.0
        out[lo:hi] = tot / np.maximum(n_pairs, 1.0)
    return out


def synthetic_stream(
    n_matches: int,
    players: SyntheticPlayers,
    seed: int = 0,
    afk_rate: float = 0.02,
    unsupported_rate: float = 0.005,
    activity_concentration: float = 1.2,
    max_activity_share: float | None = None,
    synergy_strength: float = 0.0,
) -> MatchStream:
    """Samples a chronologically ordered stream of two-team matches.

    Player selection is Zipf-flavored (``activity_concentration`` > 1 skews
    toward a hot head of active players, deepening the superstep dependency
    chain like real ladder traffic would). Winners are sampled from the
    latent-skill gap through a logistic link.

    ``synergy_strength`` > 0 adds a COMPOSITION-dependent term to the
    outcome draw: each team's effective strength gains
    ``synergy_strength * 400`` skill points per unit of mean
    archetype-pair synergy (:func:`synergy_matrix`). This is signal the
    per-player rating system CANNOT represent (it is a property of the
    team composition, not of any player), so the closed-form rating
    baseline stops being Bayes-optimal and a learned head with
    composition features has real headroom — the round-4 verdict's
    missing test bed. 0 (default) keeps the historical generator
    exactly (outcomes purely from latent skill).

    ``max_activity_share`` caps any single player's expected share of match
    slots. Unbounded Zipf gives the top player ~1/H(P, s) of ALL slots
    (~1.6% at P=300k, s=0.8) — i.e. one player "playing" 11% of a 2M-match
    history, which no human can (and which pins the superstep schedule at
    the depth of that player's match chain). A real multi-year ladder's
    hardest grinder plays a few thousand matches of tens of millions; pass
    e.g. ``1e-4`` (top player in ~0.08% of matches at ~8 slots/match) for
    that physically plausible profile. ``None`` keeps the raw Zipf weights.
    """
    rng = np.random.default_rng(seed)
    p = players.n_players
    n = n_matches

    # Heavy-tailed activity weights.
    ranks = np.arange(1, p + 1, dtype=np.float64)
    weights = 1.0 / ranks**activity_concentration
    if max_activity_share is not None:
        # Clip-and-renormalize until stable: clipping raises everyone
        # else's share, which can push new players over the cap. A cap
        # below 1/P is infeasible (uniform is the floor); the loop then
        # just converges toward uniform weights.
        cap = max(max_activity_share, 1.0 / p)
        for _ in range(64):
            clipped = np.minimum(weights, cap * weights.sum())
            if np.array_equal(clipped, weights):
                break
            weights = clipped
    rng.shuffle(weights)
    weights /= weights.sum()

    mode_id = rng.integers(0, constants.N_MODES, n).astype(np.int32)
    unsupported = rng.random(n) < unsupported_rate
    mode_id[unsupported] = constants.UNSUPPORTED_MODE_ID
    team_size = np.where(mode_id >= 0, _MODE_TEAM_SIZE[np.clip(mode_id, 0, None)], 3)

    t_max = int(team_size.max()) if n else 3
    player_idx = np.full((n, 2, t_max), -1, dtype=np.int32)
    afk = rng.random(n) < afk_rate

    # Sample 2*team_size distinct players per match, fully vectorized:
    # draw with replacement, then iteratively redraw only the rows that
    # still contain duplicates (converges in a few rounds).
    k_max = 2 * t_max
    sampler = AliasSampler(weights)
    flat = sampler.draw(rng, (n, k_max))
    need = np.arange(n)
    for _ in range(64):
        rows = flat[need]
        srt = np.sort(rows, axis=1)
        dup = (srt[:, 1:] == srt[:, :-1]).any(axis=1)
        need = need[dup]
        if need.size == 0:
            break
        flat[need] = sampler.draw(rng, (need.size, k_max))
    else:
        # Pathological weights: fix the stragglers exactly, one by one.
        for i in need:
            uniq = np.unique(flat[i])
            while uniq.size < k_max:
                extra = sampler.draw(rng, (k_max - uniq.size,))
                uniq = np.unique(np.concatenate([uniq, extra]))
            flat[i] = rng.permutation(uniq[:k_max])

    cols = np.arange(t_max)[None, :]
    ts_col = team_size[:, None]
    team0 = np.where(cols < ts_col, flat[:, :t_max], -1).astype(np.int32)
    team1 = np.where(cols < ts_col, flat[:, t_max : 2 * t_max], -1).astype(np.int32)
    player_idx[:, 0] = team0
    player_idx[:, 1] = team1

    # Outcome from latent skills: P(team0 wins) = logistic(gap / scale).
    skill = players.latent_skill
    masked = player_idx >= 0
    team_skill = np.where(masked, skill[np.clip(player_idx, 0, None)], 0.0).sum(axis=2)
    gap = team_skill[:, 0] - team_skill[:, 1]
    if synergy_strength > 0.0:
        syn = _team_synergy(players.archetype, player_idx, seed)
        gap = gap + synergy_strength * 400.0 * (syn[:, 0] - syn[:, 1])
    p_win = 1.0 / (1.0 + np.exp(-gap / (400.0 * np.maximum(team_size, 1))))
    winner = (rng.random(n) >= p_win).astype(np.int32)  # 0 if team0 wins

    return MatchStream(player_idx=player_idx, winner=winner, mode_id=mode_id, afk=afk)


TELEMETRY_STATS = ("kills", "deaths", "assists", "gold", "cs", "item_build")
N_ITEM_BUILDS = 8  # categorical: which of 8 canonical item builds was bought


def synthetic_telemetry(
    stream: MatchStream, players: SyntheticPlayers, seed: int = 0
) -> np.ndarray:
    """Per-participant POST-GAME telemetry ``[N, 2, T, 6]`` float32
    (kills, deaths, assists, gold, creep score, item build id), zero at
    padded slots. ``item_build`` is categorical in ``[0, N_ITEM_BUILDS)``
    — the "items" of BASELINE config 4, standing in for the reference's
    ``participant_items`` purchase record; builds carry a mild winrate
    bias so the head can learn meta strength from the draft histogram.

    BASELINE config 4's "MLP match-outcome predictor on full telemetry
    (items, gold, KDA)" consumes these. The reference's data model keeps
    them in ``participant_stats`` (``worker.py:75-78``) — wired into the
    ORM, never loaded by the rating path — so the telemetry head is an
    ANALYSIS model over finished matches, not a forecast (the leak-free
    forecasting features are ``models.features.match_features``).

    Signal structure: winners farm more gold/CS and trade kills for
    deaths; a player's latent skill shifts their individual output within
    the team; everything is noisy enough that the head must actually
    learn the aggregation.
    """
    rng = np.random.default_rng(seed + 7)
    n, _, t = stream.player_idx.shape
    mask = stream.player_idx >= 0
    skill = players.latent_skill[np.clip(stream.player_idx, 0, None)]
    z = ((skill - 1500.0) / 400.0).astype(np.float64)  # ~N(0,1)
    won = (np.arange(2)[None, :] == stream.winner[:, None]).astype(np.float64)
    w = won[:, :, None]  # [N,2,1]

    kills = rng.poisson(np.exp(0.25 * z + 0.7 * w - 0.1))
    deaths = rng.poisson(np.exp(-0.15 * z - 0.6 * w + 0.9))
    assists = rng.poisson(np.exp(0.15 * z + 0.5 * w + 0.4))
    gold = np.clip(rng.normal(8000 + 2500 * w + 800 * z, 1500), 0, None)
    cs = np.clip(rng.normal(120 + 25 * w + 15 * z, 30), 0, None)
    # Item builds: winners lean toward the stronger half of the meta
    # (builds 0..3), losers toward the weaker — a soft preference, so
    # the histogram is informative but not decisive.
    strong = rng.integers(0, N_ITEM_BUILDS // 2, size=(n, 2, t))
    weak = rng.integers(N_ITEM_BUILDS // 2, N_ITEM_BUILDS, size=(n, 2, t))
    prefer_strong = rng.random((n, 2, t)) < (0.35 + 0.3 * w)
    item_build = np.where(prefer_strong, strong, weak)

    out = np.stack(
        [kills, deaths, assists, gold, cs, item_build], axis=-1
    ).astype(np.float32)
    return out * mask[..., None].astype(np.float32)
