"""Synthetic match-history generation for tests and benchmarks.

Produces chronologically ordered streams with the reference's real-world
shape: a heavy-tailed player-activity distribution (a few very active
players — the worst case for superstep depth), a mix of 3v3 and 5v5 modes,
occasional AFK/invalid matches, and seed features (rank points / skill
tiers) distributed like the reference's fallback paths expect
(``rater.py:42-62``). Outcomes are sampled from latent skills so the
win-probability models (BASELINE configs 3-4) have signal to learn.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from analyzer_tpu.core import constants
from analyzer_tpu.sched.superstep import MatchStream

# 3v3 modes per MODES order: casual, ranked, blitz, br are 3v3; 5v5_* are 5.
_MODE_TEAM_SIZE = np.array([3, 3, 3, 3, 5, 5], dtype=np.int32)


@dataclasses.dataclass
class SyntheticPlayers:
    """Latent skills + observable seed features for a synthetic population."""

    latent_skill: np.ndarray  # [P] float64, the "true" skill driving outcomes
    rank_points_ranked: np.ndarray  # [P] float64, NaN = missing
    rank_points_blitz: np.ndarray  # [P] float64, NaN = missing
    skill_tier: np.ndarray  # [P] int32 in [-1, 29]

    @property
    def n_players(self) -> int:
        return self.latent_skill.shape[0]


def synthetic_players(n_players: int, seed: int = 0) -> SyntheticPlayers:
    rng = np.random.default_rng(seed)
    latent = rng.normal(1500.0, 400.0, n_players)
    # ~40% of players have rank points (fallback 1); the rest seed from tier.
    has_ranked = rng.random(n_players) < 0.35
    has_blitz = rng.random(n_players) < 0.15
    rp_ranked = np.where(has_ranked, np.clip(latent + rng.normal(0, 150, n_players), 1, None), np.nan)
    rp_blitz = np.where(has_blitz, np.clip(latent + rng.normal(0, 200, n_players), 1, None), np.nan)
    # Skill tier loosely tracks latent skill, clipped to the table range.
    tier = np.clip(((latent - 600.0) / 85.0).astype(np.int32), -1, 29)
    return SyntheticPlayers(
        latent_skill=latent,
        rank_points_ranked=rp_ranked,
        rank_points_blitz=rp_blitz,
        skill_tier=tier.astype(np.int32),
    )


def synthetic_stream(
    n_matches: int,
    players: SyntheticPlayers,
    seed: int = 0,
    afk_rate: float = 0.02,
    unsupported_rate: float = 0.005,
    activity_concentration: float = 1.2,
    max_activity_share: float | None = None,
) -> MatchStream:
    """Samples a chronologically ordered stream of two-team matches.

    Player selection is Zipf-flavored (``activity_concentration`` > 1 skews
    toward a hot head of active players, deepening the superstep dependency
    chain like real ladder traffic would). Winners are sampled from the
    latent-skill gap through a logistic link.

    ``max_activity_share`` caps any single player's expected share of match
    slots. Unbounded Zipf gives the top player ~1/H(P, s) of ALL slots
    (~1.6% at P=300k, s=0.8) — i.e. one player "playing" 11% of a 2M-match
    history, which no human can (and which pins the superstep schedule at
    the depth of that player's match chain). A real multi-year ladder's
    hardest grinder plays a few thousand matches of tens of millions; pass
    e.g. ``1e-4`` (top player in ~0.08% of matches at ~8 slots/match) for
    that physically plausible profile. ``None`` keeps the raw Zipf weights.
    """
    rng = np.random.default_rng(seed)
    p = players.n_players
    n = n_matches

    # Heavy-tailed activity weights.
    ranks = np.arange(1, p + 1, dtype=np.float64)
    weights = 1.0 / ranks**activity_concentration
    if max_activity_share is not None:
        # Clip-and-renormalize until stable: clipping raises everyone
        # else's share, which can push new players over the cap. A cap
        # below 1/P is infeasible (uniform is the floor); the loop then
        # just converges toward uniform weights.
        cap = max(max_activity_share, 1.0 / p)
        for _ in range(64):
            clipped = np.minimum(weights, cap * weights.sum())
            if np.array_equal(clipped, weights):
                break
            weights = clipped
    rng.shuffle(weights)
    weights /= weights.sum()

    mode_id = rng.integers(0, constants.N_MODES, n).astype(np.int32)
    unsupported = rng.random(n) < unsupported_rate
    mode_id[unsupported] = constants.UNSUPPORTED_MODE_ID
    team_size = np.where(mode_id >= 0, _MODE_TEAM_SIZE[np.clip(mode_id, 0, None)], 3)

    t_max = int(team_size.max()) if n else 3
    player_idx = np.full((n, 2, t_max), -1, dtype=np.int32)
    afk = rng.random(n) < afk_rate

    # Sample 2*team_size distinct players per match, fully vectorized:
    # draw with replacement, then iteratively redraw only the rows that
    # still contain duplicates (converges in a few rounds).
    k_max = 2 * t_max
    flat = rng.choice(p, size=(n, k_max), p=weights)
    need = np.arange(n)
    for _ in range(64):
        rows = flat[need]
        srt = np.sort(rows, axis=1)
        dup = (srt[:, 1:] == srt[:, :-1]).any(axis=1)
        need = need[dup]
        if need.size == 0:
            break
        flat[need] = rng.choice(p, size=(need.size, k_max), p=weights)
    else:
        # Pathological weights: fix the stragglers exactly, one by one.
        for i in need:
            uniq = np.unique(flat[i])
            while uniq.size < k_max:
                extra = rng.choice(p, size=k_max - uniq.size, p=weights)
                uniq = np.unique(np.concatenate([uniq, extra]))
            flat[i] = rng.permutation(uniq[:k_max])

    cols = np.arange(t_max)[None, :]
    ts_col = team_size[:, None]
    team0 = np.where(cols < ts_col, flat[:, :t_max], -1).astype(np.int32)
    team1 = np.where(cols < ts_col, flat[:, t_max : 2 * t_max], -1).astype(np.int32)
    player_idx[:, 0] = team0
    player_idx[:, 1] = team1

    # Outcome from latent skills: P(team0 wins) = logistic(gap / scale).
    skill = players.latent_skill
    masked = player_idx >= 0
    team_skill = np.where(masked, skill[np.clip(player_idx, 0, None)], 0.0).sum(axis=2)
    gap = team_skill[:, 0] - team_skill[:, 1]
    p_win = 1.0 / (1.0 + np.exp(-gap / (400.0 * np.maximum(team_size, 1))))
    winner = (rng.random(n) >= p_win).astype(np.int32)  # 0 if team0 wins

    return MatchStream(player_idx=player_idx, winner=winner, mode_id=mode_id, afk=afk)
