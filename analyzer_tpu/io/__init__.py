"""Host-side IO: match streams in and rating state out.

The reference's IO edge is RabbitMQ + MySQL (``worker.py:85-199``); its
"checkpoint" is the database itself (every batch commit persists all player
state — SURVEY.md section 5.4). Here the HBM-resident state is volatile, so
this package provides the replacements: synthetic match streams
(alias-method sampling), CSV interchange with a native single-pass scanner
(fastcsv.cc, ~30x the csv module; python fallback), binary .npz streams
for bulk interchange, and explicit state snapshots with match + superstep
cursors and a schedule fingerprint.
"""

from analyzer_tpu.io.synthetic import (
    synthetic_players,
    synthetic_stream,
    synthetic_telemetry,
)
from analyzer_tpu.io.csv_codec import (
    load_stream,
    load_stream_csv,
    load_stream_npz,
    save_stream,
    save_stream_csv,
    save_stream_npz,
)
from analyzer_tpu.io.checkpoint import load_checkpoint, save_checkpoint
from analyzer_tpu.io.dbgen import write_history_db

__all__ = [
    "write_history_db",
    "synthetic_stream",
    "synthetic_players",
    "synthetic_telemetry",
    "load_stream",
    "load_stream_csv",
    "load_stream_npz",
    "save_stream",
    "save_stream_csv",
    "save_stream_npz",
    "load_checkpoint",
    "save_checkpoint",
]
