"""Wire-speed columnar ingest: windowed CSV batch decode into reusable
pinned staging slabs (docs/ingest.md).

The reference ingest edge decodes matches one python object at a time;
the repo's first fast path (``csv_codec.load_stream_csv`` over
``fastcsv.cc``) already decodes a whole file natively, but into ONE
freshly allocated array set — a 10M-match stream still pays a giant
allocation up front, and the feed thread re-gathers windows out of it
before every H2D. This module is the next step: the native scanner's
windowed entry (``parse_csv_window``) decodes match windows DIRECTLY
into fixed-shape slabs leased from the process staging arena
(:class:`analyzer_tpu.sched.feed.PinnedArena`), so

  * steady state allocates nothing (slab reuse is the benchdiff
    ``ingest.arena_hit_rate`` gate);
  * each window H2Ds straight off the slab it was decoded into
    (:func:`analyzer_tpu.sched.feed.stage_ingest_window` — async DMA
    through ``pinned_host`` staging where the backend has one);
  * decode of window N+1 overlaps the in-flight transfer of window N
    when driven through a :class:`~analyzer_tpu.sched.feed.Prefetcher`
    (the bench's pipeline, ``bench.py`` BENCH_INGEST).

Semantics contract: the decoded columns are BIT-IDENTICAL to the
existing codec path — ``decode_stream_csv`` (the whole-stream parity
surface) returns exactly ``csv_codec.load_stream_csv``'s arrays, and
content-level gating downstream (AFK, unsupported-mode skips, the
``service/columnar.py`` write set) is therefore unchanged by
construction; pinned by the differential tests in
``tests/test_ingest.py``. A malformed row ends its window after the
valid prefix and raises :class:`IngestDecodeError` naming the ABSOLUTE
stream row (poison attribution); bytes the grammar cannot take at all
(quoted fields) report ``available = False`` so callers fall back to
the permissive python parser, counted in ``ingest.fallbacks_total``.
"""

from __future__ import annotations

import numpy as np

from analyzer_tpu.core import constants
from analyzer_tpu.obs import get_registry, get_tracer

#: Rows per decode window: at the default 16-slot team axis one window's
#: player slab is 4096 * 2 * 16 * 4 B = 512 KiB — big enough to amortize
#: the per-window call, small enough that a few slabs stay cache- and
#: arena-friendly.
DEFAULT_WINDOW_ROWS = 4096

#: Team-slot axis of the decode slabs (the codec's writer never exceeds
#: it; matches csv_codec.load_stream_csv's max_team).
DEFAULT_MAX_TEAM = 16


class IngestDecodeError(ValueError):
    """A malformed row in the columnar decode, attributed to its
    ABSOLUTE stream row (the poison-attribution contract: the caller
    can name the exact record, like the service lane's PoisonError)."""

    def __init__(self, row: int, byte_offset: int) -> None:
        super().__init__(
            f"malformed CSV row {row} (byte {byte_offset}) in the "
            "columnar decode; route the stream to the python parser "
            "or repair the record"
        )
        self.row = row
        self.byte_offset = byte_offset


class DecodedWindow:
    """One decoded match window living in arena slabs.

    ``player_idx`` / ``winner`` / ``mode_id`` / ``afk`` are TRIMMED
    views of the slabs (``[:rows]``); ``slabs`` is the full fixed-shape
    tuple the H2D edge commits (static shapes — one compiled transfer).
    ``release()`` returns the slabs to the arena; pass the committed
    device arrays so the return is deferred until their transfers
    report ready (``stage_ingest_window`` does this for you)."""

    __slots__ = ("slabs", "rows", "start_row", "_arena", "_released")

    def __init__(self, slabs, rows: int, start_row: int, arena) -> None:
        self.slabs = slabs
        self.rows = rows
        self.start_row = start_row
        self._arena = arena
        self._released = False

    @property
    def player_idx(self) -> np.ndarray:
        return self.slabs[0][: self.rows]

    @property
    def winner(self) -> np.ndarray:
        return self.slabs[1][: self.rows]

    @property
    def mode_id(self) -> np.ndarray:
        return self.slabs[2][: self.rows]

    @property
    def afk(self) -> np.ndarray:
        return self.slabs[3][: self.rows]

    def release(self, device_arrays=None) -> None:
        """Returns the window's slabs to the arena (idempotent). With
        ``device_arrays`` (one per slab, from the H2D commit) the
        return defers until each transfer reports ready."""
        if self._released:
            return
        self._released = True
        if device_arrays is None:
            for buf in self.slabs:
                self._arena.give(buf)
        else:
            for buf, dev in zip(self.slabs, device_arrays):
                self._arena.give_when_done(buf, dev)


class ColumnarDecoder:
    """Streaming columnar decoder over one CSV byte stream.

    ``available`` is False when the native scanner is absent or the
    bytes need the permissive python grammar (quoted fields) — callers
    fall back to ``csv_codec`` exactly like the whole-file fast path.
    Iterate :meth:`windows`; each yielded :class:`DecodedWindow` must be
    released (directly, or via ``stage_ingest_window``'s deferred
    release) before the arena can recycle its slabs.
    """

    def __init__(
        self,
        data: bytes,
        mode_names=None,
        max_team: int = DEFAULT_MAX_TEAM,
        window_rows: int = DEFAULT_WINDOW_ROWS,
        arena=None,
    ) -> None:
        from analyzer_tpu.sched.feed import get_arena

        if window_rows < 1:
            raise ValueError(f"window_rows must be >= 1, got {window_rows}")
        self.data = data
        self.max_team = int(max_team)
        self.window_rows = int(window_rows)
        self.arena = arena or get_arena()
        names = list(mode_names) if mode_names is not None else list(
            constants.MODES
        )
        self._modes_blob = "\n".join(names).encode()
        self._n_modes = len(names)
        self._cursor = np.zeros(1, np.int64)
        self.rows_decoded = 0
        self.windows_decoded = 0
        reg = get_registry()
        self._c_bytes = reg.counter("ingest.bytes_decoded_total")
        self._c_rows = reg.counter("ingest.rows_decoded_total")
        self._c_windows = reg.counter("ingest.windows_total")
        self._native = None
        self.available = False
        if b'"' not in data:
            try:
                from analyzer_tpu.io import _native_csv

                self._native = _native_csv
                self.available = True
            except ImportError:
                pass
        if not self.available:
            reg.counter("ingest.fallbacks_total").add(1)

    @property
    def bytes_consumed(self) -> int:
        return int(self._cursor[0])

    def windows(self):
        """Yields :class:`DecodedWindow`s until the stream is exhausted.
        Raises :class:`IngestDecodeError` on a malformed row (after the
        window holding the valid prefix has been yielded); raises
        RuntimeError when ``available`` is False — callers decide on
        fallback BEFORE iterating."""
        if not self.available:
            raise RuntimeError(
                "columnar decode unavailable for this stream (no native "
                "scanner, or csv-module grammar needed); fall back to "
                "csv_codec.load_stream_csv"
            )
        native = self._native
        arena = self.arena
        w, t = self.window_rows, self.max_team
        tracer = get_tracer()
        while True:
            slabs = (
                arena.take((w, 2, t), np.int32),
                arena.take((w,), np.int32),
                arena.take((w,), np.int32),
                arena.take((w,), np.uint8),
            )
            with tracer.span(
                "ingest.decode", cat="ingest", start_row=self.rows_decoded
            ):
                before = self.bytes_consumed
                try:
                    n = native.parse_csv_window(
                        self.data, self._modes_blob, self._n_modes, t,
                        self._cursor, *slabs,
                    )
                except native.WindowDecodeError as err:
                    for buf in slabs:
                        arena.give(buf)
                    raise IngestDecodeError(
                        self.rows_decoded + err.row, err.byte_offset
                    ) from err
            if n == 0:
                for buf in slabs:
                    arena.give(buf)
                return
            win = DecodedWindow(slabs, n, self.rows_decoded, arena)
            self.rows_decoded += n
            self.windows_decoded += 1
            self._c_rows.add(n)
            self._c_windows.add(1)
            self._c_bytes.add(self.bytes_consumed - before)
            yield win


def decode_stream_csv(
    data: bytes,
    mode_names=None,
    max_team: int = DEFAULT_MAX_TEAM,
    window_rows: int = DEFAULT_WINDOW_ROWS,
    arena=None,
):
    """Whole-stream decode through the windowed decoder — the parity
    surface the differential tests pin against ``csv_codec``: returns a
    MatchStream bit-identical to ``load_stream_csv``'s (trimmed to the
    stream's widest team, afk as bool), or None when the fast path
    cannot take the bytes (caller falls back, same contract as
    ``_native_csv.parse_stream_csv``)."""
    from analyzer_tpu.sched.superstep import MatchStream

    dec = ColumnarDecoder(
        data, mode_names, max_team=max_team, window_rows=window_rows,
        arena=arena,
    )
    if not dec.available:
        return None
    parts = []
    for win in dec.windows():
        parts.append((
            win.player_idx.copy(), win.winner.copy(),
            win.mode_id.copy(), win.afk.copy(),
        ))
        win.release()
    if not parts:
        return MatchStream(
            player_idx=np.full((0, 2, 1), -1, np.int32),
            winner=np.zeros(0, np.int32),
            mode_id=np.zeros(0, np.int32),
            afk=np.zeros(0, bool),
        )
    pidx = np.concatenate([p[0] for p in parts])
    # Trim the fixed slab width to the stream's widest team — the exact
    # shape the two-pass whole-file loader probes for.
    used = np.where((pidx >= 0).any(axis=(0, 1)))[0]
    tmax = int(used[-1]) + 1 if used.size else 1
    return MatchStream(
        player_idx=np.ascontiguousarray(pidx[:, :, :tmax]),
        winner=np.concatenate([p[1] for p in parts]),
        mode_id=np.concatenate([p[2] for p in parts]),
        afk=np.concatenate([p[3] for p in parts]).astype(bool),
    )
