"""Rating-state snapshots with a resume cursor.

The reference needs no checkpoint subsystem because MySQL *is* the
checkpoint: every 500-match batch commit persists all player state
(``worker.py:194``), so its blast radius on crash is one batch, and a
restarted worker resumes from the broker queue (SURVEY.md section 5.3-5.4).
With the player table living in HBM, restarts lose state — so snapshots are
explicit, and they are taken *mid-run* at superstep granularity so a long
re-rate has the same bounded blast radius.

Cursor semantics — two levels, because superstep packing is not
stream-prefix monotone (a late-stream match between fresh players can be
scheduled into an early superstep, so "state after step s" is not "state
after match m" for any m):

  * ``cursor`` — the stream offset the current schedule was packed from;
    matches before it are fully applied. A finished run stores
    ``cursor = n_matches, step_cursor = 0``.
  * ``step_cursor`` — progress within the deterministic packed schedule of
    ``stream[cursor:]``. Resume re-packs that slice (packing is a pure
    function of the stream) and re-enters the scan at this superstep.
  * ``schedule_fingerprint`` — hash of the packed schedule, verified on
    resume so a changed stream file or packing policy fails loudly instead
    of silently double-applying updates.

Format: a single ``.npz`` (atomic rename on save). The packed table carries
mu/sigma AND the precomputed seed columns, and the RatingConfig that baked
the seeds is stored alongside, so a restore needs no re-seeding and keeps
the seed/config consistency check intact. Orbax is a heavier dependency
than this state shape needs — the whole table is a handful of dense arrays
— but the layout is orbax-compatible (a flat dict of arrays) if sharded
async checkpointing becomes necessary at multi-host scale.
"""

from __future__ import annotations

import dataclasses
import os

import jax.numpy as jnp
import numpy as np

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core.state import PlayerState
from analyzer_tpu.utils.host import fetch_tree

_FIELDS = ("table", "rank_points_ranked", "rank_points_blitz", "skill_tier")
_CFG_FIELDS = tuple(f.name for f in dataclasses.fields(RatingConfig))
# v4: schedule fingerprints switched to the stream-content scheme
# (sched/superstep.py _ScheduleBase.fingerprint) — v3 mid-schedule digests
# are incomparable, so resuming one is refused with a clear error instead
# of the misleading "stream file changed".
_FORMAT_VERSION = 4


@dataclasses.dataclass(frozen=True)
class Checkpoint:
    state: PlayerState
    cursor: int  # stream offset the schedule was packed from
    step_cursor: int = 0  # superstep progress within that schedule
    schedule_fingerprint: str | None = None


def save_checkpoint(
    path: str,
    state: PlayerState,
    cursor: int = 0,
    step_cursor: int = 0,
    schedule_fingerprint: str | None = None,
) -> None:
    """Writes state + cursors atomically (tmp file + rename)."""
    # fetch_tree pipelines the D2H fetches (one link RTT, not four).
    arrays = fetch_tree({f: getattr(state, f) for f in _FIELDS})
    arrays["cursor"] = np.int64(cursor)
    arrays["step_cursor"] = np.int64(step_cursor)
    if schedule_fingerprint is not None:
        arrays["schedule_fingerprint"] = np.bytes_(schedule_fingerprint.encode())
    arrays["format_version"] = np.int64(_FORMAT_VERSION)
    cfg = state.seed_cfg
    if cfg is not None:
        arrays["seed_cfg"] = np.asarray([float(getattr(cfg, f)) for f in _CFG_FIELDS])
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)


class CheckpointWriter:
    """Asynchronous periodic snapshots: serialize + atomic-rename on a
    writer thread, so the run thread pays only the device fetch.

    The reference pays durability synchronously per 500-match commit
    (``worker.py:194``); round 2 did the same here — the full-table npz
    serialize ran on the scan thread, stalling the feed ~100 MB per
    snapshot at north-star scale (VERDICT round-2 weak #5). Now
    :meth:`save` fetches the state to host (one device sync — required
    anyway, and it pins the snapshot before the buffer is donated to the
    next chunk) and hands the write off; LATEST-WINS coalescing drops a
    still-unwritten older snapshot when a newer one arrives, because only
    the newest matters for resume. A crash mid-write is safe by the same
    atomicity as the sync path (``save_checkpoint`` writes ``.tmp`` then
    ``os.replace``): the previous snapshot file survives intact.
    :meth:`close` drains the queue and re-raises any write error.
    """

    def __init__(self, path: str) -> None:
        import threading

        self.path = path
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._pending: tuple | None = None
        self._stop = False
        self._err: BaseException | None = None
        self._thread = threading.Thread(
            target=self._loop, name="checkpoint-writer", daemon=True
        )
        self._thread.start()

    def save(
        self,
        state: PlayerState,
        cursor: int = 0,
        step_cursor: int = 0,
        schedule_fingerprint: str | None = None,
    ) -> None:
        """Fetches ``state`` to host (the only synchronous cost) and
        queues the write. Raises any error from a PREVIOUS write — a
        failing disk must not be discovered only at close()."""
        if self._err is not None:
            raise self._err
        # fetch_tree pipelines the per-field D2H round trips; this runs
        # on the scan thread, and the whole point of the async writer is
        # a short stall there.
        host = dataclasses.replace(
            state, **fetch_tree({f: getattr(state, f) for f in _FIELDS})
        )
        with self._lock:
            self._pending = (host, cursor, step_cursor, schedule_fingerprint)
            self._event.set()

    def _loop(self) -> None:
        while True:
            self._event.wait()
            with self._lock:
                self._event.clear()
                job, self._pending = self._pending, None
                stop = self._stop
            if job is not None:
                state, cursor, step_cursor, fp = job
                try:
                    save_checkpoint(
                        self.path, state, cursor=cursor,
                        step_cursor=step_cursor, schedule_fingerprint=fp,
                    )
                except BaseException as e:  # noqa: BLE001 — surfaced on save/close
                    self._err = e
            elif stop:
                return
            if stop:
                self._event.set()  # drain: re-check for a final pending job

    def close(self) -> None:
        """Drains pending writes, stops the thread, re-raises any error."""
        with self._lock:
            self._stop = True
            self._event.set()
        self._thread.join()
        if self._err is not None:
            raise self._err

    def __enter__(self) -> "CheckpointWriter":
        return self

    def __exit__(self, *exc) -> None:
        # Don't mask an in-flight exception with a write error.
        try:
            self.close()
        except BaseException:  # noqa: BLE001
            if exc[0] is None:
                raise


def load_checkpoint(path: str) -> Checkpoint:
    """Raises on unknown format version. Older finished-run snapshots
    still load (v2 predates step cursors; v3 differs only in fingerprint
    scheme); a v3 MID-schedule snapshot is refused — its fingerprint can
    never match a v4 digest, so resuming it would always be rejected with
    a misleading "stream changed" error downstream."""
    with np.load(path) as z:
        version = int(z["format_version"])
        if version not in (2, 3, _FORMAT_VERSION):
            raise ValueError(f"checkpoint format {version} != {_FORMAT_VERSION}")
        if version == 3 and "step_cursor" in z and int(z["step_cursor"]) > 0:
            raise ValueError(
                "mid-schedule checkpoint written under the old (v3) "
                "fingerprint scheme cannot be resumed by this version; "
                "re-rate from scratch or from a finished-run checkpoint"
            )
        cfg = None
        if "seed_cfg" in z:
            vals = z["seed_cfg"]
            cfg = RatingConfig(**dict(zip(_CFG_FIELDS, (float(v) for v in vals))))
        state = PlayerState(
            **{f: jnp.asarray(z[f]) for f in _FIELDS}, seed_cfg=cfg
        )
        fingerprint = None
        if "schedule_fingerprint" in z:
            fingerprint = bytes(z["schedule_fingerprint"]).decode()
        return Checkpoint(
            state=state,
            cursor=int(z["cursor"]),
            step_cursor=int(z["step_cursor"]) if "step_cursor" in z else 0,
            schedule_fingerprint=fingerprint,
        )
