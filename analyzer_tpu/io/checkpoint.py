"""Rating-state snapshots with a resume cursor.

The reference needs no checkpoint subsystem because MySQL *is* the
checkpoint: every batch commit persists all player state, and a restarted
worker resumes from the broker queue (SURVEY.md section 5.3-5.4). With the
player table living in HBM, restarts lose state — so snapshots are explicit:
the full PlayerState plus the stream cursor (index of the next unrated
match), making re-rate idempotent from any snapshot.

Format: a single ``.npz`` (atomic rename on save). The packed table carries
mu/sigma AND the precomputed seed columns, and the RatingConfig that baked
the seeds is stored alongside, so a restore needs no re-seeding and keeps
the seed/config consistency check intact. Orbax is a heavier dependency
than this state shape needs — the whole table is a handful of dense arrays
— but the layout is orbax-compatible (a flat dict of arrays) if sharded
async checkpointing becomes necessary at multi-host scale.
"""

from __future__ import annotations

import dataclasses
import os

import jax.numpy as jnp
import numpy as np

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core.state import PlayerState

_FIELDS = ("table", "rank_points_ranked", "rank_points_blitz", "skill_tier")
_CFG_FIELDS = tuple(f.name for f in dataclasses.fields(RatingConfig))
_FORMAT_VERSION = 2


def save_checkpoint(path: str, state: PlayerState, cursor: int = 0) -> None:
    """Writes state + cursor atomically (tmp file + rename)."""
    arrays = {f: np.asarray(getattr(state, f)) for f in _FIELDS}
    arrays["cursor"] = np.int64(cursor)
    arrays["format_version"] = np.int64(_FORMAT_VERSION)
    cfg = state.seed_cfg
    if cfg is not None:
        arrays["seed_cfg"] = np.asarray([float(getattr(cfg, f)) for f in _CFG_FIELDS])
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)


def load_checkpoint(path: str) -> tuple[PlayerState, int]:
    """Returns (state, cursor). Raises on version mismatch."""
    with np.load(path) as z:
        version = int(z["format_version"])
        if version != _FORMAT_VERSION:
            raise ValueError(f"checkpoint format {version} != {_FORMAT_VERSION}")
        cfg = None
        if "seed_cfg" in z:
            vals = z["seed_cfg"]
            cfg = RatingConfig(**dict(zip(_CFG_FIELDS, (float(v) for v in vals))))
        state = PlayerState(
            **{f: jnp.asarray(z[f]) for f in _FIELDS}, seed_cfg=cfg
        )
        return state, int(z["cursor"])
