"""Write a match history into a reference-schema sqlite database.

The reference's real data source is a MySQL schema of match / roster /
participant / participant_items / player rows keyed by TEXT api_ids
(``worker.py:50-83``). This generator materializes any
:class:`~analyzer_tpu.sched.superstep.MatchStream` (synthetic or
otherwise) in that shape, so the whole DB lane — service worker,
``rate --db``, ``elo/train --db``, the ingest benchmarks — can be
exercised end to end without production data:

    python -m analyzer_tpu.cli synth --matches 10000 --players 2000 --out h.db
    python -m analyzer_tpu.cli rate --db sqlite:///h.db --db-write

Deterministic id scheme (also relied on by the fixture builders):
match ``m{i:09d}`` in stream order with ascending ``created_at``,
rosters ``m...r{team}``, participants ``m...t{team}s{slot}``, players
``p{row:08d}``.
"""

from __future__ import annotations

import os
import sqlite3

import numpy as np

from analyzer_tpu.core import constants

SCHEMA = """
CREATE TABLE match (
    api_id TEXT PRIMARY KEY, game_mode TEXT, created_at INTEGER,
    trueskill_quality REAL
);
CREATE TABLE asset (id INTEGER PRIMARY KEY, match_api_id TEXT, url TEXT);
CREATE TABLE roster (
    api_id TEXT PRIMARY KEY, match_api_id TEXT, winner INTEGER
);
CREATE TABLE participant (
    api_id TEXT PRIMARY KEY, match_api_id TEXT, roster_api_id TEXT,
    player_api_id TEXT, skill_tier INTEGER, went_afk INTEGER,
    trueskill_mu REAL, trueskill_sigma REAL, trueskill_delta REAL
);
CREATE TABLE participant_stats (
    api_id TEXT PRIMARY KEY, participant_api_id TEXT, kills INTEGER
);
CREATE TABLE participant_items (
    api_id TEXT PRIMARY KEY, participant_api_id TEXT, any_afk INTEGER,
    trueskill_casual_mu REAL, trueskill_casual_sigma REAL,
    trueskill_ranked_mu REAL, trueskill_ranked_sigma REAL,
    trueskill_blitz_mu REAL, trueskill_blitz_sigma REAL,
    trueskill_br_mu REAL, trueskill_br_sigma REAL
);
CREATE TABLE player (
    api_id TEXT PRIMARY KEY, skill_tier INTEGER,
    rank_points_ranked REAL, rank_points_blitz REAL,
    trueskill_mu REAL, trueskill_sigma REAL,
    trueskill_casual_mu REAL, trueskill_casual_sigma REAL,
    trueskill_ranked_mu REAL, trueskill_ranked_sigma REAL,
    trueskill_blitz_mu REAL, trueskill_blitz_sigma REAL,
    trueskill_br_mu REAL, trueskill_br_sigma REAL,
    trueskill_5v5_casual_mu REAL, trueskill_5v5_casual_sigma REAL,
    trueskill_5v5_ranked_mu REAL, trueskill_5v5_ranked_sigma REAL
);
"""

# FK indexes: any real deployment has them; without them every selectin
# IN-list load in the service path is a full table scan (measured 81
# scans per 500-match batch). Created AFTER the bulk inserts — live
# indexes would be maintained row-by-row through millions of
# executemany rows.
INDEXES = """
CREATE INDEX idx_roster_match ON roster(match_api_id);
CREATE INDEX idx_part_match ON participant(match_api_id);
CREATE INDEX idx_part_roster ON participant(roster_api_id);
CREATE INDEX idx_items_part ON participant_items(participant_api_id);
CREATE INDEX idx_asset_match ON asset(match_api_id);
"""


def write_history_db(
    path: str, stream, players, items: bool = True,
) -> None:
    """Writes ``stream`` (+ the player features of ``players``, an
    :class:`~analyzer_tpu.io.synthetic.SyntheticPlayers`-shaped object)
    to a fresh sqlite database at ``path``. ``items=False`` skips the
    one-per-participant participant_items rows (the columnar ingest
    never reads them; the SERVICE lane requires them — rater.py:104)."""
    n_matches = stream.n_matches
    n_players = players.n_players
    # Overwrite like the .csv/.npz writers do — executescript against a
    # leftover file would raise "table match already exists".
    if os.path.exists(path):
        os.unlink(path)
    conn = sqlite3.connect(path)
    conn.executescript(SCHEMA)
    conn.execute("PRAGMA journal_mode=OFF")
    conn.execute("PRAGMA synchronous=OFF")

    def null_if_nan(x: float):
        return None if np.isnan(x) else float(x)

    conn.executemany(
        "INSERT INTO player (api_id, skill_tier, rank_points_ranked,"
        " rank_points_blitz) VALUES (?, ?, ?, ?)",
        (
            (f"p{i:08d}", int(players.skill_tier[i]),
             null_if_nan(players.rank_points_ranked[i]),
             null_if_nan(players.rank_points_blitz[i]))
            for i in range(n_players)
        ),
    )
    mode_names = {i: name for name, i in constants.MODE_TO_ID.items()}

    def match_rows():
        for m in range(n_matches):
            mid = int(stream.mode_id[m])
            name = mode_names.get(mid, "aral")  # unsupported mode name
            yield (f"m{m:09d}", name, 1_000_000 + m)

    def roster_rows():
        for m in range(n_matches):
            for t in range(2):
                yield (f"m{m:09d}r{t}", f"m{m:09d}",
                       1 if int(stream.winner[m]) == t else 0)

    def participant_rows():
        idx = stream.player_idx
        afk = stream.afk
        for m in range(n_matches):
            first = True
            for t in range(2):
                for s in range(idx.shape[2]):
                    p = int(idx[m, t, s])
                    if p < 0:
                        continue
                    yield (
                        f"m{m:09d}t{t}s{s}", f"m{m:09d}", f"m{m:09d}r{t}",
                        f"p{p:08d}", int(players.skill_tier[p]),
                        1 if (afk[m] and first) else 0,
                    )
                    first = False

    def items_rows():
        idx = stream.player_idx
        for m in range(n_matches):
            for t in range(2):
                for s in range(idx.shape[2]):
                    if int(idx[m, t, s]) < 0:
                        continue
                    pid = f"m{m:09d}t{t}s{s}"
                    yield (f"{pid}-items", pid)

    conn.executemany(
        "INSERT INTO match (api_id, game_mode, created_at) VALUES (?, ?, ?)",
        match_rows(),
    )
    conn.executemany(
        "INSERT INTO roster (api_id, match_api_id, winner) VALUES (?, ?, ?)",
        roster_rows(),
    )
    conn.executemany(
        "INSERT INTO participant (api_id, match_api_id, roster_api_id,"
        " player_api_id, skill_tier, went_afk) VALUES (?, ?, ?, ?, ?, ?)",
        participant_rows(),
    )
    if items:
        conn.executemany(
            "INSERT INTO participant_items (api_id, participant_api_id)"
            " VALUES (?, ?)",
            items_rows(),
        )
    conn.executescript(INDEXES)
    conn.commit()
    conn.close()
