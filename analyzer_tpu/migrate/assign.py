"""Incremental capacity-aware first-fit assignment — the streaming
front half's scheduler (docs/migration.md "Streaming front half" and
"Native front half").

``sched.superstep.assign_batches`` consumes a COMPLETE stream. The
migration engine's whole point is that no complete stream ever exists —
matches become visible one decode window at a time — so this module
carries the first-fit recurrence as RESTARTABLE state:
:meth:`IncrementalAssigner.feed` consumes exactly the newly decoded
slice ``[lo, hi)`` and leaves the per-player frontier, the batch fill
counts, and the union-find next-free index ready for the next window.
Feeding the windows in stream order produces assignments IDENTICAL to a
one-shot pass over the concatenated stream (pinned by
tests/test_migrate.py) — the decomposition into windows is invisible to
the result, so the emitted schedule is a pure function of (stream
bytes, capacity) regardless of decode timing.

:func:`IncrementalAssigner` is a thin ROUTER. The PRIMARY path is the
native windowed loop (``sched/packer.cc assign_ff_create/feed/finish/
destroy`` via :mod:`analyzer_tpu.sched._native`): the restartable state
lives behind a heap handle, ``feed`` runs with the GIL RELEASED and
publishes into the shared ``[2]`` int64 progress array at the pinned
:data:`PROGRESS_EVERY` cadence with release stores — so the feed
thread's sentinel-buffer visibility protocol and ``rate_stream``'s
condition-variable handshake are unchanged, and the front-half thread
stops serializing the decode behind a pure-python recurrence (ROADMAP
item 4's "front half's floor"). The python recurrence
(:class:`PyIncrementalAssigner`) remains as the always-available
FALLBACK and as the differential ORACLE: native windowed output must be
bit-identical to it — and, on filler-free streams, to the one-shot
``assign_batches_first_fit`` — across arbitrary window cuts
(tests/test_migrate.py, tests/test_native_props.py).

One deliberate divergence from the offline packer, shared by BOTH
implementations: NON-RATABLE matches (unsupported mode, AFK) are
assigned inline as capacity-consuming, dependency-free entries
(first-fit from batch 0) instead of being held back and backfilled into
other batches' padding slots. Holding them back requires knowing the
whole stream's filler population up front — exactly what streaming
forbids — and consuming them inline keeps occupancy high without it.
They read and write no rating state, so the final table and every
per-match output are bit-identical to any other placement
(``sched.runner.rate_stream``'s filler-placement argument); only the
slot a filler's gate outputs are computed in moves.
"""

from __future__ import annotations

import numpy as np

#: Periodic progress-publish interval (matches) inside one feed() slice —
#: same cadence contract as the one-shot python loop's
#: ``sched.superstep._PY_PROGRESS_EVERY`` and pinned equal to the native
#: loop's ``kFFProgressEvery`` (sched/packer.cc) so routing never
#: changes the consumer-visible publish rhythm.
PROGRESS_EVERY = 2048


def _load_native():
    """The ctypes loader, or None when the extension cannot build/load
    (or predates the windowed entries — a stale ``.so`` rebuilt lazily
    elsewhere must not crash the router)."""
    try:
        from analyzer_tpu.sched import _native

        _native.assign_ff_create  # noqa: B018 — probe the windowed ABI
    except (ImportError, AttributeError):
        return None
    return _native


_native_mod = _load_native()


def assign_native_available() -> bool:
    """Whether the GIL-released windowed first-fit loaded (the router's
    default path; surfaced as the ``migrate.assign_native`` gauge and
    ``Worker.stats()['migration']['assign_native']``)."""
    return _native_mod is not None


class PyIncrementalAssigner:
    """Restartable first-fit over a growing stream — the pure-python
    recurrence, kept as the always-available fallback AND the
    bit-exact differential oracle for the native windowed loop.

    ``out_batch`` / ``out_slot`` are the caller's preallocated int64
    buffers (sentinel-prefilled — the streamed feed's cross-thread
    visibility protocol, ``sched.runner.rate_stream``); ``progress`` is
    the shared ``[2]`` int64 publish array (``progress[0]`` = matches
    final, ``progress[1]`` = batches used, written by :meth:`finish`).
    ``on_progress`` is the condition-variable wakeup hook.
    """

    is_native = False

    def __init__(
        self,
        capacity: int,
        out_batch: np.ndarray,
        out_slot: np.ndarray,
        progress: np.ndarray | None = None,
        on_progress=None,
    ) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.out_batch = out_batch
        self.out_slot = out_slot
        self.progress = progress
        self.on_progress = on_progress
        self.n_assigned = 0
        # last[p] = batch of p's most recent ratable match, -1 if none.
        self._last = np.full(1024, -1, dtype=np.int64)
        self._fill: list[int] = []
        self._next_free: list[int] = []
        self._max_batch = -1

    # -- first-fit internals (the one-shot loop's, carried as state) ------
    def _ensure(self, b: int) -> None:
        fill, nxt = self._fill, self._next_free
        while len(fill) <= b:
            fill.append(0)
            nxt.append(len(nxt))

    def _find(self, b: int) -> int:
        self._ensure(b)
        nxt = self._next_free
        root = b
        while True:
            self._ensure(root)
            if nxt[root] == root:
                break
            root = nxt[root]
        while nxt[b] != root:
            b, nxt[b] = nxt[b], root
        return root

    def _grow_players(self, top: int) -> None:
        if top < self._last.size:
            return
        size = self._last.size
        while size <= top:
            size *= 2
        bigger = np.full(size, -1, dtype=np.int64)
        bigger[: self._last.size] = self._last
        self._last = bigger

    def _publish(self, upto: int) -> None:
        if self.progress is not None:
            # Entries [0, upto) are final; the GIL orders the out-buffer
            # stores before this publish (same contract as the one-shot
            # python loop's periodic publish).
            self.progress[0] = upto
        if self.on_progress is not None:
            self.on_progress()

    # -- public surface ---------------------------------------------------
    def feed(
        self,
        player_idx: np.ndarray,
        mode_id: np.ndarray,
        afk: np.ndarray,
        lo: int,
        hi: int,
    ) -> None:
        """Assigns matches ``[lo, hi)`` of the accumulated stream buffers
        (``player_idx [cap, 2, T]``, per-match scalars). Must be fed in
        stream order with no gaps; publishes progress at the end of the
        slice and every :data:`PROGRESS_EVERY` matches within it."""
        if hi <= lo:
            return
        if lo != self.n_assigned:
            raise ValueError(
                f"feed slices must be contiguous: expected lo="
                f"{self.n_assigned}, got {lo}"
            )
        cap = self.capacity
        last = self._last
        fill = self._fill
        out_b, out_s = self.out_batch, self.out_slot
        for i in range(lo, hi):
            if i > lo and not (i & (PROGRESS_EVERY - 1)):
                self._publish(i)
            ratable = mode_id[i] >= 0 and not afk[i]
            if ratable:
                players = player_idx[i].ravel()
                players = players[players >= 0]
                if players.size:
                    top = int(players.max())
                    if top >= last.size:
                        self._grow_players(top)
                        last = self._last
                    floor_b = int(last[players].max()) + 1
                else:
                    floor_b = 0
            else:
                players = None
                floor_b = 0  # dependency-free: first batch with room
            b = self._find(floor_b)
            out_b[i] = b
            out_s[i] = fill[b]
            fill[b] += 1
            if fill[b] == cap:
                self._ensure(b + 1)
                self._next_free[b] = b + 1
            if b > self._max_batch:
                self._max_batch = b
            if ratable and players is not None and players.size:
                last[players] = b
        self.n_assigned = hi
        self._publish(hi)

    @property
    def batches_used(self) -> int:
        """Batches holding at least one match so far."""
        return self._max_batch + 1

    def finish(self) -> None:
        """Publishes the final (n, batches-used) pair — the completion
        record the feed's tail logic reads after the join."""
        if self.progress is not None:
            self.progress[0] = self.n_assigned
            self.progress[1] = self.batches_used
        if self.on_progress is not None:
            self.on_progress()

    def close(self) -> None:
        """Interface parity with the native assigner's handle release —
        a no-op here (the state is plain python objects)."""


class NativeIncrementalAssigner:
    """The GIL-released windowed first-fit: restartable state behind a
    ``sched/packer.cc`` handle, same surface as
    :class:`PyIncrementalAssigner` (feed/finish/n_assigned/batches_used)
    and bit-identical output across any window decomposition.

    Each :meth:`feed` call passes the window-local slice pointers down;
    the C loop carries the frontier/fill/next-free state across calls
    and publishes ``progress[0]`` with release stores at the pinned
    :data:`PROGRESS_EVERY` cadence WHILE the GIL is released — a
    consumer polling under ``cv.wait(poll_interval)`` sees fresh
    entries mid-window exactly as it does under the python loop's
    in-GIL publishes (the one behavioral difference: ``on_progress``
    fires once per window, after the native call returns, because a
    GIL-released loop cannot call back into python — the engine keeps
    ``poll_interval`` around solely as the wait timeout covering that
    gap, the same contract ``sched/superstep.py`` documents for the
    one-shot loop). The handle is freed by :meth:`close` (idempotent,
    also via ``__del__``); destroy without finish is legal and leaks
    nothing (tests/sanitize_driver.py drives it under ASan).
    """

    is_native = True

    def __init__(
        self,
        capacity: int,
        out_batch: np.ndarray,
        out_slot: np.ndarray,
        progress: np.ndarray | None = None,
        on_progress=None,
        n_hint: int = 0,
    ) -> None:
        if _native_mod is None:
            raise RuntimeError(
                "native windowed assigner requested but the extension "
                "did not load (assign_native_available() is False)"
            )
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.out_batch = out_batch
        self.out_slot = out_slot
        self.progress = progress
        self.on_progress = on_progress
        self.n_assigned = 0
        self._handle = _native_mod.assign_ff_create(self.capacity, n_hint)

    def feed(
        self,
        player_idx: np.ndarray,
        mode_id: np.ndarray,
        afk: np.ndarray,
        lo: int,
        hi: int,
    ) -> None:
        """Same contract as :meth:`PyIncrementalAssigner.feed` — slices
        ``[lo, hi)`` of the accumulated stream buffers, contiguous, in
        stream order. The ratable gate vectorizes on the python side
        (one uint8 window); everything per-match runs in C."""
        if hi <= lo:
            return
        if lo != self.n_assigned:
            raise ValueError(
                f"feed slices must be contiguous: expected lo="
                f"{self.n_assigned}, got {lo}"
            )
        if self._handle is None:
            raise ValueError("assigner already closed")
        n = hi - lo
        idx = player_idx[lo:hi].reshape(n, -1)
        ratable = np.asarray(
            (mode_id[lo:hi] >= 0) & ~afk[lo:hi], dtype=np.uint8
        )
        # ``close()`` does rebind self._handle, but never concurrently
        # with a feed: the engine joins the front thread before its
        # finally-block close, and __del__ implies no live references.
        # graftlint: disable=GL041 — close() is ordered after the join
        _native_mod.assign_ff_feed(
            self._handle, idx, ratable, lo, hi,
            self.out_batch, self.out_slot, self.progress,
        )
        self.n_assigned = hi
        if self.on_progress is not None:
            self.on_progress()

    @property
    def batches_used(self) -> int:
        """Batches holding at least one match so far (reads the native
        high-water mark without publishing)."""
        if self._handle is None:
            raise ValueError("assigner already closed")
        return _native_mod.assign_ff_finish(self._handle, None)

    def finish(self) -> None:
        """Publishes the final (n, batches-used) pair — the completion
        record the feed's tail logic reads after the join."""
        if self._handle is None:
            raise ValueError("assigner already closed")
        _native_mod.assign_ff_finish(self._handle, self.progress)
        if self.on_progress is not None:
            self.on_progress()

    def close(self) -> None:
        """Releases the native handle (idempotent; finish optional)."""
        h, self._handle = self._handle, None
        if h is not None:
            _native_mod.assign_ff_destroy(h)

    def __del__(self) -> None:  # pragma: no cover — GC timing
        self.close()


def IncrementalAssigner(
    capacity: int,
    out_batch: np.ndarray,
    out_slot: np.ndarray,
    progress: np.ndarray | None = None,
    on_progress=None,
    native: bool | None = None,
):
    """The router: native windowed first-fit when the extension loads,
    the python recurrence otherwise. ``native=True`` demands the native
    path (raises when unavailable — the differential tests' knob);
    ``native=False`` forces the python oracle; ``None`` auto-selects.
    Both returns expose the same surface (``feed``/``finish``/``close``/
    ``n_assigned``/``batches_used``/``is_native``)."""
    use = assign_native_available() if native is None else native
    cls = NativeIncrementalAssigner if use else PyIncrementalAssigner
    return cls(capacity, out_batch, out_slot, progress, on_progress)
