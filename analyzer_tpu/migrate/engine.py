"""The streaming backfill engine: decode -> assign -> stage -> scan, all
overlapped on the existing feed ring (docs/migration.md).

``sched.runner.rate_stream`` overlaps ASSIGNMENT with the device scan
but still requires the whole decoded stream up front — a CSV re-rate
pays the full columnar (or python) decode as a sequential prefix, with
the decoded arrays materialized whole-file before the first assignment
step runs. This engine moves the overlap one stage upstream, completing
ROADMAP item 5's remainder:

  * a FRONT-HALF thread iterates :class:`analyzer_tpu.io.ingest.
    ColumnarDecoder` windows — each window decodes natively into pinned
    arena slabs, appends into preallocated stream buffers (sized once
    from the byte stream's newline count: steady-state host allocations
    are flat at arena-ring size), and feeds the incremental first-fit
    assigner (:mod:`analyzer_tpu.migrate.assign`), publishing progress
    through the same sentinel-buffer + condition-variable handshake as
    ``rate_stream``;
  * the FEED thread scatters newly assigned slots into the slot->match
    map, materializes each complete window, and issues its async device
    transfer (``sched/feed.py`` ring — residency/tier staging included);
  * the CONSUMER dispatches committed slabs to the scan — reference,
    fused, and tiered kernels all supported — publishing throttled view
    snapshots into the STAGING lineage and pausing under the
    :class:`~analyzer_tpu.service.broker.AdmissionController`'s verdict
    so a live plane's commits keep their headroom.

Time-to-first-dispatch is O(the planning prefix — ``plan_windows``
decode windows — + spc batches of assignment) instead of O(file). The
front half runs NATIVE by default: ``migrate/assign.py`` routes the
incremental first-fit through the GIL-released windowed loop in
``sched/packer.cc`` (the python recurrence stays as fallback and
bit-exact oracle). Determinism: the emitted schedule is a pure function
of (bytes, batch_size, steps_per_chunk) — window boundaries are fixed
multiples of ``steps_per_chunk``, the assigner is sequential over
stream order, and non-ratable matches are consumed inline (see
``migrate/assign.py`` on why, and why results are bit-identical to
every other placement); the auto-chosen batch size is itself a pure
function of (the planning-prefix bytes, the knobs), and
:func:`migration_fingerprint` folds that policy in. The final table and
collected outputs are bit-identical to ``rate_stream`` over the same decoded
stream (pinned by tests/test_migrate.py), and a resumed run
(``start_step`` from a checkpoint) reproduces the uninterrupted run's
table bit for bit — the front half re-derives the identical schedule
from the bytes and skips device work below the watermark.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from analyzer_tpu.core.state import MAX_TEAM_SIZE
from analyzer_tpu.io.ingest import ColumnarDecoder, DEFAULT_WINDOW_ROWS
from analyzer_tpu.lint.ownership import thread_role
from analyzer_tpu.migrate.assign import (
    IncrementalAssigner,
    assign_native_available,
)
from analyzer_tpu.migrate.progress import get_migration_progress
from analyzer_tpu.obs import (
    get_registry,
    get_tracer,
    maybe_sample_device_memory,
)
from analyzer_tpu.sched.feed import (
    DEFAULT_DEPTH,
    FeedStageError,
    Prefetcher,
    stage_fused_windows,
)
from analyzer_tpu.sched.residency import resolve_fuse
from analyzer_tpu.sched.runner import (
    _dispatch_fused_chunk,
    _gather_outputs,
    _scan_chunk,
)
from analyzer_tpu.sched.superstep import (
    MatchStream,
    choose_batch_size_streamed,
    compact_device_window,
    materialize_gather_window,
    materialize_scalar_window,
)
from analyzer_tpu.sched.tier import TierManager
from analyzer_tpu.utils.host import fetch_tree


#: Decode windows in the batch-size PLANNING PREFIX (``plan_windows``):
#: one window can undershoot b on heavy-tailed ladders (a 4096-row head
#: may miss the tail's width distribution); a few windows are still an
#: O(prefix) launch cost. Small by design — raise it per run, not here.
DEFAULT_PLAN_WINDOWS = 4


def migration_fingerprint(
    data: bytes,
    batch_size: int,
    spc: int,
    plan_windows: int | None = None,
    window_rows: int | None = None,
) -> str:
    """Identity of one migration's emitted schedule: the schedule is a
    pure function of (bytes, batch size, window size), so this is what a
    mid-run checkpoint stores and a resume verifies — a changed input
    file or chunking policy fails loudly instead of double-applying.

    ``plan_windows``/``window_rows`` fold the batch-size PLANNING-PREFIX
    policy in: the chosen b is a pure function of (the first
    ``plan_windows * window_rows`` rows of the byte stream, the knobs),
    so a resume under a different prefix policy — which could re-derive
    a different b and with it a different schedule — fails as loudly as
    changed bytes do. The engine always passes both; the bare 3-arg form
    (policy-free hash) remains for content-only identities."""
    h = hashlib.sha1()
    h.update(b"migrate-v1")
    h.update(hashlib.sha256(data).digest())
    h.update(np.asarray((batch_size, spc), np.int64).tobytes())
    if plan_windows is not None or window_rows is not None:
        h.update(b"plan-v2")
        h.update(
            np.asarray(
                (plan_windows or 0, window_rows or 0), np.int64
            ).tobytes()
        )
    return h.hexdigest()


class _StreamView:
    """MatchStream-shaped window over the growing decode buffers — the
    materializers only gather rows below the assigned frontier, so the
    full-capacity buffers are safe to expose while the front half is
    still appending past it (disjoint regions, plain GIL stores)."""

    __slots__ = ("player_idx", "winner", "mode_id", "afk")

    def __init__(self, player_idx, winner, mode_id, afk) -> None:
        self.player_idx = player_idx
        self.winner = winner
        self.mode_id = mode_id
        self.afk = afk

    @property
    def n_matches(self) -> int:
        return self.player_idx.shape[0]

    @property
    def team_size(self) -> int:
        return self.player_idx.shape[2]


def _decode_fallback(data: bytes):
    """The python-codec whole-stream decode (quoted grammar, or no
    native scanner) — counted, and surfaced in the bench artifact as
    ``streamed: false`` so the migrate family's vanished-block gate
    catches a silent fall-back to the offline re-rate shape."""
    import io as _io

    from analyzer_tpu.io.csv_codec import load_stream_csv

    get_registry().counter("migrate.fallbacks_total").add(1)
    return load_stream_csv(_io.StringIO(data.decode("utf-8")))


def rate_backfill(
    state,
    data: bytes,
    cfg,
    collect: bool = False,
    batch_size: int | None = None,
    steps_per_chunk: int | None = None,
    team_size: int | None = None,
    window_rows: int = DEFAULT_WINDOW_ROWS,
    plan_windows: int | None = None,
    mode_names=None,
    arena=None,
    prefetch_depth: int | None = None,
    assign_native: bool | None = None,
    kernel: str = "reference",
    fuse_window: int | None = None,
    fuse_max_rows: int | None = None,
    fuse_backend: str | None = None,
    hot_rows: int = 0,
    staging=None,
    ids=None,
    on_chunk=None,
    start_step: int = 0,
    stop_after: int | None = None,
    expected_fingerprint: str | None = None,
    fingerprint_out: dict | None = None,
    admission=None,
    live_backlog=None,
    throttle_poll_s: float = 0.002,
    poll_interval: float = 0.002,
    stats_out: dict | None = None,
):
    """Rates a raw CSV byte stream with decode, assignment, staging and
    the device scan fully overlapped. Returns ``(state, outputs)`` like
    the sched runners.

    ``staging`` is the STAGING-lineage view publisher the backfill
    publishes throttled snapshots into (plus an unthrottled final
    publish carrying ``ids`` when given) — never a live lineage;
    graftlint GL033 makes that structural. ``admission`` (an
    :class:`~analyzer_tpu.service.broker.AdmissionController`) +
    ``live_backlog`` (zero-arg callable: live messages waiting) gate
    every window dispatch: a non-zero live backlog or busy host
    telemetry pauses the consumer, which backpressures the feed ring and
    with it the backfill's staging and H2D traffic — the in-process form
    of the broker's backfill lane arbitration (decode itself runs ahead
    into the preallocated buffers: host-memory-bounded and cheap next to
    the scan). Give the engine its OWN controller instance — ``quota``
    consumes telemetry deltas, so sharing a broker's controller would
    halve both consumers' signal.

    ``start_step``/``stop_after``/``expected_fingerprint`` are the
    resume protocol: the front half always re-derives the full schedule
    from the bytes (cheap host work), windows at or below ``start_step``
    skip staging and dispatch entirely, and the fingerprint — published
    into ``fingerprint_out['fingerprint']`` before the first dispatch —
    is verified against the checkpoint's so a changed input fails loudly.
    ``stop_after`` ends the run at a window boundary at or after that
    step (the kill point of the resume tests).

    ``plan_windows`` (default :data:`DEFAULT_PLAN_WINDOWS`) is the
    batch-size PLANNING PREFIX: that many decode windows are consumed on
    the caller's thread before ``b`` commits, so a heavy-tailed ladder
    whose head undersells the width distribution no longer undershoots
    the choice. The prefix is a pure function of (stream bytes, knobs) —
    the policy folds into :func:`migration_fingerprint`, so resuming
    under a changed policy fails loudly. ``assign_native`` forces the
    assigner route (True = demand the GIL-released native windowed
    first-fit, False = the python oracle; None auto-selects — see
    ``migrate/assign.py``).

    ``kernel``/``fuse_*``/``hot_rows``/``prefetch_depth``/``collect``/
    ``on_chunk`` mirror :func:`analyzer_tpu.sched.runner.rate_stream`.
    On a stream the columnar decoder cannot take (quoted fields, no
    native scanner) the engine falls back to the non-streamed path —
    python decode then ``rate_stream`` — preserving results; the
    fall-back is counted and resume is refused there (the streamed
    schedule is the resume contract).
    """
    fuse = resolve_fuse(kernel, fuse_window, fuse_max_rows, fuse_backend)
    if hot_rows < 0:
        raise ValueError(f"hot_rows must be >= 0, got {hot_rows}")
    if start_step and collect:
        raise ValueError(
            "collect=True is not supported on a resumed run — per-match "
            "outputs below the resume watermark were produced (and "
            "discarded) by the interrupted run; collect on the full run "
            "or re-rate from scratch"
        )
    team = team_size or MAX_TEAM_SIZE
    prog = get_migration_progress()
    prog.begin(resumed_from=start_step)
    reg = get_registry()
    tracer = get_tracer()
    t_start = time.perf_counter()

    decoder = ColumnarDecoder(
        data, mode_names, max_team=team, window_rows=window_rows,
        arena=arena,
    )
    if not decoder.available:
        if start_step or expected_fingerprint:
            raise ValueError(
                "cannot resume a migration on the python-codec fallback "
                "path (the streamed schedule is the resume contract); "
                "repair the stream for the columnar grammar or re-rate "
                "from scratch"
            )
        stream = _decode_fallback(data)
        from analyzer_tpu.sched.runner import rate_stream

        stats: dict = {}
        state, outs = rate_stream(
            state, stream, cfg, collect=collect, batch_size=batch_size,
            steps_per_chunk=steps_per_chunk,
            view_publisher=staging, on_chunk=on_chunk,
            prefetch_depth=prefetch_depth, kernel=kernel,
            fuse_window=fuse_window, fuse_max_rows=fuse_max_rows,
            fuse_backend=fuse_backend, hot_rows=hot_rows,
            stats_out=stats,
        )
        if staging is not None and ids is not None:
            staging.publish_state(state, ids=ids)
        stats.update(streamed=False, matches=stream.n_matches)
        if stats_out is not None:
            stats_out.update(stats)
        prog.finish()
        return state, outs

    pad_row = state.pad_row
    tier = TierManager(state, hot_rows) if hot_rows else None
    if tier is not None and fuse is not None:
        fuse = tier.clamp_fuse(fuse)
    state = tier.hot_state() if tier is not None \
        else jax.tree.map(jnp.copy, state)

    # One allocation per column, sized from the byte stream's newline
    # count (an upper bound on rows — header and trailing newline only
    # overshoot): steady-state host allocations stay flat while the
    # decode slabs themselves recycle through the arena ring.
    n_bound = data.count(b"\n") + 1
    pidx_buf = np.full((n_bound, 2, team), -1, np.int32)
    winner_buf = np.zeros(n_bound, np.int32)
    mode_buf = np.zeros(n_bound, np.int32)
    afk_buf = np.zeros(n_bound, bool)
    view_stream = _StreamView(pidx_buf, winner_buf, mode_buf, afk_buf)

    n_decoded = [0]

    def append(win) -> tuple[int, int]:
        lo = n_decoded[0]
        hi = lo + win.rows
        if hi > n_bound:  # the newline bound is an invariant of the grammar
            raise RuntimeError(
                f"decoded {hi} rows past the {n_bound}-row byte bound"
            )
        pidx_buf[lo:hi] = win.player_idx
        winner_buf[lo:hi] = win.winner
        mode_buf[lo:hi] = win.mode_id
        afk_buf[lo:hi] = win.afk
        win.release()
        if hi > lo and int(pidx_buf[lo:hi].max()) >= pad_row:
            raise ValueError(
                f"stream references player row {int(pidx_buf[lo:hi].max())} "
                f"but the player table only has rows 0..{pad_row - 1}"
            )
        n_decoded[0] = hi
        prog.note_decoded(hi)
        return lo, hi

    # The PLANNING PREFIX decodes on THIS thread: the batch-size choice
    # needs a prefix, and committing after ONE window can undershoot b
    # on heavy-tailed ladders (a 4096-row head may miss the width
    # distribution's tail). ``plan_windows`` decode windows are consumed
    # up front instead — still O(prefix) launch latency, and the choice
    # stays a deterministic pure function of (the prefix bytes, the
    # knobs), which migration_fingerprint folds in (documented
    # divergence from rate_stream's n/8 prefix — the whole stream
    # length is unknown here).
    k_plan = (
        DEFAULT_PLAN_WINDOWS if plan_windows is None else int(plan_windows)
    )
    if k_plan < 1:
        raise ValueError(f"plan_windows must be >= 1, got {plan_windows}")
    win_iter = decoder.windows()
    prefix_windows = 0
    for _ in range(k_plan):
        win = next(win_iter, None)
        if win is None:
            break
        append(win)
        prefix_windows += 1
    n0 = n_decoded[0]
    if n0 == 0:
        if stats_out is not None:
            stats_out.update(
                n_steps=0, batch_size=0, occupancy=0.0, matches=0,
                streamed=True, ttfd_s=None,
                plan_windows=k_plan, prefix_windows=prefix_windows,
                prefix_rows=0,
                assign_native=(
                    assign_native if assign_native is not None
                    else assign_native_available()
                ),
            )
        if tier is not None:
            state = tier.finish(state.table)
        if staging is not None:
            staging.publish_state(state, ids=ids)
        prog.finish()
        return state, (
            _gather_outputs([], np.empty(0, np.int32), 0, team)
            if collect else None
        )
    if batch_size is None:
        b = choose_batch_size_streamed(
            MatchStream(
                pidx_buf[:n0], winner_buf[:n0], mode_buf[:n0], afk_buf[:n0]
            ),
            prefix=n0,
        )
    else:
        b = batch_size
    spc = steps_per_chunk or min(8192, max(256, -(-n_bound // b) // 8 or 1))
    fingerprint = migration_fingerprint(
        data, b, spc, plan_windows=k_plan, window_rows=window_rows
    )
    if fingerprint_out is not None:
        fingerprint_out["fingerprint"] = fingerprint
    if expected_fingerprint is not None and fingerprint != expected_fingerprint:
        raise ValueError(
            "checkpoint was taken mid-migration but the derived schedule "
            "no longer matches (stream bytes, batch size, or chunking "
            "changed); re-rate from scratch or fix the input"
        )

    if start_step and start_step % spc:
        # Mid-run checkpoints are only ever taken at window boundaries
        # (multiples of spc); anything else would make the first resumed
        # window straddle the watermark and double-apply its prefix.
        raise ValueError(
            f"start_step {start_step} is not a window boundary "
            f"(steps_per_chunk={spc}); resume from the checkpoint's own "
            "step cursor"
        )
    sentinel = np.iinfo(np.int64).min
    progress = np.zeros(2, np.int64)
    out_b = np.full(n_bound, sentinel, np.int64)
    out_s = np.full(n_bound, sentinel, np.int64)
    worker_err: list[BaseException] = []
    cv = threading.Condition()
    assigner_done = [False]
    stop_flag = [False]

    def notify_progress():
        with cv:
            cv.notify_all()

    assigner = IncrementalAssigner(
        b, out_b, out_s, progress, on_progress=notify_progress,
        native=assign_native,
    )
    # The front-half's route is an operator signal (the benchdiff
    # migrate family's assign-native gate catches a silent fall-back to
    # the python recurrence): gauge for scrapes, progress block for
    # /statusz, stats for the bench artifact.
    reg.gauge("migrate.assign_native").set(assigner.is_native)
    prog.note_assign_backend(assigner.is_native)

    def assign_window(lo: int, hi: int) -> None:
        with tracer.span("migrate.assign", cat="migrate", start=lo):
            assigner.feed(pidx_buf, mode_buf, afk_buf, lo, hi)
        reg.counter("migrate.assign_matches_total").add(hi - lo)
        prog.note_assigned(assigner.n_assigned)

    @thread_role("producer")
    def front():
        """The front-half thread: decode window -> append -> assign,
        repeating until the stream is exhausted (or the run stopped).
        The native assigner releases the GIL for each feed window, so
        this thread no longer serializes the decode behind a python
        recurrence; the poll_interval timeout on the consumer's wait
        covers the in-window gap where no python-side wakeup can fire."""
        try:
            if n_decoded[0]:
                assign_window(0, n_decoded[0])
            for win in win_iter:
                if stop_flag[0]:  # bounded run ended: stop decoding
                    win.release()
                    break
                lo, hi = append(win)
                assign_window(lo, hi)
            assigner.finish()
        except BaseException as e:  # noqa: BLE001 — re-raised on the consumer
            worker_err.append(e)
        finally:
            with cv:
                assigner_done[0] = True
                cv.notify_all()

    front_thread = threading.Thread(
        target=front, name="migrate-front", daemon=True
    )
    front_thread.start()

    cap_steps = max(-(-n_bound // b) + 2, 2)
    slot_map = np.full(cap_steps * b, -1, np.int32)
    fill_count = np.zeros(cap_steps, np.int32)
    done_m = 0
    emitted = 0  # windows below start_step advance this without staging
    watermark = 0
    outs = [] if collect else None

    def grow(min_steps: int) -> None:
        nonlocal slot_map, fill_count, cap_steps
        if min_steps <= cap_steps:
            return
        while cap_steps < min_steps:
            cap_steps *= 2
        bigger = np.full(cap_steps * b, -1, np.int32)
        bigger[: slot_map.size] = slot_map
        slot_map = bigger
        bigger_c = np.zeros(cap_steps, np.int32)
        bigger_c[: fill_count.size] = fill_count
        fill_count = bigger_c

    def scatter_new(p: int) -> None:
        """Consumes assignment entries [done_m, p), trimming at the
        first not-yet-visible (sentinel) entry — rate_stream's weak-
        ordering discipline verbatim; every entry here is >= 0 (fillers
        are assigned inline), so no liveness mask is needed."""
        nonlocal done_m, watermark
        if p <= done_m:
            return
        nb = out_b[done_m:p]
        ns = out_s[done_m:p]
        unwritten = np.flatnonzero((nb == sentinel) | (ns == sentinel))
        if unwritten.size:
            p = done_m + int(unwritten[0])
            if p <= done_m:
                return
            nb = out_b[done_m:p]
            ns = out_s[done_m:p]
        grow(int(nb.max()) + 1)
        slot_map[nb * b + ns] = np.arange(done_m, p, dtype=np.int32)
        counts = np.bincount(nb)
        fill_count[: counts.size] += counts.astype(np.int32)
        while watermark < cap_steps and fill_count[watermark] >= b:
            watermark += 1
        done_m = p

    def stage(e0: int, e1: int):
        mi = slot_map[e0 * b : e1 * b].reshape(e1 - e0, b)
        with tracer.span("feed.materialize", cat="sched", start=e0):
            pidx, _mask = materialize_gather_window(
                view_stream, mi, pad_row, team
            )
            winner, mode_id, afk = materialize_scalar_window(view_stream, mi)
        if fuse is not None:
            return stage_fused_windows(
                pidx, winner, mode_id, afk, pad_row, fuse,
                match_idx=mi if collect else None, start=e0, tier=tier,
            )
        if tier is not None:
            with tracer.span("feed.transfer", cat="sched", start=e0):
                return tier.stage_windows(pidx, winner, mode_id, afk)
        with tracer.span("feed.transfer", cat="sched", start=e0):
            return compact_device_window(pidx, winner, mode_id, afk)

    def stage_checked(e0: int, e1: int):
        try:
            return stage(e0, e1)
        except Exception as e:
            raise FeedStageError(e0, e1) from e

    result: dict = {}

    def emit_ready(put) -> bool:
        """Emits every window the watermark covers; returns whether any
        advanced. Windows wholly below ``start_step`` skip staging and
        dispatch (resume); ``stop_after`` ends emission at the first
        boundary at or past it (the bounded-run kill point)."""
        nonlocal emitted
        advanced = False
        while watermark - emitted >= spc:
            if stop_after is not None and emitted >= stop_after:
                result["stopped"] = True
                return advanced
            e1 = emitted + spc
            if e1 <= start_step:
                emitted = e1
            else:
                put((emitted, e1, stage_checked(emitted, e1)))
                emitted = e1
            advanced = True
        return advanced

    @thread_role("consumer")
    def produce(put) -> None:
        nonlocal emitted
        while True:
            done = assigner_done[0]  # read BEFORE consuming progress
            scatter_new(int(progress[0]))
            advanced = emit_ready(put)
            if result.get("stopped"):
                return
            if done:
                break
            if not advanced:
                with cv:
                    if not assigner_done[0] and done_m == int(progress[0]):
                        cv.wait(poll_interval)
        front_thread.join()
        if worker_err:
            raise RuntimeError(
                "streaming decode/assignment failed"
            ) from worker_err[0]
        scatter_new(int(progress[0]))
        n_final = int(progress[0])
        s_total = max(int(progress[1]), 1)
        grow(s_total)
        while emitted < s_total:
            if stop_after is not None and emitted >= stop_after:
                result["stopped"] = True
                return
            e1 = min(emitted + spc, s_total)
            if e1 <= start_step:
                emitted = e1
                continue
            put((emitted, e1, stage_checked(emitted, e1)))
            emitted = e1
        result["s_total"] = s_total
        result["n"] = n_final

    def admit() -> None:
        """The dispatch-side admission gate: live backlog or busy host
        telemetry pauses the consumer (and through ring backpressure,
        the backfill's decode + H2D) until the controller opens a slot.
        The controller never returns a zero quota on a drained live
        plane, so the backfill cannot starve forever."""
        if admission is None:
            return
        while True:
            ready = int(live_backlog()) if live_backlog is not None else 0
            if admission.quota(ready, 1) > 0:
                return
            reg.counter("migrate.throttled_total").add(1)
            time.sleep(throttle_poll_s)

    pending = None
    fused_flat = [] if (fuse is not None and collect) else None
    ttfd_s = None
    try:
        with Prefetcher(
            produce, depth=prefetch_depth or DEFAULT_DEPTH,
            name="migrate-feed",
        ) as pf:
            for e0, e1, staged in pf:
                admit()
                if ttfd_s is None:
                    ttfd_s = time.perf_counter() - t_start
                with tracer.span("batch.compute", cat="sched", start=e0):
                    if fuse is not None:
                        state, ys = _dispatch_fused_chunk(
                            state, staged, cfg, collect, fuse.backend,
                            tier=tier,
                        )
                        if fused_flat is not None:
                            fused_flat.append(staged.flat)
                    elif tier is not None:
                        state, ys = tier.dispatch_chunk(
                            state, staged, cfg, collect
                        )
                    else:
                        state, ys = _scan_chunk(
                            state, staged, cfg, collect, pad_row
                        )
                if collect:
                    try:
                        ys.copy_to_host_async()
                    except AttributeError:  # pragma: no cover — older jax
                        pass
                    if pending is not None:
                        with tracer.span("batch.fetch", cat="sched", start=e0):
                            outs.append(fetch_tree(pending))
                    pending = ys
                del staged
                if staging is not None:
                    if tier is not None:
                        tier.maybe_publish_view(staging, state.table)
                    else:
                        staging.maybe_publish_state(state)
                if on_chunk is not None:
                    on_chunk(
                        tier.full_state(state.table) if tier is not None
                        else state, e1,
                    )
                reg.counter("migrate.steps_total").add(e1 - e0)
                reg.counter("migrate.windows_total").add(1)
                prog.note_dispatched(e1, 0)
                total = int(progress[1])
                if assigner_done[0] and total:
                    prog.set_total_steps(total)
                maybe_sample_device_memory()
    finally:
        stop_flag[0] = True
        with cv:
            cv.notify_all()
        front_thread.join()
        assigner.close()  # releases the native handle (no-op for python)
    if pending is not None:
        with tracer.span("batch.fetch", cat="sched", start=emitted):
            outs.append(fetch_tree(pending))

    stopped = bool(result.get("stopped"))
    n_final = result.get("n", int(progress[0]))
    s_total = result.get("s_total", emitted)
    if not stopped:
        reg.counter("migrate.matches_total").add(n_final)
    if s_total:
        prog.set_total_steps(s_total)
    if tier is not None:
        state = tier.finish(state.table)
    if staging is not None and not stopped:
        prog.note_publishing()
        staging.publish_state(state, ids=ids)
    occupancy = n_final / (s_total * b) if s_total else 0.0
    if stats_out is not None:
        stats_out.update(
            n_steps=s_total,
            batch_size=b,
            occupancy=occupancy,
            matches=n_final,
            streamed=True,
            stopped=stopped,
            emitted_steps=emitted,
            ttfd_s=ttfd_s,
            fingerprint=fingerprint,
            window_rows=window_rows,
            plan_windows=k_plan,
            prefix_windows=prefix_windows,
            prefix_rows=n0,
            assign_native=assigner.is_native,
        )
    if stopped:
        # A bounded run's partial state: usable only through the
        # checkpoint the caller's on_chunk took at the stop boundary.
        prog.note_dispatched(emitted, 0)
        return state, None
    prog.finish()
    if not collect:
        return state, None
    if fused_flat is not None:
        flat_idx = (
            np.concatenate(fused_flat).reshape(-1)
            if fused_flat else np.empty(0, np.int32)
        )
    else:
        flat_idx = slot_map[: s_total * b]
    return state, _gather_outputs(outs, flat_idx, n_final, team)


@dataclasses.dataclass
class MigrationReport:
    """One migration run's outcome (``run_migration``)."""

    state: object
    outputs: object
    stats: dict
    view: object = None
    cutover_pause_ms: float | None = None
    finished: bool = True


def run_migration(
    state,
    data: bytes,
    cfg,
    lineage=None,
    ids=None,
    checkpoint: str | None = None,
    resume: bool = False,
    checkpoint_every: int | None = None,
    stop_after: int | None = None,
    do_cutover: bool = True,
    **engine_kw,
) -> MigrationReport:
    """The orchestrated migration: checkpoint/resume glue around
    :func:`rate_backfill`, staging-lineage publish, and the atomic
    cutover (``cli migrate``'s core, reused by the soak and the bench).

    ``lineage`` is a :class:`~analyzer_tpu.migrate.lineage.
    LineageManager` over the LIVE plane's publisher; ``begin`` runs
    here, the backfill publishes into the staging lineage, and — when
    the run finished and ``do_cutover`` — traffic cuts over atomically.
    A bounded (``stop_after``) or failed run never touches the live
    lineage (the staging lineage is simply dropped); the checkpoint
    written at the stop boundary is the resume point.
    """
    from analyzer_tpu.io.checkpoint import (
        CheckpointWriter,
        load_checkpoint,
        save_checkpoint,
    )

    prog = get_migration_progress()
    start_step = 0
    expected_fp = None
    if resume:
        if not checkpoint:
            raise ValueError("resume=True requires a checkpoint path")
        ck = load_checkpoint(checkpoint)
        state = ck.state
        start_step = ck.step_cursor
        expected_fp = ck.schedule_fingerprint
    staging = None
    if lineage is not None:
        staging = lineage.begin()
    writer = (
        CheckpointWriter(checkpoint)
        if checkpoint and (checkpoint_every or stop_after is not None)
        else None
    )
    fp_holder: dict = {}
    last_saved = [start_step]

    def on_chunk(st, next_step):
        if writer is None:
            return
        due = (
            checkpoint_every is not None
            and next_step - last_saved[0] >= checkpoint_every
        )
        at_stop = stop_after is not None and next_step >= stop_after
        if not (due or at_stop):
            return
        last_saved[0] = next_step
        writer.save(
            st, cursor=0, step_cursor=next_step,
            schedule_fingerprint=fp_holder.get("fingerprint"),
        )

    stats: dict = {}
    try:
        final_state, outputs = rate_backfill(
            state, data, cfg,
            staging=staging, ids=ids,
            start_step=start_step, stop_after=stop_after,
            expected_fingerprint=expected_fp,
            fingerprint_out=fp_holder,
            on_chunk=on_chunk if writer is not None else None,
            stats_out=stats,
            **engine_kw,
        )
    except BaseException as e:
        prog.fail(repr(e))
        if lineage is not None:
            lineage.abort()
        raise
    finally:
        if writer is not None:
            writer.close()
    finished = not stats.get("stopped", False)
    if checkpoint and finished:
        save_checkpoint(
            checkpoint, final_state, cursor=stats.get("matches", 0),
            step_cursor=0,
            schedule_fingerprint=fp_holder.get("fingerprint"),
        )
    view = None
    pause_ms = None
    if lineage is not None:
        if finished and do_cutover:
            view = lineage.cutover()
            pause_ms = round((lineage.cutover_pause_s or 0.0) * 1e3, 3)
        elif not finished:
            lineage.abort()
    return MigrationReport(
        state=final_state, outputs=outputs, stats=stats, view=view,
        cutover_pause_ms=pause_ms, finished=finished,
    )
