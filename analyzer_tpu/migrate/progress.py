"""Migration progress: the /statusz surface of a running backfill.

One process-wide :class:`MigrationProgress` (``get_migration_progress``)
is updated by the engine at window boundaries and read by
``Worker.stats()`` — so ``/statusz`` on a live worker shows the
migration's phase, lineage versions, watermark, progress % and an ETA
while the backfill runs (ROADMAP item 4's "progress exposed on
/statusz"). The ETA is derived from the HISTORY RINGS' backfill rate
(``obs/history.py``: ``window_delta`` over ``migrate.steps_total``), not
from a start-to-now average — a migration throttled by the admission
controller mid-run reports the rate it is actually sustaining now.

Writers are the engine's consumer thread; readers are the stats path.
Every field write is a single reference/int store under the GIL and the
snapshot tolerates torn field SETS (it is an operator surface, not a
correctness input), so no lock is needed on the hot path.
"""

from __future__ import annotations

import threading

from analyzer_tpu.obs import get_registry

#: History-ring window the ETA's backfill rate is measured over (s of
#: the worker's clock — virtual under the soak).
ETA_RATE_WINDOW_S = 60.0


class MigrationProgress:
    """Mutable progress record for (at most) one in-flight migration per
    process. ``phase`` walks idle -> decoding -> rating -> publishing ->
    cutover -> done (or failed); a new ``begin`` resets everything."""

    def __init__(self) -> None:
        self.phase = "idle"
        self.matches_decoded = 0
        self.matches_assigned = 0
        self.steps_emitted = 0
        self.steps_total: int | None = None
        self.matches_rated = 0
        self.resumed_from = 0
        self.lineage_live_version: int | None = None
        self.lineage_staging_version: int | None = None
        self.cutover_pause_ms: float | None = None
        self.assign_native: bool | None = None
        self.error: str | None = None

    # -- engine-side updates ----------------------------------------------
    def begin(self, resumed_from: int = 0) -> None:
        self.__init__()
        self.phase = "decoding"
        self.resumed_from = int(resumed_from)
        reg = get_registry()
        reg.gauge("migrate.active").set(True)
        reg.gauge("migrate.watermark_steps").set(resumed_from)
        reg.gauge("migrate.total_steps").set(0)
        if resumed_from:
            reg.counter("migrate.resumes_total").add(1)

    def note_decoded(self, n_matches: int) -> None:
        self.matches_decoded = int(n_matches)

    def note_assigned(self, n_matches: int) -> None:
        self.matches_assigned = int(n_matches)

    def note_assign_backend(self, native: bool) -> None:
        """Which first-fit route the front half took (True = the
        GIL-released native windowed loop, False = the python
        recurrence) — the /statusz mirror of ``migrate.assign_native``."""
        self.assign_native = bool(native)

    def note_dispatched(self, next_step: int, matches: int) -> None:
        self.phase = "rating"
        self.steps_emitted = int(next_step)
        self.matches_rated += int(matches)
        get_registry().gauge("migrate.watermark_steps").set(next_step)

    def set_total_steps(self, total: int) -> None:
        self.steps_total = int(total)
        get_registry().gauge("migrate.total_steps").set(total)

    def set_lineages(self, live, staging) -> None:
        self.lineage_live_version = live
        self.lineage_staging_version = staging

    def note_publishing(self) -> None:
        self.phase = "publishing"

    def note_cutover(self, pause_ms: float) -> None:
        self.phase = "cutover"
        self.cutover_pause_ms = round(float(pause_ms), 3)

    def finish(self) -> None:
        self.phase = "done"
        get_registry().gauge("migrate.active").set(False)

    def fail(self, error: str) -> None:
        self.phase = "failed"
        self.error = str(error)
        get_registry().gauge("migrate.active").set(False)

    # -- stats-side read --------------------------------------------------
    def snapshot(self, history=None, now: float | None = None) -> dict | None:
        """JSON-ready progress block (``Worker.stats()['migration']``),
        or None when no migration has run in this process. ``history``
        + ``now`` (the worker's clock) enable the ring-derived ETA."""
        if self.phase == "idle":
            return None
        total = self.steps_total
        emitted = self.steps_emitted
        pct = (
            round(100.0 * emitted / total, 2) if total else None
        )
        eta_s = None
        rate = None
        if history is not None and now is not None and total:
            got = history.window_delta(
                "migrate.steps_total", ETA_RATE_WINDOW_S, now
            )
            if got is not None:
                delta, span = got
                rate = delta / span if span > 0 else 0.0
                if rate > 0:
                    eta_s = round(max(0, total - emitted) / rate, 1)
        return {
            "phase": self.phase,
            "matches_decoded": self.matches_decoded,
            "matches_assigned": self.matches_assigned,
            "assign_native": self.assign_native,
            "matches_rated": self.matches_rated,
            "backfill_watermark_steps": emitted,
            "steps_total": total,
            "progress_pct": pct,
            "resumed_from_step": self.resumed_from,
            "backfill_steps_per_sec": round(rate, 3) if rate else None,
            "eta_s": eta_s,
            "lineage_live_version": self.lineage_live_version,
            "lineage_staging_version": self.lineage_staging_version,
            "cutover_pause_ms": self.cutover_pause_ms,
            "error": self.error,
        }


_progress_lock = threading.Lock()
_progress: MigrationProgress | None = None


def get_migration_progress() -> MigrationProgress:
    """The process-wide migration progress record (created on first use;
    the engine writes it, ``Worker.stats()`` / /statusz read it)."""
    global _progress
    with _progress_lock:
        if _progress is None:
            _progress = MigrationProgress()
        return _progress


def reset_migration_progress() -> MigrationProgress:
    """Replaces the process-wide record with a fresh one (tests)."""
    global _progress
    with _progress_lock:
        _progress = MigrationProgress()
        return _progress
