"""Zero-downtime global re-rate: the streaming backfill engine and the
dual-lineage serve cutover (docs/migration.md, ROADMAP item 4).

Three pieces compose a live rating migration:

  * :mod:`analyzer_tpu.migrate.engine` — the streaming front half:
    columnar CSV decode windows (``io/ingest.py``) feed an INCREMENTAL
    first-fit assigner (:mod:`analyzer_tpu.migrate.assign` — the
    GIL-released native windowed loop by default, the python recurrence
    as fallback/oracle) on one front-half thread while the device feed
    stages and the scan dispatches — decode, assignment, H2D and
    compute all overlap, so time-to-first-dispatch is O(the
    planning prefix) instead of O(file);
  * :mod:`analyzer_tpu.migrate.lineage` — the dual-lineage serve
    protocol: the backfill publishes into a STAGING view lineage while
    the live lineage keeps serving, and :func:`~analyzer_tpu.migrate.
    lineage.cutover` swaps the migrated table in as the live lineage's
    next version atomically (``serve/view.py cutover_from`` — the one
    entry graftlint GL033 sanctions);
  * :mod:`analyzer_tpu.migrate.progress` — the /statusz surface:
    watermark, progress %, and an ETA derived from the history rings'
    backfill rate (``Worker.stats()``'s ``migration`` block).
"""

from analyzer_tpu.migrate.assign import (
    IncrementalAssigner,
    NativeIncrementalAssigner,
    PyIncrementalAssigner,
    assign_native_available,
)
from analyzer_tpu.migrate.engine import (
    DEFAULT_PLAN_WINDOWS,
    MigrationReport,
    migration_fingerprint,
    rate_backfill,
    run_migration,
)
from analyzer_tpu.migrate.lineage import LineageManager, cutover
from analyzer_tpu.migrate.progress import (
    MigrationProgress,
    get_migration_progress,
    reset_migration_progress,
)

__all__ = [
    "DEFAULT_PLAN_WINDOWS",
    "IncrementalAssigner",
    "LineageManager",
    "MigrationProgress",
    "MigrationReport",
    "NativeIncrementalAssigner",
    "PyIncrementalAssigner",
    "assign_native_available",
    "cutover",
    "get_migration_progress",
    "migration_fingerprint",
    "rate_backfill",
    "reset_migration_progress",
    "run_migration",
]
