"""Dual-lineage serve protocol: staging lineage + atomic cutover.

The migration engine must publish its in-progress table somewhere a
human (or an acceptance check) can watch WITHOUT displacing the views
live traffic is being served from. The mechanism is a second, fully
independent view lineage: :class:`LineageManager.begin` creates a
staging publisher of the live plane's topology, the backfill publishes
throttled snapshots into it exactly like any re-rate (same
``ViewPublisher`` machinery, its own version sequence), and
:func:`cutover` swaps the migrated table in as the LIVE lineage's next
version in one atomic reference assignment (``serve/view.py
cutover_from``) — readers observe a monotone version sequence, never a
torn or missing view, and the staging lineage's device table is adopted
by reference (zero H2D at the cutover point; the pause is the lock +
version-object construction, measured and reported as
``cutover_pause_ms``).

graftlint GL033 pins the discipline this module exists for: backfill
code (``analyzer_tpu/migrate/``) may publish ONLY into staging-named
lineages, may not read mutable live-lineage internals, and may reach a
live lineage only through :func:`cutover` below — a torn migration is a
silent correctness bug, so the rule is structural, not a convention.
"""

from __future__ import annotations

import time

from analyzer_tpu.migrate.progress import get_migration_progress
from analyzer_tpu.obs import get_registry


def _make_staging(live):
    """A fresh publisher of ``live``'s topology — the default staging
    factory. Reads only public surface (class, shard count, throttle)."""
    from analyzer_tpu.serve import ShardedViewPublisher, ViewPublisher

    if isinstance(live, ShardedViewPublisher):
        return ShardedViewPublisher(
            live.n_shards,
            min_publish_interval_s=live.min_publish_interval_s,
        )
    if isinstance(live, ViewPublisher):
        return ViewPublisher(
            min_publish_interval_s=live.min_publish_interval_s
        )
    raise TypeError(
        f"no default staging factory for {type(live).__name__}; pass "
        "factory= explicitly"
    )


def cutover(live, staging):
    """THE designated cutover entry (graftlint GL033): swaps ``staging``'s
    latest published view in as ``live``'s next version atomically and
    returns ``(view, pause_s)``. The staging publisher is consumed (see
    ``ViewPublisher.cutover_from``); the pause is the wall duration of
    the swap itself — what a reader arriving mid-cutover could at most
    have been delayed by (in practice zero: readers never block on the
    writer lock, they just serve the previous view until the swap)."""
    t0 = time.perf_counter()
    view = live.cutover_from(staging)
    pause_s = time.perf_counter() - t0
    get_registry().counter("migrate.cutovers_total").add(1)
    prog = get_migration_progress()
    prog.note_cutover(pause_s * 1e3)
    prog.set_lineages(view.version, None)
    return view, pause_s


class LineageManager:
    """Owns the live/staging lineage pair for one migration.

    ``live`` is the serving plane's publisher (the worker's
    ``view_publisher`` — readers keep resolving it throughout);
    :meth:`begin` mints the staging lineage, :meth:`cutover` performs the
    atomic swap, :meth:`abort` drops the staging lineage without touching
    the live one (a failed backfill leaves serving exactly as it was).
    """

    def __init__(self, live, factory=None) -> None:
        self.live = live
        self._factory = factory or (lambda: _make_staging(live))
        self.staging = None
        self.cutover_pause_s: float | None = None
        self.cutovers = 0

    def begin(self):
        """Creates (and returns) the staging lineage. One migration at a
        time: a staging lineage already in flight is a caller bug."""
        if self.staging is not None:
            raise RuntimeError(
                "a staging lineage is already in flight; cut over or "
                "abort it before beginning another migration"
            )
        self.staging = self._factory()
        get_migration_progress().set_lineages(
            self.live.version, self.staging.version
        )
        return self.staging

    def begin_fabric(self, directory, host: int, clock=None):
        """The sharded-backfill seam for one fabric host: mints the
        staging lineage exactly like :meth:`begin`, then returns it
        WRAPPED in a :class:`~analyzer_tpu.fabric.publish.
        FabricShardPublisher` — the host's re-rate publishes a staging
        lineage scoped to its OWNED shards (non-owned patches emptied),
        every staging version recorded in ``directory`` so the fleet
        can watch per-owner backfill progress before any cutover.

        ``self.staging`` stays the RAW publisher: :meth:`cutover` and
        :meth:`abort` operate on the lineage itself, not the ownership
        filter (``cutover_from`` consumes publisher internals the
        wrapper deliberately does not proxy).
        """
        from analyzer_tpu.fabric.publish import FabricShardPublisher

        return FabricShardPublisher(
            directory, host, self.begin(), clock=clock
        )

    def versions(self) -> dict:
        """Operator snapshot: the two lineages' current versions."""
        return {
            "live": self.live.version,
            "staging": (
                self.staging.version if self.staging is not None else None
            ),
        }

    def cutover(self):
        """Atomic traffic cutover; returns the new live view. See
        :func:`cutover`."""
        if self.staging is None:
            raise RuntimeError("no staging lineage to cut over")
        view, pause_s = cutover(self.live, self.staging)
        self.cutover_pause_s = pause_s
        self.cutovers += 1
        self.staging = None
        return view

    def abort(self) -> None:
        """Drops the staging lineage (idempotent). Live serving is
        untouched — the whole point of the dual-lineage shape."""
        self.staging = None
