"""Conflict-free superstep construction (host-side).

A *superstep* is a set of matches in which no player appears twice, so the
whole set can be rated by one gather -> update -> scatter kernel call without
scatter collisions, while still respecting per-player chronology across
steps. The assignment is the ASAP (as-soon-as-possible) schedule of the
match dependency chain:

    step(match) = 1 + max(step(previous match of each of its players))

which is provably minimal in step count for a schedule that preserves every
player's match order, and conflict-free by construction (a player's next
match always lands in a strictly later step than their previous one).

Matches that never touch rating state — unsupported modes and AFK/invalid
matches (``rater.py:83-85,90-106``) — impose no dependencies: their outputs
(quality=0, any_afk) do not read priors. They are assigned to whatever step
has room, keeping occupancy high.

The assignment loop is a sequential recurrence over the stream and is the
host-side hot path for a full-history re-rate; a C++ implementation is used
when built (:mod:`analyzer_tpu.sched._native`), with this numpy/python
version as the always-available fallback.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib

import numpy as np

from analyzer_tpu.core import constants
from analyzer_tpu.core.state import MAX_TEAM_SIZE, MatchBatch
from analyzer_tpu.obs import get_registry as _obs_registry

import jax.numpy as jnp


@dataclasses.dataclass
class MatchStream:
    """N matches in chronological (``created_at`` ascending) order, SoA.

    player_idx: ``[N, 2, T]`` int32 rows into the player table; -1 marks an
      empty (padded) team slot.
    winner:     ``[N]`` 0/1 winning-team index.
    mode_id:    ``[N]`` index into :data:`analyzer_tpu.core.constants.MODES`,
      or -1 for an unsupported mode.
    afk:        ``[N]`` bool — any participant AFK or roster count != 2.
    """

    player_idx: np.ndarray
    winner: np.ndarray
    mode_id: np.ndarray
    afk: np.ndarray

    def __post_init__(self) -> None:
        self.player_idx = np.ascontiguousarray(self.player_idx, dtype=np.int32)
        self.winner = np.ascontiguousarray(self.winner, dtype=np.int32)
        self.mode_id = np.ascontiguousarray(self.mode_id, dtype=np.int32)
        self.afk = np.ascontiguousarray(self.afk, dtype=bool)
        if self.player_idx.ndim != 3 or self.player_idx.shape[1] != 2:
            raise ValueError(f"player_idx must be [N, 2, T], got {self.player_idx.shape}")

    @property
    def n_matches(self) -> int:
        return self.player_idx.shape[0]

    @property
    def team_size(self) -> int:
        return self.player_idx.shape[2]

    @property
    def ratable(self) -> np.ndarray:
        return (self.mode_id >= 0) & ~self.afk

    def slice(self, start: int, stop: int) -> "MatchStream":
        return MatchStream(
            self.player_idx[start:stop],
            self.winner[start:stop],
            self.mode_id[start:stop],
            self.afk[start:stop],
        )


class _ScheduleBase:
    """Shared surface of the eager and windowed schedule containers. Both
    expose the ``[S, B]`` per-slot scalars as attributes; they differ only
    in how the ``[S, B, 2, T]`` gather tensors are produced
    (``host_window``)."""

    @property
    def n_steps(self) -> int:
        return self.match_idx.shape[0]

    @property
    def batch_size(self) -> int:
        return self.match_idx.shape[1]

    @property
    def n_matches(self) -> int:
        return int((self.match_idx >= 0).sum())

    @property
    def occupancy(self) -> float:
        """Fraction of packed slots holding real matches — the efficiency of
        the schedule (padding slots burn identical FLOPs)."""
        return self.n_matches / max(self.match_idx.size, 1)

    @property
    def ratable(self) -> np.ndarray:
        """``[S, B]`` — matches that actually write rating state. The host
        mirror of ``MatchBatch.ratable`` (``rater.py:102-106`` gating); keep
        the two in lockstep."""
        return (self.mode_id >= 0) & ~self.afk

    @functools.cached_property
    def fingerprint(self) -> str:
        """Content hash of the packed schedule. Packing is a pure function
        of the stream slice, so this identifies "the same work in the same
        order" across processes — mid-run checkpoints store it and resume
        verifies it, failing loudly if the stream file or packing policy
        changed underneath a step cursor (io/checkpoint.py).

        Everything the device kernel consumes is bound: the ``[S, B]``
        scalars directly, and the gather tensors through their generators —
        ``match_idx`` + the stream's ``player_idx`` determine every window
        byte-for-byte, so hashing those is equivalent to hashing the
        materialized tensors WITHOUT paying a full materialization pass on
        a windowed schedule (a 10M-match resumable run would otherwise
        rebuild all [S,B,2,T] tensors just to hash them). Eager schedules
        made by ``pack_schedule`` retain the stream and digest identically
        to their windowed form; only a hand-built PackedSchedule (no
        stream) falls back to hashing its materialized tensors, under a
        distinct scheme tag so the two can never collide."""
        h = hashlib.sha1()
        stream = getattr(self, "stream", None)
        h.update(
            np.asarray(
                (self.n_steps, self.batch_size, self.pad_row, self.team_size),
                np.int64,
            ).tobytes()
        )
        if stream is not None:
            h.update(b"stream-v1")
            h.update(np.ascontiguousarray(stream.player_idx).tobytes())
        else:
            h.update(b"materialized-v1")
            h.update(np.ascontiguousarray(self.player_idx).tobytes())
            h.update(np.ascontiguousarray(self.slot_mask).tobytes())
        for field in (self.match_idx, self.winner, self.mode_id, self.afk):
            h.update(np.ascontiguousarray(field).tobytes())
        return h.hexdigest()

    def device_arrays(self, start: int = 0, stop: int | None = None):
        """The compact ``[S', B, ...]`` slab for a lax.scan over steps
        start..stop (see :func:`compact_device_window`)."""
        if stop is None:
            stop = self.n_steps
        pidx, _mask, winner, mode_id, afk = self.host_window(start, stop)
        return compact_device_window(pidx, winner, mode_id, afk)


@dataclasses.dataclass
class PackedSchedule(_ScheduleBase):
    """The stream packed into ``[S, B, ...]`` static-shape superstep batches.

    match_idx ``[S, B]`` maps each packed slot back to its stream position
    (-1 for padding) so per-match outputs can be scattered back into
    chronological order. ``player_idx`` padding slots already point at
    ``pad_row`` (the player-table padding row), ready for the device gather.
    """

    player_idx: np.ndarray  # [S, B, 2, T] int32
    slot_mask: np.ndarray  # [S, B, 2, T] bool
    winner: np.ndarray  # [S, B] int32
    mode_id: np.ndarray  # [S, B] int32
    afk: np.ndarray  # [S, B] bool
    match_idx: np.ndarray  # [S, B] int32
    pad_row: int
    # Retained by pack_schedule so `fingerprint` digests identically to the
    # windowed form without touching the materialized tensors; None for a
    # hand-built schedule (fingerprint then falls back to hashing those).
    stream: "MatchStream | None" = None

    @property
    def team_size(self) -> int:
        return self.player_idx.shape[-1]

    @property
    def valid_slots(self) -> np.ndarray:
        """``[S, B, 2, T]`` — slots whose player row is actually written by
        a superstep (real player in a ratable match). This is the exact set
        the device scatter commits (``update.py: scatter_rows``'s
        ``updated & slot_mask``); the sharded-table routing
        (``parallel.mesh.build_routing``) must cover exactly these."""
        return self.slot_mask & self.ratable[:, :, None, None]

    def host_window(self, start: int, stop: int):
        sl = slice(start, stop)
        return (
            self.player_idx[sl],
            self.slot_mask[sl],
            self.winner[sl],
            self.mode_id[sl],
            self.afk[sl],
        )

    def check_compact_invariant(
        self, start: int = 0, stop: int | None = None
    ) -> None:
        """Verifies ``slot_mask == (player_idx != pad_row)`` for a
        HAND-BUILT schedule (``stream is None`` — the fingerprint's
        'materialized-v1' branch). Materializer-produced schedules hold
        the invariant by construction; a hand-built one that violates it
        would be rated silently wrong by every compact-feed consumer
        (the single-device slab AND the sharded mesh feed, both of which
        derive the mask on device) — fail loudly instead."""
        if self.stream is not None:
            return
        sl = slice(start, self.n_steps if stop is None else stop)
        if not (
            self.slot_mask[sl] == (self.player_idx[sl] != self.pad_row)
        ).all():
            raise ValueError(
                "hand-built schedule violates the compact-feed "
                "invariant: slot_mask must equal "
                "(player_idx != pad_row) — point padding slots at "
                f"pad_row={self.pad_row}"
            )

    def device_arrays(self, start: int = 0, stop: int | None = None):
        if stop is None:
            stop = self.n_steps
        self.check_compact_invariant(start, stop)
        return super().device_arrays(start, stop)

    def pad_to_steps(self, n_steps: int) -> "PackedSchedule":
        """Appends inert all-padding supersteps (match_idx -1, masks False,
        unsupported mode) so the schedule has exactly ``n_steps``. Padding
        steps read and write nothing — they exist so a caller can BUCKET
        step counts to a few fixed shapes and reuse one compiled scan
        across differently-sized batches (the service loop's recompile
        guard; the reference's fixed BATCHSIZE=500 never had this problem
        because it never had shape-specialized compilation,
        ``worker.py:18``)."""
        extra = n_steps - self.n_steps
        if extra < 0:
            raise ValueError(
                f"cannot pad {self.n_steps} steps down to {n_steps}"
            )
        if extra == 0:
            return self
        b = self.batch_size
        # Step-bucketing waste: whole inert supersteps appended so the
        # compiled scan shape is reused — visible padding tax in the
        # metrics snapshot (sched.pad_steps_total / sched.pad_slots_total).
        reg = _obs_registry()
        reg.counter("sched.pad_steps_total").add(extra)
        reg.counter("sched.pad_slots_total").add(extra * b)
        pad_idx = np.full((extra, b), -1, np.int32)
        pad_gather = np.full(
            (extra, b, 2, self.team_size), self.pad_row, np.int32
        )
        # All-padding rows: the empty-stream branch of the scalar
        # materializer IS the padding convention's single owner.
        empty = MatchStream(
            np.empty((0, 2, self.team_size), np.int32),
            np.empty(0, np.int32), np.empty(0, np.int32), np.empty(0, bool),
        )
        winner, mode_id, afk = materialize_scalar_window(empty, pad_idx)
        return PackedSchedule(
            player_idx=np.concatenate([self.player_idx, pad_gather]),
            slot_mask=np.concatenate(
                [self.slot_mask, np.zeros(pad_gather.shape, bool)]
            ),
            winner=np.concatenate([self.winner, winner]),
            mode_id=np.concatenate([self.mode_id, mode_id]),
            afk=np.concatenate([self.afk, afk]),
            match_idx=np.concatenate([self.match_idx, pad_idx]),
            pad_row=self.pad_row,
            stream=self.stream,
        )

    def step_batch(self, s: int) -> MatchBatch:
        """Materializes superstep ``s`` as a device MatchBatch."""
        return MatchBatch(
            player_idx=jnp.asarray(self.player_idx[s]),
            slot_mask=jnp.asarray(self.slot_mask[s]),
            winner=jnp.asarray(self.winner[s]),
            mode_id=jnp.asarray(self.mode_id[s]),
            afk=jnp.asarray(self.afk[s]),
        )


@dataclasses.dataclass
class WindowedSchedule(_ScheduleBase):
    """A packed schedule whose ``[S, B, 2, T]`` gather tensors are
    materialized per window, on demand, from the slot->match map.

    The ``[S, B]`` scalars are eager (~13 bytes per slot); the per-player
    tensors (~50 bytes per slot — the bulk of eager packing time and
    memory) are built inside :meth:`host_window`. Fed through
    ``rate_history``'s prefetch loop, that materialization happens while
    the device is scanning the PREVIOUS chunk — the host feed overlaps
    compute instead of serializing in front of it (SURVEY.md section
    7.7's double-buffered feed), and the peak host footprint is two
    windows instead of the whole ``[S, B, 2, T]`` schedule.
    """

    stream: MatchStream
    winner: np.ndarray  # [S, B] int32
    mode_id: np.ndarray  # [S, B] int32
    afk: np.ndarray  # [S, B] bool
    match_idx: np.ndarray  # [S, B] int32
    pad_row: int
    team_size: int

    def host_window(self, start: int, stop: int):
        pidx, mask = materialize_gather_window(
            self.stream, self.match_idx[start:stop], self.pad_row, self.team_size
        )
        return (pidx, mask, self.winner[start:stop],
                self.mode_id[start:stop], self.afk[start:stop])

    def materialize(self) -> PackedSchedule:
        """The eager equivalent (identical arrays and fingerprint) — for
        consumers that need the full tensors at once (mesh routing,
        ``step_batch``)."""
        pidx, mask, winner, mode_id, afk = self.host_window(0, self.n_steps)
        return PackedSchedule(
            player_idx=pidx,
            slot_mask=mask,
            winner=winner,
            mode_id=mode_id,
            afk=afk,
            match_idx=self.match_idx,
            pad_row=self.pad_row,
            stream=self.stream,
        )


def compact_device_window(player_idx, winner, mode_id, afk):
    """H2D slab for the single-device scan runners, carrying only what
    the device cannot derive.

    The feed transfer is the end-to-end bottleneck on a tunneled host
    (BASELINE.md: ~480 MB of slabs at 10M matches), so ``slot_mask`` is
    DROPPED — every schedule producer routes through
    :func:`materialize_gather_window`, which guarantees the invariant
    ``slot_mask == (player_idx != pad_row)`` (real players occupy rows
    ``0..pad_row-1``; padding slots all point at ``pad_row``) — and the
    per-slot scalars are narrowed to int8 (``winner`` is 0/1, ``mode_id``
    lies in ``[-1, N_MODES)``). Together that is ~30% fewer bytes per
    match at team size 3. :func:`expand_step` is the in-jit inverse.
    """
    return (
        jnp.asarray(player_idx),
        jnp.asarray(winner.astype(np.int8)),
        jnp.asarray(mode_id.astype(np.int8)),
        jnp.asarray(afk),
    )


def expand_step(xs, pad_row: int):
    """Expands ONE scan step of a :func:`compact_device_window` slab back
    to ``(player_idx, slot_mask, winner, mode_id, afk)`` — traced inside
    the consumer's jit, so the mask never crosses the host->device link
    and the int8 scalars widen on device for free."""
    pidx, winner, mode_id, afk = xs
    return (
        pidx,
        pidx != pad_row,
        winner.astype(jnp.int32),
        mode_id.astype(jnp.int32),
        afk,
    )


def materialize_gather_window(
    stream: MatchStream, match_idx: np.ndarray, pad_row: int, team_size: int
) -> tuple[np.ndarray, np.ndarray]:
    """Builds the ``[W, B, 2, team_size]`` (player_idx, slot_mask) gather
    tensors for a window of the slot->match map — the shared materializer
    of :class:`WindowedSchedule` and the streaming runner
    (``sched.runner.rate_stream``). Padding slots (match_idx < 0) point at
    ``pad_row`` with a False mask; a 3-wide stream packed at team_size=5
    pads the team axis the same way."""
    if stream.n_matches == 0:  # all-padding (inert) schedule
        shape = match_idx.shape + (2, team_size)
        return np.full(shape, pad_row, np.int32), np.zeros(shape, bool)
    # Preallocate + in-place: the gather/where/astype/concatenate chain
    # allocated every [W, B, 2, T] tensor twice per window (the fancy-
    # index temp plus the where+astype copy) on the feed's hot path.
    # np.take(out=) gathers straight into the output, the mask derives
    # in place, and padding overwrites via copyto — one allocation per
    # output, which is the floor.
    t_in = stream.team_size
    shape = match_idx.shape + (2, team_size)
    pidx = np.empty(shape, np.int32)
    mask = np.zeros(shape, bool)
    if t_in < team_size:  # 3-wide stream packed at 5: inert team tail
        pidx[..., t_in:] = pad_row
    sub_p = pidx[..., :t_in]
    sub_m = mask[..., :t_in]
    rows = np.clip(match_idx, 0, None)
    if t_in == team_size:  # contiguous out — the common case
        np.take(stream.player_idx, rows, axis=0, out=sub_p)
    else:
        sub_p[...] = stream.player_idx[rows]
    np.greater_equal(sub_p, 0, out=sub_m)
    sub_m &= (match_idx >= 0)[..., None, None]
    np.copyto(sub_p, pad_row, where=~sub_m)
    return pidx, mask


def materialize_scalar_window(
    stream: MatchStream, match_idx: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Builds the (winner, mode_id, afk) per-slot scalars for a window of
    the slot->match map, with the packer's padding values (winner 0,
    ``UNSUPPORTED_MODE_ID``, afk False). The single owner of the padding
    convention — used by ``pack_schedule`` and the streaming runner; the
    ratable gate (``mode_id >= 0``) depends on it."""
    if stream.n_matches == 0:
        return (
            np.zeros(match_idx.shape, np.int32),
            np.full(match_idx.shape, constants.UNSUPPORTED_MODE_ID, np.int32),
            np.zeros(match_idx.shape, bool),
        )
    # Same preallocate + in-place discipline as the gather materializer:
    # take(out=) then overwrite the padding slots, instead of a
    # gather temp + where copy per array.
    pad = ~(match_idx >= 0)
    rows = np.clip(match_idx, 0, None)
    winner = np.empty(match_idx.shape, np.int32)
    mode_id = np.empty(match_idx.shape, np.int32)
    afk = np.empty(match_idx.shape, bool)
    np.take(stream.winner, rows, out=winner)
    np.take(stream.mode_id, rows, out=mode_id)
    np.take(stream.afk, rows, out=afk)
    np.copyto(winner, 0, where=pad)
    np.copyto(mode_id, constants.UNSUPPORTED_MODE_ID, where=pad)
    np.copyto(afk, False, where=pad)
    return winner, mode_id, afk


def assign_supersteps(stream: MatchStream) -> np.ndarray:
    """ASAP superstep index per match, ``[N]`` int64. Non-ratable matches get
    step -1 (meaning "no dependency — place anywhere")."""
    try:
        from analyzer_tpu.sched import _native

        return _native.assign_supersteps(stream)
    except ImportError:
        return _assign_supersteps_py(stream)


def _assign_supersteps_py(stream: MatchStream) -> np.ndarray:
    n = stream.n_matches
    steps = np.full(n, -1, dtype=np.int64)
    if n == 0:
        return steps
    n_players = int(stream.player_idx.max()) + 1 if n else 0
    # last_step[p] = superstep of p's most recent ratable match, -1 if none.
    last_step = np.full(max(n_players, 1), -1, dtype=np.int64)
    ratable = stream.ratable
    idx = stream.player_idx
    for i in range(n):
        if not ratable[i]:
            continue
        players = idx[i].ravel()
        players = players[players >= 0]
        s = last_step[players].max() + 1 if players.size else 0
        steps[i] = s
        last_step[players] = s
    return steps


def assign_batches(
    stream: MatchStream,
    capacity: int,
    progress: np.ndarray | None = None,
    out: np.ndarray | None = None,
    out_slot: np.ndarray | None = None,
    on_progress=None,
) -> tuple[np.ndarray, np.ndarray]:
    """Capacity-aware first-fit batch index per match (levelized schedule).

    Each ratable match, in stream order, goes to the EARLIEST batch that is
    strictly later than all of its players' previous matches' batches and
    has free capacity. Per-player chronology holds by construction, and so
    does within-batch conflict-freedom (a player's matches land in strictly
    increasing batches). Compared to slicing the ASAP supersteps into
    fixed-width batches, first-fit fills the narrow tail of the width
    histogram with later matches whose dependencies are already satisfied —
    occupancy goes from ~0.5 to ~1 on heavy-tailed ladders, and total
    scattered rows (the kernel's cost driver) shrink proportionally.

    Returns ``([N] batch id, [N] slot within batch)`` int64, -1 for
    non-ratable matches. Slot order within a batch is stream order (fill
    order), so ``batch * capacity + slot`` is a collision-free flat slot
    map with no sort needed. ``progress`` see
    :func:`_native.assign_batches_first_fit`.

    ``on_progress`` (optional zero-arg callable) is invoked by the PURE
    PYTHON loop at every periodic ``progress`` publish — the streamed
    feed's condition-variable handshake (``sched.runner.rate_stream``).
    The native loop runs with the GIL released and cannot call back into
    Python, so it ignores the callback and its consumers keep the poll
    fallback; completion is signaled by the caller around the call
    either way.
    """
    try:
        from analyzer_tpu.sched import _native

        return _native.assign_batches_first_fit(
            stream, capacity, progress, out, out_slot
        )
    except ImportError:
        return _assign_batches_first_fit_py(
            stream, capacity, progress, out, out_slot, on_progress
        )


#: Periodic-progress publish interval of the python first-fit loop
#: (matches). A power of two so the check is one mask; small enough that
#: a streamed consumer sees fresh entries every few hundred microseconds.
_PY_PROGRESS_EVERY = 2048


def _assign_batches_first_fit_py(
    stream: MatchStream,
    capacity: int,
    progress: np.ndarray | None = None,
    out: np.ndarray | None = None,
    out_slot: np.ndarray | None = None,
    on_progress=None,
) -> tuple[np.ndarray, np.ndarray]:
    n = stream.n_matches
    if out is None:
        out = np.full(n, -1, dtype=np.int64)
    else:  # the loop below only writes ratable entries
        out.fill(-1)
    if out_slot is None:
        out_slot = np.full(n, -1, dtype=np.int64)
    else:
        out_slot.fill(-1)
    if n == 0:
        if progress is not None:
            progress[:] = (0, 0)
        return out, out_slot
    n_players = int(stream.player_idx.max()) + 1
    last = np.full(max(n_players, 1), -1, dtype=np.int64)
    fill: list[int] = []
    next_free: list[int] = []

    def ensure(b: int) -> None:
        while len(fill) <= b:
            fill.append(0)
            next_free.append(len(next_free))

    def find(b: int) -> int:
        ensure(b)
        root = b
        while True:
            ensure(root)
            if next_free[root] == root:
                break
            root = next_free[root]
        while next_free[b] != root:
            b, next_free[b] = next_free[b], root
        return root

    ratable = stream.ratable
    idx = stream.player_idx
    for i in range(n):
        if progress is not None and i and not (i & (_PY_PROGRESS_EVERY - 1)):
            # Entries [0, i) are final; publish + wake a streamed
            # consumer (the GIL orders the buffer writes before this
            # store, mirroring the C loop's release publish).
            progress[0] = i
            if on_progress is not None:
                on_progress()
        if not ratable[i]:
            continue
        players = idx[i].ravel()
        players = players[players >= 0]
        floor_b = int(last[players].max()) + 1 if players.size else 0
        b = find(floor_b)
        out[i] = b
        out_slot[i] = fill[b]
        fill[b] += 1
        if fill[b] == capacity:
            ensure(b + 1)
            next_free[b] = b + 1
        last[players] = b
    if progress is not None:
        # Batches actually used — len(fill) can include an empty trailing
        # batch pre-created when the last one filled to exact capacity.
        progress[:] = (n, int(out.max()) + 1)
    return out, out_slot


# v5e-measured device cost model for auto batch sizing (fetch-timed on the
# real chip, see BASELINE.md): each scan step carries a fixed dispatch /
# loop overhead, plus the scatter-bound per-slot cost (10 row-slots per
# match slot x ~72 ns/row, core/update.py).
STEP_FIXED_COST_S = 12e-6
MATCH_SLOT_COST_S = 0.72e-6


def choose_batch_size(
    stream: MatchStream,
    batch_multiple: int = 8,
    max_batch_size: int = 4096,
    step_fixed_cost_s: float = STEP_FIXED_COST_S,
    match_slot_cost_s: float = MATCH_SLOT_COST_S,
) -> int:
    """Minimum-estimated-device-time batch size for ``stream``.

    For each candidate B, the step count of a chronology-preserving
    schedule is lower-bounded from the ASAP width histogram:

        S(B) >= max_s ( s + ceil(tail(s) / B) )

    (matches at ASAP level >= s cannot start before step s, and at most B
    of them finish per step; first-fit measures within ~1% of this bound
    on heavy-tailed ladders). Estimated device time S*(fixed + B*slot) is
    then swept over candidates — small B pays step overhead on deep
    chain-bound ladders, large B pays padded scatter slots on wide ones;
    the sweep replaces the round-1 B=mean-width heuristic that hit
    occupancy 0.50 at the 10M-match scale (VERDICT round 1).
    """
    steps = assign_supersteps(stream)
    ratable = steps >= 0
    n_ratable = int(ratable.sum())
    if n_ratable == 0:
        return batch_multiple
    depth = int(steps.max()) + 1
    widths = np.bincount(steps[ratable], minlength=depth)
    tail = np.cumsum(widths[::-1])[::-1].astype(np.int64)  # tail[s]

    # Candidates: powers-of-two-ish ladder up to the cap, plus mean width.
    mean_width = max(1, n_ratable // depth)
    cands = {batch_multiple, mean_width}
    b = batch_multiple
    while b < max_batch_size:
        b *= 2
        cands.add(min(b, max_batch_size))
    # Sample the (monotone-ish) tail at ~500 points — exact enough for a
    # max over s while keeping the sweep O(#cands * 500) at any scale.
    sample = np.arange(0, depth, max(1, depth // 500))
    best_b, best_t = batch_multiple, np.inf
    for cand in sorted(cands):
        cand = int(min(max(cand, 1), max_batch_size))
        if cand >= batch_multiple:
            cand = (cand // batch_multiple) * batch_multiple
        s_est = int((sample + -(-tail[sample] // cand)).max())
        t_est = s_est * (step_fixed_cost_s + cand * match_slot_cost_s)
        if t_est < best_t:
            best_b, best_t = cand, t_est
    return max(best_b, 1)


def choose_batch_size_streamed(
    stream: MatchStream, prefix: int | None = None, **kw
) -> int:
    """Batch sizing for the streamed feed, from a bounded PREFIX.

    :func:`choose_batch_size` runs a full ASAP assignment pass — at 10M
    matches ~1.6 s of host time ``rate_stream`` would pay as a sequential
    launch prefix before any overlap begins (VERDICT round-2 weak #2),
    doing work the first-fit pass then largely repeats. The cost-model
    argmin over B is stable under subsampling for stationary ladders (it
    depends on the ASAP width *distribution*, not its length), so sizing
    from the first ``max(256k, n/8)`` matches keeps the launch latency
    O(prefix) — ~0.2 s at 10M — while first-fit still runs at full scale
    on the worker thread. Deterministic: the prefix length is a pure
    function of ``n``, so the chosen B (and with it the whole emitted
    schedule) remains reproducible; and the final state is B-independent
    anyway (per-player chronology fixes every match's priors).

    The migration engine — which never knows ``n`` up front — passes an
    explicit ``prefix`` instead: its deterministic ``plan_windows``
    decode-window planning prefix (``migrate/engine.py``; the policy
    folds into ``migration_fingerprint`` so a resume under a different
    prefix fails loudly).
    """
    n = stream.n_matches
    p = prefix or min(n, max(1 << 18, n // 8))
    if p >= n:
        return choose_batch_size(stream, **kw)
    return choose_batch_size(stream.slice(0, p), **kw)


def pack_schedule(
    stream: MatchStream,
    pad_row: int,
    batch_size: int | None = None,
    team_size: int = MAX_TEAM_SIZE,
    batch_multiple: int = 8,
    max_batch_size: int = 4096,
    windowed: bool = False,
) -> "PackedSchedule | WindowedSchedule":
    """Packs a stream into ``[S, B, ...]`` conflict-free batches via
    capacity-aware first-fit (see :func:`assign_batches`).

    ``batch_size=None`` sweeps candidate sizes against the v5e device cost
    model (:func:`choose_batch_size`): estimated time = steps * (fixed
    overhead + B * slot cost), with steps lower-bounded from the ASAP
    width histogram. On chain-bound ladders this lands near the mean
    superstep width (occupancy ~1); on wide shallow ladders it grows B
    toward the scatter-bound optimum instead of drowning in step overhead.

    Non-ratable matches are backfilled into padding slots of existing
    batches wherever there is room (their relative order does not matter:
    they read and write no rating state), falling back to extra batches.

    ``windowed=True`` returns the lazy :class:`WindowedSchedule` — the
    large gather tensors are materialized per window inside the runner's
    prefetch loop, overlapping the device scan; use it for large streams
    fed to ``rate_history``. The default eager form is for consumers that
    touch the full tensors (mesh routing, ``step_batch``).
    """
    n = stream.n_matches
    t_in = stream.team_size
    if t_in > team_size:
        raise ValueError(f"stream team size {t_in} exceeds pack team size {team_size}")
    if n and int(stream.player_idx.max()) >= pad_row:
        # The kernel's gather/scatter clamps out-of-bounds indices (JAX
        # default), which would silently read/write the wrong player's row
        # — e.g. resuming from a checkpoint whose table predates newly
        # added players. Fail loudly instead.
        raise ValueError(
            f"stream references player row {int(stream.player_idx.max())} but the "
            f"player table only has rows 0..{pad_row - 1} (pad_row={pad_row}); "
            "rebuild the state with enough players"
        )

    if batch_size is None:
        batch_size = choose_batch_size(
            stream, batch_multiple=batch_multiple, max_batch_size=max_batch_size
        )

    batches, slot_in_batch = assign_batches(stream, batch_size)

    ratable_idx = np.flatnonzero(batches >= 0)
    filler = np.flatnonzero(batches < 0)
    n_rate_batches = int(batches.max()) + 1 if ratable_idx.size else 0

    # Free slots left in those batches, to backfill with non-ratable matches.
    free = n_rate_batches * batch_size - ratable_idx.size
    extra_batches = max(0, -(-(filler.size - free) // batch_size)) if filler.size else 0
    s_total = max(n_rate_batches + extra_batches, 1)

    # One scatter builds the slot->match map: the assigner already names
    # each ratable match's (batch, slot-within-batch) — slot order within a
    # batch is stream order by construction — and fillers take the free
    # slots in ascending order (their placement is arbitrary: they read and
    # write no rating state).
    slot_to_match = np.full(s_total * batch_size, -1, dtype=np.int32)
    if ratable_idx.size:
        slot_to_match[
            batches[ratable_idx] * batch_size + slot_in_batch[ratable_idx]
        ] = ratable_idx
    if filler.size:
        free_slots = np.flatnonzero(slot_to_match < 0)
        slot_to_match[free_slots[: filler.size]] = filler
    match_idx = slot_to_match.reshape(s_total, batch_size)

    winner, mode_id, afk = materialize_scalar_window(stream, match_idx)
    ws = WindowedSchedule(
        stream=stream,
        winner=winner,
        mode_id=mode_id,
        afk=afk,
        match_idx=match_idx,
        pad_row=pad_row,
        team_size=team_size,
    )
    # Bucket-occupancy accounting (obs): padding slots burn identical
    # FLOPs, so the waste IS a device-time tax — the histogram shows the
    # distribution across service batches, the counter the cumulative
    # slots burned. pad_to_steps adds its step-bucketing waste on top.
    reg = _obs_registry()
    reg.histogram("sched.pack_occupancy").observe(round(ws.occupancy, 4))
    reg.counter("sched.pad_slots_total").add(
        int(s_total * batch_size - n)
    )
    return ws if windowed else ws.materialize()
