"""Bounded-depth prefetching device feed: the host pipeline that keeps
the scan runners compute-bound.

BENCH_r05 put the fully-streamed feed (``rate_stream``) at **1.75x**
device-only time while the windowed ``rate_history`` ran at 1.07x — the
difference being that ``rate_stream``'s emit loop did window
materialization (numpy fancy-index gather), the H2D transfer
(``compact_device_window``), and the ``_scan_chunk`` dispatch
*synchronously, per window, on one thread*. This module is the tf.data
prefetch idiom (Murray et al.) applied to that loop:

  * a **producer thread** materializes the next window and issues the
    (async) ``jax.device_put`` of its slab while the current window's
    scan is still in flight on the device;
  * a **bounded ring** (:class:`DeviceFeed`, depth 2-3) holds the
    committed device slabs, so at most ``depth`` windows of HBM are
    resident beyond the carry — the backpressure bound;
  * the **consumer** (the runner's dispatch loop) pops committed slabs
    and only ever blocks when the ring is empty — i.e. when the feed,
    not the device, is the bottleneck. That event is *starvation* and it
    is counted, not guessed at.

Determinism: the producer stages windows strictly in order on one
thread, so the emitted schedule — and with it the final state and the
collected outputs — is exactly the synchronous loop's, bit for bit, at
every depth (pinned by tests/test_feed.py). The ring changes *when*
work happens, never *what* work happens.

Telemetry (the PR-2 registry; catalog in docs/observability.md):

  * ``feed.depth`` gauge — ring occupancy after the last put/get; a
    steady 0 with a busy device means the feed can't keep up, a steady
    ``depth`` means the device is the bottleneck (healthy);
  * ``feed.starved_total`` — consumer found the ring empty and had to
    wait. A handful per run is pipeline fill; growing counts on a busy
    run mean host-bound — raise depth or look at ``feed.materialize``
    spans;
  * ``feed.backpressure_total`` — producer found the ring full and had
    to wait: the healthy steady state (device-bound);
  * ``feed.materialize`` / ``feed.transfer`` spans — per-window host
    materialization vs H2D staging cost, on the producer thread.
"""

from __future__ import annotations

import threading
from collections import deque

import numpy as np

from analyzer_tpu.lint.ownership import thread_role
from analyzer_tpu.obs import get_registry, get_tracer
from analyzer_tpu.obs.tracer import bind_trace, current_trace

#: Default ring depth: one slab in flight on the device, one committed
#: behind it. Depth 3 buys jitter tolerance on hosts where
#: materialization time varies window to window, at one more slab of HBM.
DEFAULT_DEPTH = 2

#: Page alignment for arena buffers: DMA engines transfer aligned pages
#: without a bounce copy, and the pinned_host staging path wants its
#: source page-aligned either way.
ARENA_ALIGNMENT = 4096


class PinnedArena:
    """Reusable page-aligned host staging buffers for the ingest plane
    (docs/ingest.md "Arena layout").

    Two allocation surfaces share one allocator (and one telemetry
    stream): :meth:`take`/:meth:`give` lease fixed-shape slabs the
    columnar decoder (``io/ingest.py``) writes whole match windows into
    — steady state is ~100% reuse, pinned by the arena-hit-rate gate of
    ``cli benchdiff --family ingest`` — and :meth:`empty` hands out
    long-lived buffers (the tiered table's cold tier, ``sched/tier.py``)
    from the same aligned allocator.

    :meth:`commit` is the H2D edge: on a backend that exposes a
    ``pinned_host`` memory space (TPU), the slab stages through pinned
    memory so the device transfer is real async DMA; on CPU it degrades
    to a plain ``jnp.asarray`` with identical semantics. A committed
    slab is released back to the freelist only once its device array
    reports ready (``_deferred``), so a reused buffer can never be
    overwritten under an in-flight transfer.

    Telemetry (docs/observability.md catalog): ``ingest.arena_allocs_
    total`` / ``ingest.arena_reuses_total`` counters (their ratio is the
    hit rate), ``ingest.h2d_commits_total``, and the ``ingest.arena_
    bytes`` gauge.
    """

    def __init__(self, name: str = "ingest") -> None:
        self.name = name
        self._lock = threading.Lock()
        # (shape, dtype str) -> [buffer, ...] free slabs.
        self._free: dict[tuple, list] = {}
        # id(view) -> (key, base array) for every live lease/alloc — the
        # base reference keeps the aligned parent alive.
        self._live: dict[int, tuple] = {}
        # (device array, buffer) pairs whose H2D may still be in flight.
        self._deferred: list = []
        self._nbytes = 0
        self._transfer = None  # resolved lazily on first commit
        reg = get_registry()
        self._allocs = reg.counter("ingest.arena_allocs_total")
        self._reuses = reg.counter("ingest.arena_reuses_total")
        self._commits = reg.counter("ingest.h2d_commits_total")
        self._bytes_gauge = reg.gauge("ingest.arena_bytes")

    @staticmethod
    def _aligned(shape, dtype) -> tuple[np.ndarray, np.ndarray]:
        """(base, view): a C-contiguous ``shape``/``dtype`` view whose
        data pointer is ARENA_ALIGNMENT-aligned."""
        dt = np.dtype(dtype)
        nbytes = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        base = np.empty(nbytes + ARENA_ALIGNMENT, np.uint8)
        off = (-base.ctypes.data) % ARENA_ALIGNMENT
        view = base[off:off + nbytes].view(dt).reshape(shape)
        return base, view

    def _new(self, key) -> np.ndarray:
        shape, dtype = key
        base, view = self._aligned(shape, dtype)
        self._allocs.add(1)
        self._nbytes += view.nbytes
        self._bytes_gauge.set(self._nbytes)
        self._live[id(view)] = (key, base)
        return view

    def empty(self, shape, dtype) -> np.ndarray:
        """A long-lived aligned buffer (never enters the freelist) —
        the tiered table's cold tier and other resident host state."""
        with self._lock:
            return self._new((tuple(shape), np.dtype(dtype).str))

    @thread_role("any")
    def take(self, shape, dtype) -> np.ndarray:
        """Leases a slab (freelist hit, or a counted fresh allocation).
        Contents are UNDEFINED — the decoder overwrites every used slot
        and pads the rest itself."""
        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            self._drain_deferred()
            free = self._free.get(key)
            if free:
                buf = free.pop()
                self._reuses.add(1)
                return buf
            return self._new(key)

    @thread_role("any")
    def give(self, buf: np.ndarray) -> None:
        """Returns a leased slab to the freelist for reuse."""
        with self._lock:
            entry = self._live.get(id(buf))
            if entry is None:
                return  # not ours (or already given) — ignore
            key, _base = entry
            self._free.setdefault(key, []).append(buf)

    @thread_role("any")
    def give_when_done(self, buf: np.ndarray, device_array) -> None:
        """Like :meth:`give`, but defers the freelist return until
        ``device_array``'s transfer reports ready — the safe release
        for a slab whose H2D commit may still be reading it."""
        with self._lock:
            if id(buf) not in self._live:
                return
            self._deferred.append((device_array, buf))

    def _drain_deferred(self) -> None:
        # Lock held. is_ready() is a non-blocking completion probe; a
        # backend without it transfers synchronously (CPU), so the slab
        # is already safe to reuse.
        still = []
        for dev, buf in self._deferred:
            ready = getattr(dev, "is_ready", None)
            if ready is None or ready():
                key, _base = self._live[id(buf)]
                self._free.setdefault(key, []).append(buf)
            else:
                still.append((dev, buf))
        self._deferred = still

    # -- H2D edge ---------------------------------------------------------
    def _resolve_transfer(self):
        import jax

        dev = jax.devices()[0]
        kinds = set()
        try:
            kinds = {m.kind for m in dev.addressable_memories()}
        except Exception:  # noqa: BLE001 — older jax: no memory-space API
            pass
        if "pinned_host" in kinds:
            pinned = jax.sharding.SingleDeviceSharding(
                dev, memory_kind="pinned_host"
            )
            device = jax.sharding.SingleDeviceSharding(dev)

            def transfer(x):
                staged = jax.device_put(x, pinned)  # host -> pinned page
                return jax.device_put(staged, device)  # async DMA H2D

            return transfer, True
        import jax.numpy as jnp

        return jnp.asarray, False

    @property
    def pinned(self) -> bool:
        """True when commits stage through a real ``pinned_host`` memory
        space (resolved on first commit; False before and on CPU)."""
        if self._transfer is None:
            return False
        return self._transfer[1]

    @thread_role("producer")
    def commit(self, buf):
        """Issues the (async where the backend allows) H2D transfer of
        ``buf`` and returns the device array. The caller keeps ownership
        of the slab — pair with :meth:`give_when_done` to recycle it."""
        if self._transfer is None:
            self._transfer = self._resolve_transfer()
        self._commits.add(1)
        return self._transfer[0](buf)

    @thread_role("any")
    def stats(self) -> dict:
        """JSON-ready arena counters (the bench artifact's ``arena``
        block): allocations, reuses, hit rate, resident bytes."""
        allocs = self._allocs.value
        reuses = self._reuses.value
        total = allocs + reuses
        return {
            "allocs": int(allocs),
            "reuses": int(reuses),
            "hit_rate": round(reuses / total, 4) if total else None,
            "bytes": int(self._nbytes),
            "pinned": self.pinned,
        }


_arena_lock = threading.Lock()
_arena: PinnedArena | None = None


def get_arena() -> PinnedArena:
    """The process-wide staging arena (created on first use) — shared by
    the columnar decoder's window slabs and the tiered table's cold
    tier, so one allocator owns all pinned host staging memory."""
    global _arena
    with _arena_lock:
        if _arena is None:
            _arena = PinnedArena()
        return _arena


def reset_arena() -> PinnedArena:
    """Replaces the process-wide arena with a fresh one (tests)."""
    global _arena
    with _arena_lock:
        _arena = PinnedArena()
        return _arena


class FeedClosedError(RuntimeError):
    """``put()`` on a feed the consumer already closed (abort path: the
    consumer raised and tore the run down; the producer must stop)."""


class FeedStageError(RuntimeError):
    """A producer-thread staging failure, tagged with the window it was
    staging. Any exception raised while materializing, residency- or
    tier-planning, or committing a window's slab — including a failure
    mid staged PROMOTION on the tiered path — is wrapped in one of
    these by the runner's produce loop, so it surfaces on the consumer's
    next ``get()`` (after the already-staged prefix drains — those
    windows are valid work) carrying the window id instead of a
    context-free traceback from a daemon thread. The raw error is
    ``__cause__``."""

    def __init__(self, start: int, stop: int) -> None:
        super().__init__(
            f"feed staging failed at window [{start}, {stop})"
        )
        self.start = start
        self.stop = stop


class DeviceFeed:
    """Thread-safe bounded ring of committed window slabs.

    One producer, one consumer. ``put`` blocks while the ring is full
    (backpressure — the device is behind, which is the healthy state);
    ``get`` blocks while it is empty (starvation — the feed is behind).
    ``close()`` ends the stream: a closed-and-drained ``get`` returns
    ``None``, or raises the error ``close(error=...)`` recorded — the
    producer's exception surfaces on the consumer thread.
    """

    def __init__(self, depth: int = DEFAULT_DEPTH) -> None:
        if depth < 1:
            raise ValueError(f"feed depth must be >= 1, got {depth}")
        self.depth = depth
        self._cond = threading.Condition()
        self._items: deque = deque()
        self._closed = False
        self._error: BaseException | None = None
        reg = get_registry()
        self._depth_gauge = reg.gauge("feed.depth")
        self._starved = reg.counter("feed.starved_total")
        self._backpressure = reg.counter("feed.backpressure_total")

    @thread_role("producer")
    def put(self, item) -> None:
        """Commits one slab; blocks while the ring is at depth."""
        with self._cond:
            if len(self._items) >= self.depth and not self._closed:
                self._backpressure.add(1)
                while len(self._items) >= self.depth and not self._closed:
                    self._cond.wait()
            if self._closed:
                raise FeedClosedError("feed closed by the consumer")
            self._items.append(item)
            self._depth_gauge.set(len(self._items))
            self._cond.notify_all()

    @thread_role("consumer")
    def get(self):
        """Next committed slab; ``None`` once closed and drained."""
        with self._cond:
            if not self._items and not self._closed:
                self._starved.add(1)
                while not self._items and not self._closed:
                    self._cond.wait()
            if self._items:
                item = self._items.popleft()
                self._depth_gauge.set(len(self._items))
                self._cond.notify_all()
                return item
            if self._error is not None:
                raise self._error
            return None

    @thread_role("any")
    def close(self, error: BaseException | None = None) -> None:
        """Ends the stream (idempotent). The first recorded ``error``
        wins and is raised by the consumer's ``get`` after the drain."""
        with self._cond:
            if error is not None and self._error is None:
                self._error = error
            self._closed = True
            self._cond.notify_all()


class Prefetcher:
    """Runs ``producer(put)`` on a worker thread feeding a
    :class:`DeviceFeed`; iterate the instance to consume.

    ``producer`` is called with the feed's ``put`` and is expected to
    stage windows in order — materialize on this (worker) thread, issue
    the async device transfer, then ``put`` the committed slab. When it
    returns, the feed closes; if it raises, the exception is re-raised
    from the consumer's iteration. Use as a context manager: ``__exit__``
    closes the feed (unblocking a producer mid-``put``) and joins the
    thread, so an abandoned iteration — a consumer exception — cannot
    leak the producer.
    """

    def __init__(
        self, producer, depth: int = DEFAULT_DEPTH, name: str = "sched-feed"
    ) -> None:
        self.feed = DeviceFeed(depth)
        # Causal-trace inheritance: the producer thread stages windows ON
        # BEHALF of whatever batch/run is bound on the constructing
        # (consumer) thread, so its feed.materialize/feed.transfer spans
        # must join that trace — captured here, re-bound in _run (None
        # when tracing is off or nothing is bound: zero cost).
        self._trace = current_trace()
        self._thread = threading.Thread(
            target=self._run, args=(producer,), name=name, daemon=True
        )
        self._thread.start()

    @thread_role("producer")
    def _run(self, producer) -> None:
        try:
            with bind_trace(self._trace):
                producer(self.feed.put)
        except FeedClosedError:
            pass  # consumer aborted first; its exception is the story
        except BaseException as e:  # noqa: BLE001 — re-raised on the consumer
            self.feed.close(error=e)
        else:
            self.feed.close()

    def __iter__(self):
        while True:
            item = self.feed.get()
            if item is None:
                return
            yield item

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> bool:
        self.feed.close()
        self._thread.join()
        return False


def stage_chunk(sched, start: int, stop: int):
    """Producer-side staging of one schedule window: host materialization
    (``feed.materialize`` span) then the async H2D commit of the compact
    slab (``feed.transfer`` span). Hand-built eager schedules get the
    same compact-feed invariant check ``device_arrays`` would apply."""
    from analyzer_tpu.sched.superstep import compact_device_window

    check = getattr(sched, "check_compact_invariant", None)
    if check is not None:
        check(start, stop)
    tracer = get_tracer()
    with tracer.span("feed.materialize", cat="sched", start=start):
        pidx, _mask, winner, mode_id, afk = sched.host_window(start, stop)
    with tracer.span("feed.transfer", cat="sched", start=start):
        return compact_device_window(pidx, winner, mode_id, afk)


def stage_ingest_window(win, arena: PinnedArena | None = None):
    """The ingest plane's H2D edge (docs/ingest.md): commits one
    :class:`analyzer_tpu.io.ingest.DecodedWindow`'s column slabs to the
    device (``ingest.commit`` span; async DMA through the pinned staging
    path where the backend has one) and recycles the slabs back to the
    arena once their transfers report ready. The FULL fixed-width slabs
    are committed — window shape is static, so every window reuses one
    compiled transfer shape — and the live row count rides alongside.

    Returns ``(rows, player_idx, winner, mode_id, afk)`` device arrays.
    """
    arena = arena or get_arena()
    tracer = get_tracer()
    with tracer.span("ingest.commit", cat="ingest", rows=win.rows):
        devs = tuple(arena.commit(buf) for buf in win.slabs)
    win.release(devs)
    return (win.rows,) + devs


class FusedChunk:
    """One chunk staged for the fused window kernel: the residency-
    planned per-window device slabs (``core.fused`` layout), the padded
    slot->match map rows for collect reordering (``flat``, or None),
    the chunk's planner aggregates for bench telemetry, and — on a
    tiered run — one ``TierPlan`` per window (``tier_plans``), since the
    fused working-set gather then reads through the hot set."""

    __slots__ = ("windows", "flat", "stats", "tier_plans")

    def __init__(self, windows, flat, stats, tier_plans=None):
        self.windows = windows
        self.flat = flat
        self.stats = stats
        self.tier_plans = tier_plans


def stage_chunk_fused(sched, start: int, stop: int, fuse, collect: bool,
                      tier=None):
    """Fused-path sibling of :func:`stage_chunk`: materializes the
    chunk's gather tensors, residency-plans it into fused windows
    (``feed.materialize`` span — the plan is host packing work), and
    commits each window's slab (``feed.transfer`` span). ``tier``
    (a ``sched.tier.TierManager``) remaps each window into hot-slot
    space and attaches its promotion/demotion plan."""
    check = getattr(sched, "check_compact_invariant", None)
    if check is not None:
        check(start, stop)
    tracer = get_tracer()
    with tracer.span("feed.materialize", cat="sched", start=start):
        pidx, _mask, winner, mode_id, afk = sched.host_window(start, stop)
    return stage_fused_windows(
        pidx, winner, mode_id, afk, sched.pad_row, fuse,
        match_idx=sched.match_idx[start:stop] if collect else None,
        start=start, tier=tier,
    )


def _pad_window_steps(arr, k: int, fill):
    """Pads a window slab's leading (step) axis to the static window
    size with an inert fill value."""
    import numpy as np

    extra = k - arr.shape[0]
    if extra <= 0:
        return arr
    pad = np.full((extra,) + arr.shape[1:], fill, arr.dtype)
    return np.concatenate([arr, pad])


def stage_fused_windows(
    pidx, winner, mode_id, afk, pad_row: int, fuse,
    match_idx=None, start: int = 0, tier=None,
):
    """The shared fused staging core (windowed-schedule chunks AND the
    streamed feed): residency plans, per-window padding to the static
    window size (inert steps: slot 0, unsupported mode — they read and
    write only the pinned pad slot), and the async H2D commit of each
    window's slab. ``match_idx`` (when collecting) yields the padded
    slot->match rows, -1 on inert steps so ``_gather_outputs`` drops
    them. ``tier`` composes the hot set: each window's ``slot_rows``
    are remapped into hot slots (the fused gather then reads through
    the hot set) and its ``TierPlan`` rides along — the runner caps the
    fused ``max_rows`` at the hot capacity, so every fused window fits
    by construction."""
    import numpy as np

    import jax.numpy as jnp

    from analyzer_tpu.core import constants
    from analyzer_tpu.sched.residency import (
        plan_windows, record_plan_telemetry,
    )

    ratable = (mode_id >= 0) & ~afk
    valid = (pidx != pad_row) & ratable[:, :, None, None]
    plans = plan_windows(pidx, valid, pad_row, fuse.window, fuse.max_rows)
    record_plan_telemetry(plans, fuse.window)
    tracer = get_tracer()
    windows = []
    tier_plans = [] if tier is not None else None
    flat_parts = [] if match_idx is not None else None
    k = fuse.window
    s0 = 0
    with tracer.span("feed.transfer", cat="sched", start=start):
        for plan in plans:
            s1 = s0 + plan.n_steps
            slot_rows = plan.slot_rows
            if tier is not None:
                tplan, slot_rows = tier.plan_fused(
                    plan.slot_rows, plan.n_live, pidx[s0:s1], valid[s0:s1]
                )
                tier_plans.append(tplan)
            windows.append((
                jnp.asarray(slot_rows),
                jnp.asarray(_pad_window_steps(plan.slot_idx, k, 0)),
                jnp.asarray(_pad_window_steps(
                    winner[s0:s1].astype(np.int8), k, 0
                )),
                jnp.asarray(_pad_window_steps(
                    mode_id[s0:s1].astype(np.int8), k,
                    constants.UNSUPPORTED_MODE_ID,
                )),
                jnp.asarray(_pad_window_steps(afk[s0:s1], k, False)),
            ))
            if flat_parts is not None:
                flat_parts.append(
                    _pad_window_steps(match_idx[s0:s1], k, -1)
                )
            s0 = s1
    stats = {
        "windows": len(plans),
        "spills": sum(1 for p in plans if p.spilled),
        "writebacks_avoided": sum(p.writebacks_avoided for p in plans),
        "pad_steps": sum(k - p.n_steps for p in plans),
        "working_set_rows": max((p.n_live for p in plans), default=0),
    }
    return FusedChunk(
        windows,
        np.concatenate(flat_parts) if flat_parts else None,
        stats,
        tier_plans,
    )
