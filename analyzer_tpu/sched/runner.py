"""The device-side history runner: ``lax.scan`` over packed supersteps.

Replaces the reference's per-match Python loop (``worker.py:191-192``) with
one compiled scan: each scan iteration gathers priors for a whole
conflict-free superstep, applies the closed-form TrueSkill updates, and
scatters posteriors back into the HBM-resident player table. The scan
carries only the PlayerState; per-match outputs are optionally collected and
scattered back into stream (chronological) order by ``match_idx``.

Large histories stream through in chunks of steps so the packed schedule
never has to fit in HBM at once (the reference's CHUNKSIZE/yield_per idea,
``worker.py:191``, at superstep granularity); the state buffer is donated
between chunks so XLA updates it in place.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core import fused as fused_kernel
from analyzer_tpu.core.state import MatchBatch, PlayerState
from analyzer_tpu.core.update import pack_outputs, rate_and_apply
from analyzer_tpu.obs import (
    get_registry,
    get_tracer,
    maybe_sample_device_memory,
    track_jit,
)
from analyzer_tpu.sched.feed import (
    DEFAULT_DEPTH,
    FeedStageError,
    Prefetcher,
    stage_chunk,
    stage_chunk_fused,
    stage_fused_windows,
)
from analyzer_tpu.sched.residency import resolve_fuse
from analyzer_tpu.sched.tier import TierManager, stage_chunk_tiered
from analyzer_tpu.sched.superstep import (
    PackedSchedule,
    compact_device_window,
    expand_step,
)
from analyzer_tpu.utils.host import fetch_tree


@dataclasses.dataclass
class HistoryOutputs:
    """Per-match outputs in stream order (numpy, host-side).

    Mirrors what the reference persists per match/participant
    (``rater.py:140-169``): match quality, shared posterior snapshot +
    conservative-estimate delta, mode posterior, and the any_afk flag.
    Rows for matches that were not rated (AFK/unsupported) hold the gate
    outputs only; ``updated`` marks rows whose ratings were written.
    """

    quality: np.ndarray  # [N]
    shared_mu: np.ndarray  # [N, 2, T]
    shared_sigma: np.ndarray  # [N, 2, T]
    delta: np.ndarray  # [N, 2, T]
    mode_mu: np.ndarray  # [N, 2, T]
    mode_sigma: np.ndarray  # [N, 2, T]
    any_afk: np.ndarray  # [N]
    updated: np.ndarray  # [N]


@partial(
    jax.jit, static_argnames=("cfg", "collect", "pad_row"), donate_argnums=(0,)
)
def _scan_chunk(
    state: PlayerState, arrays, cfg: RatingConfig, collect: bool, pad_row: int
):
    """Scans rate_and_apply over a compact [S', B, ...] slab of supersteps
    (``compact_device_window`` layout: slot_mask derived on device,
    int8 scalars widened here — ``pad_row`` is static like the shapes)."""

    def step(st, xs):
        pidx, mask, winner, mode, afk = expand_step(xs, pad_row)
        batch = MatchBatch(
            player_idx=pidx, slot_mask=mask, winner=winner, mode_id=mode, afk=afk
        )
        st, out = rate_and_apply(st, batch, cfg)
        if not collect:
            return st, None
        # Collected outputs pack into ONE [B, 3 + 10T] f32 tensor (the
        # layout lives in core.update.pack_outputs, shared with the
        # fused window kernel so the two cannot drift). One tensor = ONE
        # D2H fetch per chunk: the service loop previously fetched 9
        # leaves per 500-match batch at ~a tunnel round trip each.
        # _gather_outputs unpacks.
        return st, pack_outputs(out)

    return jax.lax.scan(step, state, arrays)


# Retrace accounting (obs.retrace): the service worker's warmup compiles
# this entrypoint's whole shape ladder, so its jit-cache size moving
# AFTER warmup is a retrace — the runtime form of graftlint's GL004/GL007
# hazards, surfaced per entrypoint in every --metrics-out snapshot.
track_jit("sched._scan_chunk", _scan_chunk)
# The fused window kernel's shape ladder: one entry per (slot bucket,
# window) pair — the pow2 slot bucketing exists to keep this ladder
# short, and a moving cache after warmup means bucketing broke.
track_jit("core.fused_window_step", fused_kernel.fused_window_step)


def _dispatch_fused_chunk(state, staged, cfg, collect: bool, backend: str,
                          tier=None):
    """Consumer-side fused dispatch of one staged chunk: every residency
    window runs as one ``fused_window_step`` call (the table buffer is
    donated window to window). Returns the new state and, when
    collecting, the chunk's ``[n_windows * K, B, 3 + 10T]`` packed
    outputs — same layout the reference scan emits, so the fetch
    pipeline and ``_gather_outputs`` are shared. On a tiered run each
    window's ``TierPlan`` (promotions in, dirty demotions out) executes
    against the hot table right before its dispatch."""
    ys_parts = []
    table = state.table
    plans = staged.tier_plans or (None,) * len(staged.windows)
    for (slot_rows, slot_idx, winner, mode_id, afk), tplan in zip(
        staged.windows, plans
    ):
        if tplan is not None:
            table = tier.apply(table, tplan)
        table, ys = fused_kernel.fused_window_step(
            table, slot_rows, slot_idx, winner, mode_id, afk,
            cfg, collect, backend,
        )
        if collect:
            ys_parts.append(ys)
    state = dataclasses.replace(state, table=table)
    if not collect:
        return state, None
    return state, (
        ys_parts[0] if len(ys_parts) == 1 else jnp.concatenate(ys_parts)
    )


def rate_history(
    state: PlayerState,
    sched: PackedSchedule,
    cfg: RatingConfig,
    collect: bool = False,
    steps_per_chunk: int | None = None,
    start_step: int = 0,
    stop_after: int | None = None,
    on_chunk=None,
    view_publisher=None,
    prefetch_depth: int | None = None,
    kernel: str = "reference",
    fuse_window: int | None = None,
    fuse_max_rows: int | None = None,
    fuse_backend: str | None = None,
    hot_rows: int = 0,
) -> tuple[PlayerState, HistoryOutputs | None]:
    """Rates a packed history. Returns the final state and, when
    ``collect``, per-match outputs reordered back to stream order.

    ``hot_rows`` > 0 runs TIERED (:mod:`analyzer_tpu.sched.tier`): only
    a ``hot_rows``-slot hot set (pow2-bucketed) of the player table is
    device-resident; the rest lives in a host cold tier, promoted ahead
    of the window that needs it on the feed thread and LRU-demoted with
    dirty rows written back D2H one batch per window. Results are
    bit-identical to the untiered run at every hot-set size; 0 (the
    default) leaves today's untiered compiled paths untouched. Composes
    with ``kernel="fused"`` (the working-set gather reads through the
    hot set) and with ``view_publisher`` (views publish from the hot
    set + host shadow over the incremental patch path).

    ``kernel`` selects the device kernel: ``"reference"`` (the per-step
    gather -> update -> scatter scan) or ``"fused"`` — the VMEM-resident
    window kernel (:mod:`analyzer_tpu.core.fused`): each chunk is
    residency-planned (:mod:`analyzer_tpu.sched.residency`) into windows
    of ``fuse_window`` supersteps that gather every touched row once and
    write it back once. Chunk boundaries, hooks, publishes, and results
    are kernel-invariant — the fused path is bit-identical to the
    reference (pinned by tests/test_fused.py). ``fuse_max_rows`` bounds
    the working set (VMEM budget; overflow splits windows),
    ``fuse_backend`` picks scan / pallas / interpret (default: the
    ``ANALYZER_TPU_FUSE_BACKEND`` env, then the portable scan body).

    ``start_step`` re-enters the scan mid-schedule (checkpoint resume;
    the caller is responsible for passing the state snapshot taken at that
    step). ``stop_after`` ends the run at a chunk boundary at or after that
    step (testing / bounded ops runs). ``on_chunk(state, next_step)`` fires
    after each chunk with the superstep index the next chunk would start
    at — the periodic-checkpoint hook (io/checkpoint.py); fetching the
    state there costs one device sync, the price of a bounded crash blast
    radius (the reference pays per 500-match commit, worker.py:194).

    ``view_publisher`` (a :class:`analyzer_tpu.serve.view.ViewPublisher`)
    makes a long re-rate LIVE-SERVABLE: a throttled snapshot of the
    carried table publishes at chunk boundaries (rows addressed by
    index) plus one forced publish of the final state — same device-sync
    cost profile as the checkpoint hook, governed by the publisher's
    ``min_publish_interval_s``.

    ``prefetch_depth`` sizes the device feed's slab ring
    (:mod:`analyzer_tpu.sched.feed`, default 2): window materialization
    and the H2D transfer run on a producer thread up to ``depth``
    windows ahead of the in-flight scan. Depth changes overlap only —
    the chunk sequence, hook boundaries, and results are identical at
    every depth.
    """
    fuse = resolve_fuse(kernel, fuse_window, fuse_max_rows, fuse_backend)
    if hot_rows < 0:
        raise ValueError(f"hot_rows must be >= 0, got {hot_rows}")
    tier = TierManager(state, hot_rows) if hot_rows else None
    if tier is not None and fuse is not None:
        fuse = tier.clamp_fuse(fuse)
    n_steps = sched.n_steps if stop_after is None else min(stop_after, sched.n_steps)
    if steps_per_chunk is None:
        # ~8 chunks pipelines window materialization + H2D against the
        # device scan (measured best on v5e: 1.14x device-only at 500k vs
        # 2.1x single-chunk); the floor keeps per-dispatch overhead
        # amortized, the ceiling bounds device memory for the slabs.
        steps_per_chunk = min(8192, max(256, -(-sched.n_steps // 8)))
    if tier is not None:
        # Tiered: the compiled kernels only ever see the hot table; the
        # caller's full state became the cold tier (one D2H at entry —
        # the tiered sibling of the jnp.copy below) and is never donated.
        state = tier.hot_state()
    else:
        # The chunked scan donates its carry; copy once at entry so the
        # caller's state stays valid (the table is small — tens of MB at
        # 10M players).
        state = jax.tree.map(jnp.copy, state)
    outs = [] if collect else None
    tracer = get_tracer()
    reg = get_registry()
    reg.gauge("sched.occupancy").set(round(sched.occupancy, 4))
    reg.counter("sched.steps_total").add(max(0, n_steps - start_step))
    # Prefetched feed (sched/feed.py): a producer thread materializes
    # window k+j (j <= depth) and issues its async device_put while the
    # device executes chunk k, a bounded ring holding the committed
    # slabs. The consumer loop below only dispatches, fetches, and runs
    # hooks; the spans mirror that split — feed.materialize/feed.transfer
    # on the producer thread, batch.compute is ENQUEUE cost, batch.fetch
    # is where device time actually surfaces on the host.
    starts = list(range(start_step, n_steps, steps_per_chunk))

    def produce(put) -> None:
        for start in starts:
            stop = min(start + steps_per_chunk, n_steps)
            try:
                if fuse is not None:
                    item = stage_chunk_fused(
                        sched, start, stop, fuse, collect, tier=tier
                    )
                elif tier is not None:
                    item = stage_chunk_tiered(sched, start, stop, tier, collect)
                else:
                    item = stage_chunk(sched, start, stop)
            except Exception as e:
                # Window-id context for the consumer (sched/feed.py
                # FeedStageError): a staging failure — materialization,
                # residency/tier planning, or a staged promotion —
                # surfaces on the next get() naming the window.
                raise FeedStageError(start, stop) from e
            put((start, stop, item))

    # Fused + collect: inert window-padding steps make the emitted ys
    # rows a superset of the schedule's — the staged chunks carry their
    # own padded slot->match rows (-1 on inert steps) instead of
    # sched.match_idx.
    fused_flat = [] if (fuse is not None and collect) else None
    pending = None  # chunk k-1's outputs: fetched AFTER dispatching k
    with Prefetcher(produce, depth=prefetch_depth or DEFAULT_DEPTH) as pf:
        for start, stop, arrays in pf:
            with tracer.span("batch.compute", cat="sched", start=start):
                if fuse is not None:
                    state, ys = _dispatch_fused_chunk(
                        state, arrays, cfg, collect, fuse.backend, tier=tier
                    )
                    if fused_flat is not None:
                        fused_flat.append(arrays.flat)
                elif tier is not None:
                    state, ys = tier.dispatch_chunk(
                        state, arrays, cfg, collect
                    )
                else:
                    state, ys = _scan_chunk(
                        state, arrays, cfg, collect, sched.pad_row
                    )  # async dispatch
            del arrays  # let the consumed slab free when the scan is done
            if collect:
                # One-chunk-deep fetch pipelining: start k's D2H stream
                # now and materialize k-1's (whose transfer has been in
                # flight a whole chunk) — without this every chunk pays a
                # cold ~100 ms tunnel round trip SERIALLY, which the
                # service path's fixed 8-step chunks turned into
                # ceil(steps/8) RTTs per deep batch.
                try:
                    ys.copy_to_host_async()
                except AttributeError:  # pragma: no cover — older jax arrays
                    pass
                if pending is not None:
                    with tracer.span("batch.fetch", cat="sched", start=start):
                        outs.append(fetch_tree(pending))
                pending = ys
            if on_chunk is not None:
                # Tiered: the hook gets the logical full state (cold tier
                # + resident written rows), same snapshot cost profile as
                # the untiered hook's fetch.
                on_chunk(
                    tier.full_state(state.table) if tier is not None
                    else state, stop,
                )
            if view_publisher is not None:
                # Throttled view publish BEFORE the next chunk dispatches:
                # the carry buffer is about to be donated, so the publisher
                # fetches its host copy here or not at all. Tiered runs
                # publish hot-set rows + host shadow over the incremental
                # patch path instead of a full-table fetch.
                if tier is not None:
                    tier.maybe_publish_view(view_publisher, state.table)
                else:
                    view_publisher.maybe_publish_state(state)
            # HBM-occupancy gauges at chunk boundaries (throttled inside —
            # device.hbm_bytes_in_use / device.live_buffers,
            # obs/devicemem.py): a run creeping toward the HBM ceiling
            # shows up in /metrics and the bench telemetry block BEFORE
            # it OOMs.
            maybe_sample_device_memory()
    if view_publisher is not None:
        if tier is not None:
            tier.publish_view(view_publisher, state.table)  # unthrottled
        else:
            view_publisher.publish_state(state)  # final table, unthrottled
    if tier is not None:
        # Reconstruct the logical full state: the drained cold tier plus
        # every resident row written since entry — bit-identical to the
        # untiered runner's final table.
        state = tier.finish(state.table)
    if not collect:
        return state, None
    if pending is not None:
        with tracer.span("batch.fetch", cat="sched", start=n_steps):
            outs.append(fetch_tree(pending))

    if fused_flat is not None:
        flat_idx = (
            np.concatenate(fused_flat).reshape(-1)
            if fused_flat else np.empty(0, np.int32)
        )
    else:
        flat_idx = sched.match_idx[start_step:n_steps].reshape(-1)
    return state, _gather_outputs(
        outs, flat_idx, sched.n_matches, sched.team_size
    )


def _gather_outputs(
    outs: list, flat_idx: np.ndarray, n: int, team: int
) -> HistoryOutputs:
    """Unpacks the per-chunk [S', B, 3 + 10T] packed tensors
    (``_scan_chunk``'s collect layout) and scatters the slots back to
    stream order. Zero chunks (resume at/past the end) yields all-zero
    outputs with `updated` all-False — same shapes as a real run."""
    t2 = 2 * team
    if not outs:
        return HistoryOutputs(
            quality=np.zeros(n, np.float32),
            shared_mu=np.zeros((n, 2, team), np.float32),
            shared_sigma=np.zeros((n, 2, team), np.float32),
            delta=np.zeros((n, 2, team), np.float32),
            mode_mu=np.zeros((n, 2, team), np.float32),
            mode_sigma=np.zeros((n, 2, team), np.float32),
            any_afk=np.zeros(n, bool),
            updated=np.zeros(n, bool),
        )
    sel = flat_idx >= 0
    dest = flat_idx[sel]
    full = np.concatenate(outs, axis=0)
    outs.clear()  # chunk copies die with the concat; bounds peak memory
    full = full.reshape(-1, full.shape[-1])  # [S*B, 3 + 5*2T]
    packed = np.zeros((n, full.shape[1]), full.dtype)
    packed[dest] = full[sel]
    del full  # the concat copy (~1.3 GB at 10M matches) dies here
    # The field blocks below are VIEWS into `packed`: a column slice is
    # strided but its LAST axis stays contiguous, and splitting that
    # trailing axis (n, 2T) -> (n, 2, T) is stride-expressible, so
    # numpy's reshape returns a view, not a copy (pinned by
    # tests/test_sched.py::test_gather_outputs_blocks_are_views). The one
    # packed buffer stays alive behind the returned HistoryOutputs
    # instead of being copied out field by field.

    def block(i):
        return packed[:, 3 + i * t2: 3 + (i + 1) * t2].reshape(n, 2, team)

    return HistoryOutputs(
        quality=packed[:, 0],
        shared_mu=block(0),
        shared_sigma=block(1),
        delta=block(2),
        mode_mu=block(3),
        mode_sigma=block(4),
        any_afk=packed[:, 1] > 0.5,
        updated=packed[:, 2] > 0.5,
    )


def rate_stream(
    state: PlayerState,
    stream,
    cfg: RatingConfig,
    collect: bool = False,
    batch_size: int | None = None,
    steps_per_chunk: int | None = None,
    poll_interval: float = 0.002,
    team_size: int | None = None,
    stats_out: dict | None = None,
    mesh=None,
    view_publisher=None,
    on_chunk=None,
    prefetch_depth: int | None = None,
    kernel: str = "reference",
    fuse_window: int | None = None,
    fuse_max_rows: int | None = None,
    fuse_backend: str | None = None,
    hot_rows: int = 0,
) -> tuple[PlayerState, HistoryOutputs | None]:
    """Rates a raw MatchStream with the schedule built CONCURRENTLY with
    the device scan — the fully-streamed feed. ``stats_out`` (optional
    dict) receives n_steps / batch_size / occupancy after the run — the
    schedule never exists as one object here, so these are the only
    schedule-level observables.

    ``hot_rows`` mirrors :func:`rate_history`: > 0 keeps only a pow2-
    bucketed hot set of the table device-resident, promoting cold rows
    from the host tier on this same feed thread ahead of the window
    that needs them (:mod:`analyzer_tpu.sched.tier`); results stay
    bit-identical and 0 leaves the untiered paths untouched. Not
    composable with ``mesh=`` — each shard tiers independently is
    ROADMAP item 2's composition.

    ``kernel``/``fuse_*`` mirror :func:`rate_history`: ``"fused"``
    residency-plans each emitted window on the feed thread and
    dispatches it through the VMEM-resident window kernel; boundaries
    and results are kernel-invariant. Not composable with ``mesh=`` —
    the sharded scatter is already per-shard compacted and a per-shard
    fused working set is future work (see ``parallel.mesh``'s reuse
    accounting).

    ``mesh`` composes this feed with the sharded-table data parallelism
    (``parallel.mesh.ShardedRun``): every emitted window is routed per
    chunk and dispatched to the mesh, so a pod re-rate gets the same
    concurrent assignment + O(window) host memory as a single chip. The
    auto batch size is rounded up to a mesh-size multiple (an explicit
    ``batch_size`` must already be one); ``collect`` is not supported on
    the mesh path (the sharded scan carries only the table — use
    ``rate_history(collect=True)`` for per-match outputs).

    ``view_publisher`` publishes throttled index-addressed view
    snapshots at window boundaries (plus the final table), exactly like
    ``rate_history``'s hook — the streamed feed stays live-servable. On
    the mesh path only the final (gathered) table publishes: a mid-run
    shard gather would serialize the very overlap this feed exists for.

    ``rate_history`` overlaps window *materialization* with the scan but
    still pays the whole first-fit assignment as a sequential prefix
    (~2 s of a 10M-match run). Here the assignment runs on a worker
    thread (ctypes releases the GIL for the native loop); a FEED thread
    (:mod:`analyzer_tpu.sched.feed`) scatters newly assigned slots into
    the slot->match map, backfills non-ratable fillers into each
    window's padding slots as it goes (same occupancy as the offline
    packer), materializes each complete window and issues its async
    device transfer up to ``prefetch_depth`` (default 2) windows ahead
    — all while the assigner is still running and the device executes
    the previous chunk. The consumer loop below only dispatches the
    committed slabs (and, with ``collect``, overlaps each chunk's D2H
    fetch with the next chunk's compute). End-to-end wall time
    approaches ``choose_batch_size + max(assign, materialize, device
    scan)`` — BENCH_r05's 1.75x-device serialization was exactly the
    sum this turns into a max.

    ``on_chunk(state, next_step)`` mirrors ``rate_history``'s
    checkpoint-hook surface at window boundaries; on the mesh path the
    hook receives the snapshot THUNK protocol of
    :meth:`analyzer_tpu.parallel.mesh.ShardedRun.call_hook`.

    Cross-thread protocol (portable — no acquire/release pairing with
    the C loop is assumed): the output buffers are prefilled with a
    sentinel; aligned int64 stores don't tear, so a racy read sees
    either the sentinel or the final value, and the consumer trims its
    frontier at the first sentinel. Batch finality is DERIVED from the
    consumed data (a batch is final once its fill count reaches the
    capacity — first-fit never reopens a full batch) rather than read
    from the C loop's watermark, whose release stores would need acquire
    loads Python can't express. That loses nothing: the C loop's
    published watermark is ``find(0)`` — the first NON-FULL batch — so
    both watermarks equal the length of the full-batch prefix and differ
    only by publish granularity. ``Thread.join`` is the one trusted
    synchronization point, after which the buffers are read plainly.
    Wakeups ride a condition variable: the pure-python assigner
    signals it at every progress publish and both assigner paths signal
    completion, so the feed reacts immediately instead of sleeping out a
    poll interval; the native loop runs with the GIL released and cannot
    call back into Python, so ``poll_interval`` survives as the wait
    timeout — the poll fallback — for exactly that path.

    Occupancy caveat to the wall-time claim: batches become final only
    by FILLING, so on a chain-bound (low-occupancy) schedule whose early
    batches never reach capacity, no windows can be emitted until the
    assigner finishes and the feed serializes — overlap degrades toward
    ``rate_history``'s windowed mode (which this path never does worse
    than). No watermark scheme can do better under first-fit: a non-full
    batch legitimately remains open to any future fresh-player match.

    Deterministic: window boundaries are fixed multiples of
    ``steps_per_chunk`` and fillers are consumed in stream order, so the
    emitted schedule — and therefore the final state and outputs — is a
    pure function of (stream, batch_size, steps_per_chunk), independent
    of thread timing. Final state is bit-identical to
    ``rate_history(pack_schedule(stream))``; per-match outputs are equal
    as well (filler PLACEMENT may differ from the offline packer's, but
    non-ratable matches produce the same gate outputs wherever they sit).
    """
    import threading
    import time as _time

    from analyzer_tpu.sched.superstep import (
        assign_batches,
        choose_batch_size_streamed,
        materialize_gather_window,
        materialize_scalar_window,
    )
    from analyzer_tpu.core.state import MAX_TEAM_SIZE

    n = stream.n_matches
    team = team_size or max(MAX_TEAM_SIZE, stream.team_size)
    if stream.team_size > team:
        raise ValueError(
            f"stream team size {stream.team_size} exceeds team_size {team}"
        )
    fuse = resolve_fuse(kernel, fuse_window, fuse_max_rows, fuse_backend)
    if hot_rows < 0:
        raise ValueError(f"hot_rows must be >= 0, got {hot_rows}")
    run = None
    if mesh is not None:
        if collect:
            raise ValueError(
                "collect=True is not supported with mesh= (the sharded "
                "scan carries only the table); use rate_history"
            )
        if fuse is not None:
            raise ValueError(
                "kernel='fused' is not supported with mesh= (the sharded "
                "scatter is per-shard compacted; a per-shard fused "
                "working set is tracked by parallel.mesh's "
                "mesh.writebacks_avoidable_total accounting)"
            )
        if hot_rows:
            raise ValueError(
                "hot_rows > 0 is not supported with mesh= (each shard "
                "tiering its slice independently is the ROADMAP item 2 "
                "composition); drop mesh= or hot_rows"
            )
        from analyzer_tpu.parallel.mesh import ShardedRun

        run = ShardedRun(state, cfg, mesh)
    pad_row = state.pad_row
    tier = TierManager(state, hot_rows) if hot_rows else None
    if tier is not None and fuse is not None:
        fuse = tier.clamp_fuse(fuse)
    if run is None:
        state = tier.hot_state() if tier is not None \
            else jax.tree.map(jnp.copy, state)
    if n == 0:
        if stats_out is not None:
            stats_out.update(
                n_steps=0, batch_size=0, occupancy=0.0, choose_batch_size_s=0.0
            )
        if run is not None:
            state = run.finish()
        elif tier is not None:
            state = tier.finish(state.table)
        return state, (_gather_outputs([], np.empty(0, np.int32), 0, team)
                       if collect else None)
    if int(stream.player_idx.max()) >= pad_row:
        raise ValueError(
            f"stream references player row {int(stream.player_idx.max())} "
            f"but the player table only has rows 0..{pad_row - 1}"
        )

    # The batch-size choice is reported through stats_out (a CLI stats
    # contract), not a phase histogram — a raw clock is the right tool.
    t_choose = _time.perf_counter()  # graftlint: disable=GL023
    if run is not None:
        import math

        n_dev = int(mesh.devices.size)
        if batch_size is None:
            # Size with the mesh-aware multiple (like cli._rate_mesh /
            # bench_mesh) so B stays both lane-aligned (8) and divisible
            # by D even on non-power-of-two meshes — a plain round-up of
            # the default choice could break 8-alignment (e.g. D=6).
            m = math.lcm(8, n_dev)
            b = choose_batch_size_streamed(stream, batch_multiple=m)
            b = -(-b // m) * m  # the mean-width candidate can undershoot m
        elif batch_size % n_dev:
            raise ValueError(
                f"batch_size {batch_size} not divisible by mesh size {n_dev}"
            )
        else:
            b = batch_size
    else:
        b = batch_size or choose_batch_size_streamed(stream)
    t_choose = _time.perf_counter() - t_choose  # graftlint: disable=GL023
    spc = steps_per_chunk or min(8192, max(256, -(-n // b) // 8 or 1))

    sentinel = np.iinfo(np.int64).min
    progress = np.zeros(2, np.int64)
    out_b = np.full(n, sentinel, np.int64)
    out_s = np.full(n, sentinel, np.int64)
    worker_err: list[BaseException] = []

    # Assigner -> feed handshake: the python fallback notifies at every
    # progress publish and both paths notify completion (the `finally`),
    # so chain-bound schedules — where nothing is emittable until the
    # assigner finishes — don't pay up to poll_interval of dead time at
    # the handoff. The native loop publishes with the GIL released and
    # cannot notify, so the feed's wait keeps poll_interval as timeout.
    cv = threading.Condition()
    assigner_done = [False]

    def notify_progress():
        with cv:
            cv.notify_all()

    def work():
        try:
            assign_batches(
                stream, b, progress, out_b, out_s, on_progress=notify_progress
            )
        except BaseException as e:  # noqa: BLE001 — re-raised on the consumer
            worker_err.append(e)
        finally:
            with cv:
                assigner_done[0] = True
                cv.notify_all()

    worker = threading.Thread(target=work, daemon=True)
    worker.start()

    fillers = np.flatnonzero(~stream.ratable)
    n_fill = 0  # fillers placed so far
    cap_steps = max(-(-n // b) + 2, 2)
    slot_map = np.full(cap_steps * b, -1, np.int32)
    fill_count = np.zeros(cap_steps, np.int32)
    done_m = 0  # matches scattered into slot_map
    emitted = 0  # steps dispatched to the device
    watermark = 0  # prefix of batches known full (final)
    outs = [] if collect else None

    def grow(min_steps: int) -> None:
        nonlocal slot_map, fill_count, cap_steps
        if min_steps <= cap_steps:
            return
        while cap_steps < min_steps:
            cap_steps *= 2
        bigger = np.full(cap_steps * b, -1, np.int32)
        bigger[: slot_map.size] = slot_map
        slot_map = bigger
        bigger_c = np.zeros(cap_steps, np.int32)
        bigger_c[: fill_count.size] = fill_count
        fill_count = bigger_c

    def scatter_new(p: int) -> None:
        """Consumes assignment entries [done_m, p), trimming at the first
        not-yet-visible (sentinel) entry, and advances the derived
        watermark over newly full batches."""
        nonlocal done_m, watermark
        if p <= done_m:
            return
        nb = out_b[done_m:p]
        ns = out_s[done_m:p]
        # Trim at the first entry where EITHER buffer still shows the
        # sentinel: without acquire loads, out_b[i] can be visible while
        # out_s[i] is not (and vice versa) on weakly-ordered CPUs.
        unwritten = np.flatnonzero((nb == sentinel) | (ns == sentinel))
        if unwritten.size:
            p = done_m + int(unwritten[0])
            if p <= done_m:
                return
            nb = out_b[done_m:p]
            ns = out_s[done_m:p]
        live = nb >= 0
        if live.any():
            grow(int(nb[live].max()) + 1)
            slot_map[nb[live] * b + ns[live]] = (
                np.flatnonzero(live) + done_m
            ).astype(np.int32)
            counts = np.bincount(nb[live])
            fill_count[: counts.size] += counts.astype(np.int32)
            while watermark < cap_steps and fill_count[watermark] >= b:
                watermark += 1
        done_m = p

    tracer = get_tracer()

    def stage(e0: int, e1: int):
        """Feed-thread staging of steps [e0, e1): backfills fillers into
        the window's free slots (stream order — deterministic),
        materializes the window, and issues its async device transfer.
        Returns the committed slab (single-device: the compact arrays;
        mesh: the routed, device-put tuple for ``dispatch_staged``)."""
        nonlocal n_fill
        win = slot_map[e0 * b : e1 * b]  # view: backfill lands in slot_map
        if n_fill < fillers.size:
            free = np.flatnonzero(win < 0)
            take = min(free.size, fillers.size - n_fill)
            if take:
                win[free[:take]] = fillers[n_fill : n_fill + take].astype(np.int32)
                n_fill += take
        mi = win.reshape(e1 - e0, b)
        with tracer.span("feed.materialize", cat="sched", start=e0):
            pidx, mask = materialize_gather_window(stream, mi, pad_row, team)
            winner, mode_id, afk = materialize_scalar_window(stream, mi)
        if fuse is not None:
            # Residency-planned fused windows (spans inside): the padded
            # slot->match rows ride along for collect reordering.
            return stage_fused_windows(
                pidx, winner, mode_id, afk, pad_row, fuse,
                match_idx=mi if collect else None, start=e0, tier=tier,
            )
        if tier is not None:
            with tracer.span("feed.transfer", cat="sched", start=e0):
                return tier.stage_windows(pidx, winner, mode_id, afk)
        with tracer.span("feed.transfer", cat="sched", start=e0):
            if run is not None:
                return run.stage(pidx, mask, winner, mode_id, afk)
            return compact_device_window(pidx, winner, mode_id, afk)

    def stage_checked(e0: int, e1: int):
        """``stage`` with the window id attached to any failure — the
        consumer's next ``get()`` raises a FeedStageError naming the
        window instead of a bare producer-thread traceback (a staged
        tier PROMOTION failing mid-flight included)."""
        try:
            return stage(e0, e1)
        except Exception as e:
            raise FeedStageError(e0, e1) from e

    result: dict = {}

    def produce(put) -> None:
        """Feed-thread body: consume the assigner's output, emit every
        complete window, then the deterministic tail. Window boundaries
        are fixed multiples of ``spc`` regardless of when the data
        became visible, so depth and thread timing never change what is
        emitted — only how far ahead it is staged."""
        nonlocal emitted, watermark
        while True:
            done = assigner_done[0]  # read BEFORE consuming progress
            scatter_new(int(progress[0]))
            advanced = False
            while watermark - emitted >= spc:
                put((emitted, emitted + spc,
                     stage_checked(emitted, emitted + spc)))
                emitted += spc
                advanced = True
            if done:
                break
            if not advanced:
                with cv:
                    # Re-check under the lock: a completion or progress
                    # notify between our reads and this wait must not be
                    # lost to a full poll_interval of sleep.
                    if not assigner_done[0] and done_m == int(progress[0]):
                        cv.wait(poll_interval)
        worker.join()
        if worker_err:
            raise RuntimeError("schedule assignment failed") from worker_err[0]
        scatter_new(n)
        assert done_m == n  # join() synchronizes; every entry visible
        ratable_b = out_b[out_b >= 0]
        total_b = int(ratable_b.max()) + 1 if ratable_b.size else 0

        # Tail: remaining fillers overflow into extra all-filler batches
        # after the assigner's final batch (same rule as pack_schedule's
        # fallback).
        left = fillers.size - n_fill
        if left:
            free_rest = int(
                (slot_map[emitted * b : total_b * b] < 0).sum()
            ) if total_b > emitted else 0
            extra = max(0, -(-(left - free_rest) // b))
        else:
            extra = 0
        s_total = max(total_b + extra, emitted, 1)
        grow(s_total)
        while emitted < s_total:
            e1 = min(emitted + spc, s_total)
            put((emitted, e1, stage_checked(emitted, e1)))
            emitted = e1
        result["s_total"] = s_total

    # Consumer: dispatch committed slabs; with ``collect``, overlap each
    # chunk's D2H fetch with the next chunk's compute (one-chunk-deep
    # fetch pipelining, same protocol as rate_history).
    pending = None
    fused_flat = [] if (fuse is not None and collect) else None
    with Prefetcher(produce, depth=prefetch_depth or DEFAULT_DEPTH) as pf:
        for e0, e1, staged in pf:
            if run is not None:
                with tracer.span("batch.compute", cat="sched", start=e0):
                    run.dispatch_staged(staged)
            else:
                with tracer.span("batch.compute", cat="sched", start=e0):
                    if fuse is not None:
                        state, ys = _dispatch_fused_chunk(
                            state, staged, cfg, collect, fuse.backend,
                            tier=tier,
                        )
                        if fused_flat is not None:
                            fused_flat.append(staged.flat)
                    elif tier is not None:
                        state, ys = tier.dispatch_chunk(
                            state, staged, cfg, collect
                        )
                    else:
                        state, ys = _scan_chunk(
                            state, staged, cfg, collect, pad_row
                        )
                if collect:
                    try:
                        ys.copy_to_host_async()
                    except AttributeError:  # pragma: no cover — older jax
                        pass
                    if pending is not None:
                        with tracer.span("batch.fetch", cat="sched", start=e0):
                            outs.append(fetch_tree(pending))
                    pending = ys
                if view_publisher is not None:
                    if tier is not None:
                        tier.maybe_publish_view(view_publisher, state.table)
                    else:
                        view_publisher.maybe_publish_state(state)
            del staged  # let the consumed slab free behind the dispatch
            if on_chunk is not None:
                if run is not None:
                    run.call_hook(on_chunk, e1)
                else:
                    on_chunk(
                        tier.full_state(state.table) if tier is not None
                        else state, e1,
                    )
            maybe_sample_device_memory()  # batch-boundary HBM gauges
    if pending is not None:
        with tracer.span("batch.fetch", cat="sched", start=result["s_total"]):
            outs.append(fetch_tree(pending))

    s_total = result["s_total"]
    occupancy = n / (s_total * b)
    reg = get_registry()
    reg.gauge("sched.occupancy").set(round(occupancy, 4))
    reg.counter("sched.steps_total").add(s_total)
    if stats_out is not None:
        stats_out.update(
            n_steps=s_total, batch_size=b, occupancy=occupancy,
            choose_batch_size_s=t_choose,
        )
    if run is not None:
        state = run.finish()
        if view_publisher is not None:
            view_publisher.publish_state(state)
        return state, None
    if view_publisher is not None:
        if tier is not None:
            tier.publish_view(view_publisher, state.table)  # unthrottled
        else:
            view_publisher.publish_state(state)  # final table, unthrottled
    if tier is not None:
        state = tier.finish(state.table)
    if not collect:
        return state, None
    if fused_flat is not None:
        flat_idx = (
            np.concatenate(fused_flat).reshape(-1)
            if fused_flat else np.empty(0, np.int32)
        )
    else:
        flat_idx = slot_map[: s_total * b]
    return state, _gather_outputs(outs, flat_idx, n, team)
