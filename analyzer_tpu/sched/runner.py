"""The device-side history runner: ``lax.scan`` over packed supersteps.

Replaces the reference's per-match Python loop (``worker.py:191-192``) with
one compiled scan: each scan iteration gathers priors for a whole
conflict-free superstep, applies the closed-form TrueSkill updates, and
scatters posteriors back into the HBM-resident player table. The scan
carries only the PlayerState; per-match outputs are optionally collected and
scattered back into stream (chronological) order by ``match_idx``.

Large histories stream through in chunks of steps so the packed schedule
never has to fit in HBM at once (the reference's CHUNKSIZE/yield_per idea,
``worker.py:191``, at superstep granularity); the state buffer is donated
between chunks so XLA updates it in place.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core.state import MatchBatch, PlayerState
from analyzer_tpu.core.update import rate_and_apply
from analyzer_tpu.sched.superstep import PackedSchedule


@dataclasses.dataclass
class HistoryOutputs:
    """Per-match outputs in stream order (numpy, host-side).

    Mirrors what the reference persists per match/participant
    (``rater.py:140-169``): match quality, shared posterior snapshot +
    conservative-estimate delta, mode posterior, and the any_afk flag.
    Rows for matches that were not rated (AFK/unsupported) hold the gate
    outputs only; ``updated`` marks rows whose ratings were written.
    """

    quality: np.ndarray  # [N]
    shared_mu: np.ndarray  # [N, 2, T]
    shared_sigma: np.ndarray  # [N, 2, T]
    delta: np.ndarray  # [N, 2, T]
    mode_mu: np.ndarray  # [N, 2, T]
    mode_sigma: np.ndarray  # [N, 2, T]
    any_afk: np.ndarray  # [N]
    updated: np.ndarray  # [N]


@partial(jax.jit, static_argnames=("cfg", "collect"), donate_argnums=(0,))
def _scan_chunk(state: PlayerState, arrays, cfg: RatingConfig, collect: bool):
    """Scans rate_and_apply over a [S', B, ...] slab of supersteps."""

    def step(st, xs):
        pidx, mask, winner, mode, afk = xs
        batch = MatchBatch(
            player_idx=pidx, slot_mask=mask, winner=winner, mode_id=mode, afk=afk
        )
        st, out = rate_and_apply(st, batch, cfg)
        if not collect:
            return st, None
        # Drop the [B,2,T,16] state rows from the collected ys — they are
        # scatter plumbing, not a per-match output, and would dominate memory.
        return st, dataclasses.replace(out, new_rows=None)

    return jax.lax.scan(step, state, arrays)


def rate_history(
    state: PlayerState,
    sched: PackedSchedule,
    cfg: RatingConfig,
    collect: bool = False,
    steps_per_chunk: int = 8192,
) -> tuple[PlayerState, HistoryOutputs | None]:
    """Rates a full packed history. Returns the final state and, when
    ``collect``, per-match outputs reordered back to stream order."""
    n_steps = sched.n_steps
    # The chunked scan donates its carry; copy once at entry so the caller's
    # state stays valid (the table is small — tens of MB at 10M players).
    state = jax.tree.map(jnp.copy, state)
    outs = [] if collect else None
    for start in range(0, n_steps, steps_per_chunk):
        stop = min(start + steps_per_chunk, n_steps)
        arrays = sched.device_arrays(start, stop)
        state, ys = _scan_chunk(state, arrays, cfg, collect)
        if collect:
            outs.append(jax.tree.map(np.asarray, ys))
    if not collect:
        return state, None

    n = sched.n_matches
    flat_idx = sched.match_idx.reshape(-1)
    sel = flat_idx >= 0
    dest = flat_idx[sel]

    def gather(field):
        full = np.concatenate([getattr(y, field) for y in outs], axis=0)
        full = full.reshape((-1,) + full.shape[2:])  # [S*B, ...]
        out = np.zeros((n,) + full.shape[1:], dtype=full.dtype)
        out[dest] = full[sel]
        return out

    return state, HistoryOutputs(
        quality=gather("quality"),
        shared_mu=gather("shared_mu"),
        shared_sigma=gather("shared_sigma"),
        delta=gather("delta"),
        mode_mu=gather("mode_mu"),
        mode_sigma=gather("mode_sigma"),
        any_afk=gather("any_afk"),
        updated=gather("updated"),
    )
