"""The device-side history runner: ``lax.scan`` over packed supersteps.

Replaces the reference's per-match Python loop (``worker.py:191-192``) with
one compiled scan: each scan iteration gathers priors for a whole
conflict-free superstep, applies the closed-form TrueSkill updates, and
scatters posteriors back into the HBM-resident player table. The scan
carries only the PlayerState; per-match outputs are optionally collected and
scattered back into stream (chronological) order by ``match_idx``.

Large histories stream through in chunks of steps so the packed schedule
never has to fit in HBM at once (the reference's CHUNKSIZE/yield_per idea,
``worker.py:191``, at superstep granularity); the state buffer is donated
between chunks so XLA updates it in place.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core.state import MatchBatch, PlayerState
from analyzer_tpu.core.update import rate_and_apply
from analyzer_tpu.sched.superstep import PackedSchedule


@dataclasses.dataclass
class HistoryOutputs:
    """Per-match outputs in stream order (numpy, host-side).

    Mirrors what the reference persists per match/participant
    (``rater.py:140-169``): match quality, shared posterior snapshot +
    conservative-estimate delta, mode posterior, and the any_afk flag.
    Rows for matches that were not rated (AFK/unsupported) hold the gate
    outputs only; ``updated`` marks rows whose ratings were written.
    """

    quality: np.ndarray  # [N]
    shared_mu: np.ndarray  # [N, 2, T]
    shared_sigma: np.ndarray  # [N, 2, T]
    delta: np.ndarray  # [N, 2, T]
    mode_mu: np.ndarray  # [N, 2, T]
    mode_sigma: np.ndarray  # [N, 2, T]
    any_afk: np.ndarray  # [N]
    updated: np.ndarray  # [N]


@partial(jax.jit, static_argnames=("cfg", "collect"), donate_argnums=(0,))
def _scan_chunk(state: PlayerState, arrays, cfg: RatingConfig, collect: bool):
    """Scans rate_and_apply over a [S', B, ...] slab of supersteps."""

    def step(st, xs):
        pidx, mask, winner, mode, afk = xs
        batch = MatchBatch(
            player_idx=pidx, slot_mask=mask, winner=winner, mode_id=mode, afk=afk
        )
        st, out = rate_and_apply(st, batch, cfg)
        if not collect:
            return st, None
        # Drop the [B,2,T,16] state rows from the collected ys — they are
        # scatter plumbing, not a per-match output, and would dominate memory.
        return st, dataclasses.replace(out, new_rows=None)

    return jax.lax.scan(step, state, arrays)


def rate_history(
    state: PlayerState,
    sched: PackedSchedule,
    cfg: RatingConfig,
    collect: bool = False,
    steps_per_chunk: int | None = None,
    start_step: int = 0,
    stop_after: int | None = None,
    on_chunk=None,
) -> tuple[PlayerState, HistoryOutputs | None]:
    """Rates a packed history. Returns the final state and, when
    ``collect``, per-match outputs reordered back to stream order.

    ``start_step`` re-enters the scan mid-schedule (checkpoint resume;
    the caller is responsible for passing the state snapshot taken at that
    step). ``stop_after`` ends the run at a chunk boundary at or after that
    step (testing / bounded ops runs). ``on_chunk(state, next_step)`` fires
    after each chunk with the superstep index the next chunk would start
    at — the periodic-checkpoint hook (io/checkpoint.py); fetching the
    state there costs one device sync, the price of a bounded crash blast
    radius (the reference pays per 500-match commit, worker.py:194).
    """
    n_steps = sched.n_steps if stop_after is None else min(stop_after, sched.n_steps)
    if steps_per_chunk is None:
        # ~8 chunks pipelines window materialization + H2D against the
        # device scan (measured best on v5e: 1.14x device-only at 500k vs
        # 2.1x single-chunk); the floor keeps per-dispatch overhead
        # amortized, the ceiling bounds device memory for the slabs.
        steps_per_chunk = min(8192, max(256, -(-sched.n_steps // 8)))
    # The chunked scan donates its carry; copy once at entry so the caller's
    # state stays valid (the table is small — tens of MB at 10M players).
    state = jax.tree.map(jnp.copy, state)
    outs = [] if collect else None
    # Double-buffered feed: the [S',B,...] slab for chunk k+1 is put on
    # device while chunk k's scan runs. jax dispatch is async, so the only
    # host blocking in the loop is the staging copy of the NEXT slab —
    # which overlaps the device executing the CURRENT chunk.
    starts = list(range(start_step, n_steps, steps_per_chunk))
    arrays = (
        sched.device_arrays(starts[0], min(starts[0] + steps_per_chunk, n_steps))
        if starts
        else None
    )
    for i, start in enumerate(starts):
        state, ys = _scan_chunk(state, arrays, cfg, collect)  # async dispatch
        arrays = None  # let the consumed slab free as soon as the scan is done
        if i + 1 < len(starts):  # stage k+1's slab while k executes
            arrays = sched.device_arrays(
                starts[i + 1], min(starts[i + 1] + steps_per_chunk, n_steps)
            )
        if collect:
            outs.append(jax.tree.map(np.asarray, ys))
        if on_chunk is not None:
            on_chunk(state, min(start + steps_per_chunk, n_steps))
    if not collect:
        return state, None

    n = sched.n_matches
    flat_idx = sched.match_idx[start_step:n_steps].reshape(-1)
    sel = flat_idx >= 0
    dest = flat_idx[sel]
    # Zero-chunk run (start_step at/past the end): all-zero outputs, same
    # shapes as a real run — `updated` is all-False, nothing was rated.
    team = sched.host_window(0, 1)[0].shape[-1]
    empty_shapes = {
        "quality": (), "shared_mu": (2, team), "shared_sigma": (2, team),
        "delta": (2, team), "mode_mu": (2, team), "mode_sigma": (2, team),
        "any_afk": (), "updated": (),
    }
    empty_dtypes = {"any_afk": bool, "updated": bool}

    def gather(field):
        if not outs:
            return np.zeros(
                (n,) + empty_shapes[field],
                dtype=empty_dtypes.get(field, np.float32),
            )
        full = np.concatenate([getattr(y, field) for y in outs], axis=0)
        full = full.reshape((-1,) + full.shape[2:])  # [S*B, ...]
        out = np.zeros((n,) + full.shape[1:], dtype=full.dtype)
        out[dest] = full[sel]
        return out

    return state, HistoryOutputs(
        quality=gather("quality"),
        shared_mu=gather("shared_mu"),
        shared_sigma=gather("shared_sigma"),
        delta=gather("delta"),
        mode_mu=gather("mode_mu"),
        mode_sigma=gather("mode_sigma"),
        any_afk=gather("any_afk"),
        updated=gather("updated"),
    )
