// Native superstep assignment — the host-side hot loop of the scheduler.
//
// ASAP schedule over the chronological match stream (see superstep.py for
// the invariant): step(match) = 1 + max(last step of each of its players).
// The recurrence is inherently sequential (each match depends on the
// running per-player last-step table), so it cannot be vectorized in
// numpy; at 10M matches the Python fallback costs tens of seconds while
// this loop is memory-bound on the last-step table and runs in well under
// a second. Built on demand by _native.py (g++ -O3 -shared) and loaded via
// ctypes — no pybind11 dependency.
//
// Contract (mirrors _assign_supersteps_py):
//   idx       [n_matches, slots] int32 player rows, -1 for empty slots
//   ratable   [n_matches] uint8, 0 => step -1 (no state access)
//   out       [n_matches] int64 superstep index, -1 for non-ratable

#include <cstddef>
#include <cstdint>
#include <vector>

extern "C" {

void assign_supersteps(const int32_t* idx, int64_t n_matches,
                       int64_t slots, const uint8_t* ratable,
                       int64_t n_players, int64_t* out) {
  std::vector<int64_t> last(static_cast<size_t>(n_players > 0 ? n_players : 1),
                            -1);
  for (int64_t i = 0; i < n_matches; ++i) {
    if (!ratable[i]) {
      out[i] = -1;
      continue;
    }
    const int32_t* row = idx + i * slots;
    int64_t s = -1;
    for (int64_t j = 0; j < slots; ++j) {
      const int32_t p = row[j];
      if (p >= 0 && last[p] > s) s = last[p];
    }
    ++s;
    out[i] = s;
    for (int64_t j = 0; j < slots; ++j) {
      const int32_t p = row[j];
      if (p >= 0) last[p] = s;
    }
  }
}

}  // extern "C"
