// Native superstep assignment — the host-side hot loop of the scheduler.
//
// ASAP schedule over the chronological match stream (see superstep.py for
// the invariant): step(match) = 1 + max(last step of each of its players).
// The recurrence is inherently sequential (each match depends on the
// running per-player last-step table), so it cannot be vectorized in
// numpy; at 10M matches the Python fallback costs tens of seconds while
// this loop is memory-bound on the last-step table and runs in well under
// a second. Built on demand by _native.py (g++ -O3 -shared) and loaded via
// ctypes — no pybind11 dependency.
//
// Contract (mirrors _assign_supersteps_py):
//   idx       [n_matches, slots] int32 player rows, -1 for empty slots
//   ratable   [n_matches] uint8, 0 => step -1 (no state access)
//   out       [n_matches] int64 superstep index, -1 for non-ratable

#include <cstddef>
#include <cstdint>
#include <vector>

namespace {

// Restartable first-fit state: the exact fields migrate/assign.py's
// python recurrence carries between decode windows — the per-player
// frontier (last[p] = batch of p's most recent ratable match), the
// per-batch fill counts, the DSU "next batch with space" skip pointer,
// the high-water batch, and the stream cursor. Heap-owned behind a
// void* handle so a feed thread can keep the loop GIL-released across
// an arbitrary window decomposition (the migration engine never sees a
// complete stream; docs/migration.md "Native front half").
struct AssignFFState {
  int64_t capacity;
  int64_t n_assigned = 0;
  int64_t max_batch = -1;
  std::vector<int64_t> last;
  std::vector<int64_t> fill;
  std::vector<int64_t> next_free;

  AssignFFState(int64_t cap, int64_t n_hint)
      : capacity(cap),
        last(static_cast<size_t>(n_hint > 0 ? n_hint : 1024), -1) {}

  void ensure(int64_t b) {
    while (static_cast<int64_t>(fill.size()) <= b) {
      fill.push_back(0);
      next_free.push_back(static_cast<int64_t>(next_free.size()));
    }
  }
  int64_t find(int64_t b) {
    ensure(b);
    int64_t root = b;
    while (true) {
      ensure(root);
      if (next_free[root] == root) break;
      root = next_free[root];
    }
    while (next_free[b] != root) {  // path compression
      int64_t nb = next_free[b];
      next_free[b] = root;
      b = nb;
    }
    return root;
  }
  void grow_players(int64_t top) {
    // Geometric doubling, -1 filled — mirrors the python frontier's
    // _grow_players so the two sides stay field-for-field comparable.
    int64_t size = static_cast<int64_t>(last.size());
    while (size <= top) size *= 2;
    last.resize(static_cast<size_t>(size), -1);
  }
};

// Publish cadence of the windowed loop (matches) — pinned equal to
// migrate/assign.py's PROGRESS_EVERY so routing between the native and
// python assigners never changes the consumer-visible publish rhythm.
// Power of two: the check is one mask.
constexpr int64_t kFFProgressEvery = 2048;

}  // namespace

extern "C" {

void assign_supersteps(const int32_t* idx, int64_t n_matches,
                       int64_t slots, const uint8_t* ratable,
                       int64_t n_players, int64_t* out) {
  std::vector<int64_t> last(static_cast<size_t>(n_players > 0 ? n_players : 1),
                            -1);
  for (int64_t i = 0; i < n_matches; ++i) {
    if (!ratable[i]) {
      out[i] = -1;
      continue;
    }
    const int32_t* row = idx + i * slots;
    int64_t s = -1;
    for (int64_t j = 0; j < slots; ++j) {
      const int32_t p = row[j];
      if (p >= 0 && last[p] > s) s = last[p];
    }
    ++s;
    out[i] = s;
    for (int64_t j = 0; j < slots; ++j) {
      const int32_t p = row[j];
      if (p >= 0) last[p] = s;
    }
  }
}

// Capacity-aware first-fit batch assignment ("levelized" scheduling).
//
// ASAP minimizes *depth* but produces a heavy-tailed width histogram: a few
// wide steps and a long thin tail, so fixed-width batches run half empty
// (occupancy ~0.5 on realistic ladders). First-fit instead assigns each
// ratable match, in stream order, to the EARLIEST batch that (a) is
// strictly later than every one of its players' previous match's batch and
// (b) still has free capacity. Per-player chronology is preserved by (a);
// conflict-freedom within a batch follows because a player's matches get
// strictly increasing batch indices. A disjoint-set "next batch with
// space" pointer makes the whole pass O(n alpha(n)).
//
//   capacity  slots per batch (B)
//   out       [n_matches] int64 batch index, -1 for non-ratable matches
//   out_slot  [n_matches] int64 slot within the batch (fill order = stream
//             order within a batch), -1 for non-ratable — lets the packer
//             build the slot->match map with one scatter instead of a sort
//   progress  [2] int64, published periodically with release semantics:
//             progress[0] = matches processed so far, progress[1] = the
//             watermark (first batch that can still receive matches; every
//             batch below it is final). A consumer thread can materialize
//             and feed windows below the watermark while this loop is
//             still running (the GIL is released during the call).

void assign_batches_first_fit(const int32_t* idx, int64_t n_matches,
                              int64_t slots, const uint8_t* ratable,
                              int64_t n_players, int64_t capacity,
                              int64_t* out, int64_t* out_slot,
                              int64_t* progress) {
  std::vector<int64_t> last(static_cast<size_t>(n_players > 0 ? n_players : 1),
                            -1);
  std::vector<int64_t> fill;       // per-batch occupancy
  std::vector<int64_t> next_free;  // DSU skip pointer: first batch >= b with space

  auto ensure = [&](int64_t b) {
    while (static_cast<int64_t>(fill.size()) <= b) {
      fill.push_back(0);
      next_free.push_back(static_cast<int64_t>(next_free.size()));
    }
  };
  auto find = [&](int64_t b) {
    ensure(b);
    int64_t root = b;
    while (true) {
      ensure(root);
      if (next_free[root] == root) break;
      root = next_free[root];
    }
    while (next_free[b] != root) {  // path compression
      int64_t nb = next_free[b];
      next_free[b] = root;
      b = nb;
    }
    return root;
  };

  constexpr int64_t kPublishEvery = 16384;
  int64_t max_b = -1;  // highest batch actually assigned
  for (int64_t i = 0; i < n_matches; ++i) {
    if (!ratable[i]) {
      out[i] = -1;
      out_slot[i] = -1;
    } else {
      const int32_t* row = idx + i * slots;
      int64_t floor_b = 0;
      for (int64_t j = 0; j < slots; ++j) {
        const int32_t p = row[j];
        if (p >= 0 && last[p] + 1 > floor_b) floor_b = last[p] + 1;
      }
      const int64_t b = find(floor_b);
      out[i] = b;
      if (b > max_b) max_b = b;
      out_slot[i] = fill[b];
      if (++fill[b] == capacity) {
        ensure(b + 1);
        next_free[b] = b + 1;
      }
      for (int64_t j = 0; j < slots; ++j) {
        const int32_t p = row[j];
        if (p >= 0) last[p] = b;
      }
    }
    if (progress && (i + 1) % kPublishEvery == 0) {
      const int64_t wm = find(0);
      __atomic_store_n(&progress[1], wm, __ATOMIC_RELAXED);
      // Release: out/out_slot writes for [0, i] are visible before the
      // published progress count.
      __atomic_store_n(&progress[0], i + 1, __ATOMIC_RELEASE);
    }
  }
  if (progress) {
    // Final watermark = batches actually used, NOT fill.size(): filling a
    // batch to exactly capacity pre-creates an empty successor that no
    // match may ever land in.
    __atomic_store_n(&progress[1], max_b + 1, __ATOMIC_RELAXED);
    __atomic_store_n(&progress[0], n_matches, __ATOMIC_RELEASE);
  }
}

// Windowed, state-carrying first-fit — the migration engine's native
// front half (docs/migration.md "Native front half"). The one-shot loop
// above needs the whole stream; the streaming engine only ever has a
// prefix, so the recurrence's state lives behind a handle and each
// decode window feeds exactly its newly visible slice:
//
//   h = assign_ff_create(capacity, n_hint)   n_hint sizes the player
//                                            frontier (0 -> 1024)
//   assign_ff_feed(h, idx_window, slots, ratable_window, lo, hi,
//                  out_batch, out_slot, progress) -> consumed
//   assign_ff_finish(h, progress) -> batches used (idempotent)
//   assign_ff_destroy(h)
//
// idx_window/ratable_window are WINDOW-local ([hi-lo, slots] int32 /
// [hi-lo] uint8); lo/hi, out_batch/out_slot and the published progress
// counts are absolute stream positions, so the caller passes the same
// full-stream output buffers every call and a concurrent consumer reads
// entries below progress[0] exactly as it does under the one-shot loop.
// progress[0] is published with release semantics at absolute multiples
// of kFFProgressEvery and at the end of every window; progress[1] is
// written only by finish (batches used), matching the python
// incremental assigner's contract. feed returns hi - lo, or -1 on a
// contract violation (null handle, hi < lo, or a non-contiguous lo —
// the loader raises instead of corrupting state).
//
// DIVERGENCE from the one-shot loop, shared with migrate/assign.py:
// non-ratable matches are consumed INLINE as dependency-free capacity
// (first-fit from batch 0, frontier untouched) instead of being held
// for a backfill pass — holding them back needs the whole stream's
// filler population, which streaming forbids. Result-invariant: they
// read and write no rating state.

void* assign_ff_create(int64_t capacity, int64_t n_hint) {
  if (capacity < 1) return nullptr;
  return new AssignFFState(capacity, n_hint);
}

int64_t assign_ff_feed(void* handle, const int32_t* idx, int64_t slots,
                       const uint8_t* ratable, int64_t lo, int64_t hi,
                       int64_t* out_batch, int64_t* out_slot,
                       int64_t* progress) {
  AssignFFState* st = static_cast<AssignFFState*>(handle);
  if (st == nullptr || hi < lo || lo != st->n_assigned) return -1;
  const int64_t cap = st->capacity;
  for (int64_t i = lo; i < hi; ++i) {
    if (progress && i > lo && (i & (kFFProgressEvery - 1)) == 0) {
      // Release: out_batch/out_slot stores for [lo, i) are visible
      // before the published count — the streamed feed's sentinel
      // visibility protocol (sched/runner.rate_stream).
      __atomic_store_n(&progress[0], i, __ATOMIC_RELEASE);
    }
    const int32_t* row = idx + (i - lo) * slots;
    const bool rat = ratable[i - lo] != 0;
    int64_t floor_b = 0;
    if (rat) {
      for (int64_t j = 0; j < slots; ++j) {
        const int32_t p = row[j];
        if (p < 0) continue;
        if (p >= static_cast<int64_t>(st->last.size())) st->grow_players(p);
        if (st->last[p] + 1 > floor_b) floor_b = st->last[p] + 1;
      }
    }
    const int64_t b = st->find(floor_b);
    out_batch[i] = b;
    out_slot[i] = st->fill[b];
    if (++st->fill[b] == cap) {
      st->ensure(b + 1);
      st->next_free[b] = b + 1;
    }
    if (b > st->max_batch) st->max_batch = b;
    if (rat) {
      for (int64_t j = 0; j < slots; ++j) {
        const int32_t p = row[j];
        if (p >= 0) st->last[p] = b;
      }
    }
  }
  st->n_assigned = hi;
  if (progress) __atomic_store_n(&progress[0], hi, __ATOMIC_RELEASE);
  return hi - lo;
}

int64_t assign_ff_finish(void* handle, int64_t* progress) {
  AssignFFState* st = static_cast<AssignFFState*>(handle);
  if (st == nullptr) return -1;
  const int64_t used = st->max_batch + 1;
  if (progress) {
    __atomic_store_n(&progress[1], used, __ATOMIC_RELAXED);
    __atomic_store_n(&progress[0], st->n_assigned, __ATOMIC_RELEASE);
  }
  return used;
}

void assign_ff_destroy(void* handle) {
  delete static_cast<AssignFFState*>(handle);
}

}  // extern "C"
