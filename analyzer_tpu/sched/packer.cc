// Native superstep assignment — the host-side hot loop of the scheduler.
//
// ASAP schedule over the chronological match stream (see superstep.py for
// the invariant): step(match) = 1 + max(last step of each of its players).
// The recurrence is inherently sequential (each match depends on the
// running per-player last-step table), so it cannot be vectorized in
// numpy; at 10M matches the Python fallback costs tens of seconds while
// this loop is memory-bound on the last-step table and runs in well under
// a second. Built on demand by _native.py (g++ -O3 -shared) and loaded via
// ctypes — no pybind11 dependency.
//
// Contract (mirrors _assign_supersteps_py):
//   idx       [n_matches, slots] int32 player rows, -1 for empty slots
//   ratable   [n_matches] uint8, 0 => step -1 (no state access)
//   out       [n_matches] int64 superstep index, -1 for non-ratable

#include <cstddef>
#include <cstdint>
#include <vector>

extern "C" {

void assign_supersteps(const int32_t* idx, int64_t n_matches,
                       int64_t slots, const uint8_t* ratable,
                       int64_t n_players, int64_t* out) {
  std::vector<int64_t> last(static_cast<size_t>(n_players > 0 ? n_players : 1),
                            -1);
  for (int64_t i = 0; i < n_matches; ++i) {
    if (!ratable[i]) {
      out[i] = -1;
      continue;
    }
    const int32_t* row = idx + i * slots;
    int64_t s = -1;
    for (int64_t j = 0; j < slots; ++j) {
      const int32_t p = row[j];
      if (p >= 0 && last[p] > s) s = last[p];
    }
    ++s;
    out[i] = s;
    for (int64_t j = 0; j < slots; ++j) {
      const int32_t p = row[j];
      if (p >= 0) last[p] = s;
    }
  }
}

// Capacity-aware first-fit batch assignment ("levelized" scheduling).
//
// ASAP minimizes *depth* but produces a heavy-tailed width histogram: a few
// wide steps and a long thin tail, so fixed-width batches run half empty
// (occupancy ~0.5 on realistic ladders). First-fit instead assigns each
// ratable match, in stream order, to the EARLIEST batch that (a) is
// strictly later than every one of its players' previous match's batch and
// (b) still has free capacity. Per-player chronology is preserved by (a);
// conflict-freedom within a batch follows because a player's matches get
// strictly increasing batch indices. A disjoint-set "next batch with
// space" pointer makes the whole pass O(n alpha(n)).
//
//   capacity  slots per batch (B)
//   out       [n_matches] int64 batch index, -1 for non-ratable matches
//   out_slot  [n_matches] int64 slot within the batch (fill order = stream
//             order within a batch), -1 for non-ratable — lets the packer
//             build the slot->match map with one scatter instead of a sort
//   progress  [2] int64, published periodically with release semantics:
//             progress[0] = matches processed so far, progress[1] = the
//             watermark (first batch that can still receive matches; every
//             batch below it is final). A consumer thread can materialize
//             and feed windows below the watermark while this loop is
//             still running (the GIL is released during the call).

void assign_batches_first_fit(const int32_t* idx, int64_t n_matches,
                              int64_t slots, const uint8_t* ratable,
                              int64_t n_players, int64_t capacity,
                              int64_t* out, int64_t* out_slot,
                              int64_t* progress) {
  std::vector<int64_t> last(static_cast<size_t>(n_players > 0 ? n_players : 1),
                            -1);
  std::vector<int64_t> fill;       // per-batch occupancy
  std::vector<int64_t> next_free;  // DSU skip pointer: first batch >= b with space

  auto ensure = [&](int64_t b) {
    while (static_cast<int64_t>(fill.size()) <= b) {
      fill.push_back(0);
      next_free.push_back(static_cast<int64_t>(next_free.size()));
    }
  };
  auto find = [&](int64_t b) {
    ensure(b);
    int64_t root = b;
    while (true) {
      ensure(root);
      if (next_free[root] == root) break;
      root = next_free[root];
    }
    while (next_free[b] != root) {  // path compression
      int64_t nb = next_free[b];
      next_free[b] = root;
      b = nb;
    }
    return root;
  };

  constexpr int64_t kPublishEvery = 16384;
  int64_t max_b = -1;  // highest batch actually assigned
  for (int64_t i = 0; i < n_matches; ++i) {
    if (!ratable[i]) {
      out[i] = -1;
      out_slot[i] = -1;
    } else {
      const int32_t* row = idx + i * slots;
      int64_t floor_b = 0;
      for (int64_t j = 0; j < slots; ++j) {
        const int32_t p = row[j];
        if (p >= 0 && last[p] + 1 > floor_b) floor_b = last[p] + 1;
      }
      const int64_t b = find(floor_b);
      out[i] = b;
      if (b > max_b) max_b = b;
      out_slot[i] = fill[b];
      if (++fill[b] == capacity) {
        ensure(b + 1);
        next_free[b] = b + 1;
      }
      for (int64_t j = 0; j < slots; ++j) {
        const int32_t p = row[j];
        if (p >= 0) last[p] = b;
      }
    }
    if (progress && (i + 1) % kPublishEvery == 0) {
      const int64_t wm = find(0);
      __atomic_store_n(&progress[1], wm, __ATOMIC_RELAXED);
      // Release: out/out_slot writes for [0, i] are visible before the
      // published progress count.
      __atomic_store_n(&progress[0], i + 1, __ATOMIC_RELEASE);
    }
  }
  if (progress) {
    // Final watermark = batches actually used, NOT fill.size(): filling a
    // batch to exactly capacity pre-creates an empty successor that no
    // match may ever land in.
    __atomic_store_n(&progress[1], max_b + 1, __ATOMIC_RELAXED);
    __atomic_store_n(&progress[0], n_matches, __ATOMIC_RELEASE);
  }
}

}  // extern "C"
