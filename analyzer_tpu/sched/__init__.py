"""Chronology-respecting scheduling of the match stream onto the TPU.

The reference processes matches strictly in ``created_at`` order inside a
single-threaded loop (``worker.py:176,191-192``) because ratings are a
temporal recurrence: the posterior of match *t* is the prior of match *t+1*
for every shared player. Naively vmapping a batch of matches breaks that
(SURVEY.md section 7, hard part #1). This package turns the time-ordered
stream into **conflict-free supersteps** — maximal groups of matches with no
shared player, each safely rated as one batched kernel call — and drives a
``lax.scan`` over the packed steps.
"""

from analyzer_tpu.sched.superstep import (
    MatchStream,
    PackedSchedule,
    WindowedSchedule,
    assign_batches,
    assign_supersteps,
    choose_batch_size,
    choose_batch_size_streamed,
    pack_schedule,
)
from analyzer_tpu.sched.feed import DeviceFeed, FeedStageError, Prefetcher
from analyzer_tpu.sched.tier import TierManager
from analyzer_tpu.sched.residency import (
    FuseSpec,
    ResidencyPlan,
    check_plan,
    plan_windows,
    rate_window_checked,
    resolve_fuse,
)
from analyzer_tpu.sched.runner import HistoryOutputs, rate_history, rate_stream

__all__ = [
    "DeviceFeed",
    "FeedStageError",
    "FuseSpec",
    "TierManager",
    "MatchStream",
    "PackedSchedule",
    "Prefetcher",
    "ResidencyPlan",
    "WindowedSchedule",
    "assign_batches",
    "assign_supersteps",
    "check_plan",
    "choose_batch_size",
    "choose_batch_size_streamed",
    "pack_schedule",
    "plan_windows",
    "rate_window_checked",
    "resolve_fuse",
    "HistoryOutputs",
    "rate_history",
    "rate_stream",
]
