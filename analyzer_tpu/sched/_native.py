"""ctypes loader for the native superstep packer (packer.cc).

Compiled/loaded via the shared helper (``analyzer_tpu.native_build``),
exposing ``assign_supersteps``/``assign_batches_first_fit`` with the same
contract as the numpy fallbacks in superstep.py. Import fails -> the
caller falls back to pure Python; any numerical divergence is a bug
(tested equal in tests/test_sched.py).
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from analyzer_tpu.native_build import build_and_load

_DIR = os.path.dirname(os.path.abspath(__file__))
_lib = build_and_load(
    os.path.join(_DIR, "packer.cc"), os.path.join(_DIR, "_packer.so")
)
_lib.assign_supersteps.argtypes = [
    ctypes.POINTER(ctypes.c_int32),
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64),
]
_lib.assign_supersteps.restype = None
_lib.assign_batches_first_fit.argtypes = [
    ctypes.POINTER(ctypes.c_int32),
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_int64),
]
_lib.assign_batches_first_fit.restype = None


def _prep(stream):
    n = stream.n_matches
    idx = np.ascontiguousarray(
        stream.player_idx.reshape(n, 2 * stream.team_size), dtype=np.int32
    )
    ratable = np.ascontiguousarray(stream.ratable, dtype=np.uint8)
    n_players = int(idx.max()) + 1 if n else 1
    return n, idx, ratable, n_players


def assign_supersteps(stream) -> np.ndarray:
    n, idx, ratable, n_players = _prep(stream)
    out = np.empty(n, dtype=np.int64)
    if n == 0:
        return out
    _lib.assign_supersteps(
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n,
        idx.shape[1],
        ratable.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n_players,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


def assign_batches_first_fit(
    stream,
    capacity: int,
    progress: np.ndarray | None = None,
    out: np.ndarray | None = None,
    out_slot: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Returns (batch_id, slot_in_batch), each [N] int64, -1 for
    non-ratable. ``progress`` (optional [2] int64 array) is published
    periodically by the C loop — (matches processed, batch watermark) —
    and can be polled from another thread while this call runs (ctypes
    releases the GIL for the duration). ``out``/``out_slot`` let that
    consumer pre-allocate the result buffers and read entries below the
    published progress count while the loop is still filling the rest
    (the release store on ``progress[0]`` orders the writes)."""
    n, idx, ratable, n_players = _prep(stream)
    if out is None:
        out = np.empty(n, dtype=np.int64)
    if out_slot is None:
        out_slot = np.empty(n, dtype=np.int64)
    for name, buf in (("out", out), ("out_slot", out_slot)):
        # The C loop writes n int64 entries through the raw pointer — an
        # undersized/non-contiguous/wrong-dtype buffer would corrupt the
        # heap, so validate loudly.
        if (
            buf.dtype != np.int64
            or buf.size != n
            or not buf.flags["C_CONTIGUOUS"]
        ):
            raise ValueError(
                f"{name} must be a C-contiguous int64 array of size {n}, "
                f"got dtype={buf.dtype} size={buf.size} "
                f"contiguous={buf.flags['C_CONTIGUOUS']}"
            )
    if n == 0:
        if progress is not None:
            progress[:] = (0, 0)
        return out, out_slot
    prog_ptr = (
        progress.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        if progress is not None
        else ctypes.POINTER(ctypes.c_int64)()
    )
    _lib.assign_batches_first_fit(
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n,
        idx.shape[1],
        ratable.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n_players,
        capacity,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        out_slot.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        prog_ptr,
    )
    return out, out_slot
