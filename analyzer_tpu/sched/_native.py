"""ctypes loader for the native superstep packer (packer.cc).

Compiled/loaded via the shared helper (``analyzer_tpu.native_build``),
exposing ``assign_supersteps``/``assign_batches_first_fit`` with the same
contract as the numpy fallbacks in superstep.py, plus the windowed
restartable first-fit handle API (``assign_ff_create``/``feed``/
``finish``/``destroy``) that ``migrate/assign.py`` routes the streaming
front half through. Import fails -> the caller falls back to pure
Python; any numerical divergence is a bug (tested equal in
tests/test_sched.py, tests/test_migrate.py and tests/test_native_props.py).
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from analyzer_tpu.native_build import build_and_load

_DIR = os.path.dirname(os.path.abspath(__file__))
_lib = build_and_load(
    os.path.join(_DIR, "packer.cc"), os.path.join(_DIR, "_packer.so")
)
_lib.assign_supersteps.argtypes = [
    ctypes.POINTER(ctypes.c_int32),
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64),
]
_lib.assign_supersteps.restype = None
_lib.assign_batches_first_fit.argtypes = [
    ctypes.POINTER(ctypes.c_int32),
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_int64),
]
_lib.assign_batches_first_fit.restype = None
# Windowed, state-carrying first-fit (the migration engine's native
# front half — see packer.cc's contract comment; the handle is opaque).
_lib.assign_ff_create.argtypes = [ctypes.c_int64, ctypes.c_int64]
_lib.assign_ff_create.restype = ctypes.c_void_p
_lib.assign_ff_feed.argtypes = [
    ctypes.c_void_p,
    ctypes.POINTER(ctypes.c_int32),
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_int64),
]
_lib.assign_ff_feed.restype = ctypes.c_int64
_lib.assign_ff_finish.argtypes = [
    ctypes.c_void_p,
    ctypes.POINTER(ctypes.c_int64),
]
_lib.assign_ff_finish.restype = ctypes.c_int64
_lib.assign_ff_destroy.argtypes = [ctypes.c_void_p]
_lib.assign_ff_destroy.restype = None

_NULL_I64 = ctypes.POINTER(ctypes.c_int64)()


def _prep(stream):
    n = stream.n_matches
    idx = np.ascontiguousarray(
        stream.player_idx.reshape(n, 2 * stream.team_size), dtype=np.int32
    )
    ratable = np.ascontiguousarray(stream.ratable, dtype=np.uint8)
    n_players = int(idx.max()) + 1 if n else 1
    return n, idx, ratable, n_players


def assign_supersteps(stream) -> np.ndarray:
    n, idx, ratable, n_players = _prep(stream)
    out = np.empty(n, dtype=np.int64)
    if n == 0:
        return out
    _lib.assign_supersteps(
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n,
        idx.shape[1],
        ratable.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n_players,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


def assign_batches_first_fit(
    stream,
    capacity: int,
    progress: np.ndarray | None = None,
    out: np.ndarray | None = None,
    out_slot: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Returns (batch_id, slot_in_batch), each [N] int64, -1 for
    non-ratable. ``progress`` (optional [2] int64 array) is published
    periodically by the C loop — (matches processed, batch watermark) —
    and can be polled from another thread while this call runs (ctypes
    releases the GIL for the duration). ``out``/``out_slot`` let that
    consumer pre-allocate the result buffers and read entries below the
    published progress count while the loop is still filling the rest
    (the release store on ``progress[0]`` orders the writes)."""
    n, idx, ratable, n_players = _prep(stream)
    if out is None:
        out = np.empty(n, dtype=np.int64)
    if out_slot is None:
        out_slot = np.empty(n, dtype=np.int64)
    for name, buf in (("out", out), ("out_slot", out_slot)):
        # The C loop writes n int64 entries through the raw pointer — an
        # undersized/non-contiguous/wrong-dtype buffer would corrupt the
        # heap, so validate loudly.
        if (
            buf.dtype != np.int64
            or buf.size != n
            or not buf.flags["C_CONTIGUOUS"]
        ):
            raise ValueError(
                f"{name} must be a C-contiguous int64 array of size {n}, "
                f"got dtype={buf.dtype} size={buf.size} "
                f"contiguous={buf.flags['C_CONTIGUOUS']}"
            )
    if n == 0:
        if progress is not None:
            progress[:] = (0, 0)
        return out, out_slot
    prog_ptr = (
        progress.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        if progress is not None
        else ctypes.POINTER(ctypes.c_int64)()
    )
    _lib.assign_batches_first_fit(
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n,
        idx.shape[1],
        ratable.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n_players,
        capacity,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        out_slot.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        prog_ptr,
    )
    return out, out_slot


# -- windowed restartable first-fit (migrate/assign.py's native path) ------
def _check_i64_out(name: str, buf: np.ndarray, min_size: int) -> None:
    # The C loop writes int64 entries at absolute positions through the
    # raw pointer — an undersized/non-contiguous/wrong-dtype buffer
    # would corrupt the heap, so validate loudly (same contract as the
    # one-shot loop's buffer check above).
    if (
        buf.dtype != np.int64
        or buf.size < min_size
        or not buf.flags["C_CONTIGUOUS"]
    ):
        raise ValueError(
            f"{name} must be a C-contiguous int64 array of size >= "
            f"{min_size}, got dtype={buf.dtype} size={buf.size} "
            f"contiguous={buf.flags['C_CONTIGUOUS']}"
        )


def assign_ff_create(capacity: int, n_hint: int = 0) -> int:
    """Allocates a restartable first-fit state handle (packer.cc's
    ``AssignFFState``). ``n_hint`` pre-sizes the player frontier (0 ->
    1024; it grows geometrically either way). The handle MUST be
    released with :func:`assign_ff_destroy`."""
    if capacity < 1:
        raise ValueError(f"capacity must be >= 1, got {capacity}")
    handle = _lib.assign_ff_create(int(capacity), int(n_hint))
    if not handle:
        raise MemoryError("assign_ff_create returned NULL")
    return handle


def assign_ff_feed(
    handle: int,
    idx_window: np.ndarray,
    ratable_window: np.ndarray,
    lo: int,
    hi: int,
    out_batch: np.ndarray,
    out_slot: np.ndarray,
    progress: np.ndarray | None = None,
) -> int:
    """Consumes stream slice ``[lo, hi)``. ``idx_window`` is the
    WINDOW-local ``[hi-lo, slots]`` int32 player-row block and
    ``ratable_window`` the ``[hi-lo]`` uint8 gate; ``out_batch``/
    ``out_slot``/``progress`` carry ABSOLUTE stream positions (the
    caller passes its full-stream buffers every call). Runs with the
    GIL released; ``progress[0]`` is published with release semantics
    at the pinned cadence (packer.cc ``kFFProgressEvery`` ==
    ``migrate.assign.PROGRESS_EVERY``). Returns ``hi - lo``; raises on
    a contract violation instead of corrupting the native state."""
    n = hi - lo
    if n < 0:
        raise ValueError(f"feed window [{lo}, {hi}) is negative")
    idx = np.ascontiguousarray(idx_window, dtype=np.int32)
    if idx.ndim != 2 or idx.shape[0] != n:
        raise ValueError(
            f"idx_window must be [{n}, slots], got shape {idx.shape}"
        )
    rat = np.ascontiguousarray(ratable_window, dtype=np.uint8)
    if rat.shape != (n,):
        raise ValueError(
            f"ratable_window must be [{n}], got shape {rat.shape}"
        )
    _check_i64_out("out_batch", out_batch, hi)
    _check_i64_out("out_slot", out_slot, hi)
    if progress is not None:
        _check_i64_out("progress", progress, 2)
    if n == 0:
        return 0
    consumed = _lib.assign_ff_feed(
        handle,
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        idx.shape[1],
        rat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        lo,
        hi,
        out_batch.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        out_slot.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        progress.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        if progress is not None else _NULL_I64,
    )
    if consumed != n:
        raise ValueError(
            f"feed slices must be contiguous (native loop refused "
            f"window [{lo}, {hi}))"
        )
    return consumed


def assign_ff_finish(handle: int, progress: np.ndarray | None = None) -> int:
    """Publishes the final (n, batches-used) pair into ``progress``
    (when given) and returns batches used. Idempotent and state-free —
    callable mid-stream to read the current high-water batch count."""
    if progress is not None:
        _check_i64_out("progress", progress, 2)
    used = _lib.assign_ff_finish(
        handle,
        progress.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        if progress is not None else _NULL_I64,
    )
    if used < 0:
        raise ValueError("assign_ff_finish on a null handle")
    return used


def assign_ff_destroy(handle: int) -> None:
    """Frees the native state. Safe on a handle never finished; must be
    called exactly once per :func:`assign_ff_create`."""
    if handle:
        _lib.assign_ff_destroy(handle)
