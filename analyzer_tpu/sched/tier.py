"""Tiered ratings table: HBM-resident hot set with prefetch-ahead host
spill.

Until this module, the WHOLE ``[P+1, 16]`` player table had to live in
device memory for the scan runners to rate against it — player count per
chip was hard-capped by HBM, and every run paid device bytes for rows it
never touched. The tier manager turns HBM into a managed cache:

  * a **hot set** — a device-resident ``[H+1, 16]`` table of ``hot_rows``
    slots (pow2-bucketed like the slot ladder, row ``H`` the padding
    row) — is all the compiled kernels ever see;
  * a **cold tier** — the full ``[P+1, 16]`` table as host float32 (the
    authoritative copy for every non-resident row) — holds the rest;
  * an explicit **page table** (row -> hot slot) is maintained on the
    FEED thread: the same producer that materializes windows already
    names every window's touched rows, so promotion is planned exactly
    ``depth`` windows ahead and the cold-row H2D copies ride the
    existing prefetch ring, overlapping the in-flight scan;
  * **demotion** is LRU at window granularity: when a window needs slots,
    the least-recently-used resident rows not touched by it are evicted;
    rows the device wrote since promotion (**dirty**) are gathered off
    the hot table in one batched D2H per window, materialized into the
    cold tier one window later — the consumer never blocks on a miss in
    steady state.

Split of authority (the cross-thread contract):

  * the PRODUCER (feed thread) owns the page table, the LRU clock, the
    dirty bits, and ``host_version`` — it plans every promotion/demotion
    sequentially, so its model of future device state is exact, just
    ahead of time;
  * the CONSUMER (dispatch loop) owns the cold tier's WRITES (writeback
    materialization), the pending-writeback queue, and ``applied`` — the
    highest plan whose writebacks are guaranteed materialized;
  * the producer may stage a cold row's H2D eagerly ("fresh") only when
    ``host_version[row] <= applied`` — i.e. no writeback of that row is
    still in flight. Otherwise the promotion is DEFERRED: the consumer
    gathers it from the cold tier at dispatch time, after draining the
    queue. The GIL orders the consumer's host-table writes before its
    ``applied`` store and the producer's ``applied`` load before its
    host-table reads, so the fresh path never reads a stale row.

Bit-identity: tiering is a memory-PLACEMENT change, not a numeric one.
Remapped indices gather and scatter the same float32 values in the same
order through the same kernels (``hot_rows=0`` doesn't even construct a
manager — the untiered compiled paths are byte-for-byte untouched), so
the final table, the collected outputs, and every published view are
bit-identical to the untiered runner at every hot-set size, depth, and
kernel (pinned by tests/test_tier.py).

Telemetry (docs/observability.md catalog): ``tier.hits_total`` /
``misses_total`` / ``promotions_total`` / ``demotions_total`` /
``dirty_writebacks_total`` / ``spills_total`` counters, the
``tier.hot_rows`` and ``tier.host_bytes`` gauges (the latter sampled by
``obs.devicemem`` next to the HBM gauges so one /statusz scrape shows
both sides of the budget), and ``tier.promote`` / ``tier.demote`` spans
on the staging and writeback paths.
"""

from __future__ import annotations

import dataclasses
import threading
import weakref
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from analyzer_tpu.lint.ownership import thread_role
from analyzer_tpu.obs import get_registry, get_tracer, track_jit
from analyzer_tpu.obs.devicemem import set_host_tier_sampler

#: Pow2 bucket floor for the promotion/writeback row-count axis, so the
#: tier's gather/scatter kernels compile a short shape ladder instead of
#: one entry per miss count (the serve patch path's PATCH_BUCKET_FLOOR
#: idea applied to the write plane).
TIER_BUCKET_FLOOR = 64

#: Smallest hot-set capacity: below this the pow2 ladder floor dominates
#: and a single superstep rarely fits anyway.
MIN_HOT_ROWS = 8


def _pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


@partial(jax.jit, donate_argnums=(0,))
def _scatter_hot(table, idx, rows):
    """Writes promoted rows into their hot slots. Bucket-padding entries
    point at the hot padding slot and carry the pristine pad-row values,
    so the duplicate scatter resolves to identical bits and the pad row
    stays a fixed point. Donated: the hot table is the run's carry."""
    return table.at[idx].set(rows)


@jax.jit
def _gather_hot(table, idx):
    """Batched demotion read: the dirty rows' current values off the hot
    table (bucket-padding entries read the pad slot and are dropped)."""
    return table[idx]


track_jit("tier._scatter_hot", _scatter_hot)
track_jit("tier._gather_hot", _gather_hot)

#: Live managers for the devicemem host-bytes probe (obs/devicemem.py
#: samples the cold tier next to the HBM gauges).
_MANAGERS: "weakref.WeakSet[TierManager]" = weakref.WeakSet()
_SAMPLER_INSTALLED = False
_SAMPLER_LOCK = threading.Lock()


def _host_tier_bytes() -> int:
    return sum(m.host_nbytes for m in list(_MANAGERS))


@dataclasses.dataclass
class TierPlan:
    """One dispatch window's page-table transaction, planned on the feed
    thread and executed by the consumer before the window's compute.

    ``wb_*`` name the dirty evictions (batched D2H); ``fresh_*`` carry
    the eagerly staged promotions (the H2D already issued on the feed
    thread); ``deferred_*`` are promotions whose latest value is a
    not-yet-materialized writeback — the consumer fills them from the
    cold tier after draining the queue. ``evict_rows`` /
    ``promote_rows``+``promote_slots`` / ``written_rows`` replay the
    transaction into the consumer's own row->slot map (the publish /
    final-reconstruction view of residency)."""

    seq: int
    wb_idx: object | None  # jnp [nb] bucketed hot slots to gather
    wb_rows: np.ndarray  # [n_wb] cold-tier rows the gather lands in
    fresh_idx: object | None  # jnp [nb] bucketed destination slots
    fresh_rows: object | None  # jnp [nb, 16] staged promotion data
    deferred_rows: np.ndarray  # [n_def]
    deferred_slots: np.ndarray  # [n_def]
    evict_rows: np.ndarray  # all evicted rows (clean included)
    promote_rows: np.ndarray  # all promoted rows
    promote_slots: np.ndarray
    written_rows: np.ndarray  # rows this window's scatter commits


class TieredChunk:
    """One staged chunk of the reference-kernel tiered path: budget-split
    sub-windows, each a (plan, compact slab) pair dispatched in order."""

    __slots__ = ("parts",)

    def __init__(self, parts):
        self.parts = parts


class TierManager:
    """The hot-set/cold-tier state machine. One per tiered run; the feed
    thread calls the ``plan_*``/``stage_*`` half, the dispatch loop the
    ``apply``/``finish``/``publish`` half (see the module docstring for
    the cross-thread contract)."""

    def __init__(self, state, hot_rows: int) -> None:
        if hot_rows < 1:
            raise ValueError(f"hot_rows must be >= 1, got {hot_rows}")
        global _SAMPLER_INSTALLED
        self._template = state
        self.pad_row = state.pad_row
        self.n_players = state.pad_row
        # Entry-point fetch of the authoritative table: the cold tier
        # starts as the caller's full state. One sync at run start, the
        # tiered sibling of the untiered path's jnp.copy. The tier lives
        # in a page-aligned buffer from the process staging arena
        # (sched/feed.py PinnedArena — the same allocator as the ingest
        # decode slabs), so demotion D2H and promotion H2D run against
        # DMA-friendly pinned pages where the backend supports them;
        # values are copied in, so bit-identity is untouched.
        from analyzer_tpu.sched.feed import get_arena

        # graftlint: disable=GL025 — one intentional run-entry D2H fetch
        src = np.array(state.table, np.float32)
        self._host_table = get_arena().empty(src.shape, np.float32)
        self._host_table[...] = src
        del src
        self.capacity = _pow2(max(hot_rows, MIN_HOT_ROWS))
        self.hot_pad = self.capacity
        self._pad_vals = self._host_table[self.pad_row].copy()
        # -- producer-owned page table --
        self._slot_lut = np.full(self.pad_row + 1, -1, np.int32)
        self._slot_lut[self.pad_row] = self.hot_pad
        self._row_of = np.full(self.capacity, -1, np.int32)
        self._dirty = np.zeros(self.capacity, bool)
        self._last_use = np.zeros(self.capacity, np.int64)
        self._free = list(range(self.capacity - 1, -1, -1))  # slot 0 first
        self._host_version = np.full(self.pad_row + 1, -1, np.int64)
        self._seq = 0
        # -- consumer-owned --
        self._applied = -1
        self._pending: list = []  # (rows, n, device gather) FIFO
        self._c_slot_of = np.full(self.pad_row + 1, -1, np.int32)
        self._written_pub = np.zeros(self.pad_row + 1, bool)
        self._written_start = np.zeros(self.pad_row + 1, bool)
        reg = get_registry()
        self._hits = reg.counter("tier.hits_total")
        self._misses = reg.counter("tier.misses_total")
        self._promotions = reg.counter("tier.promotions_total")
        self._demotions = reg.counter("tier.demotions_total")
        self._writebacks = reg.counter("tier.dirty_writebacks_total")
        self._spills = reg.counter("tier.spills_total")
        reg.gauge("tier.hot_rows").set(self.capacity)
        reg.gauge("tier.host_bytes").set(self.host_nbytes)
        self._tracer = get_tracer()
        _MANAGERS.add(self)
        # Managers may be constructed from any thread (tests spin them
        # up concurrently); the install-once flag needs the lock even
        # though a duplicate install would be harmless.
        with _SAMPLER_LOCK:
            if not _SAMPLER_INSTALLED:
                set_host_tier_sampler(_host_tier_bytes)
                _SAMPLER_INSTALLED = True

    # -- sizing ----------------------------------------------------------
    @property
    def host_nbytes(self) -> int:
        """Cold-tier host bytes: the table plus the page-table arrays —
        what the obs/devicemem ``tier.host_bytes`` gauge reports."""
        return int(
            self._host_table.nbytes + self._slot_lut.nbytes
            + self._row_of.nbytes + self._last_use.nbytes
            + self._host_version.nbytes + self._c_slot_of.nbytes
        )

    def hot_state(self):
        """The device-resident hot PlayerState the compiled kernels run
        against: a ``[capacity+1, 16]`` table whose last row is the
        padding row (copied from the full table so masked gathers read
        identical bits); free slots hold zeros and are never gathered.
        Feature arrays are inert placeholders — the rating kernel never
        reads them (core/state.py docstring)."""
        hot = np.zeros((self.capacity + 1, self._host_table.shape[1]),
                       np.float32)
        hot[self.hot_pad] = self._pad_vals
        return dataclasses.replace(
            self._template,
            table=jnp.asarray(hot),
            rank_points_ranked=jnp.zeros(self.capacity + 1, jnp.float32),
            rank_points_blitz=jnp.zeros(self.capacity + 1, jnp.float32),
            skill_tier=jnp.zeros(self.capacity + 1, jnp.int32),
        )

    def clamp_fuse(self, fuse):
        """Caps the fused working-set budget at the hot capacity so every
        fused window's touched rows fit the hot set by construction (the
        residency planner's budget cut then doubles as the tier's
        forced-miss split)."""
        return dataclasses.replace(
            fuse, max_rows=min(fuse.max_rows, self.capacity)
        )

    # -- producer half (feed thread) -------------------------------------
    @thread_role("producer")
    def split_spans(self, player_idx: np.ndarray) -> list[tuple[int, int]]:
        """Cuts a chunk at step boundaries so each sub-window's distinct
        touched rows fit the hot capacity — the forced-miss/thrash path:
        a window bigger than the hot set still rates correctly, paying
        extra promotion traffic (counted as ``tier.spills_total``). The
        cut is exact, from first-touch prefix counts (the same math as
        the fused planner's VMEM budget cut)."""
        s_total = player_idx.shape[0]
        per_step = int(np.prod(player_idx.shape[1:]))
        spans: list[tuple[int, int]] = []
        s0 = 0
        while s0 < s_total:
            sub = player_idx[s0:]
            flat = np.concatenate(
                [np.full(1, self.pad_row, player_idx.dtype), sub.ravel()]
            )
            u, first = np.unique(flat, return_index=True)
            first_step = np.maximum(first - 1, 0) // per_step
            cum = np.cumsum(np.bincount(first_step, minlength=s_total - s0))
            # cum counts the padding row once (the virtual element), so
            # real rows in a prefix are cum - 1.
            fits = int(np.searchsorted(cum, self.capacity + 1, side="right"))
            if fits == 0:
                raise ValueError(
                    f"one superstep touches {int(cum[0]) - 1} distinct rows "
                    f"but the hot set holds {self.capacity}; raise hot_rows "
                    "or shrink the batch size"
                )
            spans.append((s0, s0 + fits))
            s0 += fits
        if len(spans) > 1:
            self._spills.add(len(spans) - 1)
        return spans

    @thread_role("producer")
    def plan_rows(self, touched: np.ndarray, written: np.ndarray) -> TierPlan:
        """The page-table transaction for one dispatch window: ``touched``
        (unique, pad-free) must all be resident when the window runs,
        ``written`` (unique, pad-free) become dirty. Returns the plan the
        consumer executes; the page table here is updated immediately —
        the producer's model runs ahead of the device by exactly the
        prefetch depth."""
        seq = self._seq
        if touched.size > self.capacity:
            raise ValueError(
                f"window touches {touched.size} rows but the hot set "
                f"holds {self.capacity} (split_spans missed a cut)"
            )
        slots = self._slot_lut[touched]
        miss_mask = slots < 0
        misses = touched[miss_mask]
        n_hit = int(touched.size - misses.size)
        if n_hit:
            self._hits.add(n_hit)
        evict_rows = np.empty(0, np.int32)
        wb_slots = np.empty(0, np.int32)
        wb_rows = np.empty(0, np.int32)
        assign = np.empty(0, np.int32)
        if misses.size:
            self._misses.add(int(misses.size))
            self._promotions.add(int(misses.size))
            take = min(len(self._free), misses.size)
            freed = [self._free.pop() for _ in range(take)]
            need = misses.size - take
            if need:
                # LRU among resident slots the window does not touch;
                # deterministic tie-break on the slot id.
                lu = np.where(
                    self._row_of >= 0, self._last_use, np.iinfo(np.int64).max
                )
                lu[slots[~miss_mask]] = np.iinfo(np.int64).max
                order = np.lexsort((np.arange(self.capacity), lu))
                ev = order[:need].astype(np.int32)
                evict_rows = self._row_of[ev].copy()
                ev_dirty = self._dirty[ev]
                wb_slots = ev[ev_dirty]
                wb_rows = evict_rows[ev_dirty]
                self._demotions.add(int(ev.size))
                if wb_rows.size:
                    self._writebacks.add(int(wb_rows.size))
                    self._host_version[wb_rows] = seq
                self._slot_lut[evict_rows] = -1
                self._row_of[ev] = -1
                self._dirty[ev] = False
                assign = np.concatenate(
                    [np.fromiter(freed, np.int32, count=take), ev]
                )
            else:
                assign = np.fromiter(freed, np.int32, count=take)
            self._slot_lut[misses] = assign
            self._row_of[assign] = misses
        # Fresh vs deferred: a row whose last dirty demotion the consumer
        # has already materialized (host_version <= applied, read ONCE)
        # can be staged eagerly from the cold tier on this thread.
        applied = self._applied
        fresh_idx = fresh_rows = None
        deferred_rows = np.empty(0, np.int32)
        deferred_slots = np.empty(0, np.int32)
        if misses.size:
            fresh_mask = self._host_version[misses] <= applied
            f_rows = misses[fresh_mask]
            f_slots = assign[fresh_mask]
            deferred_rows = misses[~fresh_mask]
            deferred_slots = assign[~fresh_mask]
            if f_rows.size:
                with self._tracer.span("tier.promote", cat="tier", seq=seq):
                    nb = _pow2(max(int(f_rows.size), TIER_BUCKET_FLOOR))
                    idx = np.full(nb, self.hot_pad, np.int32)
                    idx[: f_rows.size] = f_slots
                    data = np.broadcast_to(
                        self._pad_vals, (nb, self._pad_vals.size)
                    ).copy()
                    data[: f_rows.size] = self._host_table[f_rows]
                    fresh_idx = jnp.asarray(idx)
                    fresh_rows = jnp.asarray(data)  # async H2D, rides ahead
        self._last_use[self._slot_lut[touched]] = seq
        if written.size:
            self._dirty[self._slot_lut[written]] = True
        wb_idx = None
        if wb_slots.size:
            nb = _pow2(max(int(wb_slots.size), TIER_BUCKET_FLOOR))
            idx = np.full(nb, self.hot_pad, np.int32)
            idx[: wb_slots.size] = wb_slots
            wb_idx = jnp.asarray(idx)
        self._seq = seq + 1
        return TierPlan(
            seq=seq,
            wb_idx=wb_idx,
            wb_rows=wb_rows,
            fresh_idx=fresh_idx,
            fresh_rows=fresh_rows,
            deferred_rows=deferred_rows,
            deferred_slots=deferred_slots,
            evict_rows=evict_rows,
            promote_rows=misses,
            promote_slots=assign,
            written_rows=written,
        )

    @thread_role("producer")
    def plan_window(self, player_idx: np.ndarray, valid: np.ndarray):
        """Reference-kernel staging of one (already budget-split)
        sub-window: plans residency for its touched rows and remaps the
        gather indices into hot-slot space. ``valid`` is the written-slot
        mask (``slot_mask & ratable``) — exactly the rows the device
        scatter commits, which is what dirtiness means."""
        touched = np.unique(player_idx)
        if touched.size and touched[-1] == self.pad_row:
            touched = touched[:-1]
        written = np.unique(player_idx[valid])
        plan = self.plan_rows(
            touched.astype(np.int32), written.astype(np.int32)
        )
        hot_pidx = self._slot_lut[player_idx]
        return plan, hot_pidx

    @thread_role("producer")
    def plan_fused(self, slot_rows: np.ndarray, n_live: int,
                   player_idx: np.ndarray, valid: np.ndarray):
        """Fused-kernel staging of one residency window: the fused plan
        already names the touched rows (``slot_rows[1:n_live]`` — slot 0
        is the padding row), so the tier plan reuses them and the remap
        is a single take over ``slot_rows`` (bucket-padding entries map
        to the hot padding slot). The fused working set then reads
        through the hot set — composition is exactly this remap."""
        touched = np.sort(slot_rows[1:n_live]).astype(np.int32)
        written = np.unique(player_idx[valid]).astype(np.int32)
        plan = self.plan_rows(touched, written)
        return plan, self._slot_lut[slot_rows]

    @thread_role("producer")
    def stage_windows(self, player_idx, winner, mode_id, afk) -> TieredChunk:
        """Producer-side staging of one reference-kernel chunk: budget
        splits, per-sub-window residency plans, index remap, and the
        async H2D commit of each remapped compact slab."""
        from analyzer_tpu.sched.superstep import compact_device_window

        ratable = (mode_id >= 0) & ~afk
        parts = []
        for s0, s1 in self.split_spans(player_idx):
            sub = player_idx[s0:s1]
            valid = (sub != self.pad_row) & ratable[s0:s1][:, :, None, None]
            plan, hot_pidx = self.plan_window(sub, valid)
            slab = compact_device_window(
                hot_pidx, winner[s0:s1], mode_id[s0:s1], afk[s0:s1]
            )
            parts.append((plan, slab))
        return TieredChunk(parts)

    # -- consumer half (dispatch loop) ------------------------------------
    @thread_role("consumer")
    def _drain(self) -> None:
        """Materializes every queued writeback into the cold tier. The
        queued gathers have had at least one window of device time to
        complete, so this is a cheap host copy in steady state."""
        while self._pending:
            rows, n, dev = self._pending.pop(0)
            # graftlint: disable=GL025 — intentional batched writeback
            host = np.asarray(dev)
            self._host_table[rows] = host[:n]

    @thread_role("consumer")
    def apply(self, table, plan: TierPlan):
        """Executes one plan against the hot table, in the only order
        that is correct: drain earlier writebacks (the cold tier becomes
        current through ``plan.seq - 1``), gather THIS plan's dirty
        evictions off the table (before their slots are overwritten),
        then scatter the promotions in. Returns the new hot table; the
        caller dispatches the window's compute against it."""
        self._drain()
        self._applied = plan.seq - 1  # GIL orders the host writes first
        if plan.wb_rows.size:
            with self._tracer.span("tier.demote", cat="tier", seq=plan.seq):
                dev = _gather_hot(table, plan.wb_idx)
                try:
                    dev.copy_to_host_async()
                except AttributeError:  # pragma: no cover — older jax
                    pass
                self._pending.append(
                    (plan.wb_rows, int(plan.wb_rows.size), dev)
                )
        if plan.fresh_idx is not None:
            table = _scatter_hot(table, plan.fresh_idx, plan.fresh_rows)
        if plan.deferred_rows.size:
            # The miss path: the row's latest value was still in flight
            # at plan time. The drain above made the cold tier current,
            # so this gather-H2D is correct — just not overlapped.
            with self._tracer.span("tier.promote", cat="tier",
                                   seq=plan.seq, deferred=True):
                nb = _pow2(max(int(plan.deferred_rows.size),
                               TIER_BUCKET_FLOOR))
                idx = np.full(nb, self.hot_pad, np.int32)
                idx[: plan.deferred_slots.size] = plan.deferred_slots
                data = np.broadcast_to(
                    self._pad_vals, (nb, self._pad_vals.size)
                ).copy()
                data[: plan.deferred_rows.size] = (
                    self._host_table[plan.deferred_rows]
                )
                table = _scatter_hot(
                    table, jnp.asarray(idx), jnp.asarray(data)
                )
        # Replay the transaction into the consumer's own residency view
        # (the publish / final-reconstruction side never reads producer
        # state, which runs ahead of the device).
        if plan.evict_rows.size:
            self._c_slot_of[plan.evict_rows] = -1
        if plan.promote_rows.size:
            self._c_slot_of[plan.promote_rows] = plan.promote_slots
        if plan.written_rows.size:
            self._written_pub[plan.written_rows] = True
            self._written_start[plan.written_rows] = True
        return table

    @thread_role("consumer")
    def dispatch_chunk(self, state, staged: TieredChunk, cfg, collect):
        """Consumer-side dispatch of one reference-kernel tiered chunk:
        apply each sub-window's plan, scan it, concatenate the collected
        outputs (one fetchable tensor per chunk, like the fused path)."""
        from analyzer_tpu.sched.runner import _scan_chunk

        ys_parts = []
        for plan, slab in staged.parts:
            table = self.apply(state.table, plan)
            state = dataclasses.replace(state, table=table)
            state, ys = _scan_chunk(state, slab, cfg, collect, self.hot_pad)
            if collect:
                ys_parts.append(ys)
        if not collect:
            return state, None
        return state, (
            ys_parts[0] if len(ys_parts) == 1 else jnp.concatenate(ys_parts)
        )

    @thread_role("consumer")
    def _fetch_resident(self, table, rows: np.ndarray) -> np.ndarray:
        """Current values of resident ``rows`` off the hot table (one
        bucketed gather + D2H)."""
        nb = _pow2(max(int(rows.size), TIER_BUCKET_FLOOR))
        idx = np.full(nb, self.hot_pad, np.int32)
        idx[: rows.size] = self._c_slot_of[rows]
        # graftlint: disable=GL025 — snapshot/publish boundary sync
        return np.asarray(_gather_hot(table, jnp.asarray(idx)))[: rows.size]

    @thread_role("consumer")
    def full_table(self, table) -> np.ndarray:
        """The logical full ``[P+1, 16]`` table as of the last dispatched
        window: the cold tier (drained) plus the current values of every
        resident row written since run start. Used for the final state,
        checkpoint hooks, and full view rebuilds."""
        self._drain()
        full = self._host_table.copy()
        changed = np.flatnonzero(self._written_start)
        resident = changed[self._c_slot_of[changed] >= 0]
        if resident.size:
            full[resident] = self._fetch_resident(table, resident)
        return full

    @thread_role("consumer")
    def full_state(self, table):
        """A PlayerState view of :meth:`full_table` (checkpoint hooks —
        same one-sync-per-snapshot cost profile as the untiered hook)."""
        return dataclasses.replace(
            self._template, table=jnp.asarray(self.full_table(table))
        )

    @thread_role("consumer")
    def finish(self, table):
        """Final state of a tiered run: drain, reconstruct, and return a
        PlayerState bit-identical to the untiered runner's."""
        return self.full_state(table)

    # -- serve-view publish ------------------------------------------------
    @thread_role("consumer")
    def publish_view(self, publisher, table, force: bool = True):
        """Publishes the logical table through ``publisher`` from the hot
        set: rows written since the last publish come from the hot table
        (resident) or the drained cold tier (demoted), and ride the
        incremental ``.at[rows].set`` patch path; everything else is the
        host-side shadow the previous view already serves. Views stay
        snapshot-consistent and bit-identical to untiered publishes."""
        if not force and not publisher.due():
            return None
        self._drain()
        changed = np.flatnonzero(self._written_pub)
        vals = self._host_table[changed].copy()
        res_mask = self._c_slot_of[changed] >= 0
        if res_mask.any():
            vals[res_mask] = self._fetch_resident(table, changed[res_mask])
        view = publisher.publish_state_patch(
            changed, vals, self.n_players,
            full_table=lambda: self.full_table(table),
        )
        self._written_pub[:] = False
        return view

    @thread_role("consumer")
    def maybe_publish_view(self, publisher, table):
        """Throttled :meth:`publish_view` — the chunk-boundary hook."""
        return self.publish_view(publisher, table, force=False)


def stage_chunk_tiered(sched, start: int, stop: int, tier: TierManager,
                       collect: bool) -> TieredChunk:
    """Tiered sibling of ``feed.stage_chunk``: materializes the window
    (``feed.materialize`` span), then splits/plans/remaps/commits it
    through the tier manager (promotion H2D inside ``tier.promote``
    spans). ``collect`` needs no extra staging — the collected-output
    layout is row-id-free and the chunk's slot->match map is unchanged
    by the split (sub-windows are prefixes in order)."""
    check = getattr(sched, "check_compact_invariant", None)
    if check is not None:
        check(start, stop)
    tracer = get_tracer()
    with tracer.span("feed.materialize", cat="sched", start=start):
        pidx, _mask, winner, mode_id, afk = sched.host_window(start, stop)
    with tracer.span("feed.transfer", cat="sched", start=start):
        return tier.stage_windows(pidx, winner, mode_id, afk)
