"""Per-window residency planning for the fused rating kernel.

The fused window kernel (:mod:`analyzer_tpu.core.fused`) keeps every
player row a window of supersteps touches resident in a working set —
gathered from the HBM table once, written back once. The device side
needs three things the schedule doesn't directly carry: which rows to
gather (``slot_rows``), the per-step batches re-addressed in working-set
slots (``slot_idx``), and a guarantee the working set fits the VMEM
budget. All three are host-side facts the scheduler already knows — the
assigner names every window's touched rows — so the plan is computed
here, on the feed thread, alongside window materialization, and shipped
with the slab (:func:`analyzer_tpu.sched.feed.stage_fused_windows`).

Plan construction per window:

  * slots are assigned in FIRST-TOUCH order (deterministic, so the whole
    emitted schedule stays a pure function of the stream) with slot 0
    unconditionally the padding row — the kernel derives the slot mask
    as ``slot_idx != 0`` and routes every no-write to slot 0;
  * ``first_use``/``last_use`` record each slot's live range within the
    window (introspection + the overflow split below; the kernel itself
    holds every slot for the whole window — eviction granularity is the
    window boundary);
  * the slot count is bucketed to the next power of two so consecutive
    windows reuse one compiled kernel shape (unused slots point at the
    padding row; they gather and write back the pristine pad row, which
    duplicate-scatter-resolves deterministically because every copy is
    bit-identical).

VMEM budget / spill policy: when a window's working set would exceed
``max_rows``, the window is CUT at the last step that still fits and the
remainder becomes its own window(s) — a bulk spill at the cut, the whole
working set written back and the next window re-gathering what it needs.
The cut is exact, not iterative: with first-touch steps in hand, the
working-set size of any prefix is the count of rows first touched at or
before it. Cuts are counted (``fused.spills_total``) and shorter windows
are padded back to the static window size with inert steps
(``fused.pad_steps_total`` — the padding tax of a spill). Finer-grained
eviction (per-slot LRU writeback mid-window) would need per-step
variable writebacks inside the kernel; the window cut gets the same
correctness at static shapes, and docs/kernels.md records the budget
math that makes cuts rare at production batch sizes.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

from analyzer_tpu.core import constants
from analyzer_tpu.obs import get_registry as _obs_registry

#: Default fused window: supersteps per dispatch. 16 amortizes the
#: window gather/writeback over enough steps that recurring rows pay the
#: scatter floor once, while keeping the working set (<= K * B * 2T new
#: rows, far fewer with reuse) inside the slot budget at B=512.
DEFAULT_WINDOW = 16

#: Default working-set budget in table rows, rounded up to a power of
#: two. 32768 rows x 64 B = 2 MiB — the VMEM budget math in
#: docs/kernels.md: working set + its HBM staging copy + the K-step slab
#: must fit ~16 MiB/core with double-buffering headroom.
DEFAULT_MAX_ROWS = 32768

#: Env override for the fused backend ("scan" | "pallas" | "interpret");
#: the CLI/bench only expose kernel + window, so a TPU run can opt into
#: the Pallas body without a code change.
BACKEND_ENV = "ANALYZER_TPU_FUSE_BACKEND"


@dataclasses.dataclass(frozen=True)
class FuseSpec:
    """Resolved fused-kernel parameters, threaded through the runners."""

    window: int = DEFAULT_WINDOW
    max_rows: int = DEFAULT_MAX_ROWS
    backend: str = "scan"


def resolve_fuse(
    kernel: str,
    fuse_window: int | None = None,
    fuse_max_rows: int | None = None,
    fuse_backend: str | None = None,
) -> FuseSpec | None:
    """``kernel`` ("reference" | "fused") + optional overrides -> a
    :class:`FuseSpec`, or None for the reference path. The backend
    defaults from ``ANALYZER_TPU_FUSE_BACKEND``, then "scan"."""
    if kernel == "reference":
        return None
    if kernel != "fused":
        raise ValueError(
            f"unknown kernel {kernel!r}; use 'reference' or 'fused'"
        )
    backend = fuse_backend or os.environ.get(BACKEND_ENV) or "scan"
    window = DEFAULT_WINDOW if fuse_window is None else fuse_window
    if window < 1:
        raise ValueError(f"fuse window must be >= 1, got {window}")
    max_rows = _pow2(
        DEFAULT_MAX_ROWS if fuse_max_rows is None else fuse_max_rows
    )
    return FuseSpec(window=window, max_rows=max_rows, backend=backend)


def _pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


@dataclasses.dataclass
class ResidencyPlan:
    """One fused window's row -> VMEM-slot map.

    slot_rows [n_slots] int32: player row per slot; slot 0 is the padding
      row, unused bucket-padding slots also hold the padding row.
    slot_idx  [n_steps, B, 2, T] int32: the window's batches re-addressed
      in slots (REAL steps only; the stage pads to the static window).
    first_use/last_use [n_live] int32: per-live-slot live range (step
      indices within the window).
    n_live: live slots including slot 0; the working-set size the VMEM
      budget constrains.
    writebacks_avoided: per-step scatter row-instances the fusion
      eliminated (valid written slots minus unique written rows).
    spilled: True when the VMEM budget cut this window short of the
      requested window size.
    """

    slot_rows: np.ndarray
    slot_idx: np.ndarray
    first_use: np.ndarray
    last_use: np.ndarray
    n_live: int
    writebacks_avoided: int
    spilled: bool

    @property
    def n_steps(self) -> int:
        return self.slot_idx.shape[0]

    @property
    def n_slots(self) -> int:
        return self.slot_rows.size


def plan_windows(
    player_idx: np.ndarray,
    valid: np.ndarray,
    pad_row: int,
    window: int,
    max_rows: int,
) -> list[ResidencyPlan]:
    """Splits a chunk's ``[S, B, 2, T]`` gather window into fused windows
    of at most ``window`` supersteps whose working set fits ``max_rows``
    slots. ``valid`` is the written-slot mask (``slot_mask & ratable``),
    used for the writebacks-avoided accounting only — residency itself
    covers EVERY touched row (non-ratable matches still gather).

    Deterministic and exact: the prefix working-set size is derived from
    first-touch steps, so each cut lands on the last step that fits."""
    if max_rows != _pow2(max_rows):
        raise ValueError(f"max_rows must be a power of two, got {max_rows}")
    s_total = player_idx.shape[0]
    per_step = int(np.prod(player_idx.shape[1:]))
    plans: list[ResidencyPlan] = []
    s0 = 0
    while s0 < s_total:
        s1 = min(s0 + window, s_total)
        sub = player_idx[s0:s1]
        # Working-set size of every prefix from first-touch steps: a row
        # first touched at step f is resident in any prefix reaching f.
        flat = np.concatenate(
            [np.full(1, pad_row, player_idx.dtype), sub.ravel()]
        )
        u, first = np.unique(flat, return_index=True)
        first_step = np.maximum(first - 1, 0) // per_step
        cum = np.cumsum(np.bincount(first_step, minlength=s1 - s0))
        fits = int(np.searchsorted(cum, max_rows, side="right"))
        if fits == 0:
            raise ValueError(
                f"one superstep touches {int(cum[0])} rows but the fused "
                f"working-set budget is {max_rows}; raise fuse_max_rows "
                "or shrink the batch size"
            )
        spilled = fits < (s1 - s0)
        if spilled:
            s1 = s0 + fits
            sub = player_idx[s0:s1]
        plans.append(
            _build_plan(sub, valid[s0:s1], pad_row, spilled)
        )
        s0 = s1
    return plans


def _build_plan(
    sub: np.ndarray, valid: np.ndarray, pad_row: int, spilled: bool
) -> ResidencyPlan:
    per_step = int(np.prod(sub.shape[1:]))
    flat = np.concatenate([np.full(1, pad_row, sub.dtype), sub.ravel()])
    u, first, inv = np.unique(flat, return_index=True, return_inverse=True)
    order = np.argsort(first, kind="stable")  # first-touch order
    rank = np.empty(u.size, np.int64)
    rank[order] = np.arange(u.size)
    slots_all = rank[inv]
    # The virtual element at flat[0] makes the padding row's first touch
    # position 0 unconditionally -> slot 0 (core.fused.PAD_SLOT).
    slot_idx = slots_all[1:].reshape(sub.shape).astype(np.int32)
    n_live = int(u.size)
    n_slots = _pow2(max(n_live, 8))
    slot_rows = np.full(n_slots, pad_row, np.int32)
    slot_rows[rank] = u
    first_use = np.empty(n_live, np.int32)
    first_use[rank] = (np.maximum(first - 1, 0) // per_step).astype(np.int32)
    last_pos = np.zeros(n_live, np.int64)
    np.maximum.at(last_pos, slots_all[1:], np.arange(sub.size))
    last_use = (last_pos // per_step).astype(np.int32)
    written = sub[valid]
    writebacks_avoided = int(written.size - np.unique(written).size)
    return ResidencyPlan(
        slot_rows=slot_rows,
        slot_idx=slot_idx,
        first_use=first_use,
        last_use=last_use,
        n_live=n_live,
        writebacks_avoided=writebacks_avoided,
        spilled=spilled,
    )


def record_plan_telemetry(plans: list[ResidencyPlan], window: int) -> None:
    """The fused feed's observables (docs/observability.md catalog):
    windows staged, budget spills, scatter rows avoided, inert padding
    steps, and the working-set high-water mark."""
    reg = _obs_registry()
    reg.counter("fused.windows_total").add(len(plans))
    spills = sum(1 for p in plans if p.spilled)
    if spills:
        reg.counter("fused.spills_total").add(spills)
    avoided = sum(p.writebacks_avoided for p in plans)
    if avoided:
        reg.counter("fused.writebacks_avoided_total").add(avoided)
    pad_steps = sum(window - p.n_steps for p in plans)
    if pad_steps:
        reg.counter("fused.pad_steps_total").add(pad_steps)
    gauge = reg.gauge("fused.working_set_rows")
    hi = max((p.n_live for p in plans), default=0)
    if hi > gauge.value:
        gauge.set(hi)


def check_plan(
    plan: ResidencyPlan, player_idx: np.ndarray, pad_row: int
) -> None:
    """Validates an UNTRUSTED residency plan against its window.

    The planner holds these by construction; a hand-built or corrupted
    plan that aliases two live rows to one VMEM slot would make the fused
    chain silently rate one player with another's posterior — the fused
    sibling of the scatter-collision race ``check_conflict_free`` guards
    (SURVEY.md section 5.2). Raises ValueError with the offending slots.
    """
    live = plan.slot_rows[: plan.n_live]
    uniq, counts = np.unique(live, return_counts=True)
    dup = uniq[counts > 1]
    if dup.size:
        raise ValueError(
            f"residency plan aliases player rows {dup[:16].tolist()} onto "
            "shared VMEM slots: two live rows per slot means the fused "
            "chain rates one player with another's posterior"
        )
    if plan.slot_rows[0] != pad_row:
        raise ValueError(
            f"residency plan slot 0 holds row {int(plan.slot_rows[0])}, "
            f"not the padding row {pad_row}; the kernel routes every "
            "masked write to slot 0 and would corrupt that player"
        )
    n_steps = plan.n_steps
    if player_idx.shape[0] < n_steps:
        raise ValueError(
            f"residency plan covers {n_steps} steps but the window has "
            f"{player_idx.shape[0]}"
        )
    recon = plan.slot_rows[plan.slot_idx]
    # graftlint: disable=GL025 — untrusted-entry validation syncs on purpose
    want = np.asarray(player_idx[:n_steps])
    if not np.array_equal(recon, want):
        bad = np.argwhere(recon != want)[:4]
        raise ValueError(
            "residency plan slot map disagrees with the window's player "
            f"rows at (step, slot) {bad.tolist()}; the fused gather would "
            "read the wrong players"
        )


def rate_window_checked(
    state,
    player_idx: np.ndarray,
    winner: np.ndarray,
    mode_id: np.ndarray,
    afk: np.ndarray,
    cfg,
    plan: ResidencyPlan | None = None,
    collect: bool = False,
    backend: str = "scan",
):
    """Entry point for *untrusted* fused windows — the fused sibling of
    ``core.update.rate_and_apply_checked``. Anything not produced by the
    scheduler/planner pipeline (hand-built windows, replayed slabs) runs
    the window-level race detector and the plan-aliasing check before the
    fused dispatch commits K steps at once. ``plan=None`` builds a fresh
    plan (then the checks pin the planner's own invariants)."""
    from analyzer_tpu.core.fused import fused_apply_window
    from analyzer_tpu.core.update import check_window_conflict_free

    player_idx = np.ascontiguousarray(player_idx, np.int32)
    # graftlint: disable=GL025 — untrusted-entry validation syncs on purpose
    ratable = (np.asarray(mode_id) >= 0) & ~np.asarray(afk)
    pad_row = state.pad_row
    check_window_conflict_free(player_idx, ratable, pad_row=pad_row)
    if plan is None:
        valid = (player_idx != pad_row) & ratable[:, :, None, None]
        plans = plan_windows(
            player_idx, valid, pad_row,
            window=player_idx.shape[0], max_rows=DEFAULT_MAX_ROWS,
        )
        if len(plans) != 1:  # pragma: no cover - budget >= one window here
            raise ValueError("window exceeds the default residency budget")
        plan = plans[0]
    check_plan(plan, player_idx, pad_row)
    return fused_apply_window(
        state, plan.slot_rows, plan.slot_idx,
        winner.astype(np.int32), mode_id.astype(np.int32), afk,
        cfg, collect=collect, backend=backend,
    )


def window_reuse_stats(rows: np.ndarray) -> tuple[int, int]:
    """(unique_rows, row_instances) over a window's written-row list —
    the residency reuse measure. Shared with the sharded mesh feed
    (:mod:`analyzer_tpu.parallel.mesh`), which applies it to its
    per-shard compacted row lists to report how much a per-shard fused
    window would save (``mesh.writebacks_avoidable_total``)."""
    # graftlint: disable=GL025 — host row lists only (mesh routing input)
    rows = np.asarray(rows).ravel()
    return int(np.unique(rows).size), int(rows.size)
