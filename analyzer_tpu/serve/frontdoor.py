"""The production-scale serve front door: a concurrent socket plane.

:class:`~analyzer_tpu.serve.server.ServeServer` rides the stdlib
``ThreadingHTTPServer`` — one OS thread per connection, a fresh TCP
handshake per request (HTTP/1.0 until PR 20), and a ``json.dumps`` walk
per response. Fine for obsd scrape rates; hopeless for ROADMAP's
"millions of users". This module is the replacement edge for the hot
``/v1/*`` read path:

  * **persistent connections** — HTTP/1.1 keep-alive with pipelined
    request framing: a client may write N requests back-to-back and
    read N responses, IN ORDER, off one socket;
  * **a small reader pool** — each reader thread runs a ``selectors``
    event loop over its share of the connections (every reader also
    polls the shared listening socket, so accepts spread without a
    dispatcher). Readers never block on the engine: a parsed request is
    submitted to the engine's existing submit/tick microbatcher (which
    is already the correct backpressure surface) and the returned
    pending handle is queued per-connection; responses are written
    strictly in request order as the head handle resolves, so
    pipelining cannot tear or reorder;
  * **native response encoding** — each reader owns a
    :class:`~analyzer_tpu.serve.fastjson.ResponseCodec`: hot responses
    render straight from numpy slabs into a reusable arena,
    byte-identical to the python encoder (differential-pinned), with
    any unrecognized shape falling back, counted.

Route semantics are exactly ``ServeServer``'s (same param validation,
same error mapping to 400/404/503, same JSON error bodies); the
RoutedHTTPServer plane stays for the low-rate obsd endpoints.

:class:`FollowerGroup` is follower mode: N read replicas — each a
fabric :class:`~analyzer_tpu.fabric.route.FollowerPlane` adopting the
leader's published views BY REFERENCE (zero copy, zero re-keying) —
each behind its own :class:`FrontDoor`, with one refresher thread
polling adoption on a fixed cadence. Staleness is bounded by
``refresh_interval_s`` plus the leader's publish throttle, and
:meth:`FollowerGroup.versions` is the per-replica versions vector an
operator compares against the leader (docs/serving.md "Front door").

Clock discipline (graftlint GL049): this module never reads a wall
clock — latency stamps live in the engine's pending handles
(caller-injected clock), and the loops pace on selector/Event timeouts
only. GL049 also bans ``json.dumps`` here: the ONE cold-path exception
is :func:`_error_body` (designated helper — error bodies are not worth
a native shape).
"""

from __future__ import annotations

import json
import selectors
import socket
import threading
import urllib.parse
from collections import deque

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.fabric.route import FollowerPlane
from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs import get_registry
from analyzer_tpu.obs.httpd import DEFAULT_HOST, HttpError
from analyzer_tpu.serve.engine import UnknownPlayerError
from analyzer_tpu.serve.fastjson import ResponseCodec
from analyzer_tpu.serve.server import MAX_LEADERBOARD_K, _ids_param

logger = get_logger(__name__)

#: Header-block cap per request: a connection that exceeds it without
#: completing a request is answered 431 and closed.
MAX_REQUEST_BYTES = 32_768
#: Pipelining depth per connection: beyond this, parsing pauses (bytes
#: stay buffered) until responses drain — backpressure, not an error.
MAX_INFLIGHT_PER_CONN = 256

# Select timeouts: short while any connection has work in flight (the
# engine tick is ~1ms, so resolution polls ride just under it), long
# when idle. Timeouts pace the loop; they are not wall-clock reads.
_BUSY_SELECT_S = 0.0005
_IDLE_SELECT_S = 0.05

_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 431: "Request Header Fields Too Large",
    500: "Internal Server Error", 503: "Service Unavailable",
}


def _error_body(message: str) -> bytes:
    # GL049 designated helper: the one json.dumps in the front door.
    # Error bodies match RoutedHTTPServer's json_errors rendering.
    return (json.dumps({"error": message}, sort_keys=True) + "\n").encode(
        "utf-8"
    )


def _head(status: int, length: int, ctype: str, close: bool) -> bytes:
    return (
        f"HTTP/1.1 {status} {_REASONS.get(status, 'OK')}\r\n"
        f"Content-Type: {ctype}; charset=utf-8\r\n"
        f"Content-Length: {length}\r\n"
        f"Connection: {'close' if close else 'keep-alive'}\r\n"
        "\r\n"
    ).encode("latin-1")


class _Job:
    """One pipelined request's slot in a connection's response queue.

    ``ready`` is the rendered ``(status, body, ctype)`` (immediate for
    /healthz and parse errors); until then ``pendings`` holds the
    engine handles this response waits on (two for tiers?score=).
    ``close_after`` marks the last response on this connection."""

    __slots__ = ("kind", "pendings", "ready", "close_after")

    def __init__(self, kind, pendings=(), ready=None, close_after=False):
        self.kind = kind
        self.pendings = pendings
        self.ready = ready
        self.close_after = close_after


class _Conn:
    __slots__ = (
        "sock", "rbuf", "wbuf", "inflight", "closing", "eof", "dead",
    )

    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.rbuf = bytearray()
        self.wbuf = bytearray()
        self.inflight: deque[_Job] = deque()
        self.closing = False  # responses drain, then close
        self.eof = False      # peer half-closed; finish, then close
        self.dead = False     # remove from the loop now


class FrontDoor:
    """The concurrent ``/v1/*`` socket plane over one ServePlane.

    ``engine`` is anything satisfying the ServePlane submit surface —
    the single-device QueryEngine, the sharded engine, or a follower's
    — with its tick thread already started (``Worker(serve_port=)`` /
    ``cli serve`` ownership rules apply unchanged). ``port=0`` binds
    ephemeral; ``readers`` sizes the event-loop pool (each reader owns
    its accepted connections exclusively, so the loops share nothing
    but the listening socket and the engine queue)."""

    def __init__(
        self,
        engine,
        port: int = 0,
        host: str = DEFAULT_HOST,
        readers: int = 4,
        backlog: int = 512,
    ) -> None:
        self.engine = engine
        self.host = host
        self._listen = socket.create_server((host, port), backlog=backlog)
        self._listen.setblocking(False)
        self._port = self._listen.getsockname()[1]
        self._stop = False
        self._nconn = 0
        self._nconn_lock = threading.Lock()
        self.codecs: list[ResponseCodec] = [
            ResponseCodec() for _ in range(max(1, int(readers)))
        ]
        self._threads = [
            threading.Thread(
                target=self._reader_loop, args=(i,), daemon=True,
                name=f"analyzer-frontdoor-{i}",
            )
            for i in range(len(self.codecs))
        ]
        for t in self._threads:
            t.start()
        logger.info("frontdoor listening on %s (%d readers)",
                    self.url, len(self._threads))

    @property
    def port(self) -> int:
        return self._port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self._port}"

    def codec_stats(self) -> dict:
        """Aggregate codec route accounting across readers — the serve
        bench's ``native`` flag reads exactly this."""
        encodes = sum(c.encodes for c in self.codecs)
        fallbacks = sum(c.fallbacks for c in self.codecs)
        return {
            "native": bool(
                all(c.native for c in self.codecs) and fallbacks == 0
            ),
            "encodes": encodes,
            "fallbacks": fallbacks,
        }

    def close(self) -> None:
        """Stops the readers and closes every connection. Idempotent;
        the engine is closed by its owner, not here."""
        if self._stop:
            return
        self._stop = True
        for t in self._threads:
            t.join(timeout=5)
        try:
            self._listen.close()
        except OSError:
            pass
        logger.info("frontdoor stopped")

    # -- connection bookkeeping -------------------------------------------
    def _track(self, delta: int) -> None:
        with self._nconn_lock:
            self._nconn += delta
            n = self._nconn
        get_registry().gauge("frontdoor.connections").set(n)

    # -- the reader event loop --------------------------------------------
    def _reader_loop(self, idx: int) -> None:
        codec = self.codecs[idx]
        sel = selectors.DefaultSelector()
        sel.register(self._listen, selectors.EVENT_READ, None)
        conns: dict[int, _Conn] = {}
        try:
            while not self._stop:
                busy = any(
                    c.inflight or c.wbuf or c.rbuf for c in conns.values()
                )
                events = sel.select(_BUSY_SELECT_S if busy
                                    else _IDLE_SELECT_S)
                for key, mask in events:
                    if key.data is None:
                        self._accept(sel, conns)
                    elif mask & selectors.EVENT_READ:
                        self._readable(key.data, codec)
                for conn in conns.values():
                    self._pump(conn, codec)
                for conn in [c for c in conns.values() if c.dead]:
                    self._drop_conn(sel, conns, conn)
        except Exception:  # noqa: BLE001 — a reader must die loudly in
            # the log, not silently strand its share of the sockets.
            logger.exception("frontdoor reader %d crashed", idx)
        finally:
            for conn in list(conns.values()):
                self._drop_conn(sel, conns, conn)
            sel.close()

    def _accept(self, sel, conns) -> None:
        while True:
            try:
                sock, _addr = self._listen.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock)
            conns[sock.fileno()] = conn
            sel.register(sock, selectors.EVENT_READ, conn)
            self._track(+1)

    def _drop_conn(self, sel, conns, conn) -> None:
        fd = conn.sock.fileno()
        try:
            sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        conns.pop(fd, None)
        try:
            conn.sock.close()
        except OSError:
            pass
        self._track(-1)

    def _readable(self, conn: _Conn, codec: ResponseCodec) -> None:
        try:
            while True:
                chunk = conn.sock.recv(65536)
                if not chunk:
                    conn.eof = True
                    break
                conn.rbuf += chunk
                if len(chunk) < 65536:
                    break
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            conn.dead = True
            return
        self._parse(conn, codec)

    # -- request framing ---------------------------------------------------
    def _parse(self, conn: _Conn, codec: ResponseCodec) -> None:
        while not conn.closing:
            if len(conn.inflight) >= MAX_INFLIGHT_PER_CONN:
                return  # backpressure: resume once responses drain
            end = conn.rbuf.find(b"\r\n\r\n")
            if end < 0:
                if len(conn.rbuf) > MAX_REQUEST_BYTES:
                    self._reject(conn, 431, "request header block too large")
                return
            if end > MAX_REQUEST_BYTES:
                # Oversized even though terminated — the cap bounds the
                # request, not just the buffer.
                self._reject(conn, 431, "request header block too large")
                return
            head = bytes(conn.rbuf[:end])
            del conn.rbuf[:end + 4]
            self._one_request(conn, head, codec)

    def _reject(self, conn: _Conn, status: int, message: str) -> None:
        """A protocol-level failure: answer ``status`` and close — a
        framing we couldn't parse leaves the byte stream unsafe to
        resync, so the connection cannot be kept."""
        conn.inflight.append(_Job(
            "error",
            ready=(status, _error_body(message), "application/json"),
            close_after=True,
        ))
        conn.closing = True
        conn.rbuf.clear()

    def _one_request(self, conn: _Conn, head: bytes, codec) -> None:
        lines = head.split(b"\r\n")
        try:
            method, target, version = (
                lines[0].decode("latin-1").split(" ", 2)
            )
        except ValueError:
            self._reject(conn, 400, "malformed request line")
            return
        if version not in ("HTTP/1.1", "HTTP/1.0"):
            self._reject(conn, 400, f"unsupported protocol {version!r}")
            return
        headers = {}
        for raw in lines[1:]:
            name, sep, value = raw.partition(b":")
            if not sep:
                self._reject(conn, 400, "malformed header line")
                return
            headers[name.strip().lower()] = value.strip()
        if headers.get(b"transfer-encoding"):
            self._reject(conn, 400, "request bodies are not accepted")
            return
        length = headers.get(b"content-length", b"0")
        try:
            has_body = int(length) > 0
        except ValueError:
            has_body = True
        if has_body:
            self._reject(conn, 400, "request bodies are not accepted")
            return
        conn_hdr = headers.get(b"connection", b"").lower()
        close_after = (
            conn_hdr == b"close"
            or (version == "HTTP/1.0" and conn_hdr != b"keep-alive")
        )
        if method != "GET":
            conn.inflight.append(_Job(
                "error",
                ready=(405, _error_body(f"method {method} not allowed"),
                       "application/json"),
                close_after=close_after,
            ))
        else:
            job = self._route(target)
            job.close_after = close_after
            conn.inflight.append(job)
        if close_after:
            conn.closing = True
            conn.rbuf.clear()

    # -- routing (ServeServer semantics, submit instead of block) ----------
    def _route(self, target: str) -> _Job:
        # Deferred like server.py (core.state pulls jax); hoisted out of
        # the try so the GL021 crash guard never masks a broken import.
        from analyzer_tpu.core.state import MAX_TEAM_SIZE

        try:
            parsed = urllib.parse.urlsplit(target)
            params = {
                k: v[-1]
                for k, v in urllib.parse.parse_qs(parsed.query).items()
            }
            path = parsed.path
            if path == "/healthz":
                return _Job("health", ready=(200, b"ok\n", "text/plain"))
            if path == "/v1/ratings":
                ids = _ids_param(params, "ids", self.engine.max_batch)
                return _Job("ratings", pendings=(
                    self.engine.submit("ratings", tuple(ids)),
                ))
            if path == "/v1/leaderboard":
                raw = params.get("k", "10")
                try:
                    k = int(raw)
                except ValueError as err:
                    raise HttpError(
                        400, f"k must be an integer, got {raw!r}"
                    ) from err
                if not 1 <= k <= MAX_LEADERBOARD_K:
                    raise HttpError(400, f"k must be in 1..{MAX_LEADERBOARD_K}")
                return _Job("leaderboard", pendings=(
                    self.engine.submit("leaderboard", k),
                ))
            if path == "/v1/winprob":
                a = _ids_param(params, "a", MAX_TEAM_SIZE)
                b = _ids_param(params, "b", MAX_TEAM_SIZE)
                return _Job("winprob", pendings=(
                    self.engine.submit("winprob", (tuple(a), tuple(b))),
                ))
            if path == "/v1/tiers":
                raw = params.get("score")
                if raw is None:
                    return _Job("tiers", pendings=(
                        self.engine.submit("tiers"),
                    ))
                try:
                    score = float(raw)
                except ValueError as err:
                    raise HttpError(
                        400, f"score must be a number, got {raw!r}"
                    ) from err
                return _Job("tiers", pendings=(
                    self.engine.submit("tiers"),
                    self.engine.submit("percentile", score),
                ))
            raise HttpError(404, "not found")
        except HttpError as err:
            return _Job("error", ready=(
                err.status, _error_body(err.message), "application/json"
            ))
        except Exception:  # noqa: BLE001 — same crash guard as the
            # routed server: a broken route answers 500, the loop lives.
            logger.exception("frontdoor route failed for %s", target)
            return _Job("error", ready=(
                500, _error_body("internal error"), "application/json"
            ))

    # -- response pumping --------------------------------------------------
    def _finish(self, job: _Job, codec: ResponseCodec):
        for p in job.pendings:
            if p.error is not None:
                return self._map_error(p.error)
        value = job.pendings[0].value
        if job.kind == "tiers" and len(job.pendings) == 2:
            pct = job.pendings[1].value
            value = {**value, "percentile": pct["percentile"],
                     "score": pct["score"], "below": pct["below"]}
        return 200, codec.encode(job.kind, value), "application/json"

    def _map_error(self, err: BaseException):
        if isinstance(err, UnknownPlayerError):
            return 404, _error_body(str(err)), "application/json"
        if isinstance(err, ValueError):
            return 400, _error_body(str(err)), "application/json"
        if isinstance(err, RuntimeError):
            # "no ratings view published yet" / engine closed — plane
            # up, cannot answer; 503 tells a balancer so.
            return 503, _error_body(str(err)), "application/json"
        logger.error("frontdoor query failed: %r", err)
        return 500, _error_body("internal error"), "application/json"

    def _pump(self, conn: _Conn, codec: ResponseCodec) -> None:
        if conn.dead:
            return
        if conn.rbuf and not conn.closing:
            self._parse(conn, codec)  # resume deferred pipelined bytes
        q = conn.inflight
        reg = get_registry()
        while q:
            job = q[0]
            if job.ready is None:
                if not all(p.done.is_set() for p in job.pendings):
                    break
                job.ready = self._finish(job, codec)
            status, body, ctype = job.ready
            conn.wbuf += _head(status, len(body), ctype, job.close_after)
            conn.wbuf += body
            reg.counter("frontdoor.requests_total").add(1)
            reg.counter("frontdoor.encode_bytes_total").add(len(body))
            q.popleft()
            if job.close_after:
                break
        self._flush(conn)

    def _flush(self, conn: _Conn) -> None:
        if conn.wbuf:
            try:
                sent = conn.sock.send(conn.wbuf)
                del conn.wbuf[:sent]
            except (BlockingIOError, InterruptedError):
                pass
            except OSError:
                conn.dead = True
                return
        if not conn.wbuf and not conn.inflight and (conn.closing or conn.eof):
            conn.dead = True


class FollowerGroup:
    """N follower read replicas behind their own front doors.

    Each replica is a :class:`~analyzer_tpu.fabric.route.FollowerPlane`
    — a private ViewPublisher adopting the ``leader`` publisher's
    views by reference plus its own QueryEngine — fronted by its own
    :class:`FrontDoor`, so reads scale horizontally without copying or
    re-keying the table (threads stand in for reader processes; the
    adoption mechanism is process-shape-blind). One refresher thread
    polls every replica on an Event cadence: a replica's staleness is
    bounded by ``refresh_interval_s`` plus the leader's publish
    throttle, and :meth:`versions` is the vector an operator compares
    against the leader's version (docs/serving.md)."""

    def __init__(
        self,
        leader,
        cfg: RatingConfig | None = None,
        n_followers: int = 2,
        refresh_interval_s: float = 0.005,
        max_batch: int = 256,
        readers: int = 2,
        host: str = DEFAULT_HOST,
        clock=None,
    ) -> None:
        self.leader = leader
        self.refresh_interval_s = float(refresh_interval_s)
        self.planes = [
            FollowerPlane(leader, cfg=cfg, max_batch=max_batch, clock=clock)
            for _ in range(int(n_followers))
        ]
        self._readers = int(readers)
        self._host = host
        self.doors: list[FrontDoor] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "FollowerGroup":
        if self._thread is not None:
            return self
        for plane in self.planes:
            plane.start()
        self.doors = [
            FrontDoor(plane.engine, readers=self._readers, host=self._host)
            for plane in self.planes
        ]
        self._thread = threading.Thread(
            target=self._refresh_loop, name="analyzer-follower-refresh",
            daemon=True,
        )
        self._thread.start()
        return self

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self.refresh_interval_s):
            for plane in self.planes:
                plane.refresh()

    def refresh(self) -> int:
        """One synchronous adoption sweep; returns how many replicas
        advanced (tests drive this for deterministic staleness)."""
        return sum(1 for plane in self.planes if plane.refresh())

    @property
    def versions(self) -> list[int]:
        """Per-replica adopted versions — the bounded-staleness vector."""
        return [plane.version for plane in self.planes]

    @property
    def urls(self) -> list[str]:
        return [door.url for door in self.doors]

    def close(self) -> None:
        thread, self._thread = self._thread, None
        if thread is not None:
            self._stop.set()
            thread.join(timeout=5)
        for door in self.doors:
            door.close()
        for plane in self.planes:
            plane.close()
