"""QueryEngine: microbatched, retrace-free query execution over a view.

Serving shape discipline (the read-plane mirror of the worker's compile
ladder): every query type runs as ONE jitted kernel per tick whose
shapes come from two small power-of-two ladders — the view's row bucket
(``view.py``) and the per-tick request bucket (floor
``QUERY_BUCKET_FLOOR``, cap ``max_batch``). Concurrent requests queue;
the tick thread drains them, groups by kind, pads each group to its
bucket and dispatches once. Steady state therefore compiles NOTHING —
``experiments/serve_bench.py`` pins ``jax.retraces_total`` flat while
the engine serves — and each tiny query pays ~1/occupancy of a device
dispatch instead of a whole one (Clipper's adaptive-batching argument,
NSDI '17).

Bit-reproducibility split (the oracle contract, ``serve/oracle.py``):
the device kernels do only IEEE-exact work — row gathers, NaN→seed
selects, comparisons, and FIXED-ORDER float32 team reductions (explicit
unrolled adds; XLA does not reassociate a written dependency chain) —
so a pure-Python float32 oracle replays them bit-for-bit. The final
transcendentals (Phi for win probability, sqrt·exp for quality) run on
the host in float64 over the fetched per-query statistics, rounded once
to float32 — deterministic, platform-stable libm-on-doubles, and exactly
replicable by the oracle. The formulas are
:func:`analyzer_tpu.ops.trueskill.win_probability` / ``quality``
verbatim (c² = Σσ² + n·β², no tau inflation); a tolerance cross-check
against those device kernels rides in tests/test_serve.py.

Consistency: a tick resolves ``ViewPublisher.current()`` ONCE and
answers every request in that tick against it, so each response is
internally consistent with exactly one published version (reported as
``version`` in every result).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core.state import (
    COL_SEED_MU,
    COL_SEED_SIGMA,
    MAX_TEAM_SIZE,
    MU_LO,
    SIGMA_LO,
)
from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs import get_registry
from analyzer_tpu.obs.retrace import track_jit
from analyzer_tpu.serve.view import RatingsView

logger = get_logger(__name__)

#: Smallest per-tick request bucket — single queries pad to this.
QUERY_BUCKET_FLOOR = 8

#: The ratings gather ladder extends this far past ``max_batch``: one
#: ratings request legitimately carries a page of ids, not one.
RATINGS_ID_FACTOR = 8

#: Conservative-score multiplier: rank by mu - 3*sigma (the "99.7% sure
#: you are at least this good" estimate the reference's trueskill_delta
#: is a delta of, rater.py:149).
CONSERVATIVE_K = 3.0

#: Default tier edges over the conservative score, mu0/sigma0-scale
#: (mu0=1500, sigma0=1000): fresh players sit far negative, converged
#: ones land between 0 and ~2500. Operators tune via
#: ``QueryEngine(tier_edges=)``.
DEFAULT_TIER_EDGES = (
    -2000.0, -1000.0, -500.0, 0.0, 250.0, 500.0, 750.0,
    1000.0, 1250.0, 1500.0, 1750.0, 2000.0, 2500.0,
)

_KINDS = ("ratings", "winprob", "leaderboard", "tiers", "percentile")


class UnknownPlayerError(KeyError):
    """A query named player ids the addressed view has never published."""

    def __init__(self, ids) -> None:
        self.ids = tuple(ids)
        super().__init__(f"unknown player id(s): {', '.join(self.ids)}")

    def __str__(self) -> str:  # KeyError's repr-quoting is noise in HTTP bodies
        return self.args[0]


def query_bucket(n: int, cap: int) -> int:
    """Power-of-two request bucket, floor QUERY_BUCKET_FLOOR, cap
    ``cap`` (the engine's max_batch) — the ONE owner of the per-tick
    shape ladder, shared by execution and warmup."""
    b = max(QUERY_BUCKET_FLOOR, 1 << max(n - 1, 0).bit_length())
    return min(b, max(cap, QUERY_BUCKET_FLOOR))


# -- jitted kernels (one dispatch per kind per tick) ----------------------


@jax.jit
def _gather_rows(table, idx):
    """Whole-row gather for player lookups: [Qb] -> [Qb, 16]."""
    return table[idx]


@partial(jax.jit, static_argnames=("team",))
def _team_stats(table, idx, mask, team: int):
    """Fixed-order float32 sufficient statistics for [Qb] two-team
    matchups: idx/mask are [Qb, 2, T]. Returns (n, s2_sum, mu_diff)
    where priors resolve NaN -> baked seed (rater.py:114-121) and every
    reduction is an explicit team-major, slot-minor add chain — the
    order ``serve/oracle.py`` replays bit-for-bit."""
    rows = table[idx]  # [Qb, 2, T, 16]
    mu_raw = rows[..., MU_LO]
    sg_raw = rows[..., SIGMA_LO]
    unrated = jnp.isnan(mu_raw)
    mu = jnp.where(unrated, rows[..., COL_SEED_MU], mu_raw)
    sg = jnp.where(unrated, rows[..., COL_SEED_SIGMA], sg_raw)
    zero = jnp.zeros(idx.shape[0], mu.dtype)
    n = zero
    s2 = zero
    team_mu = [zero, zero]
    for t in range(2):
        for s in range(team):
            m = mask[:, t, s]
            n = n + jnp.where(m, jnp.float32(1.0), jnp.float32(0.0))
            s2 = s2 + jnp.where(m, sg[:, t, s] * sg[:, t, s], jnp.float32(0.0))
            team_mu[t] = team_mu[t] + jnp.where(
                m, mu[:, t, s], jnp.float32(0.0)
            )
    return n, s2, team_mu[0] - team_mu[1]


def _conservative(mu, sg):
    """mu - 3*sigma in float32 WITHOUT a multiply: ``sg+sg`` is exact
    (power-of-two scale), so ``(sg+sg)+sg`` is the correctly-rounded
    3*sigma — and with no mul feeding the subtract, XLA cannot contract
    the expression into an FMA, whose single rounding would silently
    break the oracle's bit-for-bit replay (``serve/oracle.py``)."""
    return mu - ((sg + sg) + sg)


def _host_conservative(mu, sg) -> np.float32:
    """The host replay of :func:`_conservative` (same rounding order)."""
    mu = np.float32(mu)
    sg = np.float32(sg)
    return np.float32(mu - np.float32(np.float32(sg + sg) + sg))


@partial(jax.jit, static_argnames=("k",))
def _leaderboard(table, k: int):
    """Top-k rows by conservative score mu - 3*sigma (shared column),
    unrated rows excluded via -inf. ``jax.lax.top_k`` breaks ties toward
    the lower row index, matching the oracle's stable sort."""
    mu = table[:, MU_LO]
    score = _conservative(mu, table[:, SIGMA_LO])
    score = jnp.where(jnp.isnan(mu), -jnp.inf, score)
    return jax.lax.top_k(score, k)


@jax.jit
def _tier_counts(table, edges):
    """(count of rated rows with score >= edge_i, rated total). Integer
    counts of exact float32 comparisons — bit-free of rounding by
    construction."""
    mu = table[:, MU_LO]
    score = _conservative(mu, table[:, SIGMA_LO])
    rated = ~jnp.isnan(mu)
    ge = (score[None, :] >= edges[:, None]) & rated[None, :]
    return ge.sum(axis=1).astype(jnp.int32), rated.sum().astype(jnp.int32)


@jax.jit
def _count_below(table, values):
    """For each query value: how many rated rows score strictly below it
    (the percentile numerator), plus the rated total."""
    mu = table[:, MU_LO]
    score = _conservative(mu, table[:, SIGMA_LO])
    rated = ~jnp.isnan(mu)
    below = (score[None, :] < values[:, None]) & rated[None, :]
    return below.sum(axis=1).astype(jnp.int32), rated.sum().astype(jnp.int32)


track_jit("serve._gather_rows", _gather_rows)
track_jit("serve._team_stats", _team_stats)
track_jit("serve._leaderboard", _leaderboard)
track_jit("serve._tier_counts", _tier_counts)
track_jit("serve._count_below", _count_below)


def _finish_winprob(n, s2, mu_diff, beta2: float):
    """Host float64 finish of P(team A wins) = Phi(mu_diff / c) from the
    kernel's float32 statistics, rounded once to float32. Pure
    double-precision libm — the oracle replays it exactly."""
    out = np.empty(len(n), np.float32)
    for i in range(len(n)):
        c2 = max(float(s2[i]) + float(n[i]) * beta2, 1e-20)
        t = float(mu_diff[i]) / math.sqrt(c2)
        out[i] = np.float32(0.5 * math.erfc(-t / math.sqrt(2.0)))
    return out


def _finish_quality(n, s2, mu_diff, beta2: float):
    """Host float64 finish of the draw-probability match quality
    (ops.trueskill.quality's closed form, no tau inflation)."""
    out = np.empty(len(n), np.float32)
    for i in range(len(n)):
        nb = float(n[i]) * beta2
        denom = max(nb + float(s2[i]), 1e-20)
        d = float(mu_diff[i])
        out[i] = np.float32(
            math.sqrt(nb / denom) * math.exp(-(d * d) / (2.0 * denom))
        )
    return out


class _Pending:
    """One queued request: resolved by the tick that executes it. The
    submit/done stamps give the client-observed latency the serve bench
    reports (queue wait + microbatch execution)."""

    __slots__ = (
        "kind", "payload", "done", "value", "error", "t_submit", "t_done",
    )

    def __init__(self, kind: str, payload) -> None:
        self.kind = kind
        self.payload = payload
        self.done = threading.Event()
        self.value = None
        self.error: BaseException | None = None
        self.t_submit = time.monotonic()
        self.t_done: float | None = None

    def resolve(self, value) -> None:
        self.value = value
        self.t_done = time.monotonic()
        self.done.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.t_done = time.monotonic()
        self.done.set()

    @property
    def latency_s(self) -> float | None:
        return None if self.t_done is None else self.t_done - self.t_submit

    def result(self, timeout: float | None = 30.0):
        if not self.done.wait(timeout):
            raise TimeoutError(f"{self.kind} query not served in {timeout}s")
        if self.error is not None:
            raise self.error
        return self.value


class QueryEngine:
    """Coalesces concurrent queries into per-tick microbatches.

    ``source`` is a :class:`~analyzer_tpu.serve.view.ViewPublisher` (or
    anything with ``current() -> RatingsView | None``). Two driving
    modes:

      * **threaded** (:meth:`start` — the server / worker wiring): a
        tick thread wakes on submissions, drains the queue, and executes
        one microbatch per kind;
      * **inline** (default — tests, naive baselines): blocking helpers
        execute their own single-request microbatch; ``submit`` +
        :meth:`tick` give a test deterministic coalescing control.

    Every result dict carries ``version`` — the exactly-one published
    version it was computed against.
    """

    def __init__(
        self,
        source,
        cfg: RatingConfig | None = None,
        max_batch: int = 256,
        tick_interval_s: float = 0.001,
        tier_edges=None,
        clock=time.monotonic,
    ) -> None:
        self.source = source
        self.cfg = cfg or RatingConfig()
        self.max_batch = int(max_batch)
        self.tick_interval_s = tick_interval_s
        self.tier_edges = np.asarray(
            tier_edges if tier_edges is not None else DEFAULT_TIER_EDGES,
            np.float32,
        )
        self.clock = clock
        self.queries_total = 0
        self._pending: deque[_Pending] = deque()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self._stop = False
        # Version-keyed result caches (leaderboard / tiers): one entry
        # each — a new publish changes the version and naturally evicts.
        self._lb_cache: tuple[int, int, np.ndarray, np.ndarray] | None = None
        self._tier_cache: tuple[int, list] | None = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "QueryEngine":
        """Starts the tick thread (idempotent)."""
        if self._thread is None:
            self._stop = False
            self._thread = threading.Thread(
                target=self._tick_loop, name="analyzer-ratesrv-tick",
                daemon=True,
            )
            self._thread.start()
        return self

    def close(self) -> None:
        """Stops the tick thread; queued requests fail cleanly."""
        thread, self._thread = self._thread, None
        if thread is None:
            return
        self._stop = True
        self._wake.set()
        thread.join(timeout=5)
        with self._lock:
            stranded = list(self._pending)
            self._pending.clear()
        for req in stranded:
            req.fail(RuntimeError("query engine closed"))

    # -- request API ------------------------------------------------------
    def submit(self, kind: str, payload=None) -> _Pending:
        """Enqueues a request for the next tick (threaded mode) or for an
        explicit :meth:`tick` call, returning the pending handle."""
        if kind not in _KINDS:
            raise ValueError(f"unknown query kind {kind!r}")
        req = _Pending(kind, payload)
        with self._lock:
            self._pending.append(req)
        self._wake.set()
        return req

    def _call(self, kind: str, payload=None):
        if self._thread is not None:
            return self.submit(kind, payload).result()
        req = _Pending(kind, payload)
        self._execute([req])
        return req.result(timeout=0)

    def get_ratings(self, player_ids) -> dict:
        """Rating lookup: shared + per-mode (mu, sigma) for each id."""
        return self._call("ratings", tuple(player_ids))

    def win_probability(self, team_a, team_b) -> dict:
        """P(team_a beats team_b) + match quality for one matchup."""
        return self._call("winprob", (tuple(team_a), tuple(team_b)))

    def leaderboard(self, k: int = 10) -> dict:
        """Top-k rated players by conservative estimate mu - 3*sigma."""
        return self._call("leaderboard", int(k))

    def tier_histogram(self) -> dict:
        """Rated-player counts per conservative-score tier band."""
        return self._call("tiers")

    def percentile(self, score: float) -> dict:
        """Fraction of rated players strictly below ``score``."""
        return self._call("percentile", float(score))

    # -- execution --------------------------------------------------------
    def tick(self) -> int:
        """Drains and executes up to ``max_batch`` queued requests per
        kind; returns how many requests were served. Tests drive this
        directly for deterministic coalescing."""
        with self._lock:
            reqs = list(self._pending)
            self._pending.clear()
        if not reqs:
            return 0
        overflow = self._execute(reqs)
        if overflow:
            with self._lock:
                self._pending.extendleft(reversed(overflow))
            self._wake.set()
        return len(reqs) - len(overflow)

    def _tick_loop(self) -> None:
        while not self._stop:
            self._wake.wait(timeout=0.2)
            self._wake.clear()
            if self._stop:
                return
            try:
                served = self.tick()
            except Exception:  # noqa: BLE001 — a tick crash must not
                # silently kill the serving thread; per-request errors
                # were already routed, so log and keep ticking.
                logger.exception("serve tick failed")
                continue
            if served and self.tick_interval_s:
                # A short lag window lets the next burst of concurrent
                # requests pile up into one microbatch instead of each
                # opening its own tick (Clipper's batching delay).
                time.sleep(self.tick_interval_s)

    def warmup(self, view: RatingsView | None = None) -> int:
        """Compiles every (row-bucket, request-bucket) kernel shape the
        current view can serve, so no production query pays XLA (mirrors
        ``Worker.warmup`` on the write plane). Returns the number of
        kernel shapes visited."""
        view = view or self._current_view()
        shapes = 0
        b = QUERY_BUCKET_FLOOR
        buckets = []
        # The gather ladder runs RATINGS_ID_FACTOR further than the
        # request ladder: one ratings request may carry many ids.
        while b <= max(self.max_batch, QUERY_BUCKET_FLOOR) * RATINGS_ID_FACTOR:
            buckets.append(b)
            b *= 2
        for qb in buckets:
            idx1 = jnp.zeros(qb, jnp.int32)
            _gather_rows(view.table, idx1).block_until_ready()
            if qb > self.max_batch:
                shapes += 1
                continue
            idx2 = jnp.full((qb, 2, MAX_TEAM_SIZE), view.pad_row, jnp.int32)
            mask = jnp.zeros((qb, 2, MAX_TEAM_SIZE), bool)
            jax.block_until_ready(
                _team_stats(view.table, idx2, mask, MAX_TEAM_SIZE)
            )
            vals = jnp.zeros(qb, jnp.float32)
            jax.block_until_ready(_count_below(view.table, vals))
            shapes += 3
        rows = view.table.shape[0]
        k = QUERY_BUCKET_FLOOR
        while True:
            _leaderboard(view.table, min(k, rows))
            shapes += 1
            if k >= rows:
                break
            k *= 2
        jax.block_until_ready(
            _tier_counts(view.table, jnp.asarray(self.tier_edges))
        )
        return shapes + 1

    def _current_view(self) -> RatingsView:
        src = self.source
        view = src.current() if hasattr(src, "current") else src()
        if view is None:
            raise RuntimeError(
                "no ratings view published yet (serve.view readiness)"
            )
        return view

    def _execute(self, reqs: list) -> list:
        """Runs one microbatch per kind against ONE view snapshot.
        Returns requests deferred to the next tick (per-kind max_batch
        overflow). Request-level failures (unknown ids, bad payloads)
        resolve that request's error without touching its batchmates."""
        try:
            view = self._current_view()
        except Exception as err:  # noqa: BLE001 — no view / dead source:
            # every request fails cleanly rather than hanging forever.
            for req in reqs:
                req.fail(err)
            return []
        reg = get_registry()
        reg.gauge("serve.view_age_seconds").set(round(view.age_s, 3))
        by_kind: dict[str, list] = {}
        overflow: list = []
        id_cap = self.max_batch * RATINGS_ID_FACTOR
        ids_in_batch = 0
        for req in reqs:
            group = by_kind.setdefault(req.kind, [])
            if req.kind == "ratings":
                # Ratings coalesce by TOTAL id count (one request can
                # carry a page of ids); the gather bucket ladder caps it.
                n_ids = max(len(req.payload), 1)
                if len(group) >= self.max_batch or (
                    group and ids_in_batch + n_ids > id_cap
                ):
                    overflow.append(req)
                else:
                    group.append(req)
                    ids_in_batch += n_ids
            elif len(group) >= self.max_batch:
                overflow.append(req)
            else:
                group.append(req)
        for kind, group in by_kind.items():
            reg.counter("serve.queries_total").add(len(group))
            reg.counter("serve.queries_total", kind=kind).add(len(group))
            self.queries_total += len(group)
            try:
                getattr(self, "_run_" + kind)(view, group)
            except Exception as err:  # noqa: BLE001 — a kernel-level
                # failure answers the whole microbatch; the engine and
                # its other kinds keep serving.
                logger.exception("serve microbatch %s failed", kind)
                for req in group:
                    if not req.done.is_set():
                        req.fail(err)
        return overflow

    @staticmethod
    def _resolve_or_fail(view: RatingsView, ids, req: _Pending):
        rows = []
        missing = []
        for pid in ids:
            row = view.resolve(pid)
            if row is None:
                missing.append(pid)
            else:
                rows.append(row)
        if missing:
            req.fail(UnknownPlayerError(missing))
            return None
        return rows

    def _observe_occupancy(self, kind: str, filled: int, bucket: int) -> None:
        get_registry().histogram(
            "serve.microbatch_occupancy", kind=kind
        ).observe(filled / bucket if bucket else 0.0)

    # -- per-kind microbatches -------------------------------------------
    def _run_ratings(self, view: RatingsView, group: list) -> None:
        """All requests' ids coalesce into ONE padded gather."""
        flat: list[int] = []
        spans: list = []  # (req, start, ids, unknown)
        for req in group:
            ids = req.payload
            start = len(flat)
            known = []
            unknown = []
            for pid in ids:
                row = view.resolve(pid)
                if row is None:
                    unknown.append(pid)
                else:
                    known.append((pid, row))
                    flat.append(row)
            spans.append((req, start, known, unknown))
        qb = query_bucket(
            max(len(flat), 1), self.max_batch * RATINGS_ID_FACTOR
        )
        if len(flat) > qb:
            raise ValueError(
                f"{len(flat)} ids in one ratings microbatch exceeds the "
                f"engine cap {qb}; split the request"
            )
        idx = np.full(qb, view.pad_row, np.int32)
        if flat:
            idx[: len(flat)] = flat
        self._observe_occupancy("ratings", len(flat), qb)
        rows = np.asarray(_gather_rows(view.table, jnp.asarray(idx)))
        for req, start, known, unknown in spans:
            out = []
            for j, (pid, _row) in enumerate(known):
                r = rows[start + j]
                mu, sg = float(r[MU_LO]), float(r[SIGMA_LO])
                rated = not math.isnan(mu)
                out.append({
                    "id": pid,
                    "rated": rated,
                    "mu": mu if rated else None,
                    "sigma": sg if rated else None,
                    "conservative": (
                        float(_host_conservative(r[MU_LO], r[SIGMA_LO]))
                        if rated else None
                    ),
                    "seed_mu": float(r[COL_SEED_MU]),
                    "seed_sigma": float(r[COL_SEED_SIGMA]),
                })
            req.resolve({
                "version": view.version, "ratings": out, "unknown": unknown,
            })

    def _run_winprob(self, view: RatingsView, group: list) -> None:
        """[Q, 2, T] matchups -> one _team_stats dispatch + host finish."""
        t = MAX_TEAM_SIZE
        live: list = []
        for req in group:
            a, b = req.payload
            if not (1 <= len(a) <= t and 1 <= len(b) <= t):
                req.fail(ValueError(
                    f"teams must have 1..{t} players (got {len(a)} vs "
                    f"{len(b)})"
                ))
                continue
            rows_a = self._resolve_or_fail(view, a, req)
            if rows_a is None:
                continue
            rows_b = self._resolve_or_fail(view, b, req)
            if rows_b is None:
                continue
            live.append((req, rows_a, rows_b))
        if not live:
            return
        q = len(live)
        qb = query_bucket(q, self.max_batch)
        idx = np.full((qb, 2, t), view.pad_row, np.int32)
        mask = np.zeros((qb, 2, t), bool)
        for i, (_req, rows_a, rows_b) in enumerate(live):
            idx[i, 0, : len(rows_a)] = rows_a
            idx[i, 1, : len(rows_b)] = rows_b
            mask[i, 0, : len(rows_a)] = True
            mask[i, 1, : len(rows_b)] = True
        self._observe_occupancy("winprob", q, qb)
        n, s2, mu_diff = (
            np.asarray(x)
            for x in _team_stats(
                view.table, jnp.asarray(idx), jnp.asarray(mask), t
            )
        )
        beta2 = self.cfg.beta2
        p = _finish_winprob(n[:q], s2[:q], mu_diff[:q], beta2)
        quality = _finish_quality(n[:q], s2[:q], mu_diff[:q], beta2)
        for i, (req, _ra, _rb) in enumerate(live):
            req.resolve({
                "version": view.version,
                "p_a": float(p[i]),
                "quality": float(quality[i]),
            })

    def _leaderboard_rows(self, view: RatingsView, k: int):
        """(scores, rows) for the top-k_bucket, version-keyed cache."""
        rows_total = view.table.shape[0]
        kb = min(query_bucket(k, rows_total), rows_total)
        cached = self._lb_cache
        if cached is not None and cached[0] == view.version and cached[1] >= kb:
            get_registry().counter("serve.leaderboard_cache_hits_total").add(1)
            return cached[2], cached[3]
        vals, idx = _leaderboard(view.table, kb)
        vals, idx = np.asarray(vals), np.asarray(idx)
        self._lb_cache = (view.version, kb, vals, idx)
        return vals, idx

    def _run_leaderboard(self, view: RatingsView, group: list) -> None:
        kmax = max(req.payload for req in group)
        self._observe_occupancy("leaderboard", len(group), len(group))
        vals, idx = self._leaderboard_rows(view, kmax)
        host = view.host_table()
        for req in group:
            k = req.payload
            leaders = []
            for rank in range(min(k, len(vals))):
                if not math.isfinite(vals[rank]):
                    break  # fewer than k rated players
                row = int(idx[rank])
                leaders.append({
                    "rank": rank + 1,
                    "id": view.id_of(row),
                    "mu": float(host[row, MU_LO]),
                    "sigma": float(host[row, SIGMA_LO]),
                    "conservative": float(vals[rank]),
                })
            req.resolve({"version": view.version, "leaders": leaders})

    def _run_tiers(self, view: RatingsView, group: list) -> None:
        self._observe_occupancy("tiers", len(group), len(group))
        cached = self._tier_cache
        if cached is not None and cached[0] == view.version:
            get_registry().counter("serve.tier_cache_hits_total").add(1)
            value = cached[1]
        else:
            ge, rated = _tier_counts(
                view.table, jnp.asarray(self.tier_edges)
            )
            ge = [int(x) for x in np.asarray(ge)]
            rated = int(rated)
            counts = [rated - ge[0]]
            counts += [ge[i] - ge[i + 1] for i in range(len(ge) - 1)]
            counts.append(ge[-1])
            value = {
                "edges": [float(e) for e in self.tier_edges],
                "counts": counts,
                "rated": rated,
            }
            self._tier_cache = (view.version, value)
        for req in group:
            req.resolve({"version": view.version, **value})

    def _run_percentile(self, view: RatingsView, group: list) -> None:
        q = len(group)
        qb = query_bucket(q, self.max_batch)
        vals = np.zeros(qb, np.float32)
        for i, req in enumerate(group):
            vals[i] = req.payload
        self._observe_occupancy("percentile", q, qb)
        below, rated = _count_below(view.table, jnp.asarray(vals))
        below = np.asarray(below)
        rated = int(rated)
        for i, req in enumerate(group):
            req.resolve({
                "version": view.version,
                "score": float(np.float32(req.payload)),
                "below": int(below[i]),
                "rated": rated,
                "percentile": (int(below[i]) / rated) if rated else None,
            })

    # -- naive baseline ---------------------------------------------------
    def query_now(self, kind: str, payload=None):
        """The NAIVE one-query-per-dispatch path: executes a single
        request immediately on the calling thread with no coalescing —
        the baseline ``experiments/serve_bench.py`` measures the
        microbatched engine against. Same kernels, same buckets, one
        device dispatch per call."""
        req = _Pending(kind, payload)
        self._execute([req])
        return req.result(timeout=0)

    def stats(self) -> dict:
        """The serve keys Worker.stats() re-exports."""
        src = self.source
        view = src.current() if hasattr(src, "current") else src()
        return {
            "view_version": None if view is None else view.version,
            "view_age_s": (
                None if view is None else round(view.age_s, 3)
            ),
            "queries_total": self.queries_total,
        }
