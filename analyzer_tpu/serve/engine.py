"""QueryEngine: microbatched, retrace-free query execution over a view.

Serving shape discipline (the read-plane mirror of the worker's compile
ladder): every query type runs as ONE jitted kernel per tick whose
shapes come from two small power-of-two ladders — the view's row bucket
(``view.py``) and the per-tick request bucket (floor
``QUERY_BUCKET_FLOOR``, cap ``max_batch``). Concurrent requests queue;
the tick thread drains them, groups by kind, pads each group to its
bucket and dispatches once. Steady state therefore compiles NOTHING —
``experiments/serve_bench.py`` pins ``jax.retraces_total`` flat while
the engine serves — and each tiny query pays ~1/occupancy of a device
dispatch instead of a whole one (Clipper's adaptive-batching argument,
NSDI '17).

Bit-reproducibility split (the oracle contract, ``serve/oracle.py``):
the device kernels do only IEEE-exact work — row gathers, NaN→seed
selects, comparisons, and FIXED-ORDER float32 team reductions (explicit
unrolled adds; XLA does not reassociate a written dependency chain) —
so a pure-Python float32 oracle replays them bit-for-bit. The final
transcendentals (Phi for win probability, sqrt·exp for quality) run on
the host in float64 over the fetched per-query statistics, rounded once
to float32 — deterministic, platform-stable libm-on-doubles, and exactly
replicable by the oracle. The formulas are
:func:`analyzer_tpu.ops.trueskill.win_probability` / ``quality``
verbatim (c² = Σσ² + n·β², no tau inflation); a tolerance cross-check
against those device kernels rides in tests/test_serve.py.

Consistency: a tick resolves ``ViewPublisher.current()`` ONCE and
answers every request in that tick against it, so each response is
internally consistent with exactly one published version (reported as
``version`` in every result).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from functools import partial
from typing import Protocol, runtime_checkable

import jax
import jax.numpy as jnp
import numpy as np

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core.state import (
    COL_SEED_MU,
    COL_SEED_SIGMA,
    MAX_TEAM_SIZE,
    MU_LO,
    SIGMA_LO,
)
from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs import get_registry
from analyzer_tpu.obs.retrace import track_jit
from analyzer_tpu.serve.view import RatingsView

logger = get_logger(__name__)

#: Smallest per-tick request bucket — single queries pad to this.
QUERY_BUCKET_FLOOR = 8

#: The ratings gather ladder extends this far past ``max_batch``: one
#: ratings request legitimately carries a page of ids, not one.
RATINGS_ID_FACTOR = 8

#: Conservative-score multiplier: rank by mu - 3*sigma (the "99.7% sure
#: you are at least this good" estimate the reference's trueskill_delta
#: is a delta of, rater.py:149).
CONSERVATIVE_K = 3.0

#: Default tier edges over the conservative score, mu0/sigma0-scale
#: (mu0=1500, sigma0=1000): fresh players sit far negative, converged
#: ones land between 0 and ~2500. Operators tune via
#: ``QueryEngine(tier_edges=)``.
DEFAULT_TIER_EDGES = (
    -2000.0, -1000.0, -500.0, 0.0, 250.0, 500.0, 750.0,
    1000.0, 1250.0, 1500.0, 1750.0, 2000.0, 2500.0,
)

_KINDS = ("ratings", "winprob", "leaderboard", "tiers", "percentile")


class UnknownPlayerError(KeyError):
    """A query named player ids the addressed view has never published."""

    def __init__(self, ids) -> None:
        self.ids = tuple(ids)
        super().__init__(f"unknown player id(s): {', '.join(self.ids)}")

    def __str__(self) -> str:  # KeyError's repr-quoting is noise in HTTP bodies
        return self.args[0]


def query_bucket(n: int, cap: int) -> int:
    """Power-of-two request bucket, floor QUERY_BUCKET_FLOOR, cap
    ``cap`` (the engine's max_batch) — the ONE owner of the per-tick
    shape ladder, shared by execution and warmup."""
    b = max(QUERY_BUCKET_FLOOR, 1 << max(n - 1, 0).bit_length())
    return min(b, max(cap, QUERY_BUCKET_FLOOR))


# -- jitted kernels (one dispatch per kind per tick) ----------------------


@jax.jit
def _gather_rows(table, idx):
    """Whole-row gather for player lookups: [Qb] -> [Qb, 16]."""
    return table[idx]


@partial(jax.jit, static_argnames=("team",))
def _team_stats(table, idx, mask, team: int):
    """Fixed-order float32 sufficient statistics for [Qb] two-team
    matchups: idx/mask are [Qb, 2, T]. Returns (n, s2_sum, mu_diff)
    where priors resolve NaN -> baked seed (rater.py:114-121) and every
    reduction is an explicit team-major, slot-minor add chain — the
    order ``serve/oracle.py`` replays bit-for-bit."""
    rows = table[idx]  # [Qb, 2, T, 16]
    mu_raw = rows[..., MU_LO]
    sg_raw = rows[..., SIGMA_LO]
    unrated = jnp.isnan(mu_raw)
    mu = jnp.where(unrated, rows[..., COL_SEED_MU], mu_raw)
    sg = jnp.where(unrated, rows[..., COL_SEED_SIGMA], sg_raw)
    zero = jnp.zeros(idx.shape[0], mu.dtype)
    n = zero
    s2 = zero
    team_mu = [zero, zero]
    for t in range(2):
        for s in range(team):
            m = mask[:, t, s]
            n = n + jnp.where(m, jnp.float32(1.0), jnp.float32(0.0))
            s2 = s2 + jnp.where(m, sg[:, t, s] * sg[:, t, s], jnp.float32(0.0))
            team_mu[t] = team_mu[t] + jnp.where(
                m, mu[:, t, s], jnp.float32(0.0)
            )
    return n, s2, team_mu[0] - team_mu[1]


def _conservative(mu, sg):
    """mu - 3*sigma in float32 WITHOUT a multiply: ``sg+sg`` is exact
    (power-of-two scale), so ``(sg+sg)+sg`` is the correctly-rounded
    3*sigma — and with no mul feeding the subtract, XLA cannot contract
    the expression into an FMA, whose single rounding would silently
    break the oracle's bit-for-bit replay (``serve/oracle.py``)."""
    return mu - ((sg + sg) + sg)


def _host_conservative(mu, sg) -> np.float32:
    """The host replay of :func:`_conservative` (same rounding order)."""
    mu = np.float32(mu)
    sg = np.float32(sg)
    return np.float32(mu - np.float32(np.float32(sg + sg) + sg))


@partial(jax.jit, static_argnames=("k",))
def _leaderboard(table, k: int):
    """Top-k rows by conservative score mu - 3*sigma (shared column),
    unrated rows excluded via -inf. ``jax.lax.top_k`` breaks ties toward
    the lower row index, matching the oracle's stable sort."""
    mu = table[:, MU_LO]
    score = _conservative(mu, table[:, SIGMA_LO])
    score = jnp.where(jnp.isnan(mu), -jnp.inf, score)
    return jax.lax.top_k(score, k)


@jax.jit
def _tier_counts(table, edges):
    """(count of rated rows with score >= edge_i, rated total). Integer
    counts of exact float32 comparisons — bit-free of rounding by
    construction."""
    mu = table[:, MU_LO]
    score = _conservative(mu, table[:, SIGMA_LO])
    rated = ~jnp.isnan(mu)
    ge = (score[None, :] >= edges[:, None]) & rated[None, :]
    return ge.sum(axis=1).astype(jnp.int32), rated.sum().astype(jnp.int32)


@jax.jit
def _count_below(table, values):
    """For each query value: how many rated rows score strictly below it
    (the percentile numerator), plus the rated total."""
    mu = table[:, MU_LO]
    score = _conservative(mu, table[:, SIGMA_LO])
    rated = ~jnp.isnan(mu)
    below = (score[None, :] < values[:, None]) & rated[None, :]
    return below.sum(axis=1).astype(jnp.int32), rated.sum().astype(jnp.int32)


track_jit("serve._gather_rows", _gather_rows)
track_jit("serve._team_stats", _team_stats)
track_jit("serve._leaderboard", _leaderboard)
track_jit("serve._tier_counts", _tier_counts)
track_jit("serve._count_below", _count_below)


def merge_topk_candidates(entries, k: int | None = None) -> list:
    """THE serving plane's boundary-safe top-k merge, exported so every
    tier that stitches partial top-k lists — the sharded engine's
    per-shard merge here, the fabric's per-HOST merge
    (:mod:`analyzer_tpu.fabric.route`) — uses one pinned key.

    ``entries`` are ``(score, global_row, payload)`` triples; the result
    is sorted by ``(-score, global_row)`` — ``lax.top_k``'s descending
    order with low-index tie-break on the UNSHARDED table, which makes
    ties spanning shard (and host) boundaries land exactly where the
    single-device plane puts them — truncated to ``k`` when given.
    Float negation is exact, so the key loses no bits."""
    cand = sorted(entries, key=lambda c: (-c[0], c[1]))
    return cand if k is None else cand[:k]


def _finish_winprob(n, s2, mu_diff, beta2: float):
    """Host float64 finish of P(team A wins) = Phi(mu_diff / c) from the
    kernel's float32 statistics, rounded once to float32. Pure
    double-precision libm — the oracle replays it exactly."""
    out = np.empty(len(n), np.float32)
    for i in range(len(n)):
        c2 = max(float(s2[i]) + float(n[i]) * beta2, 1e-20)
        t = float(mu_diff[i]) / math.sqrt(c2)
        out[i] = np.float32(0.5 * math.erfc(-t / math.sqrt(2.0)))
    return out


def _finish_quality(n, s2, mu_diff, beta2: float):
    """Host float64 finish of the draw-probability match quality
    (ops.trueskill.quality's closed form, no tau inflation)."""
    out = np.empty(len(n), np.float32)
    for i in range(len(n)):
        nb = float(n[i]) * beta2
        denom = max(nb + float(s2[i]), 1e-20)
        d = float(mu_diff[i])
        out[i] = np.float32(
            math.sqrt(nb / denom) * math.exp(-(d * d) / (2.0 * denom))
        )
    return out


class _Pending:
    """One queued request: resolved by the tick that executes it. The
    submit/done stamps give the client-observed latency the serve bench
    reports (queue wait + microbatch execution)."""

    __slots__ = (
        "kind", "payload", "done", "value", "error", "t_submit", "t_done",
        "audit",
    )

    def __init__(self, kind: str, payload) -> None:
        self.kind = kind
        self.payload = payload
        self.done = threading.Event()
        self.value = None
        self.error: BaseException | None = None
        self.t_submit = time.monotonic()
        self.t_done: float | None = None
        # (auditor, view) when a shadow auditor is attached — set by
        # _execute before the microbatch runs, consumed in resolve().
        self.audit = None

    def resolve(self, value) -> None:
        self.value = value
        # Shadow-audit offer BEFORE done.set(): once a caller observes
        # the response, the sampling decision has already been recorded
        # — the audit's sampled set is synchronous with the traffic, so
        # a drain at any quiesce point sees a deterministic count (the
        # soak's artifact `audit` block relies on exactly this).
        if self.audit is not None:
            auditor, view = self.audit
            auditor.offer(self.kind, self.payload, value, view)
        self.t_done = time.monotonic()
        self.done.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.t_done = time.monotonic()
        self.done.set()

    @property
    def latency_s(self) -> float | None:
        return None if self.t_done is None else self.t_done - self.t_submit

    def result(self, timeout: float | None = 30.0):
        if not self.done.wait(timeout):
            raise TimeoutError(f"{self.kind} query not served in {timeout}s")
        if self.error is not None:
            raise self.error
        return self.value


class QueryEngine:
    """Coalesces concurrent queries into per-tick microbatches.

    ``source`` is a :class:`~analyzer_tpu.serve.view.ViewPublisher` (or
    anything with ``current() -> RatingsView | None``). Two driving
    modes:

      * **threaded** (:meth:`start` — the server / worker wiring): a
        tick thread wakes on submissions, drains the queue, and executes
        one microbatch per kind;
      * **inline** (default — tests, naive baselines): blocking helpers
        execute their own single-request microbatch; ``submit`` +
        :meth:`tick` give a test deterministic coalescing control.

    Every result dict carries ``version`` — the exactly-one published
    version it was computed against.
    """

    def __init__(
        self,
        source,
        cfg: RatingConfig | None = None,
        max_batch: int = 256,
        tick_interval_s: float = 0.001,
        tier_edges=None,
        clock=time.monotonic,
    ) -> None:
        self.source = source
        self.cfg = cfg or RatingConfig()
        self.max_batch = int(max_batch)
        self.tick_interval_s = tick_interval_s
        self.tier_edges = np.asarray(
            tier_edges if tier_edges is not None else DEFAULT_TIER_EDGES,
            np.float32,
        )
        self.clock = clock
        self.queries_total = 0
        # Shadow audit (obs/audit.py): when attached, every successfully
        # resolved response is OFFERED at the end of its microbatch (one
        # seeded hash + a bounded append for the sampled few — the
        # oracle replay itself runs off the hot path in the auditor's
        # drain). Topology-blind: the sharded engine shares _execute.
        self.auditor = None
        self._pending: deque[_Pending] = deque()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self._stop = False
        # Version-keyed result caches (leaderboard / tiers): one entry
        # each — a new publish changes the version and naturally evicts.
        self._lb_cache: tuple[int, int, np.ndarray, np.ndarray] | None = None
        self._tier_cache: tuple[int, list] | None = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "QueryEngine":
        """Starts the tick thread (idempotent)."""
        if self._thread is None:
            self._stop = False
            self._thread = threading.Thread(
                target=self._tick_loop, name="analyzer-ratesrv-tick",
                daemon=True,
            )
            self._thread.start()
        return self

    def close(self) -> None:
        """Stops the tick thread; queued requests fail cleanly."""
        thread, self._thread = self._thread, None
        if thread is None:
            return
        self._stop = True
        self._wake.set()
        thread.join(timeout=5)
        with self._lock:
            stranded = list(self._pending)
            self._pending.clear()
        for req in stranded:
            req.fail(RuntimeError("query engine closed"))

    # -- request API ------------------------------------------------------
    def submit(self, kind: str, payload=None) -> _Pending:
        """Enqueues a request for the next tick (threaded mode) or for an
        explicit :meth:`tick` call, returning the pending handle."""
        if kind not in _KINDS:
            raise ValueError(f"unknown query kind {kind!r}")
        req = _Pending(kind, payload)
        with self._lock:
            self._pending.append(req)
        self._wake.set()
        return req

    def _call(self, kind: str, payload=None):
        if self._thread is not None:
            return self.submit(kind, payload).result()
        req = _Pending(kind, payload)
        self._execute([req])
        return req.result(timeout=0)

    def get_ratings(self, player_ids) -> dict:
        """Rating lookup: shared + per-mode (mu, sigma) for each id."""
        return self._call("ratings", tuple(player_ids))

    def win_probability(self, team_a, team_b) -> dict:
        """P(team_a beats team_b) + match quality for one matchup."""
        return self._call("winprob", (tuple(team_a), tuple(team_b)))

    def leaderboard(self, k: int = 10) -> dict:
        """Top-k rated players by conservative estimate mu - 3*sigma."""
        return self._call("leaderboard", int(k))

    def tier_histogram(self) -> dict:
        """Rated-player counts per conservative-score tier band."""
        return self._call("tiers")

    def percentile(self, score: float) -> dict:
        """Fraction of rated players strictly below ``score``."""
        return self._call("percentile", float(score))

    # -- execution --------------------------------------------------------
    def tick(self) -> int:
        """Drains and executes up to ``max_batch`` queued requests per
        kind; returns how many requests were served. Tests drive this
        directly for deterministic coalescing."""
        with self._lock:
            reqs = list(self._pending)
            self._pending.clear()
        if not reqs:
            return 0
        overflow = self._execute(reqs)
        if overflow:
            with self._lock:
                self._pending.extendleft(reversed(overflow))
            self._wake.set()
        return len(reqs) - len(overflow)

    def _tick_loop(self) -> None:
        while not self._stop:
            self._wake.wait(timeout=0.2)
            self._wake.clear()
            if self._stop:
                return
            try:
                served = self.tick()
            except Exception:  # noqa: BLE001 — a tick crash must not
                # silently kill the serving thread; per-request errors
                # were already routed, so log and keep ticking.
                logger.exception("serve tick failed")
                continue
            if served and self.tick_interval_s:
                # A short lag window lets the next burst of concurrent
                # requests pile up into one microbatch instead of each
                # opening its own tick (Clipper's batching delay).
                time.sleep(self.tick_interval_s)

    def warmup(self, view: RatingsView | None = None) -> int:
        """Compiles every (row-bucket, request-bucket) kernel shape the
        current view can serve, so no production query pays XLA (mirrors
        ``Worker.warmup`` on the write plane). Returns the number of
        kernel shapes visited."""
        view = view or self._current_view()
        shapes = 0
        b = QUERY_BUCKET_FLOOR
        buckets = []
        # The gather ladder runs RATINGS_ID_FACTOR further than the
        # request ladder: one ratings request may carry many ids.
        while b <= max(self.max_batch, QUERY_BUCKET_FLOOR) * RATINGS_ID_FACTOR:
            buckets.append(b)
            b *= 2
        for qb in buckets:
            idx1 = jnp.zeros(qb, jnp.int32)
            _gather_rows(view.table, idx1).block_until_ready()
            if qb > self.max_batch:
                shapes += 1
                continue
            idx2 = jnp.full((qb, 2, MAX_TEAM_SIZE), view.pad_row, jnp.int32)
            mask = jnp.zeros((qb, 2, MAX_TEAM_SIZE), bool)
            jax.block_until_ready(
                _team_stats(view.table, idx2, mask, MAX_TEAM_SIZE)
            )
            vals = jnp.zeros(qb, jnp.float32)
            jax.block_until_ready(_count_below(view.table, vals))
            shapes += 3
        rows = view.table.shape[0]
        k = QUERY_BUCKET_FLOOR
        while True:
            _leaderboard(view.table, min(k, rows))
            shapes += 1
            if k >= rows:
                break
            k *= 2
        jax.block_until_ready(
            _tier_counts(view.table, jnp.asarray(self.tier_edges))
        )
        return shapes + 1

    def _current_view(self) -> RatingsView:
        src = self.source
        view = src.current() if hasattr(src, "current") else src()
        if view is None:
            raise RuntimeError(
                "no ratings view published yet (serve.view readiness)"
            )
        return view

    def _execute(self, reqs: list) -> list:
        """Runs one microbatch per kind against ONE view snapshot.
        Returns requests deferred to the next tick (per-kind max_batch
        overflow). Request-level failures (unknown ids, bad payloads)
        resolve that request's error without touching its batchmates."""
        try:
            view = self._current_view()
        except Exception as err:  # noqa: BLE001 — no view / dead source:
            # every request fails cleanly rather than hanging forever.
            for req in reqs:
                req.fail(err)
            return []
        reg = get_registry()
        reg.gauge("serve.view_age_seconds").set(round(view.age_s, 3))
        by_kind: dict[str, list] = {}
        overflow: list = []
        id_cap = self.max_batch * RATINGS_ID_FACTOR
        ids_in_batch = 0
        for req in reqs:
            group = by_kind.setdefault(req.kind, [])
            if req.kind == "ratings":
                # Ratings coalesce by TOTAL id count (one request can
                # carry a page of ids); the gather bucket ladder caps it.
                n_ids = max(len(req.payload), 1)
                if len(group) >= self.max_batch or (
                    group and ids_in_batch + n_ids > id_cap
                ):
                    overflow.append(req)
                else:
                    group.append(req)
                    ids_in_batch += n_ids
            elif len(group) >= self.max_batch:
                overflow.append(req)
            else:
                group.append(req)
        for kind, group in by_kind.items():
            reg.counter("serve.queries_total").add(len(group))
            reg.counter("serve.queries_total", kind=kind).add(len(group))
            self.queries_total += len(group)
            if self.auditor is not None:
                for req in group:
                    req.audit = (self.auditor, view)
            try:
                getattr(self, "_run_" + kind)(view, group)
            except Exception as err:  # noqa: BLE001 — a kernel-level
                # failure answers the whole microbatch; the engine and
                # its other kinds keep serving.
                logger.exception("serve microbatch %s failed", kind)
                for req in group:
                    if not req.done.is_set():
                        req.fail(err)
        return overflow

    @staticmethod
    def _resolve_or_fail(view: RatingsView, ids, req: _Pending):
        rows = []
        missing = []
        for pid in ids:
            row = view.resolve(pid)
            if row is None:
                missing.append(pid)
            else:
                rows.append(row)
        if missing:
            req.fail(UnknownPlayerError(missing))
            return None
        return rows

    def _observe_occupancy(self, kind: str, filled: int, bucket: int) -> None:
        get_registry().histogram(
            "serve.microbatch_occupancy", kind=kind
        ).observe(filled / bucket if bucket else 0.0)

    # -- per-kind microbatches -------------------------------------------
    def _ratings_gather(self, view, flat: list) -> np.ndarray:
        """ONE padded whole-row gather for the tick's coalesced ids —
        the single-device dispatch. The sharded engine overrides this
        with per-shard routed gathers; everything above (coalescing) and
        below (response formatting) is topology-blind."""
        qb = query_bucket(
            max(len(flat), 1), self.max_batch * RATINGS_ID_FACTOR
        )
        if len(flat) > qb:
            raise ValueError(
                f"{len(flat)} ids in one ratings microbatch exceeds the "
                f"engine cap {qb}; split the request"
            )
        idx = np.full(qb, view.pad_row, np.int32)
        if flat:
            idx[: len(flat)] = flat
        self._observe_occupancy("ratings", len(flat), qb)
        return np.asarray(_gather_rows(view.table, jnp.asarray(idx)))

    def _run_ratings(self, view, group: list) -> None:
        """All requests' ids coalesce into ONE padded gather."""
        flat: list[int] = []
        spans: list = []  # (req, start, ids, unknown)
        for req in group:
            ids = req.payload
            start = len(flat)
            known = []
            unknown = []
            for pid in ids:
                row = view.resolve(pid)
                if row is None:
                    unknown.append(pid)
                else:
                    known.append((pid, row))
                    flat.append(row)
            spans.append((req, start, known, unknown))
        rows = self._ratings_gather(view, flat)
        for req, start, known, unknown in spans:
            out = []
            for j, (pid, _row) in enumerate(known):
                r = rows[start + j]
                mu, sg = float(r[MU_LO]), float(r[SIGMA_LO])
                rated = not math.isnan(mu)
                out.append({
                    "id": pid,
                    "rated": rated,
                    "mu": mu if rated else None,
                    "sigma": sg if rated else None,
                    "conservative": (
                        float(_host_conservative(r[MU_LO], r[SIGMA_LO]))
                        if rated else None
                    ),
                    "seed_mu": float(r[COL_SEED_MU]),
                    "seed_sigma": float(r[COL_SEED_SIGMA]),
                })
            req.resolve({
                "version": view.version, "ratings": out, "unknown": unknown,
            })

    def _winprob_stats(self, view, live: list):
        """(n, s2, mu_diff) float32 arrays (length >= len(live)) for the
        tick's matchups — one ``_team_stats`` dispatch on the
        single-device plane. The sharded engine overrides this with
        routed per-shard row gathers plus the SAME fixed-order float32
        reduction replayed on host: every operation is a
        correctly-rounded float32 primitive in the kernel's pinned
        team-major slot-minor order, so the bits cannot differ."""
        t = MAX_TEAM_SIZE
        q = len(live)
        qb = query_bucket(q, self.max_batch)
        idx = np.full((qb, 2, t), view.pad_row, np.int32)
        mask = np.zeros((qb, 2, t), bool)
        for i, (_req, rows_a, rows_b) in enumerate(live):
            idx[i, 0, : len(rows_a)] = rows_a
            idx[i, 1, : len(rows_b)] = rows_b
            mask[i, 0, : len(rows_a)] = True
            mask[i, 1, : len(rows_b)] = True
        self._observe_occupancy("winprob", q, qb)
        return tuple(
            np.asarray(x)
            for x in _team_stats(
                view.table, jnp.asarray(idx), jnp.asarray(mask), t
            )
        )

    def _run_winprob(self, view, group: list) -> None:
        """[Q, 2, T] matchups -> one _team_stats dispatch + host finish."""
        t = MAX_TEAM_SIZE
        live: list = []
        for req in group:
            a, b = req.payload
            if not (1 <= len(a) <= t and 1 <= len(b) <= t):
                req.fail(ValueError(
                    f"teams must have 1..{t} players (got {len(a)} vs "
                    f"{len(b)})"
                ))
                continue
            rows_a = self._resolve_or_fail(view, a, req)
            if rows_a is None:
                continue
            rows_b = self._resolve_or_fail(view, b, req)
            if rows_b is None:
                continue
            live.append((req, rows_a, rows_b))
        if not live:
            return
        q = len(live)
        n, s2, mu_diff = self._winprob_stats(view, live)
        beta2 = self.cfg.beta2
        p = _finish_winprob(n[:q], s2[:q], mu_diff[:q], beta2)
        quality = _finish_quality(n[:q], s2[:q], mu_diff[:q], beta2)
        for i, (req, _ra, _rb) in enumerate(live):
            req.resolve({
                "version": view.version,
                "p_a": float(p[i]),
                "quality": float(quality[i]),
            })

    def _leaderboard_rows(self, view, k: int):
        """(scores, rows) for the top-k_bucket, version-keyed cache."""
        rows_total = view.table.shape[0]
        kb = min(query_bucket(k, rows_total), rows_total)
        cached = self._lb_cache
        if cached is not None and cached[0] == view.version and cached[1] >= kb:
            get_registry().counter("serve.leaderboard_cache_hits_total").add(1)
            return cached[2], cached[3]
        vals, idx = _leaderboard(view.table, kb)
        vals, idx = np.asarray(vals), np.asarray(idx)
        self._lb_cache = (view.version, kb, vals, idx)
        return vals, idx

    def _leader_rows(self, view, rows_idx: list) -> np.ndarray:
        """``[len(rows_idx), 16]`` float32 response rows for the winning
        GLOBAL rows — a host-table slice here (the single-device host
        mirror is one cached fetch per version). The sharded engine
        overrides this with routed per-shard gathers so leaderboard
        formatting never reassembles a cross-shard host table on the
        serving path (GL029)."""
        host = view.host_table()
        return host[rows_idx]

    def _run_leaderboard(self, view, group: list) -> None:
        kmax = max(req.payload for req in group)
        self._observe_occupancy("leaderboard", len(group), len(group))
        vals, idx = self._leaderboard_rows(view, kmax)
        cut = 0
        while cut < min(kmax, len(vals)) and math.isfinite(vals[cut]):
            cut += 1  # the -inf tail = fewer than k rated players
        rows_host = self._leader_rows(view, [int(r) for r in idx[:cut]])
        for req in group:
            k = req.payload
            leaders = []
            for rank in range(min(k, cut)):
                row = int(idx[rank])
                leaders.append({
                    "rank": rank + 1,
                    "id": view.id_of(row),
                    "mu": float(rows_host[rank, MU_LO]),
                    "sigma": float(rows_host[rank, SIGMA_LO]),
                    "conservative": float(vals[rank]),
                })
            req.resolve({"version": view.version, "leaders": leaders})

    def _tier_ge(self, view) -> tuple[list, int]:
        """(>= edge counts, rated total) — one device dispatch here; the
        sharded engine sums per-shard partial counts on host (integer
        counts of exact float32 comparisons: the sum order is free)."""
        ge, rated = _tier_counts(view.table, jnp.asarray(self.tier_edges))
        return [int(x) for x in np.asarray(ge)], int(rated)

    def _run_tiers(self, view, group: list) -> None:
        self._observe_occupancy("tiers", len(group), len(group))
        cached = self._tier_cache
        if cached is not None and cached[0] == view.version:
            get_registry().counter("serve.tier_cache_hits_total").add(1)
            value = cached[1]
        else:
            ge, rated = self._tier_ge(view)
            counts = [rated - ge[0]]
            counts += [ge[i] - ge[i + 1] for i in range(len(ge) - 1)]
            counts.append(ge[-1])
            value = {
                "edges": [float(e) for e in self.tier_edges],
                "counts": counts,
                "rated": rated,
            }
            self._tier_cache = (view.version, value)
        for req in group:
            req.resolve({"version": view.version, **value})

    def _percentile_counts(self, view, vals: np.ndarray):
        """(below counts, rated total) for the padded query values — one
        dispatch here, per-shard partial counts summed on host in the
        sharded engine (exact integers)."""
        below, rated = _count_below(view.table, jnp.asarray(vals))
        return np.asarray(below), int(rated)

    def _run_percentile(self, view, group: list) -> None:
        q = len(group)
        qb = query_bucket(q, self.max_batch)
        vals = np.zeros(qb, np.float32)
        for i, req in enumerate(group):
            vals[i] = req.payload
        self._observe_occupancy("percentile", q, qb)
        below, rated = self._percentile_counts(view, vals)
        for i, req in enumerate(group):
            req.resolve({
                "version": view.version,
                "score": float(np.float32(req.payload)),
                "below": int(below[i]),
                "rated": rated,
                "percentile": (int(below[i]) / rated) if rated else None,
            })

    # -- naive baseline ---------------------------------------------------
    def query_now(self, kind: str, payload=None):
        """The NAIVE one-query-per-dispatch path: executes a single
        request immediately on the calling thread with no coalescing —
        the baseline ``experiments/serve_bench.py`` measures the
        microbatched engine against. Same kernels, same buckets, one
        device dispatch per call."""
        req = _Pending(kind, payload)
        self._execute([req])
        return req.result(timeout=0)

    def stats(self) -> dict:
        """The serve keys Worker.stats() re-exports."""
        src = self.source
        view = src.current() if hasattr(src, "current") else src()
        return {
            "view_version": None if view is None else view.version,
            "view_age_s": (
                None if view is None else round(view.age_s, 3)
            ),
            "queries_total": self.queries_total,
        }


@runtime_checkable
class ServePlane(Protocol):
    """The topology-blind serving surface: everything above the engine
    — ``serve/server.py``'s ``/v1/*`` routes, the worker's serve wiring,
    ``loadgen``'s ServeClient, ``cli serve`` — programs against THIS,
    so the single-device :class:`QueryEngine` and the mesh-backed
    :class:`ShardedQueryEngine` interchange without a caller edit
    (``docs/serving.md`` "Sharded plane")."""

    max_batch: int

    def start(self): ...

    def close(self) -> None: ...

    def warmup(self, view=None) -> int: ...

    def get_ratings(self, player_ids) -> dict: ...

    def win_probability(self, team_a, team_b) -> dict: ...

    def leaderboard(self, k: int = 10) -> dict: ...

    def tier_histogram(self) -> dict: ...

    def percentile(self, score: float) -> dict: ...

    def stats(self) -> dict: ...


#: Mesh axis name for the serve plane's all-gather top-k variant.
SHARD_AXIS = "shard"


class ShardedQueryEngine(QueryEngine):
    """The sharded plane's engine: point lookups route by
    player-id -> shard (the mesh's interleaved layout,
    ``serve/view.py:shard_of_row``) and coalesce into PER-SHARD jitted
    microbatches on the same pow2 bucket ladder; leaderboards run
    per-shard ``lax.top_k`` + a host merge of the S·k candidates; tier
    histograms and percentiles sum per-shard partial counts on host
    (exact integers). ``source`` is a
    :class:`~analyzer_tpu.serve.view.ShardedViewPublisher`.

    Bit-identity contract (pinned by tests/test_serve_sharded.py):
    every response equals the single-device :class:`QueryEngine`'s and
    the pure-Python oracle's, bit for bit — gathers move identical
    float32 rows, the winprob reduction replays the kernel's pinned
    float32 order on host, the leaderboard merge key
    ``(-score, global_row)`` reproduces ``lax.top_k``'s tie-break on
    the unsharded table, and count sums are integer-exact.

    Shard tables share ONE local row bucket (``ShardedViewPublisher``),
    so each kernel compiles once per (table bucket, request bucket) and
    serves every shard — :meth:`warmup` walks all shards (a no-op after
    the first on a single device; one compile per device on a spread
    plane) and steady state compiles NOTHING per shard.

    ``all_gather_topk=True`` (the rig flag) replaces the S top-k
    dispatches with ONE ``shard_map``'d call over a serve mesh: each
    device computes its shard's top-k and ``all_gather``s the
    candidates, the same host merge finishing — bit-identical by
    construction, one dispatch instead of S (``docs/serving.md`` on
    when to flip it)."""

    def __init__(
        self,
        source,
        cfg: RatingConfig | None = None,
        max_batch: int = 256,
        tick_interval_s: float = 0.001,
        tier_edges=None,
        clock=time.monotonic,
        all_gather_topk: bool = False,
    ) -> None:
        super().__init__(
            source,
            cfg=cfg,
            max_batch=max_batch,
            tick_interval_s=tick_interval_s,
            tier_edges=tier_edges,
            clock=clock,
        )
        self.all_gather_topk = bool(all_gather_topk)
        # Winprob flattens up to max_batch * 2T ids through the routed
        # gather — extend the gather ladder to cover whichever of the
        # two coalescing caps is larger.
        self._gather_cap = self.max_batch * max(
            RATINGS_ID_FACTOR, 2 * MAX_TEAM_SIZE
        )
        self._ag_mesh = None
        self._ag_fns: dict = {}
        self._stack_cache = None  # (version, [S, A+1, 16] sharded stack)

    # -- routed gathers ---------------------------------------------------
    def _sharded_gather(self, view, flat: list) -> np.ndarray:
        """Whole-row gather for GLOBAL rows ``flat``, routed by owner
        shard: one padded ``_gather_rows`` microbatch per shard that
        owns any of the tick's rows, results scattered back into
        request order. The cross-shard 'gather' is per-row response
        assembly on host — never a whole-table transfer (GL029)."""
        if len(flat) > self._gather_cap:
            raise ValueError(
                f"{len(flat)} ids in one routed microbatch exceeds the "
                f"engine cap {self._gather_cap}; split the request"
            )
        n_shards = view.n_shards
        out = np.empty((len(flat), view.shards[0].table.shape[1]), np.float32)
        per: list[list] = [[] for _ in range(n_shards)]
        for pos, row in enumerate(flat):
            per[row % n_shards].append((pos, row // n_shards))
        reg = get_registry()
        for d, pairs in enumerate(per):
            if not pairs:
                continue
            shard = view.shards[d]
            qb = query_bucket(len(pairs), self._gather_cap)
            idx = np.full(qb, shard.pad_row, np.int32)
            idx[: len(pairs)] = [loc for _pos, loc in pairs]
            reg.counter("serve.shard.queries_total", shard=str(d)).add(
                len(pairs)
            )
            rows = np.asarray(_gather_rows(shard.table, jnp.asarray(idx)))
            out[[pos for pos, _loc in pairs]] = rows[: len(pairs)]
        return out

    def _ratings_gather(self, view, flat: list) -> np.ndarray:
        qb = query_bucket(
            max(len(flat), 1), self.max_batch * RATINGS_ID_FACTOR
        )
        if len(flat) > qb:
            raise ValueError(
                f"{len(flat)} ids in one ratings microbatch exceeds the "
                f"engine cap {qb}; split the request"
            )
        self._observe_occupancy("ratings", len(flat), qb)
        return self._sharded_gather(view, flat)

    def _winprob_stats(self, view, live: list):
        """Routed row gathers + the kernel's fixed-order float32 team
        reduction replayed on host. Every add/multiply below is a
        correctly-rounded ``np.float32`` primitive in ``_team_stats``'
        exact team-major slot-minor order, so the statistics — and the
        float64 finish downstream — carry the same bits as the
        single-device dispatch (the oracle's argument, applied on the
        serving path itself)."""
        q = len(live)
        qb = query_bucket(q, self.max_batch)
        self._observe_occupancy("winprob", q, qb)
        flat: list[int] = []
        for _req, rows_a, rows_b in live:
            flat.extend(rows_a)
            flat.extend(rows_b)
        rows = self._sharded_gather(view, flat)
        one = np.float32(1.0)
        n = np.zeros(q, np.float32)
        s2 = np.zeros(q, np.float32)
        mu_diff = np.zeros(q, np.float32)
        pos = 0
        for i, (_req, rows_a, rows_b) in enumerate(live):
            acc_n = np.float32(0.0)
            acc_s2 = np.float32(0.0)
            team_mu = [np.float32(0.0), np.float32(0.0)]
            for t, team_rows in enumerate((rows_a, rows_b)):
                for _row in team_rows:
                    r = rows[pos]
                    pos += 1
                    mu = np.float32(r[MU_LO])
                    sg = np.float32(r[SIGMA_LO])
                    if math.isnan(float(mu)):
                        mu = np.float32(r[COL_SEED_MU])
                        sg = np.float32(r[COL_SEED_SIGMA])
                    acc_n = np.float32(acc_n + one)
                    acc_s2 = np.float32(acc_s2 + np.float32(sg * sg))
                    team_mu[t] = np.float32(team_mu[t] + mu)
            n[i] = acc_n
            s2[i] = acc_s2
            mu_diff[i] = np.float32(team_mu[0] - team_mu[1])
        return n, s2, mu_diff

    # -- distributed top-k ------------------------------------------------
    def _shard_topk(self, view, kb: int):
        """(vals, local_idx) ``[S, kb]`` — per-shard ``lax.top_k``
        dispatches, or the one-dispatch all-gather variant behind the
        rig flag."""
        reg = get_registry()
        if self.all_gather_topk:
            return self._allgather_topk(view, kb)
        n_shards = view.n_shards
        vals = np.empty((n_shards, kb), np.float32)
        idx = np.empty((n_shards, kb), np.int64)
        for d, shard in enumerate(view.shards):
            v, i = _leaderboard(shard.table, kb)
            vals[d] = np.asarray(v)
            idx[d] = np.asarray(i)
            reg.counter("serve.shard.queries_total", shard=str(d)).add(1)
        return vals, idx

    def _leaderboard_rows(self, view, k: int):
        """Per-shard top-k_bucket + host merge of the S·k candidates.
        The merge key ``(-score, global_row)`` with global row
        ``local*S + d`` reproduces ``lax.top_k``'s descending order and
        low-index tie-break on the unsharded table exactly — including
        ties that span shard boundaries."""
        rows_local = view.shards[0].table.shape[0]
        kb = min(query_bucket(k, rows_local), rows_local)
        cached = self._lb_cache
        if cached is not None and cached[0] == view.version and cached[1] >= kb:
            get_registry().counter("serve.leaderboard_cache_hits_total").add(1)
            kb, vals_s, idx_s = cached[1], cached[2], cached[3]
        else:
            vals_s, idx_s = self._shard_topk(view, kb)
            self._lb_cache = (view.version, kb, vals_s, idx_s)
        n_shards = view.n_shards
        reg = get_registry()
        reg.counter("serve.shard.merges_total").add(1)
        reg.counter("serve.shard.merge_candidates_total").add(n_shards * kb)
        entries = []
        for d in range(n_shards):
            for j in range(kb):
                v = float(vals_s[d, j])
                if not math.isfinite(v):
                    break  # the shard's rated rows ran out (-inf tail)
                entries.append((v, int(idx_s[d, j]) * n_shards + d, vals_s[d, j]))
        merged = merge_topk_candidates(entries)
        vals = np.array([c[2] for c in merged], np.float32)
        idx = np.array([c[1] for c in merged], np.int64)
        return vals, idx

    def _leader_rows(self, view, rows_idx: list) -> np.ndarray:
        """Routed per-shard gathers for the winning rows (chunked to the
        gather ladder's cap) — the response rows carry the same bits the
        host-table slice would, without a cross-shard table reassembly
        on the serving path."""
        width = view.shards[0].table.shape[1]
        out = np.empty((len(rows_idx), width), np.float32)
        for lo in range(0, len(rows_idx), self._gather_cap):
            chunk = list(rows_idx[lo : lo + self._gather_cap])
            out[lo : lo + len(chunk)] = self._sharded_gather(view, chunk)
        return out

    def _serve_mesh(self, n_shards: int):
        from jax.sharding import Mesh

        if self._ag_mesh is None or self._ag_mesh.devices.size != n_shards:
            devices = jax.devices()
            if len(devices) < n_shards:
                raise RuntimeError(
                    f"all_gather_topk wants one device per shard "
                    f"({n_shards}); only {len(devices)} available"
                )
            self._ag_mesh = Mesh(np.asarray(devices[:n_shards]), (SHARD_AXIS,))
        return self._ag_mesh

    def _stacked_tables(self, view):
        """Designated merge helper (graftlint GL029): the ``[S, A+1,
        16]`` device stack the all-gather top-k consumes, row-sharded
        one shard per device, built once per published version."""
        from jax.sharding import NamedSharding, PartitionSpec

        cached = self._stack_cache
        if cached is not None and cached[0] == view.version:
            return cached[1]
        host = np.stack([shard.host_table() for shard in view.shards])
        mesh = self._serve_mesh(view.n_shards)
        # graftlint: disable=GL027 — the serve stack is the sharded plane's sanctioned per-shard double buffer (one slice per device)
        stacked = jax.device_put(
            host, NamedSharding(mesh, PartitionSpec(SHARD_AXIS, None, None))
        )
        self._stack_cache = (view.version, stacked)
        return stacked

    def _allgather_fn(self, n_shards: int, kb: int):
        fn = self._ag_fns.get((n_shards, kb))
        if fn is not None:
            return fn
        # jax.shard_map (new) or jax.experimental.shard_map (older
        # builds) — the replication-check kwarg renamed across the move.
        shard_map = getattr(jax, "shard_map", None)
        check_kw = "check_vma"
        if shard_map is None:
            try:
                from jax.experimental.shard_map import shard_map
            except ImportError as err:  # pragma: no cover — ancient jax
                raise RuntimeError(
                    "shard_map unavailable on this jax build; run with "
                    "all_gather_topk=False"
                ) from err
            check_kw = "check_rep"
        from jax.sharding import PartitionSpec as P

        mesh = self._serve_mesh(n_shards)

        def local(tables):  # [1, A+1, 16]: this device's shard slice
            mu = tables[0, :, MU_LO]
            score = _conservative(mu, tables[0, :, SIGMA_LO])
            score = jnp.where(jnp.isnan(mu), -jnp.inf, score)
            v, i = jax.lax.top_k(score, kb)
            gather = lambda x: jax.lax.all_gather(
                x[None], SHARD_AXIS, axis=0, tiled=True
            )
            return gather(v), gather(i)

        # The replication check is off as in parallel/mesh.py: the
        # all_gather output is replicated by construction.
        fn = jax.jit(shard_map(
            local,
            mesh=mesh,
            in_specs=P(SHARD_AXIS, None, None),
            out_specs=(P(), P()),
            **{check_kw: False},
        ))
        self._ag_fns[(n_shards, kb)] = fn
        return fn

    def _allgather_topk(self, view, kb: int):
        stacked = self._stacked_tables(view)
        vals, idx = self._allgather_fn(view.n_shards, kb)(stacked)
        return np.asarray(vals), np.asarray(idx).astype(np.int64)

    # -- per-shard partial counts ----------------------------------------
    def _tier_ge(self, view) -> tuple[list, int]:
        edges = jnp.asarray(self.tier_edges)
        reg = get_registry()
        ge = np.zeros(len(self.tier_edges), np.int64)
        rated = 0
        for d, shard in enumerate(view.shards):
            g, r = _tier_counts(shard.table, edges)
            ge += np.asarray(g, np.int64)
            rated += int(r)
            reg.counter("serve.shard.queries_total", shard=str(d)).add(1)
        return [int(x) for x in ge], rated

    def _percentile_counts(self, view, vals: np.ndarray):
        jvals = jnp.asarray(vals)
        below = np.zeros(len(vals), np.int64)
        rated = 0
        for shard in view.shards:
            b, r = _count_below(shard.table, jvals)
            below += np.asarray(b, np.int64)
            rated += int(r)
        return below, rated

    # -- lifecycle --------------------------------------------------------
    def warmup(self, view=None) -> int:
        """Compiles every (shard-table bucket, request bucket) shape the
        current sharded view can serve. Shard tables share one shape, so
        after the first shard the walk is jit-cache hits — unless the
        plane spreads shards over devices, where each device compiles
        its own executable exactly once. Zero steady-state retraces per
        shard is pinned by tests/test_serve_sharded.py."""
        view = view or self._current_view()
        shapes = 0
        edges = jnp.asarray(self.tier_edges)
        for shard in view.shards:
            table = shard.table
            b = QUERY_BUCKET_FLOOR
            while b <= self._gather_cap:
                _gather_rows(table, jnp.zeros(b, jnp.int32)).block_until_ready()
                shapes += 1
                if b <= self.max_batch:
                    jax.block_until_ready(
                        _count_below(table, jnp.zeros(b, jnp.float32))
                    )
                    shapes += 1
                b *= 2
            rows = table.shape[0]
            k = QUERY_BUCKET_FLOOR
            while True:
                _leaderboard(table, min(k, rows))
                shapes += 1
                if k >= rows:
                    break
                k *= 2
            jax.block_until_ready(_tier_counts(table, edges))
            shapes += 1
        if self.all_gather_topk:
            rows = view.shards[0].table.shape[0]
            k = QUERY_BUCKET_FLOOR
            while True:
                self._allgather_topk(view, min(k, rows))
                shapes += 1
                if k >= rows:
                    break
                k *= 2
        get_registry().gauge("serve.shards").set(view.n_shards)
        return shapes
