"""Response codec for the serve front door: native fast path + counted
python fallback.

:class:`ResponseCodec` renders the four hot ``/v1/*`` response shapes
(ratings, leaderboard, winprob, tiers — the dicts
:class:`~analyzer_tpu.serve.engine.QueryEngine` resolves) to the exact
bytes ``json.dumps(obj, sort_keys=True) + "\\n"`` would produce — the
wire contract every client of the RoutedHTTPServer path already parses.
The fast path packs each response's numeric fields into reusable numpy
slabs and hands them to ``fastjson.cc`` (built on demand via
``native_build.build_and_load``), which formats floats with CPython's
repr algorithm and writes the whole body into a reusable output arena:
no per-response dict-to-str walk on the hot path.

Route discipline: anything the fast path does not recognize — an
unexpected key (a fabric ``versions`` vector, a future field), a
non-float where a float belongs, a string that will not encode — falls
back to the python encoder, bit-identical by construction, and is
COUNTED (``frontdoor.codec_fallbacks_total`` + :attr:`fallbacks`): the
serve bench stamps ``native: false`` when the fallback carried the
phase, and ``cli benchdiff --family serve`` fails a candidate whose
native capture vanished (the ingest/assign gate pattern).

NaN/inf guarantee: a non-finite float raises :class:`ValueError`
instead of encoding — JSON has no NaN/Infinity and the engine never
produces one (unrated rows render null), so a non-finite here is a bug
upstream, not a value to serialize (``json.dumps`` would happily emit
python-only ``NaN`` and break every client).

One codec instance is single-threaded (reusable arenas); the front
door builds one per reader thread.
"""

from __future__ import annotations

import ctypes
import json
from itertools import accumulate as _accumulate

import numpy as np

from analyzer_tpu.obs import get_registry

try:
    from analyzer_tpu.serve import _native_json
except ImportError:  # build/load failed: pure-python route, counted
    _native_json = None

#: True when the native encoder compiled and loaded in this process.
NATIVE = _native_json is not None

# Shape recognition relies on the oracle sorting keys: a dict with
# exactly the expected key SET encodes identically regardless of
# insertion order, so `len(d) == N` plus N successful lookups (KeyError
# falls back) proves the set without building comparison tuples.


class _Fallback(Exception):
    """Internal: this response is not fast-path-shaped."""


def _dumps(obj) -> bytes:
    # The codec's designated python fallback — the json.dumps oracle the
    # native path is differential-pinned against (graftlint GL049
    # exempts this module; every other serve/ hot path must come here
    # or go native).
    return (json.dumps(obj, sort_keys=True) + "\n").encode("utf-8")


def _float(x) -> float:
    if type(x) is not float:
        raise _Fallback
    return x


def _int(x) -> int:
    if type(x) is not int:
        raise _Fallback
    return x


class ResponseCodec:
    """Encodes serve responses to wire bytes; see the module docstring.

    :attr:`native` is this instance's route (False when the extension
    failed to build); :attr:`encodes`/:attr:`fallbacks` count traffic
    for the bench block's ``native`` flag.
    """

    def __init__(self, arena_bytes: int = 1 << 16) -> None:
        self.native = NATIVE
        self.encodes = 0
        self.fallbacks = 0
        if NATIVE:
            self._out = ctypes.create_string_buffer(arena_bytes)
            self._vals = np.zeros((256, 5), np.float64)
            self._vals3 = np.zeros((256, 3), np.float64)
            self._flags = np.zeros(256, np.uint8)
            self._ranks = np.zeros(256, np.int64)
            self._off = np.zeros(257, np.int64)
            self._unk_off = np.zeros(257, np.int64)
            self._counts = np.zeros(64, np.int64)
            self._edges = np.zeros(64, np.float64)

    # -- arena plumbing ---------------------------------------------------
    def _grow_rows(self, n: int) -> None:
        cap = len(self._flags)
        while cap < n:
            cap *= 2
        if cap != len(self._flags):
            self._vals = np.zeros((cap, 5), np.float64)
            self._vals3 = np.zeros((cap, 3), np.float64)
            self._flags = np.zeros(cap, np.uint8)
            self._ranks = np.zeros(cap, np.int64)
            self._off = np.zeros(cap + 1, np.int64)

    def _pack_ids(self, ids, off: np.ndarray) -> bytes:
        n = len(ids)
        try:
            blob = "".join(ids).encode("utf-8")
        except (TypeError, UnicodeEncodeError) as err:  # non-str / lone
            raise _Fallback from err                    # surrogates
        lens = list(map(len, ids))
        if len(blob) == sum(lens):  # pure ASCII: char offsets == bytes
            off[0] = 0
            off[1:n + 1] = list(_accumulate(lens))
            return blob
        pos = 0
        for i, s in enumerate(ids):
            off[i] = pos
            pos += len(s.encode("utf-8"))
        off[n] = pos
        return blob

    def _call(self, fn, *args) -> bytes:
        """One encoder call with grow-and-retry on arena overflow."""
        while True:
            n = fn(*args, self._out, len(self._out))
            if n >= 0:
                return self._out.raw[:n]
            if n == -2:
                raise ValueError(
                    "non-finite float in a serve response — JSON has no "
                    "NaN/Infinity and the engine never emits one"
                )
            if n == -3:
                raise _Fallback
            self._out = ctypes.create_string_buffer(len(self._out) * 2)

    # -- public surface ---------------------------------------------------
    def encode(self, kind: str, obj: dict) -> bytes:
        """``json.dumps(obj, sort_keys=True) + "\\n"`` as UTF-8 bytes,
        natively when ``obj`` matches the engine's ``kind`` shape."""
        self.encodes += 1
        if self.native:
            try:
                return getattr(self, "_encode_" + kind)(obj)
            except (_Fallback, KeyError, TypeError, AttributeError):
                pass  # not fast-path-shaped: counted python route
        self.fallbacks += 1
        get_registry().counter("frontdoor.codec_fallbacks_total").add(1)
        return _dumps(obj)

    # -- per-shape fast paths ---------------------------------------------
    def _encode_ratings(self, obj: dict) -> bytes:
        if len(obj) != 3:
            raise _Fallback
        version = _int(obj["version"])
        entries = obj["ratings"]
        unknown = obj["unknown"]
        if type(entries) is not list or type(unknown) is not list:
            raise _Fallback
        n = len(entries)
        self._grow_rows(n)
        flags_l = []
        rows = []
        for e in entries:
            if len(e) != 7:
                raise _Fallback
            rated = e["rated"]
            seed_mu = e["seed_mu"]
            seed_sigma = e["seed_sigma"]
            if type(seed_mu) is not float or type(seed_sigma) is not float:
                raise _Fallback
            if rated is True:
                mu, sg, cons = e["mu"], e["sigma"], e["conservative"]
                if (type(mu) is not float or type(sg) is not float
                        or type(cons) is not float):
                    raise _Fallback
                flags_l.append(1)
                rows.append((mu, sg, cons, seed_mu, seed_sigma))
            elif rated is False:
                if (e["mu"] is not None or e["sigma"] is not None
                        or e["conservative"] is not None):
                    raise _Fallback
                flags_l.append(0)
                rows.append((0.0, 0.0, 0.0, seed_mu, seed_sigma))
            else:
                raise _Fallback
        if n:
            self._vals[:n] = rows
            self._flags[:n] = flags_l
        blob = self._pack_ids([e["id"] for e in entries], self._off)
        m = len(unknown)
        if m + 1 > len(self._unk_off):
            self._unk_off = np.zeros(m + 1, np.int64)
        unk_blob = self._pack_ids(unknown, self._unk_off)
        return self._call(
            _native_json.lib.fj_encode_ratings,
            n, blob, _p_i64(self._off), _p_u8(self._flags),
            _p_f64(self._vals), m, unk_blob, _p_i64(self._unk_off), version,
        )

    def _encode_leaderboard(self, obj: dict) -> bytes:
        if len(obj) != 2:
            raise _Fallback
        version = _int(obj["version"])
        leaders = obj["leaders"]
        if type(leaders) is not list:
            raise _Fallback
        n = len(leaders)
        self._grow_rows(n)
        rows = []
        ranks_l = []
        ids = []
        for e in leaders:
            if len(e) != 5:
                raise _Fallback
            mu, sg, cons, r = e["mu"], e["sigma"], e["conservative"], e["rank"]
            if not (type(mu) is float and type(sg) is float
                    and type(cons) is float and type(r) is int):
                raise _Fallback
            rows.append((mu, sg, cons))
            ranks_l.append(r)
            ids.append(e["id"])
        if n:
            self._vals3[:n] = rows
            self._ranks[:n] = ranks_l
        blob = self._pack_ids(ids, self._off)
        return self._call(
            _native_json.lib.fj_encode_leaderboard,
            n, _p_i64(self._ranks), blob, _p_i64(self._off),
            _p_f64(self._vals3), version,
        )

    def _encode_winprob(self, obj: dict) -> bytes:
        if len(obj) != 3:
            raise _Fallback
        return self._call(
            _native_json.lib.fj_encode_winprob,
            _float(obj["p_a"]), _float(obj["quality"]),
            _int(obj["version"]),
        )

    def _encode_tiers(self, obj: dict) -> bytes:
        if len(obj) == 4:
            has_score = 0
        elif len(obj) == 7:
            has_score = 1
        else:
            raise _Fallback
        edges = obj["edges"]
        counts = obj["counts"]
        if type(edges) is not list or type(counts) is not list:
            raise _Fallback
        ne, nc = len(edges), len(counts)
        if ne > len(self._edges) or nc > len(self._counts):
            self._edges = np.zeros(max(ne, len(self._edges) * 2), np.float64)
            self._counts = np.zeros(max(nc, len(self._counts) * 2), np.int64)
        for e in edges:
            if type(e) is not float:
                raise _Fallback
        for c in counts:
            if type(c) is not int:
                raise _Fallback
        if ne:
            self._edges[:ne] = edges
        if nc:
            self._counts[:nc] = counts
        score = below = 0
        has_pct = 0
        pct = 0.0
        if has_score:
            score = _float(obj["score"])
            below = _int(obj["below"])
            if obj["percentile"] is not None:
                has_pct = 1
                pct = _float(obj["percentile"])
        return self._call(
            _native_json.lib.fj_encode_tiers,
            _p_f64(self._edges), ne, _p_i64(self._counts), nc,
            _int(obj["rated"]), _int(obj["version"]),
            has_score, float(score), int(below), has_pct, pct,
        )


def _p_f64(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def _p_i64(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _p_u8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
