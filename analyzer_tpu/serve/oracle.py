"""Pure-Python oracle for the serving plane's query results.

The parity contract (tests/test_serve.py, ISSUE 4 acceptance): for any
published view, leaderboard, tier histogram, percentile, win
probability and quality computed here must equal the
:class:`~analyzer_tpu.serve.engine.QueryEngine`'s responses
**bit-for-bit**. That is possible — not just approximately true —
because the engine splits every query into

  * device work that is IEEE-exact and order-pinned: row gathers,
    NaN→seed selects, comparisons, and float32 team reductions written
    as explicit team-major slot-minor add chains (XLA does not
    reassociate a written dependency chain), every operation a
    correctly-rounded float32 primitive this module replays with
    ``np.float32`` scalars in the same order;
  * a host float64 finish for the transcendentals (Phi via
    ``math.erfc``, quality's ``sqrt``·``exp``), rounded once to float32
    — plain double libm, identical here and there.

Host-side and loop-shaped by design; used only by tests and never
imported by the serving path (mirroring ``ops/oracle.py``'s role for
the rating kernels).

All functions take a HOST table — ``RatingsView.host_table()`` — in the
packed ``[alloc+1, 16]`` layout of :mod:`analyzer_tpu.core.state`.
"""

from __future__ import annotations

import math

import numpy as np

from analyzer_tpu.core.state import (
    COL_SEED_MU,
    COL_SEED_SIGMA,
    MU_LO,
    SIGMA_LO,
)

_CONSERVATIVE_K = np.float32(3.0)  # documented rank metric: mu - 3*sigma


def resolve_prior(table: np.ndarray, row: int):
    """(mu, sigma) float32 with the NaN -> baked-seed resolution the
    kernels apply (rater.py:114-121)."""
    mu = np.float32(table[row, MU_LO])
    sg = np.float32(table[row, SIGMA_LO])
    if math.isnan(float(mu)):
        return (
            np.float32(table[row, COL_SEED_MU]),
            np.float32(table[row, COL_SEED_SIGMA]),
        )
    return mu, sg


def conservative_score(table: np.ndarray, row: int) -> np.float32:
    """mu - 3*sigma in float32 (shared column; NaN for unrated rows),
    in the engine kernels' FMA-proof rounding order: exact ``sg+sg``,
    one rounding for ``+sg``, one for the subtract."""
    mu = np.float32(table[row, MU_LO])
    sg = np.float32(table[row, SIGMA_LO])
    return np.float32(mu - np.float32(np.float32(sg + sg) + sg))


def team_stats(table: np.ndarray, rows_a, rows_b):
    """The kernel's fixed-order float32 statistics: (n, sigma2_sum,
    mu_diff) accumulated team-major, slot-minor — team A's slots in
    order, then team B's."""
    n = np.float32(0.0)
    s2 = np.float32(0.0)
    team_mu = [np.float32(0.0), np.float32(0.0)]
    for t, rows in enumerate((rows_a, rows_b)):
        for row in rows:
            mu, sg = resolve_prior(table, row)
            n = np.float32(n + np.float32(1.0))
            s2 = np.float32(s2 + np.float32(sg * sg))
            team_mu[t] = np.float32(team_mu[t] + mu)
    return n, s2, np.float32(team_mu[0] - team_mu[1])


def win_probability(table: np.ndarray, rows_a, rows_b, beta2: float) -> np.float32:
    """P(team A wins) with the engine's float64 host finish."""
    n, s2, mu_diff = team_stats(table, rows_a, rows_b)
    c2 = max(float(s2) + float(n) * beta2, 1e-20)
    t = float(mu_diff) / math.sqrt(c2)
    return np.float32(0.5 * math.erfc(-t / math.sqrt(2.0)))


def quality(table: np.ndarray, rows_a, rows_b, beta2: float) -> np.float32:
    """Match quality (draw probability) with the engine's host finish."""
    n, s2, mu_diff = team_stats(table, rows_a, rows_b)
    nb = float(n) * beta2
    denom = max(nb + float(s2), 1e-20)
    d = float(mu_diff)
    return np.float32(math.sqrt(nb / denom) * math.exp(-(d * d) / (2.0 * denom)))


def leaderboard(table: np.ndarray, n_players: int, k: int):
    """Top-k rated rows as (row, conservative_score) — descending score,
    ties broken toward the lower row index (jax.lax.top_k's order,
    replicated with a stable sort)."""
    entries = []
    for row in range(n_players):
        if math.isnan(float(table[row, MU_LO])):
            continue
        entries.append((row, conservative_score(table, row)))
    entries.sort(key=lambda e: (-float(e[1]), e[0]))
    return entries[:k]


def tier_histogram(table: np.ndarray, n_players: int, edges):
    """(counts, rated_total): counts[0] is below edges[0], counts[i]
    covers [edges[i-1], edges[i]), counts[-1] is >= edges[-1] — float32
    comparisons, integer counts."""
    edges32 = [np.float32(e) for e in edges]
    rated = 0
    ge = [0] * len(edges32)
    for row in range(n_players):
        if math.isnan(float(table[row, MU_LO])):
            continue
        rated += 1
        score = conservative_score(table, row)
        for i, e in enumerate(edges32):
            if score >= e:
                ge[i] += 1
    counts = [rated - ge[0]]
    counts += [ge[i] - ge[i + 1] for i in range(len(ge) - 1)]
    counts.append(ge[-1])
    return counts, rated


def percentile(table: np.ndarray, n_players: int, score) -> tuple[int, int]:
    """(rows strictly below ``score``, rated total) — float32 compare."""
    s = np.float32(score)
    below = 0
    rated = 0
    for row in range(n_players):
        if math.isnan(float(table[row, MU_LO])):
            continue
        rated += 1
        if conservative_score(table, row) < s:
            below += 1
    return below, rated
