"""Versioned, snapshot-consistent views of the live rating table.

The write plane commits batches continuously; readers must never observe
a half-committed table. The mechanism is double-buffering at the publish
boundary:

  * the publisher owns a HOST staging table (numpy float32, the same
    ``[P+1, 16]`` packed layout as :mod:`analyzer_tpu.core.state`) that
    only the writer thread mutates;
  * ``publish_*`` materializes a NEW immutable device table from the
    staging buffer (an incremental ``.at[rows].set`` patch of the
    previous view's table when the row bucket is unchanged — one small
    H2D transfer + device scatter — or a full rebuild when the table
    grew a bucket) and swaps the current-view reference in one atomic
    assignment;
  * a reader grabs :meth:`ViewPublisher.current` ONCE per request tick
    and computes everything against that :class:`RatingsView`. The view
    object is frozen: its device table is a jax array nothing donates or
    mutates, its id list and row map only ever APPEND (guarded by the
    view's own ``n_players``), so a view taken at version ``v`` answers
    exactly as the table stood at ``v`` forever, no matter how far the
    writer has advanced.

Publishing never blocks readers and readers never block publishing —
the only lock is writer-side, serializing concurrent publishers.

Row sizing rides the same power-of-two bucket ladder as the write path
(``service.encode.row_bucket``), so the serving kernels see a handful of
table shapes, not one per player-count — the serve half of the package's
zero-steady-state-retrace discipline (``docs/serving.md``).

The SHARDED plane (:class:`ShardedViewPublisher`) applies the same
contract per mesh shard: the table splits by the mesh's interleaved
ownership (global row ``r`` -> shard ``r % S`` at local row ``r // S``,
the :mod:`analyzer_tpu.parallel.mesh` layout invariant), every publish
swaps ONE :class:`ShardedRatingsView` holding all ``S`` per-shard
snapshots under a single monotone version — a reader can never observe
a torn cross-shard version — and per-shard updates ride the same
``.at[rows].set`` patch kernel, so only each shard's touched rows cross
H2D (``docs/serving.md`` "Sharded plane").
"""

from __future__ import annotations

import threading
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from analyzer_tpu.core.state import TABLE_WIDTH
from analyzer_tpu.lint.ownership import thread_role
from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs import get_registry
from analyzer_tpu.obs.retrace import track_jit
from analyzer_tpu.service.encode import row_bucket

logger = get_logger(__name__)

#: Pad bucket ladder floor for the patch scatter's row-count axis.
PATCH_BUCKET_FLOOR = 64


def _pow2_bucket(n: int, floor: int) -> int:
    return max(floor, 1 << max(n - 1, 0).bit_length())


def shard_of_row(row: int, n_shards: int) -> int:
    """Interleaved shard ownership — THE mesh layout invariant (global
    row ``r`` lives in shard ``r % S``; ``parallel/mesh.py:_owner``).
    The serve plane and the write mesh must agree or routed lookups read
    the wrong shard; pinned against the mesh helpers by test."""
    return row % n_shards


def local_of_row(row: int, n_shards: int) -> int:
    """Shard-local row for a global row (``r // S`` — see
    :func:`shard_of_row`)."""
    return row // n_shards


def shard_player_count(n_players: int, shard: int, n_shards: int) -> int:
    """How many of the first ``n_players`` global rows shard owns."""
    return max(0, -(-(n_players - shard) // n_shards))


def _count_publish_bytes(nbytes: int) -> None:
    """H2D accounting for the publish path: the patch-vs-rebuild split
    is invisible in wall time at test scale, so the byte counter is what
    pins "appends ride the patch path" (tests/test_serve.py)."""
    reg = get_registry()
    reg.counter("serve.view_publish_bytes_total").add(int(nbytes))


@jax.jit
def _patch_rows(table, idx, rows):
    """New table with ``rows[i]`` written at row ``idx[i]``. Pad entries
    point at the padding row and carry NaN — rewriting the NaN pad row
    with NaN keeps the invariant. NOT donated: the previous view keeps
    serving from its buffer."""
    return table.at[idx].set(rows)


track_jit("serve._patch_rows", _patch_rows)


class RatingsView:
    """One immutable published snapshot: a device rating table plus the
    id mapping frozen at ``n_players``.

    ``table`` is ``[alloc+1, 16]`` float32 in the packed
    :mod:`core.state` layout; rows ``n_players..alloc-1`` are NaN ghost
    rows and row ``alloc`` is the padding row kernels aim masked slots
    at. ``_ids``/``_row_of`` may be shared append-only structures — the
    ``n_players`` guard is what freezes them for this version."""

    __slots__ = (
        "version", "table", "n_players", "published_at", "_row_of",
        "_ids", "_host",
    )

    def __init__(self, version, table, n_players, row_of, ids) -> None:
        self.version = version
        self.table = table
        self.n_players = n_players
        self.published_at = time.monotonic()
        self._row_of = row_of
        self._ids = ids
        self._host = None

    @property
    def pad_row(self) -> int:
        return self.table.shape[0] - 1

    @property
    def age_s(self) -> float:
        return time.monotonic() - self.published_at

    def resolve(self, player_id: str) -> int | None:
        """Row for ``player_id`` at THIS version, or None when the player
        was not yet published (including players added in later
        versions — the shared map may know them, this table does not)."""
        if self._row_of is None:  # identity mode: ids ARE row indices
            try:
                row = int(player_id)
            except (TypeError, ValueError):
                return None
        else:
            row = self._row_of.get(player_id)
            if row is None:
                return None
        return row if 0 <= row < self.n_players else None

    def id_of(self, row: int) -> str:
        """The player id published at ``row`` (< ``n_players``)."""
        if self._ids is None:
            return str(row)
        return self._ids[row]

    def host_table(self) -> np.ndarray:
        """The table as host float32 (fetched once, cached) — the oracle
        and debug surfaces read this; the serving path never does."""
        if self._host is None:
            self._host = np.asarray(self.table)
        return self._host


class ViewPublisher:
    """The write side: merges committed rating rows and publishes
    immutable :class:`RatingsView` versions.

    Two modes, fixed by the first publish:

      * **merge mode** (:meth:`publish_rows` — the service worker):
        per-batch posterior rows keyed by player api id accumulate into
        the staging table; unknown ids append new rows;
      * **table mode** (:meth:`publish_state` — ``cli serve``, the sched
        runners): a whole ``PlayerState`` table replaces the staging
        buffer, with an optional id list (None = rows are addressed by
        index).

    Thread contract: any single thread may publish at a time (writer
    lock); :meth:`current` is safe from any thread, lock-free.
    """

    def __init__(self, min_publish_interval_s: float = 2.0) -> None:
        self._lock = threading.Lock()
        self._row_of: dict[str, int] | None = {}
        self._ids: list[str] | None = []
        self._staging = np.full(
            (PATCH_BUCKET_FLOOR + 1, TABLE_WIDTH), np.nan, np.float32
        )
        self._view: RatingsView | None = None
        self._version = 0
        self.min_publish_interval_s = min_publish_interval_s
        self._last_publish: float | None = None
        # Set by a cutover CONSUMING this publisher as a staging lineage:
        # its buffers were adopted by the live lineage, so further
        # publishes here would tear the adopted state (_swap refuses).
        self._retired = False

    # -- read side --------------------------------------------------------
    @thread_role("any")
    def current(self) -> RatingsView | None:
        """The latest published view (None before the first publish).
        One atomic reference read — never blocks, never tears."""
        return self._view

    @property
    def version(self) -> int:
        return self._version

    def view_age_s(self) -> float | None:
        view = self._view
        return None if view is None else view.age_s

    # -- write side -------------------------------------------------------
    @thread_role("any")
    def publish_rows(self, ids, rows) -> RatingsView:
        """Merges ``rows`` (``[n, 16]`` float32, packed layout) for the
        players named by ``ids`` and publishes a new version. New ids
        append; existing ids overwrite their row. The worker calls this
        at each batch commit boundary with the batch's posterior table."""
        rows = np.asarray(rows, np.float32)
        if rows.ndim != 2 or rows.shape[1] != TABLE_WIDTH or len(ids) != rows.shape[0]:
            raise ValueError(
                f"publish_rows wants [n, {TABLE_WIDTH}] rows matching ids; "
                f"got {rows.shape} for {len(ids)} ids"
            )
        with self._lock:
            if self._row_of is None:
                raise ValueError(
                    "publisher is in table mode (publish_state with "
                    "index-addressed rows); per-id merges need id-mapped "
                    "publishes from the start"
                )
            prev = self._view
            touched = np.empty(len(ids), np.int64)
            for i, pid in enumerate(ids):
                row = self._row_of.get(pid)
                if row is None:
                    row = len(self._ids)
                    self._row_of[pid] = row
                    self._ids.append(pid)
                touched[i] = row
            p = len(self._ids)
            alloc = row_bucket(p)
            self._grow(alloc)
            self._staging[touched] = rows
            if prev is not None and prev.table.shape[0] == alloc + 1:
                # Incremental path: patch only the touched rows into the
                # previous version's device table (copy-on-write scatter).
                nb = _pow2_bucket(len(ids), PATCH_BUCKET_FLOOR)
                idx = np.full(nb, alloc, np.int32)
                idx[: len(ids)] = touched
                pad_rows = np.full((nb, TABLE_WIDTH), np.nan, np.float32)
                pad_rows[: len(ids)] = rows
                _count_publish_bytes(idx.nbytes + pad_rows.nbytes)
                table = _patch_rows(prev.table, jnp.asarray(idx),
                                    jnp.asarray(pad_rows))
            else:
                # jnp.array, NOT asarray: the CPU backend's asarray can
                # alias the numpy buffer zero-copy, and an aliased view
                # would mutate under later staging merges — the exact
                # torn-read class this double buffer exists to kill.
                _count_publish_bytes(self._staging[: alloc + 1].nbytes)
                table = jnp.array(self._staging[: alloc + 1])
            return self._swap(table, p)

    @thread_role("any")
    def publish_state(self, state, ids=None) -> RatingsView:
        """Publishes a whole rating table: ``state`` is a ``PlayerState``
        (or a raw ``[P+1, 16]`` array — the last row being the padding
        row either way). ``ids`` maps rows to player ids; None serves
        rows by index (full-history re-rates, checkpoints). The table is
        fetched to host FIRST — the caller's device buffer may be
        donated into the next scan chunk right after this returns."""
        table = getattr(state, "table", state)
        host = np.asarray(table, np.float32)
        p = host.shape[0] - 1
        if ids is not None and len(ids) != p:
            raise ValueError(f"{len(ids)} ids for a {p}-player table")
        with self._lock:
            alloc = row_bucket(p)
            if ids is None:
                self._row_of = None
                self._ids = None
            else:
                self._row_of = {pid: i for i, pid in enumerate(ids)}
                self._ids = list(ids)
            self._staging = np.full(
                (alloc + 1, TABLE_WIDTH), np.nan, np.float32
            )
            self._staging[:p] = host[:p]
            # jnp.array (owning copy) — see publish_rows on aliasing.
            _count_publish_bytes(self._staging.nbytes)
            return self._swap(jnp.array(self._staging), p)

    @thread_role("any")
    def publish_state_patch(
        self, rows_idx, rows, n_players: int, full_table
    ) -> RatingsView:
        """Table-mode INCREMENTAL publish for a writer that knows exactly
        which index-addressed rows changed since the previous version —
        the tiered runner (``sched/tier.py``), whose hot set names every
        row written since the last publish. Only those rows cross H2D,
        riding the same ``.at[rows].set`` patch path as
        :meth:`publish_rows`; the staging buffer keeps the full-table
        invariant so later publishes (either method) stay consistent.

        ``full_table`` is a zero-arg callable producing the whole
        ``[P+1, 16]`` host table — the rebuild fallback, paid only when
        there is no patchable previous view (first publish, an id-mapped
        publisher, or a row-bucket change). A GROWN ``n_players`` within
        the same row bucket stays on the patch path: index-addressed
        appends are just patches past the previous view's ``n_players``,
        and the per-view ``n_players`` guard already freezes the old
        version — re-uploading the whole table there was pure waste
        (pinned by a transfer-bytes assertion in tests/test_serve.py)."""
        rows = np.asarray(rows, np.float32)
        rows_idx = np.asarray(rows_idx, np.int64)
        with self._lock:
            alloc = row_bucket(n_players)
            prev = self._view
            patchable = (
                prev is not None
                and self._row_of is None
                and prev.table.shape[0] == alloc + 1
                and prev.n_players <= n_players
                and self._staging.shape[0] == alloc + 1
            )
            if not patchable:
                host = np.asarray(full_table(), np.float32)
                self._row_of = None
                self._ids = None
                self._staging = np.full(
                    (alloc + 1, TABLE_WIDTH), np.nan, np.float32
                )
                self._staging[:n_players] = host[:n_players]
                # jnp.array (owning copy) — see publish_rows on aliasing.
                _count_publish_bytes(self._staging.nbytes)
                return self._swap(jnp.array(self._staging), n_players)
            self._staging[rows_idx] = rows
            nb = _pow2_bucket(len(rows_idx), PATCH_BUCKET_FLOOR)
            idx = np.full(nb, alloc, np.int32)
            idx[: len(rows_idx)] = rows_idx
            pad_rows = np.full((nb, TABLE_WIDTH), np.nan, np.float32)
            pad_rows[: len(rows_idx)] = rows
            _count_publish_bytes(idx.nbytes + pad_rows.nbytes)
            table = _patch_rows(
                prev.table, jnp.asarray(idx), jnp.asarray(pad_rows)
            )
            return self._swap(table, n_players)

    def due(self) -> bool:
        """Whether the publish throttle window has elapsed. Callers whose
        publish is expensive to PREPARE (the tiered runner's dirty-row
        fetch) check this before building the payload; the first publish
        is always due."""
        return (
            self._last_publish is None
            or time.monotonic() - self._last_publish
            >= self.min_publish_interval_s
        )

    @thread_role("any")
    def maybe_publish_state(self, state, ids=None) -> RatingsView | None:
        """Throttled :meth:`publish_state` — the sched runners call this
        at chunk boundaries, where an unthrottled publish would pay a
        device fetch per chunk. The first call always publishes."""
        if not self.due():
            return None
        return self.publish_state(state, ids=ids)

    @thread_role("any")
    def warm_patch_buckets(self, cap_ids: int) -> int:
        """Pre-compiles the patch-scatter ladder for every id-count
        bucket up to ``cap_ids`` by re-publishing EXISTING rows
        (idempotent values; versions advance). Without this the Nth
        distinct commit size compiles mid-serve and counts against the
        zero-steady-state-retrace SLO (``loadgen`` calls this in
        ``SoakDriver.prepare``). Returns the number of warm publishes —
        the ladder length is a pure function of ``cap_ids`` and the
        published population, identical across plane topologies, so a
        soak's version sequence does not depend on the plane it warmed."""
        with self._lock:
            ids = list(self._ids or [])
            if not ids:
                return 0
            row_of = dict(self._row_of)
            staging = self._staging
            n = len(ids)
            cap = _pow2_bucket(
                min(int(cap_ids), max(n, 1)), PATCH_BUCKET_FLOOR
            )
            pages = []
            b = PATCH_BUCKET_FLOOR
            while b <= cap:
                page = [ids[i % n] for i in range(b)]
                rows = staging[[row_of[pid] for pid in page]].copy()
                pages.append((page, rows))
                b *= 2
        for page, rows in pages:
            self.publish_rows(page, rows)
        return len(pages)

    @thread_role("any")
    def cutover_from(self, staging: "ViewPublisher") -> RatingsView:
        """THE dual-lineage cutover entry (docs/migration.md): adopts the
        ``staging`` publisher's latest view as this (live) lineage's next
        version — one ``_swap`` under the live writer lock, the staging
        lineage's device table reused BY REFERENCE (zero H2D). Readers
        resolving ``current()`` observe a monotone version sequence with
        no torn or missing view: they serve the old lineage until the
        single reference assignment inside ``_swap``, and the new view's
        table is the staging lineage's immutable published buffer.

        The staging publisher is CONSUMED: its id map and staging buffer
        transfer to the live lineage (so later live publishes — merge or
        table mode — continue from the migrated state), and it is marked
        retired; any further publish into it raises instead of tearing
        the adopted buffers. The two publisher locks are taken
        SEQUENTIALLY (staging snapshot first, then the live swap), never
        nested — no ordering hazard. graftlint GL033 pins this as the
        ONLY path by which backfill code may reach a live lineage."""
        with staging._lock:
            view = staging._view
            if view is None:
                raise ValueError(
                    "staging lineage has no published view to cut over to"
                )
            row_of, ids, buf = staging._row_of, staging._ids, staging._staging
            staging._retired = True
        with self._lock:
            self._row_of = row_of
            self._ids = ids
            self._staging = buf
            get_registry().counter("serve.view_cutovers_total").add(1)
            return self._swap(view.table, view.n_players)

    @thread_role("any")
    def adopt_view(self, view: RatingsView) -> bool:
        """FOLLOWER adoption (the fabric read path, docs/fabric.md):
        makes ``view`` — another lineage's published snapshot — this
        publisher's current view BY REFERENCE, ``cutover_from``'s
        mechanism without consuming the source. The leader keeps
        publishing into its own lineage; a follower re-adopts each new
        version as it observes one, and its readers get the same
        atomic-reference guarantee as the leader's: one assignment, no
        torn state, version numbers tracking the LEADER's monotone
        sequence (not a local counter).

        Returns True when the view was adopted, False when the follower
        already serves this version (the idempotent re-poll). A version
        moving backwards raises — same protocol violation
        ``FabricDirectory.observe`` rejects. A follower is read-only by
        contract: its own staging buffer never merges the adopted
        tables, so publishing into it afterwards would fork the lineage
        — don't."""
        with self._lock:
            if self._retired:
                raise RuntimeError(
                    "publisher was retired by a lineage cutover; a retired "
                    "lineage cannot adopt views"
                )
            cur = self._view
            if cur is not None and view.version == cur.version:
                return False
            if cur is not None and view.version < cur.version:
                raise ValueError(
                    f"adopt_view would rewind {cur.version} -> "
                    f"{view.version}; followers adopt monotone leader "
                    "versions only (a restarted leader means a fresh "
                    "follower)"
                )
            self._view = view
            self._version = view.version
            self._last_publish = time.monotonic()
            reg = get_registry()
            reg.gauge("serve.view_version").set(self._version)
            reg.counter("serve.view_adoptions_total").add(1)
            return True

    def _grow(self, alloc: int) -> None:
        if alloc + 1 <= self._staging.shape[0]:
            return
        bigger = np.full((alloc + 1, TABLE_WIDTH), np.nan, np.float32)
        bigger[: self._staging.shape[0] - 1] = self._staging[:-1]
        self._staging = bigger

    def _swap(self, table, n_players: int) -> RatingsView:
        """Builds the next version and swaps the reference (the one
        atomic publication point). Caller holds the writer lock."""
        if self._retired:
            raise RuntimeError(
                "publisher was retired by a lineage cutover (its buffers "
                "now back the live lineage); publish into the live "
                "publisher instead"
            )
        self._version += 1
        view = RatingsView(
            self._version, table, n_players, self._row_of, self._ids
        )
        self._view = view
        self._last_publish = time.monotonic()
        reg = get_registry()
        reg.gauge("serve.view_version").set(self._version)
        reg.gauge("serve.view_age_seconds").set(0.0)
        reg.counter("serve.view_publishes_total").add(1)
        return view


class ShardedRatingsView:
    """One immutable published snapshot of the SHARDED serving plane:
    ``S`` per-shard :class:`RatingsView` objects frozen under a single
    version number. A reader resolving ``current()`` once can never mix
    shard tables from two publishes — the cross-shard torn-read guard
    is this object's existence, not any per-shard discipline.

    Per-shard tables are ``[local_alloc+1, 16]`` in shard-LOCAL row
    order (global row ``r`` -> shard ``r % S`` local row ``r // S`` —
    the mesh's interleaved layout, :func:`shard_of_row`), all shards
    sharing ONE local row bucket so the serving kernels compile one
    shape ladder for the whole mesh."""

    __slots__ = (
        "version", "shards", "n_players", "n_shards", "published_at",
        "_row_of", "_ids", "_host",
    )

    def __init__(self, version, shards, n_players, row_of, ids) -> None:
        self.version = version
        self.shards = tuple(shards)
        self.n_players = n_players
        self.n_shards = len(self.shards)
        self.published_at = time.monotonic()
        self._row_of = row_of
        self._ids = ids
        self._host = None

    @property
    def age_s(self) -> float:
        return time.monotonic() - self.published_at

    def resolve(self, player_id: str) -> int | None:
        """GLOBAL row for ``player_id`` at this version (same contract
        as :meth:`RatingsView.resolve`)."""
        if self._row_of is None:  # identity mode: ids ARE row indices
            try:
                row = int(player_id)
            except (TypeError, ValueError):
                return None
        else:
            row = self._row_of.get(player_id)
            if row is None:
                return None
        return row if 0 <= row < self.n_players else None

    def locate(self, player_id: str) -> tuple[int, int] | None:
        """(shard, local_row) for ``player_id``, or None when unknown —
        the routed-lookup primitive the sharded engine groups by."""
        row = self.resolve(player_id)
        if row is None:
            return None
        return shard_of_row(row, self.n_shards), local_of_row(
            row, self.n_shards
        )

    def id_of(self, row: int) -> str:
        """The player id published at GLOBAL ``row`` (< ``n_players``)."""
        if self._ids is None:
            return str(row)
        return self._ids[row]

    def host_table(self) -> np.ndarray:
        """The logical ``[n_players, 16]`` host table reassembled from
        the per-shard slices (fetched once, cached). This is a
        DESIGNATED merge helper (graftlint GL029): the oracle acceptance
        path and leaderboard response formatting read it; the routed
        query kernels never do."""
        if self._host is None:
            out = np.empty((self.n_players, TABLE_WIDTH), np.float32)
            for d, shard in enumerate(self.shards):
                ln = shard.n_players
                if ln:
                    out[d :: self.n_shards] = shard.host_table()[:ln]
            self._host = out
        return self._host


class ShardedViewPublisher:
    """The sharded plane's write side: one version-consistent
    :class:`RatingsView` per mesh shard, swapped atomically as a single
    :class:`ShardedRatingsView` under one monotone version.

    Mirrors :class:`ViewPublisher`'s modes (id-merge ``publish_rows``,
    whole-table ``publish_state``) and adds the mesh runner's
    per-shard incremental entry :meth:`publish_shard_patches` — each
    shard's touched rows ride the same ``.at[rows].set`` patch kernel,
    so a commit's H2D cost is per-shard rows, never the table.

    ``devices`` (optional) commits shard ``d``'s table to
    ``devices[d % len(devices)]`` — the rig shape where each serving
    chip holds only its slice; None serves every shard from the default
    device (the CPU test shape).

    Thread contract: identical to :class:`ViewPublisher` — one writer
    at a time (writer lock), :meth:`current` lock-free from any thread.
    """

    def __init__(
        self,
        n_shards: int,
        min_publish_interval_s: float = 2.0,
        devices=None,
    ) -> None:
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.n_shards = int(n_shards)
        self._lock = threading.Lock()
        self._row_of: dict[str, int] | None = {}
        self._ids: list[str] | None = []
        self._devices = list(devices) if devices is not None else None
        self._local_alloc = PATCH_BUCKET_FLOOR
        self._staging = [
            np.full(
                (self._local_alloc + 1, TABLE_WIDTH), np.nan, np.float32
            )
            for _ in range(self.n_shards)
        ]
        self._view: ShardedRatingsView | None = None
        self._version = 0
        self.min_publish_interval_s = min_publish_interval_s
        self._last_publish: float | None = None
        self._retired = False  # see ViewPublisher: consumed by a cutover

    # -- read side --------------------------------------------------------
    @thread_role("any")
    def current(self) -> ShardedRatingsView | None:
        """The latest published sharded view (None before the first
        publish). One atomic reference read — never blocks, never tears
        across shards."""
        return self._view

    @property
    def version(self) -> int:
        return self._version

    def view_age_s(self) -> float | None:
        view = self._view
        return None if view is None else view.age_s

    def due(self) -> bool:
        """Same throttle contract as :meth:`ViewPublisher.due`."""
        return (
            self._last_publish is None
            or time.monotonic() - self._last_publish
            >= self.min_publish_interval_s
        )

    # -- write side -------------------------------------------------------
    @thread_role("any")
    def publish_rows(self, ids, rows) -> ShardedRatingsView:
        """Id-merge publish (the service worker's commit boundary):
        routes each id's row to its owner shard and patches only the
        shards a commit touched — untouched shards carry their previous
        device table forward with zero transfer."""
        rows = np.asarray(rows, np.float32)
        if (
            rows.ndim != 2
            or rows.shape[1] != TABLE_WIDTH
            or len(ids) != rows.shape[0]
        ):
            raise ValueError(
                f"publish_rows wants [n, {TABLE_WIDTH}] rows matching ids; "
                f"got {rows.shape} for {len(ids)} ids"
            )
        with self._lock:
            if self._row_of is None:
                raise ValueError(
                    "publisher is in table mode (publish_state with "
                    "index-addressed rows); per-id merges need id-mapped "
                    "publishes from the start"
                )
            prev = self._view
            touched = np.empty(len(ids), np.int64)
            for i, pid in enumerate(ids):
                row = self._row_of.get(pid)
                if row is None:
                    row = len(self._ids)
                    self._row_of[pid] = row
                    self._ids.append(pid)
                touched[i] = row
            p = len(self._ids)
            alloc = row_bucket(shard_player_count(p, 0, self.n_shards))
            patchable = (
                prev is not None and alloc == self._local_alloc
            )
            self._grow_local(alloc)
            shard = shard_of_row(touched, self.n_shards)
            local = local_of_row(touched, self.n_shards)
            tables = []
            for d in range(self.n_shards):
                mine = shard == d
                self._staging[d][local[mine]] = rows[mine]
                if patchable and not mine.any():
                    tables.append(prev.shards[d].table)  # zero transfer
                elif patchable:
                    tables.append(
                        self._patch_shard(
                            prev.shards[d].table, local[mine], rows[mine]
                        )
                    )
                else:
                    tables.append(self._rebuild_shard(d))
            return self._swap(tables, p)

    @thread_role("any")
    def publish_state(self, state, ids=None) -> ShardedRatingsView:
        """Whole-table publish, split by interleaved ownership — the
        topology-blind bootstrap (``cli serve --shards``, checkpoint
        standbys, the sched runners' final snapshot)."""
        table = getattr(state, "table", state)
        host = np.asarray(table, np.float32)
        p = host.shape[0] - 1
        if ids is not None and len(ids) != p:
            raise ValueError(f"{len(ids)} ids for a {p}-player table")
        with self._lock:
            if ids is None:
                self._row_of = None
                self._ids = None
            else:
                self._row_of = {pid: i for i, pid in enumerate(ids)}
                self._ids = list(ids)
            self._local_alloc = row_bucket(
                shard_player_count(p, 0, self.n_shards)
            )
            tables = []
            for d in range(self.n_shards):
                self._staging[d] = np.full(
                    (self._local_alloc + 1, TABLE_WIDTH), np.nan, np.float32
                )
                mine = host[:p][d :: self.n_shards]
                self._staging[d][: mine.shape[0]] = mine
                tables.append(self._rebuild_shard(d))
            return self._swap(tables, p)

    @thread_role("any")
    def maybe_publish_state(self, state, ids=None) -> ShardedRatingsView | None:
        """Throttled :meth:`publish_state` (the sched-runner surface)."""
        if not self.due():
            return None
        return self.publish_state(state, ids=ids)

    @thread_role("any")
    def publish_shard_patches(
        self, patches, n_players: int, full_slices
    ) -> ShardedRatingsView:
        """Table-mode INCREMENTAL publish from a writer that already
        holds per-shard slices in shard-local order — the sharded mesh
        runner (``parallel.mesh.ShardedRun``), whose routing names every
        row each shard wrote since the last publish.

        ``patches``: one ``(local_rows_idx, rows)`` pair per shard —
        only those rows cross H2D, riding the per-shard patch kernel.
        ``full_slices``: zero-arg callable producing per-shard
        ``[>= local_n, 16]`` host slices in local row order — the
        rebuild fallback (first publish, id-mapped publisher, bucket
        growth), mirroring :meth:`ViewPublisher.publish_state_patch`."""
        if len(patches) != self.n_shards:
            raise ValueError(
                f"{len(patches)} shard patches for a {self.n_shards}-shard "
                "publisher"
            )
        with self._lock:
            alloc = row_bucket(
                shard_player_count(n_players, 0, self.n_shards)
            )
            prev = self._view
            patchable = (
                prev is not None
                and self._row_of is None
                and alloc == self._local_alloc
                and prev.n_players <= n_players
            )
            if not patchable:
                slices = full_slices()
                self._row_of = None
                self._ids = None
                self._local_alloc = alloc
                tables = []
                for d in range(self.n_shards):
                    ln = shard_player_count(n_players, d, self.n_shards)
                    self._staging[d] = np.full(
                        (alloc + 1, TABLE_WIDTH), np.nan, np.float32
                    )
                    self._staging[d][:ln] = np.asarray(
                        slices[d], np.float32
                    )[:ln]
                    tables.append(self._rebuild_shard(d))
                return self._swap(tables, n_players)
            tables = []
            for d, (idx, rows) in enumerate(patches):
                idx = np.asarray(idx, np.int64)
                rows = np.asarray(rows, np.float32)
                self._staging[d][idx] = rows
                if idx.size:
                    tables.append(
                        self._patch_shard(prev.shards[d].table, idx, rows)
                    )
                else:
                    tables.append(prev.shards[d].table)
            return self._swap(tables, n_players)

    @thread_role("any")
    def warm_patch_buckets(self, cap_ids: int) -> int:
        """The sharded mirror of
        :meth:`ViewPublisher.warm_patch_buckets`: one publish per ladder
        bucket — each carrying ``b`` ids PER SHARD so every shard's
        patch bucket compiles — keeping the publish COUNT (and therefore
        the version sequence a soak digests) identical to the
        single-device plane's ladder."""
        with self._lock:
            ids = list(self._ids or [])
            if not ids:
                return 0
            row_of = dict(self._row_of)
            owned = [
                [pid for pid in ids if shard_of_row(row_of[pid], self.n_shards) == d]
                for d in range(self.n_shards)
            ]
            n = len(ids)
            cap = _pow2_bucket(
                min(int(cap_ids), max(n, 1)), PATCH_BUCKET_FLOOR
            )
            pages = []
            b = PATCH_BUCKET_FLOOR
            while b <= cap:
                page = []
                for mine in owned:
                    if mine:
                        page.extend(mine[i % len(mine)] for i in range(b))
                rows = np.stack(
                    [
                        self._staging[shard_of_row(row_of[pid], self.n_shards)][
                            local_of_row(row_of[pid], self.n_shards)
                        ]
                        for pid in page
                    ]
                )
                pages.append((page, rows))
                b *= 2
        for page, rows in pages:
            self.publish_rows(page, rows)
        return len(pages)

    @thread_role("any")
    def cutover_from(self, staging: "ShardedViewPublisher") -> ShardedRatingsView:
        """The sharded mirror of :meth:`ViewPublisher.cutover_from`: all
        ``S`` per-shard tables of the staging lineage's latest view are
        adopted by reference under ONE new version, so a reader can never
        mix pre- and post-cutover shards (the single-reference contract
        of :class:`ShardedRatingsView`). Topologies must match — a
        cross-shard-count cutover would need a re-split, which is a
        ``publish_state`` of the migrated table, not a reference swap."""
        if staging.n_shards != self.n_shards:
            raise ValueError(
                f"cannot cut over a {staging.n_shards}-shard staging "
                f"lineage into a {self.n_shards}-shard live plane; "
                "publish_state the migrated table instead"
            )
        with staging._lock:
            view = staging._view
            if view is None:
                raise ValueError(
                    "staging lineage has no published view to cut over to"
                )
            row_of, ids = staging._row_of, staging._ids
            bufs, alloc = staging._staging, staging._local_alloc
            staging._retired = True
        with self._lock:
            self._row_of = row_of
            self._ids = ids
            self._staging = bufs
            self._local_alloc = alloc
            get_registry().counter("serve.view_cutovers_total").add(1)
            return self._swap(
                [shard.table for shard in view.shards], view.n_players
            )

    # -- internals --------------------------------------------------------
    def _device_of(self, d: int):
        if self._devices is None:
            return None
        return self._devices[d % len(self._devices)]

    def _patch_shard(self, prev_table, local_idx, rows):
        """One shard's ``.at[rows].set`` patch, padded to the shared
        pow2 bucket ladder (pad entries aim at the shard's pad row)."""
        nb = _pow2_bucket(len(local_idx), PATCH_BUCKET_FLOOR)
        idx = np.full(nb, self._local_alloc, np.int32)
        idx[: len(local_idx)] = local_idx
        pad_rows = np.full((nb, TABLE_WIDTH), np.nan, np.float32)
        pad_rows[: len(local_idx)] = rows
        _count_publish_bytes(idx.nbytes + pad_rows.nbytes)
        return _patch_rows(prev_table, jnp.asarray(idx), jnp.asarray(pad_rows))

    def _rebuild_shard(self, d: int):
        """One shard's owning full-slice upload (jnp.array — see
        :meth:`ViewPublisher.publish_rows` on aliasing), committed to
        the shard's device when a device list was given."""
        _count_publish_bytes(self._staging[d].nbytes)
        dev = self._device_of(d)
        if dev is None:
            return jnp.array(self._staging[d])
        return jax.device_put(np.ascontiguousarray(self._staging[d]), dev)

    def _grow_local(self, alloc: int) -> None:
        if alloc <= self._local_alloc:
            return
        for d in range(self.n_shards):
            bigger = np.full((alloc + 1, TABLE_WIDTH), np.nan, np.float32)
            bigger[: self._staging[d].shape[0] - 1] = self._staging[d][:-1]
            self._staging[d] = bigger
        self._local_alloc = alloc

    def _swap(self, tables, n_players: int) -> ShardedRatingsView:
        """Builds the next version — ALL shards under one number — and
        swaps the single reference. Caller holds the writer lock."""
        if self._retired:
            raise RuntimeError(
                "publisher was retired by a lineage cutover (its buffers "
                "now back the live lineage); publish into the live "
                "publisher instead"
            )
        self._version += 1
        shards = [
            RatingsView(
                self._version,
                t,
                shard_player_count(n_players, d, self.n_shards),
                None,
                None,
            )
            for d, t in enumerate(tables)
        ]
        view = ShardedRatingsView(
            self._version, shards, n_players, self._row_of, self._ids
        )
        self._view = view
        self._last_publish = time.monotonic()
        reg = get_registry()
        reg.gauge("serve.view_version").set(self._version)
        reg.gauge("serve.view_age_seconds").set(0.0)
        reg.gauge("serve.shards").set(self.n_shards)
        reg.counter("serve.view_publishes_total").add(1)
        return view
