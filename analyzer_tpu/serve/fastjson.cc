// Native zero-copy response codec for the serve front door.
//
// Encodes the four hot /v1/* response shapes (ratings, leaderboard,
// winprob, tiers) straight from the engine's numpy result slabs into a
// caller-provided reusable output arena — no per-response python dict
// walk, no intermediate str objects, one memcpy-free pass per body.
//
// The byte contract (docs/serving.md "Front door"): output is
// BIT-IDENTICAL to ``json.dumps(obj, sort_keys=True) + "\n"`` on the
// python response dict. That pins three sub-contracts:
//
//   * float formatting reproduces CPython's ``repr(float)`` — the
//     SHORTEST decimal string that round-trips to the same double,
//     rendered fixed for decimal exponents in (-4, 16] and scientific
//     ("1e+16", two-digit signed exponent) outside. This toolchain's
//     libstdc++ (GCC 10) has no floating-point std::to_chars, so the
//     shortest digits come from a binary search over printf precision
//     with a strtod round-trip check: both sides of that probe are
//     correctly rounded (ties-to-even) per IEEE-754, which is the same
//     choice CPython's dtoa makes, so the digit strings agree. A small
//     thread-local direct-mapped cache short-circuits repeated values
//     (padded ratings pages repeat ids; seed columns repeat per tier).
//   * string escaping matches ensure_ascii=True: `"` `\` named control
//     escapes, \u00xx for other C0 bytes, \uxxxx (lowercase hex,
//     surrogate pairs above the BMP) for everything non-ASCII.
//   * key order is the sorted order json.dumps(sort_keys=True) emits,
//     baked per shape.
//
// Non-finite floats return an error instead of bytes: JSON has no
// NaN/Infinity, the engine never produces them (unrated rows are
// null), and silently emitting python-style "NaN" would hand every
// client a parse error — the NaN/inf-free guarantee is differential-
// pinned in tests/test_frontdoor.py.
//
// Return convention (all encoders): bytes written into `out`, or
//   -1  output arena too small (caller grows and retries),
//   -2  non-finite float in the payload,
//   -3  invalid UTF-8 in a string slab.
//
// Built on demand by _native_json.py (g++ -O3 -shared, ctypes), same
// pattern as io/_native_csv.py; ImportError on any failure routes the
// caller to the counted python json.dumps fallback.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

// ---------------------------------------------------------------------
// CPython-repr double formatting.

struct ReprCacheEntry {
  uint64_t bits;
  uint8_t len;  // 0 = empty slot
  char txt[25];
};

constexpr int kCacheSlots = 4096;  // direct-mapped, per thread (no races)
thread_local ReprCacheEntry g_repr_cache[kCacheSlots];

// Shortest scientific digits: the smallest precision p in [1, 17] whose
// correctly-rounded "%.*e" rendering parses back to exactly v. The
// round-trip property is monotone in p (more digits never parse
// farther from v), so binary search is sound.
inline int shortest_sci(double v, char* buf, size_t bufsz) {
  int lo = 1, hi = 17;
  while (lo < hi) {
    int mid = (lo + hi) / 2;
    snprintf(buf, bufsz, "%.*e", mid - 1, v);
    if (strtod(buf, nullptr) == v) {
      hi = mid;
    } else {
      lo = mid + 1;
    }
  }
  return snprintf(buf, bufsz, "%.*e", lo - 1, v);
}

// repr(float) bytes for a FINITE v into out (>= 25 bytes). Returns the
// length. The scientific rendering is re-shaped to CPython's rule:
// fixed notation for decimal point positions in (-4, 16], scientific
// with a signed two-digit-minimum exponent otherwise.
inline int repr_double_uncached(double v, char* out) {
  char buf[48];
  shortest_sci(v, buf, sizeof buf);
  const char* p = buf;
  char* w = out;
  if (*p == '-') {
    *w++ = '-';
    ++p;
  }
  // Mantissa digits: first digit, optional separator (locale byte —
  // rendered back as '.') and more digits, then 'e'.
  char digits[24];
  int nd = 0;
  digits[nd++] = *p++;
  if (*p != 'e' && *p != 'E') {
    ++p;  // decimal separator, whatever the locale made it
    while (*p != 'e' && *p != 'E' && *p != '\0') digits[nd++] = *p++;
  }
  ++p;  // 'e'
  int esign = (*p == '-') ? -1 : 1;
  ++p;  // exponent sign (printf always emits one)
  int e10 = 0;
  while (*p >= '0' && *p <= '9') e10 = e10 * 10 + (*p++ - '0');
  e10 *= esign;
  int decpt = e10 + 1;  // v = 0.digits * 10^decpt
  if (-4 < decpt && decpt <= 16) {
    if (decpt <= 0) {
      *w++ = '0';
      *w++ = '.';
      for (int i = 0; i < -decpt; ++i) *w++ = '0';
      memcpy(w, digits, nd);
      w += nd;
    } else if (decpt >= nd) {
      memcpy(w, digits, nd);
      w += nd;
      for (int i = 0; i < decpt - nd; ++i) *w++ = '0';
      *w++ = '.';
      *w++ = '0';
    } else {
      memcpy(w, digits, decpt);
      w += decpt;
      *w++ = '.';
      memcpy(w, digits + decpt, nd - decpt);
      w += nd - decpt;
    }
  } else {
    *w++ = digits[0];
    if (nd > 1) {
      *w++ = '.';
      memcpy(w, digits + 1, nd - 1);
      w += nd - 1;
    }
    *w++ = 'e';
    *w++ = (e10 < 0) ? '-' : '+';
    int mag = (e10 < 0) ? -e10 : e10;
    char ebuf[8];
    int en = 0;
    do {
      ebuf[en++] = static_cast<char>('0' + mag % 10);
      mag /= 10;
    } while (mag);
    if (en < 2) ebuf[en++] = '0';  // repr pads the exponent to 2 digits
    while (en) *w++ = ebuf[--en];
  }
  return static_cast<int>(w - out);
}

// Cached repr: returns length, or -2 for non-finite v.
inline int repr_double(double v, char* out) {
  uint64_t bits;
  memcpy(&bits, &v, sizeof bits);
  if ((bits & 0x7ff0000000000000ULL) == 0x7ff0000000000000ULL) {
    return -2;  // inf / nan — JSON-hostile, the engine never emits them
  }
  uint64_t h = bits * 0x9e3779b97f4a7c15ULL;
  ReprCacheEntry& e = g_repr_cache[(h >> 40) & (kCacheSlots - 1)];
  if (e.len != 0 && e.bits == bits) {
    memcpy(out, e.txt, e.len);
    return e.len;
  }
  int n = repr_double_uncached(v, out);
  if (n > 0 && n <= static_cast<int>(sizeof e.txt)) {
    e.bits = bits;
    memcpy(e.txt, out, n);
    e.len = static_cast<uint8_t>(n);
  }
  return n;
}

// ---------------------------------------------------------------------
// Output writer over the caller's arena.

struct Writer {
  char* out;
  int64_t cap;
  int64_t n = 0;
  int err = 0;  // sticky: -1 overflow, -2 non-finite, -3 bad utf-8

  explicit Writer(char* o, int64_t c) : out(o), cap(c) {}

  inline void byte(char c) {
    if (n >= cap) {
      err = err ? err : -1;
      return;
    }
    out[n++] = c;
  }

  inline void raw(const char* s, int64_t len) {
    if (n + len > cap) {
      err = err ? err : -1;
      n = cap;
      return;
    }
    memcpy(out + n, s, len);
    n += len;
  }

  inline void lit(const char* s) { raw(s, static_cast<int64_t>(strlen(s))); }

  inline void num_f64(double v) {
    char buf[32];
    int len = repr_double(v, buf);
    if (len < 0) {
      err = err ? err : len;
      return;
    }
    raw(buf, len);
  }

  inline void num_i64(int64_t v) {
    char buf[24];
    char* w = buf + sizeof buf;
    uint64_t mag = (v < 0) ? 0 - static_cast<uint64_t>(v)
                           : static_cast<uint64_t>(v);
    do {
      *--w = static_cast<char>('0' + mag % 10);
      mag /= 10;
    } while (mag);
    if (v < 0) *--w = '-';
    raw(w, buf + sizeof buf - w);
  }

  inline void hex4(uint32_t cp) {
    static const char* kHex = "0123456789abcdef";  // json.dumps lowercase
    byte('\\');
    byte('u');
    byte(kHex[(cp >> 12) & 0xf]);
    byte(kHex[(cp >> 8) & 0xf]);
    byte(kHex[(cp >> 4) & 0xf]);
    byte(kHex[cp & 0xf]);
  }

  // One JSON string from UTF-8 bytes, ensure_ascii semantics.
  void str(const char* s, int64_t len) {
    byte('"');
    int64_t i = 0;
    while (i < len) {
      unsigned char c = static_cast<unsigned char>(s[i]);
      if (c < 0x80) {
        switch (c) {
          case '"': lit("\\\""); break;
          case '\\': lit("\\\\"); break;
          case '\b': lit("\\b"); break;
          case '\t': lit("\\t"); break;
          case '\n': lit("\\n"); break;
          case '\f': lit("\\f"); break;
          case '\r': lit("\\r"); break;
          default:
            if (c < 0x20) {
              hex4(c);
            } else {
              byte(static_cast<char>(c));
            }
        }
        ++i;
        continue;
      }
      // Multi-byte UTF-8 -> codepoint -> \uxxxx (+ surrogate pair).
      int extra;
      uint32_t cp;
      if ((c & 0xe0) == 0xc0) {
        extra = 1;
        cp = c & 0x1f;
      } else if ((c & 0xf0) == 0xe0) {
        extra = 2;
        cp = c & 0x0f;
      } else if ((c & 0xf8) == 0xf0) {
        extra = 3;
        cp = c & 0x07;
      } else {
        err = err ? err : -3;
        return;
      }
      if (i + extra >= len) {
        err = err ? err : -3;
        return;
      }
      for (int k = 1; k <= extra; ++k) {
        unsigned char cc = static_cast<unsigned char>(s[i + k]);
        if ((cc & 0xc0) != 0x80) {
          err = err ? err : -3;
          return;
        }
        cp = (cp << 6) | (cc & 0x3f);
      }
      i += extra + 1;
      if (cp > 0x10ffff) {
        err = err ? err : -3;
        return;
      }
      if (cp >= 0x10000) {
        cp -= 0x10000;
        hex4(0xd800 + (cp >> 10));
        hex4(0xdc00 + (cp & 0x3ff));
      } else {
        hex4(cp);
      }
    }
    byte('"');
  }

  inline int64_t finish() {
    if (err) return err;
    byte('\n');  // json_body's trailing newline — part of the contract
    return err ? err : n;
  }
};

}  // namespace

extern "C" {

// repr(float) probe surface: writes CPython's repr of v into out
// (>= 32 bytes), returns the length or -2 for non-finite v. The
// differential parity tests drive this directly.
int64_t fj_repr_double(double v, char* out) {
  int n = repr_double(v, out);
  return static_cast<int64_t>(n);
}

// {"ratings": [entry...], "unknown": [id...], "version": V}
// entry = {"conservative": f|null, "id": s, "mu": f|null, "rated": b,
//          "seed_mu": f, "seed_sigma": f, "sigma": f|null}
// ids/unknown arrive as one UTF-8 blob + (n+1)/(n_unknown+1) offsets;
// vals is [n, 5] float64: mu, sigma, conservative, seed_mu, seed_sigma
// (rows with rated=0 read only the seed columns).
int64_t fj_encode_ratings(int64_t n, const char* ids_blob,
                          const int64_t* ids_off, const uint8_t* rated,
                          const double* vals, int64_t n_unknown,
                          const char* unk_blob, const int64_t* unk_off,
                          int64_t version, char* out, int64_t cap) {
  Writer w(out, cap);
  w.lit("{\"ratings\": [");
  for (int64_t i = 0; i < n; ++i) {
    if (i) w.lit(", ");
    const double* row = vals + i * 5;
    w.lit("{\"conservative\": ");
    if (rated[i]) {
      w.num_f64(row[2]);
    } else {
      w.lit("null");
    }
    w.lit(", \"id\": ");
    w.str(ids_blob + ids_off[i], ids_off[i + 1] - ids_off[i]);
    w.lit(", \"mu\": ");
    if (rated[i]) {
      w.num_f64(row[0]);
    } else {
      w.lit("null");
    }
    w.lit(rated[i] ? ", \"rated\": true" : ", \"rated\": false");
    w.lit(", \"seed_mu\": ");
    w.num_f64(row[3]);
    w.lit(", \"seed_sigma\": ");
    w.num_f64(row[4]);
    w.lit(", \"sigma\": ");
    if (rated[i]) {
      w.num_f64(row[1]);
    } else {
      w.lit("null");
    }
    w.byte('}');
  }
  w.lit("], \"unknown\": [");
  for (int64_t i = 0; i < n_unknown; ++i) {
    if (i) w.lit(", ");
    w.str(unk_blob + unk_off[i], unk_off[i + 1] - unk_off[i]);
  }
  w.lit("], \"version\": ");
  w.num_i64(version);
  w.byte('}');
  return w.finish();
}

// {"leaders": [{"conservative": f, "id": s, "mu": f, "rank": N,
//               "sigma": f}...], "version": V}
// vals is [n, 3] float64: mu, sigma, conservative; ranks int64[n].
int64_t fj_encode_leaderboard(int64_t n, const int64_t* ranks,
                              const char* ids_blob, const int64_t* ids_off,
                              const double* vals, int64_t version, char* out,
                              int64_t cap) {
  Writer w(out, cap);
  w.lit("{\"leaders\": [");
  for (int64_t i = 0; i < n; ++i) {
    if (i) w.lit(", ");
    const double* row = vals + i * 3;
    w.lit("{\"conservative\": ");
    w.num_f64(row[2]);
    w.lit(", \"id\": ");
    w.str(ids_blob + ids_off[i], ids_off[i + 1] - ids_off[i]);
    w.lit(", \"mu\": ");
    w.num_f64(row[0]);
    w.lit(", \"rank\": ");
    w.num_i64(ranks[i]);
    w.lit(", \"sigma\": ");
    w.num_f64(row[1]);
    w.byte('}');
  }
  w.lit("], \"version\": ");
  w.num_i64(version);
  w.byte('}');
  return w.finish();
}

// {"p_a": f, "quality": f, "version": V}
int64_t fj_encode_winprob(double p_a, double quality, int64_t version,
                          char* out, int64_t cap) {
  Writer w(out, cap);
  w.lit("{\"p_a\": ");
  w.num_f64(p_a);
  w.lit(", \"quality\": ");
  w.num_f64(quality);
  w.lit(", \"version\": ");
  w.num_i64(version);
  w.byte('}');
  return w.finish();
}

// Without score (has_score=0):
//   {"counts": [...], "edges": [...], "rated": N, "version": V}
// With score (the /v1/tiers?score= merge):
//   {"below": N, "counts": [...], "edges": [...], "percentile": f|null,
//    "rated": N, "score": f, "version": V}
// has_pct=0 renders percentile as null (rated == 0).
int64_t fj_encode_tiers(const double* edges, int64_t n_edges,
                        const int64_t* counts, int64_t n_counts,
                        int64_t rated, int64_t version, int32_t has_score,
                        double score, int64_t below, int32_t has_pct,
                        double percentile, char* out, int64_t cap) {
  Writer w(out, cap);
  w.byte('{');
  if (has_score) {
    w.lit("\"below\": ");
    w.num_i64(below);
    w.lit(", ");
  }
  w.lit("\"counts\": [");
  for (int64_t i = 0; i < n_counts; ++i) {
    if (i) w.lit(", ");
    w.num_i64(counts[i]);
  }
  w.lit("], \"edges\": [");
  for (int64_t i = 0; i < n_edges; ++i) {
    if (i) w.lit(", ");
    w.num_f64(edges[i]);
  }
  w.lit("], ");
  if (has_score) {
    w.lit("\"percentile\": ");
    if (has_pct) {
      w.num_f64(percentile);
    } else {
      w.lit("null");
    }
    w.lit(", ");
  }
  w.lit("\"rated\": ");
  w.num_i64(rated);
  if (has_score) {
    w.lit(", \"score\": ");
    w.num_f64(score);
  }
  w.lit(", \"version\": ");
  w.num_i64(version);
  w.byte('}');
  return w.finish();
}

}  // extern "C"
