"""ratesrv: the snapshot-consistent TPU query-serving plane.

The write plane (``service/worker.py``, ``sched/runner.py``) rates
matches into an HBM-resident rating table; this package is the READ
plane that serves queries against it — player lookups, leaderboards,
tier histograms, and win-probability — Clipper-style (Crankshaw et al.,
NSDI '17): many tiny concurrent queries coalesce into one fixed-shape
jitted device call per tick, the same whole-batch trick the rating
kernel itself exploits.

Three layers:

  * :mod:`~analyzer_tpu.serve.view` — :class:`RatingsView`, an immutable
    published snapshot of the rating table + id-to-row mapping,
    double-buffered by a :class:`ViewPublisher` so the rater publishes at
    commit boundaries and readers never observe torn mid-commit state;
  * :mod:`~analyzer_tpu.serve.engine` — :class:`QueryEngine`, the
    microbatching executor (pad-to-bucket shapes, zero steady-state
    retraces, version-keyed leaderboard cache);
  * :mod:`~analyzer_tpu.serve.server` — the ``/v1/*`` HTTP endpoints on
    the shared :mod:`analyzer_tpu.obs.httpd` plumbing, started via
    ``Worker(serve_port=)`` or ``cli serve``.

The SHARDED plane mirrors each layer across the mesh:
:class:`ShardedViewPublisher` publishes one per-shard view per commit
under a single monotone version, :class:`ShardedQueryEngine` routes
point lookups by player-id shard and merges per-shard ``lax.top_k``
leaderboards on host — bit-identical to the single-device plane — and
everything above programs against the :class:`ServePlane` protocol, so
``/v1/*``, the worker, and loadgen are topology-blind.

``serve/oracle.py`` is the pure-Python reference the parity tests pin
bit-for-bit results against; it is never imported by the serving path.

Consistency model and operational notes: ``docs/serving.md``.
"""

from analyzer_tpu.serve.engine import (
    QueryEngine,
    ServePlane,
    ShardedQueryEngine,
    UnknownPlayerError,
)
from analyzer_tpu.serve.view import (
    RatingsView,
    ShardedRatingsView,
    ShardedViewPublisher,
    ViewPublisher,
)

__all__ = [
    "QueryEngine",
    "RatingsView",
    "ServePlane",
    "ServeServer",
    "ShardedQueryEngine",
    "ShardedRatingsView",
    "ShardedViewPublisher",
    "UnknownPlayerError",
    "ViewPublisher",
]


def __getattr__(name):
    # ServeServer pulls in the HTTP layer; keep it lazy so embedded
    # engine users (tests, bench) don't pay for it.
    if name == "ServeServer":
        from analyzer_tpu.serve.server import ServeServer

        return ServeServer
    raise AttributeError(name)
