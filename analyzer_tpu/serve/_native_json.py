"""ctypes loader for the native response codec (fastjson.cc).

Compiled/loaded via the shared helper (``analyzer_tpu.native_build``):
ImportError on ANY build or load failure so the caller's pure-python
``json.dumps`` encoder engages instead (counted — the serve bench's
``frontdoor.native`` flag and the benchdiff vanished-native gate watch
exactly that route flip).

The argtypes/restype declarations below are the ABI contract graftlint
GL010–GL013 cross-checks against the ``extern "C"`` signatures in the
``.cc`` — keep them in lockstep.
"""

from __future__ import annotations

import ctypes
import os

from analyzer_tpu.native_build import build_and_load

_DIR = os.path.dirname(os.path.abspath(__file__))
_lib = build_and_load(
    os.path.join(_DIR, "fastjson.cc"), os.path.join(_DIR, "_fastjson.so")
)

_lib.fj_repr_double.argtypes = [ctypes.c_double, ctypes.c_char_p]
_lib.fj_repr_double.restype = ctypes.c_int64

_lib.fj_encode_ratings.argtypes = [
    ctypes.c_int64,
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.POINTER(ctypes.c_double),
    ctypes.c_int64,
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_int64),
    ctypes.c_int64,
    ctypes.c_char_p,
    ctypes.c_int64,
]
_lib.fj_encode_ratings.restype = ctypes.c_int64

_lib.fj_encode_leaderboard.argtypes = [
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64),
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_double),
    ctypes.c_int64,
    ctypes.c_char_p,
    ctypes.c_int64,
]
_lib.fj_encode_leaderboard.restype = ctypes.c_int64

_lib.fj_encode_winprob.argtypes = [
    ctypes.c_double,
    ctypes.c_double,
    ctypes.c_int64,
    ctypes.c_char_p,
    ctypes.c_int64,
]
_lib.fj_encode_winprob.restype = ctypes.c_int64

_lib.fj_encode_tiers.argtypes = [
    ctypes.POINTER(ctypes.c_double),
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64),
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.c_int64,
    ctypes.c_int32,
    ctypes.c_double,
    ctypes.c_int64,
    ctypes.c_int32,
    ctypes.c_double,
    ctypes.c_char_p,
    ctypes.c_int64,
]
_lib.fj_encode_tiers.restype = ctypes.c_int64


def repr_double(v: float) -> bytes:
    """CPython ``repr(float)`` bytes via the native formatter. Raises
    ValueError for non-finite ``v`` (the NaN/inf-free guarantee)."""
    buf = ctypes.create_string_buffer(32)
    n = _lib.fj_repr_double(float(v), buf)
    if n < 0:
        raise ValueError(f"non-finite float {v!r} has no JSON rendering")
    return buf.raw[:n]


lib = _lib
