"""ratesrv HTTP endpoints: the query-serving plane's front door.

Rides the shared :mod:`analyzer_tpu.obs.httpd` plumbing (route table on
a daemon ``ThreadingHTTPServer``); each handler thread issues a blocking
engine call, so CONCURRENT requests coalesce into the engine's per-tick
microbatches — the HTTP layer is exactly as wide as the engine is
batched. Binds localhost by default like every plane in the package
(graftlint GL024).

  ``GET /v1/ratings?ids=a,b,c``       per-player shared rating + seeds;
                                      unknown ids are reported, not 404s;
  ``GET /v1/leaderboard?k=10``        top-k by conservative estimate;
  ``GET /v1/winprob?a=x,y&b=u,v``     P(team a wins) + match quality
                                      (404 when a named id is unknown);
  ``GET /v1/tiers[?score=S]``         conservative-score tier histogram,
                                      plus S's percentile when given;
  ``GET /healthz``                    liveness.

Every response carries ``version`` — the single published view it was
computed against (``docs/serving.md`` on the consistency model). A 503
with ``no ratings view published yet`` means the rater has not committed
a batch since this process started — the same condition obsd's
``/readyz`` ``serve.view`` probe reports.
"""

from __future__ import annotations

from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs.httpd import (
    DEFAULT_HOST,
    HttpError,
    RoutedHTTPServer,
    json_body,
    text_body,
)
from analyzer_tpu.serve.engine import ServePlane, UnknownPlayerError

logger = get_logger(__name__)

#: Leaderboard depth an HTTP caller may request (the engine's bucket
#: ladder caps at the table size anyway; this bounds response bytes).
MAX_LEADERBOARD_K = 10_000


def _ids_param(params: dict, key: str, limit: int) -> list[str]:
    raw = params.get(key, "").strip()
    ids = [x for x in (part.strip() for part in raw.split(",")) if x]
    if not ids:
        raise HttpError(400, f"query param {key!r} wants comma-separated ids")
    if len(ids) > limit:
        raise HttpError(400, f"too many ids in {key!r} (max {limit})")
    return ids


class ServeServer:
    """The ratesrv thread: routes ``/v1/*`` onto a :class:`ServePlane`.

    ``engine`` is anything satisfying the ServePlane protocol — the
    single-device :class:`~analyzer_tpu.serve.engine.QueryEngine` or the
    mesh-backed :class:`~analyzer_tpu.serve.engine.ShardedQueryEngine`;
    the HTTP layer is topology-blind (``docs/serving.md`` "Sharded
    plane"). ``port=0`` binds an ephemeral port (tests); the bound port
    is readable at :attr:`port`. The caller owns the engine's lifecycle
    — ``Worker(serve_port=)`` and ``cli serve`` start the engine's tick
    thread before the server and close both on shutdown."""

    def __init__(
        self,
        engine: ServePlane,
        port: int = 0,
        host: str = DEFAULT_HOST,
    ) -> None:
        self.engine = engine
        self._httpd = RoutedHTTPServer(
            routes={
                "/healthz": lambda params: text_body("ok\n"),
                "/v1/ratings": self._route_ratings,
                "/v1/leaderboard": self._route_leaderboard,
                "/v1/winprob": self._route_winprob,
                "/v1/tiers": self._route_tiers,
            },
            port=port,
            host=host,
            name="analyzer-ratesrv",
            json_errors=True,
        )
        self.host = host
        logger.info("ratesrv listening on %s", self.url)

    @property
    def port(self) -> int:
        return self._httpd.port

    @property
    def url(self) -> str:
        return self._httpd.url

    def close(self) -> None:
        """Stops serving and joins the thread. Idempotent; the engine is
        closed by its owner, not here."""
        self._httpd.close()
        logger.info("ratesrv stopped")

    # -- routes -----------------------------------------------------------
    def _engine_call(self, fn, *args):
        try:
            return fn(*args)
        except UnknownPlayerError as err:
            raise HttpError(404, str(err)) from err
        except ValueError as err:
            raise HttpError(400, str(err)) from err
        except RuntimeError as err:
            # "no ratings view published yet" / engine closed — the
            # plane is up but cannot answer; 503 tells a balancer so.
            raise HttpError(503, str(err)) from err

    def _route_ratings(self, params):
        ids = _ids_param(params, "ids", self.engine.max_batch)
        return json_body(self._engine_call(self.engine.get_ratings, ids))

    def _route_leaderboard(self, params):
        raw = params.get("k", "10")
        try:
            k = int(raw)
        except ValueError as err:
            raise HttpError(400, f"k must be an integer, got {raw!r}") from err
        if not 1 <= k <= MAX_LEADERBOARD_K:
            raise HttpError(400, f"k must be in 1..{MAX_LEADERBOARD_K}")
        return json_body(self._engine_call(self.engine.leaderboard, k))

    def _route_winprob(self, params):
        from analyzer_tpu.core.state import MAX_TEAM_SIZE

        a = _ids_param(params, "a", MAX_TEAM_SIZE)
        b = _ids_param(params, "b", MAX_TEAM_SIZE)
        return json_body(self._engine_call(self.engine.win_probability, a, b))

    def _route_tiers(self, params):
        out = self._engine_call(self.engine.tier_histogram)
        raw = params.get("score")
        if raw is not None:
            try:
                score = float(raw)
            except ValueError as err:
                raise HttpError(
                    400, f"score must be a number, got {raw!r}"
                ) from err
            pct = self._engine_call(self.engine.percentile, score)
            out = {**out, "percentile": pct["percentile"],
                   "score": pct["score"], "below": pct["below"]}
        return json_body(out)
