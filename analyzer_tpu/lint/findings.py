"""Finding record, rule catalog, and suppression handling for graftlint.

A finding is one (rule, file, line) occurrence. Suppressions are line
scoped: a ``# graftlint: disable=GL004`` comment on the flagged line (or
on the line directly above it) silences that rule there — IDs are
comma-separated, ``all`` silences every rule on the line. There is
deliberately no file- or project-level off switch: the linter exists to
keep the whole tree clean, and a wide suppression would rot silently.
"""

from __future__ import annotations

import dataclasses
import re

#: Stable rule catalog. IDs are append-only — a retired rule keeps its
#: number (documented in docs/lint.md) so old suppressions never change
#: meaning.
RULES: dict[str, str] = {
    # -- JAX hazards (host-device sync, PRNG hygiene, retrace storms) --
    "GL001": "host sync inside jitted code: .item()/.tolist() on a traced value",
    "GL002": "host sync inside jitted code: float()/int()/bool() on a traced value",
    "GL003": "host sync inside jitted code: np.asarray/np.array on a traced value",
    "GL004": "Python branch on a traced value inside jitted code",
    "GL005": "PRNG key reused by two consumers without an interposing split",
    "GL006": "PRNG key minted from a literal or defaulted seed in library code",
    "GL007": "jax.jit called inside a loop body (retrace/recompile storm)",
    "GL008": "jit static arg with an unhashable (mutable) default",
    "GL009": "leftover jax.debug.* call",
    # -- Native ABI cross-check (extern \"C\" vs ctypes loader) --
    "GL010": "ctypes argtypes arity differs from the extern \"C\" signature",
    "GL011": "ctypes arg/restype width or pointer-ness differs from the C type",
    "GL012": "ctypes loader declares a symbol the .cc does not export",
    "GL013": "extern \"C\" symbol has no argtypes declaration in its loader",
    # -- Service-shell rules --
    "GL020": "bare except: (catches SystemExit/KeyboardInterrupt)",
    "GL021": "import fallback caught too broadly (catch ImportError, not Exception)",
    "GL022": "mutable default argument",
    "GL023": "raw time.perf_counter() timing in service/sched code (use analyzer_tpu.obs)",
    "GL024": (
        "listening socket outside analyzer_tpu/obs/ + analyzer_tpu/serve/, "
        "or a bare 0.0.0.0 bind"
    ),
    "GL025": (
        "blocking device sync (np.asarray on a device array / "
        ".block_until_ready()) in the sched feed hot path"
    ),
    "GL026": (
        "Pallas containment: pallas/pltpu import outside "
        "analyzer_tpu/core/, or a literal interpret=True left enabled "
        "outside tests"
    ),
    "GL027": (
        "whole-table device transfer (jax.device_put / jnp.array on a "
        "*table* value) outside the tier manager (sched/tier.py) and "
        "the view publisher (serve/view.py)"
    ),
    "GL028": (
        "unseeded randomness (random.*, global np.random, seedless "
        "np.random.default_rng()) or a wall-clock read "
        "(time.time/monotonic/perf_counter/sleep, datetime.now) inside "
        "analyzer_tpu/loadgen/ — the soak harness must be "
        "deterministic per seed, on a virtual clock"
    ),
    "GL029": (
        "whole-table cross-shard gather in analyzer_tpu/serve/ "
        "(jax.device_get, or np.asarray/np.array/jnp.array/"
        "jax.device_put on a *table* value) outside the designated "
        "merge helpers — routed per-shard microbatches must not decay "
        "into per-query host round-trips"
    ),
    "GL030": (
        "runtime-emitted metric/span name not in the pre-declared "
        "schema: a string-literal counter()/gauge()/histogram() name "
        "outside STANDARD_COUNTERS/GAUGES/HISTOGRAMS, or a "
        ".span()/.instant() name outside SPAN_CATALOG, inside "
        "analyzer_tpu/service/, sched/ or serve/ — a typo'd name "
        "silently mints a series no dashboard reads"
    ),
    "GL031": (
        "per-row Python loop (for over a non-literal range/enumerate "
        "with subscript stores) or unpinned staging (np.frombuffer, "
        "bytes .decode) in the ingest decode hot path (io/ loaders + "
        "sched/feed.py) — decode whole windows through the columnar "
        "decoder (io/ingest.py) into PinnedArena slabs"
    ),
    "GL032": (
        "live SLO plane hygiene: an SLO Objective(...) whose literal "
        "metric name does not resolve to the pre-declared STANDARD "
        "schema (a typo'd metric silently never burns), or a wall-clock "
        "read (time.*, datetime.now) inside obs/history.py / obs/slo.py "
        "— the plane is clock-injected so the soak stays deterministic"
    ),
    "GL033": (
        "dual-lineage migration hygiene inside analyzer_tpu/migrate/: a "
        "view-publish call (publish_rows/publish_state/"
        "publish_state_patch/publish_shard_patches/maybe_publish_state/"
        "warm_patch_buckets) on a receiver not named as the staging "
        "lineage, a cutover_from call outside the designated cutover "
        "entry, or a read of mutable publisher internals (._view/"
        "._staging) — backfill code may reach the live lineage only "
        "through the atomic cutover, or a torn migration serves silently "
        "wrong ratings"
    ),
    "GL034": (
        "fleet-plane hygiene: a counter()/gauge()/histogram() call "
        "passing a reserved label key (host=/fleet= — "
        "obs.registry.RESERVED_LABELS) outside obs/federate.py, which "
        "would collide with the Collector's federated host= merge; or "
        "a wall-clock read (time.*, datetime.now) inside "
        "obs/federate.py — the Collector is clock-injected like the "
        "history/SLO plane, scrape(now) takes the caller's timestamp"
    ),
    # -- Thread-ownership rules (project mode; lint/threadrules.py) --
    "GL040": (
        "role-owned attribute (lint/ownership.py OWNED_ATTRS) written "
        "from a function whose thread_role does not match the owning "
        "thread (unannotated counts as mismatched; __init__ is exempt)"
    ),
    "GL041": (
        "buffer lifetime hole across a GIL-released native call: a "
        "self-attribute passed into a GIL-released ctypes entry while "
        "some method rebinds that attribute outside __init__, or a "
        ".ctypes.data/.data_as pointer used after its array was "
        "rebound or deleted"
    ),
    "GL042": (
        "lock-order cycle: two locks acquired in opposite nesting "
        "orders somewhere across the project (direct `with` nesting "
        "plus one level of same-class/imported calls)"
    ),
    "GL043": (
        "user callback (on_*/..._hook/..._callback) invoked while "
        "holding a lock — snapshot under the lock, call after release"
    ),
    "GL044": (
        "Condition.wait() outside a predicate loop (or untimed inside "
        "`while True:`) — spurious wakeups and stolen notifications "
        "make a bare wait a missed-update bug"
    ),
    "GL045": (
        "module-global mutable state written without a lock from a "
        "module that declares thread roles — any thread may call in"
    ),
    "GL046": (
        "profile-intelligence purity: a wall-clock read in "
        "obs/profview.py or obs/advisor.py (clock-injected like "
        "GL032/GL034 — attribution and the advisor's byte-identical "
        "report must be deterministic), or a peak-magnitude numeric "
        "literal (>= 1e10) outside obs/hw.py, the roofline ledger's "
        "one sanctioned peak table"
    ),
    "GL047": (
        "rating-quality purity: a wall-clock read in obs/quality.py "
        "(the calibration ledger is clock-injected — the soak's "
        "quality block is byte-identical per (seed, config)), or a "
        "float threshold literal outside the module's one declared "
        "QUALITY_TABLE (bin edges and alert floors have ONE home; "
        "0.0/0.5/1.0/2.0 arithmetic identities are exempt)"
    ),
    "GL048": (
        "fabric discipline: a wall-clock read inside analyzer_tpu/"
        "fabric/ (clock-injected like GL032/GL034/GL046/GL047 — the "
        "soak's deterministic block is bit-identical per (seed, config) "
        "at every host count, so decisions ride the injected clock), "
        "or a direct host_table() access outside fabric/route.py and "
        "fabric/host.py (cross-host table reads go through the "
        "directory/route helpers; a raw read of a non-owned shard is "
        "the torn-view bug the version protocol prevents)"
    ),
    "GL049": (
        "front-door discipline: a json.dumps call in analyzer_tpu/"
        "serve/ outside the codec module (serve/fastjson.py) and the "
        "designated _error_body helpers (responses render through "
        "ResponseCodec — byte-identical to the dumps oracle, python "
        "fallback counted; a stray dumps walk dodges the vanished-"
        "native benchdiff gate), or a wall-clock read in serve/"
        "frontdoor.py (the event loop paces on selector readiness and "
        "engine ticks; latency timestamps ride the engine's pendings, "
        "so the HTTP-mode soak block stays bit-identical per (seed, "
        "config))"
    ),
}

_DISABLE_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def suppressed_rules(source: str) -> dict[int, set[str]]:
    """Maps 1-based line number -> set of rule IDs disabled there.

    A disable comment covers its own line AND the next line, so the
    comment can sit above a long flagged statement without fighting the
    line-length budget. (AST nodes report their first line, which is
    where multi-line statements are flagged.)
    """
    out: dict[int, set[str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _DISABLE_RE.search(text)
        if not m:
            continue
        ids = {t.strip().upper() for t in m.group(1).split(",") if t.strip()}
        if "ALL" in ids:
            ids = set(RULES)
        for line in (i, i + 1):
            out.setdefault(line, set()).update(ids)
    return out


def apply_suppressions(
    findings: list[Finding], suppressions: dict[int, set[str]]
) -> list[Finding]:
    return [
        f
        for f in findings
        if f.rule not in suppressions.get(f.line, ())
    ]
