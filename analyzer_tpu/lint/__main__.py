"""CLI entry: ``python -m analyzer_tpu.lint [paths] [--json]``.

Exit codes (CI contract):
  0  clean
  1  findings (or unparseable files)
  2  usage error

The linter itself never imports jax, but a linted loader module is next
to ``.so`` artifacts and the process may be embedded in larger tooling —
pin JAX_PLATFORMS=cpu defensively so nothing an import chain drags in
ever probes for a TPU.
"""

from __future__ import annotations

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import argparse  # noqa: E402
import json  # noqa: E402
import sys  # noqa: E402

from analyzer_tpu.lint.findings import RULES  # noqa: E402
from analyzer_tpu.lint.runner import lint_paths  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m analyzer_tpu.lint",
        description="graftlint: JAX-hazard + native-ABI static analysis",
    )
    p.add_argument(
        "paths", nargs="*", default=["analyzer_tpu"],
        help="files or directories to lint (default: analyzer_tpu)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="machine-readable output (one JSON object)",
    )
    p.add_argument(
        "--rules", action="store_true", help="print the rule catalog and exit"
    )
    try:
        args = p.parse_args(argv)
    except SystemExit as e:
        return 0 if e.code in (0, None) else 2
    if args.rules:
        for rule_id, desc in sorted(RULES.items()):
            print(f"{rule_id}  {desc}")
        return 0
    missing = [path for path in args.paths if not os.path.exists(path)]
    if missing:
        print(f"error: no such path: {', '.join(missing)}", file=sys.stderr)
        return 2
    findings, errors = lint_paths(args.paths)
    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.to_json() for f in findings],
                    "errors": errors,
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.render())
        for e in errors:
            print(f"error: {e}", file=sys.stderr)
        if not findings and not errors:
            print("graftlint: clean")
        elif findings:
            print(f"graftlint: {len(findings)} finding(s)")
    return 1 if findings or errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
