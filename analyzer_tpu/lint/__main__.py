"""CLI entry: ``python -m analyzer_tpu.lint [paths] [--json]``.

Exit codes (CI contract):
  0  clean
  1  findings (or unparseable files, or stale baseline entries)
  2  usage error

The linter itself never imports jax, but a linted loader module is next
to ``.so`` artifacts and the process may be embedded in larger tooling —
pin JAX_PLATFORMS=cpu defensively so nothing an import chain drags in
ever probes for a TPU.
"""

from __future__ import annotations

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import argparse  # noqa: E402
import json  # noqa: E402
import sys  # noqa: E402

from analyzer_tpu.lint.findings import RULES, Finding  # noqa: E402
from analyzer_tpu.lint.runner import lint_paths  # noqa: E402


def _baseline_entry(f: Finding, line_text: str) -> dict:
    return {
        "rule": f.rule, "path": f.path, "line": f.line,
        "text": line_text.strip(),
    }


def _flagged_line(f: Finding, cache: dict[str, list[str]]) -> str:
    if f.path not in cache:
        try:
            with open(f.path, encoding="utf-8") as fh:
                cache[f.path] = fh.read().splitlines()
        except OSError:
            cache[f.path] = []
    lines = cache[f.path]
    return lines[f.line - 1] if 0 < f.line <= len(lines) else ""


def apply_baseline(
    findings: list[Finding], baseline: list[dict],
) -> tuple[list[Finding], list[str]]:
    """Splits findings into (kept, stale-entry errors).

    A baseline entry matches a finding by (rule, path suffix, flagged
    line text) — NOT by line number, so unrelated edits above the site
    don't expire it. An entry that matches nothing is stale: the
    flagged line was fixed or vanished, and carrying the suppression
    forward would hide a future regression — it must be removed."""
    cache: dict[str, list[str]] = {}
    unmatched = list(baseline)
    kept: list[Finding] = []
    for f in findings:
        text = _flagged_line(f, cache).strip()
        hit = None
        for entry in unmatched:
            if (
                entry.get("rule") == f.rule
                and f.path.endswith(str(entry.get("path", "")))
                and entry.get("text", "") == text
            ):
                hit = entry
                break
        if hit is not None:
            unmatched.remove(hit)
        else:
            kept.append(f)
    stale = [
        f"stale baseline entry {e.get('rule')} {e.get('path')}:"
        f"{e.get('line')} ({e.get('text', '')!r}): the flagged line no "
        f"longer lints dirty — remove it from the baseline"
        for e in unmatched
    ]
    return kept, stale


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m analyzer_tpu.lint",
        description="graftlint: JAX-hazard + native-ABI + thread-ownership "
                    "static analysis",
    )
    p.add_argument(
        "paths", nargs="*", default=["analyzer_tpu"],
        help="files or directories to lint (default: analyzer_tpu)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="machine-readable output (one JSON object, incl. timings_s)",
    )
    p.add_argument(
        "--rules", action="store_true", help="print the rule catalog and exit"
    )
    p.add_argument(
        "--project", action=argparse.BooleanOptionalAction, default=True,
        help="run the cross-module thread rules GL040-GL045 (default on)",
    )
    p.add_argument(
        "--baseline", metavar="FILE",
        help="JSON suppression snapshot: findings matching an entry are "
             "dropped; entries whose flagged line vanished fail loudly",
    )
    p.add_argument(
        "--write-baseline", metavar="FILE",
        help="write the current findings as a baseline snapshot and exit 0",
    )
    try:
        args = p.parse_args(argv)
    except SystemExit as e:
        return 0 if e.code in (0, None) else 2
    if args.rules:
        for rule_id, desc in sorted(RULES.items()):
            print(f"{rule_id}  {desc}")
        return 0
    missing = [path for path in args.paths if not os.path.exists(path)]
    if missing:
        print(f"error: no such path: {', '.join(missing)}", file=sys.stderr)
        return 2
    timings: dict[str, float] = {}
    findings, errors = lint_paths(
        args.paths, project=args.project, timings=timings
    )
    if args.write_baseline:
        cache: dict[str, list[str]] = {}
        entries = [
            _baseline_entry(f, _flagged_line(f, cache)) for f in findings
        ]
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            json.dump({"entries": entries}, fh, indent=2)
            fh.write("\n")
        print(
            f"graftlint: wrote {len(entries)} baseline entrie(s) to "
            f"{args.write_baseline}"
        )
        return 0
    if args.baseline:
        try:
            with open(args.baseline, encoding="utf-8") as fh:
                entries = json.load(fh).get("entries", [])
        except (OSError, ValueError) as e:
            print(f"error: unreadable baseline: {e}", file=sys.stderr)
            return 2
        findings, stale = apply_baseline(findings, entries)
        errors = errors + stale
    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.to_json() for f in findings],
                    "errors": errors,
                    "timings_s": {
                        k: round(v, 6) for k, v in sorted(timings.items())
                    },
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.render())
        for e in errors:
            print(f"error: {e}", file=sys.stderr)
        if not findings and not errors:
            print("graftlint: clean")
        elif findings:
            print(f"graftlint: {len(findings)} finding(s)")
    return 1 if findings or errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
