"""Native ABI cross-check (GL010-GL013): ``extern "C"`` signatures in the
C++ sources vs the ``argtypes``/``restype`` declarations in their ctypes
loaders.

ctypes has no compiler in the loop — a drifted declaration (wrong width,
missed pointer, stale arity) is undefined behavior at call time, usually
a heap smash that surfaces far from the cause. This pass re-derives both
sides: a small tokenizer over the ``.cc`` (no clang dependency; the
sources keep to plain C types + simple typedefs, which is all the ABI
boundary may use anyway) and an AST walk over the loader. Types compare
as (kind, bits, pointer-depth); signedness counts.

Pairs are discovered, not configured: any linted module that calls
``build_and_load`` and names exactly one ``.cc`` source is checked
against that source (resolved next to the module, the loader layout).
"""

from __future__ import annotations

import ast
import os
import re

from analyzer_tpu.lint.findings import Finding

#: kind -> canonical (category, bits). ``char``/``void`` stay nominal so
#: char* vs void* mismatches are visible in messages.
_C_BASE = {
    "void": "void", "char": "char", "bool": "u8",
    "int": "i32", "unsigned": "u32", "unsigned int": "u32",
    "short": "i16", "unsigned short": "u16", "short int": "i16",
    "long": "i64", "unsigned long": "u64", "long long": "i64",
    "unsigned long long": "u64", "long int": "i64",
    "float": "f32", "double": "f64",
    "int8_t": "i8", "uint8_t": "u8", "int16_t": "i16", "uint16_t": "u16",
    "int32_t": "i32", "uint32_t": "u32", "int64_t": "i64", "uint64_t": "u64",
    "size_t": "u64", "ssize_t": "i64", "intptr_t": "i64", "uintptr_t": "u64",
}

_CTYPES_BASE = {
    "c_int8": "i8", "c_byte": "i8", "c_uint8": "u8", "c_ubyte": "u8",
    "c_int16": "i16", "c_short": "i16", "c_uint16": "u16", "c_ushort": "u16",
    "c_int32": "i32", "c_int": "i32", "c_uint32": "u32", "c_uint": "u32",
    "c_int64": "i64", "c_long": "i64", "c_longlong": "i64",
    "c_uint64": "u64", "c_ulong": "u64", "c_ulonglong": "u64",
    "c_size_t": "u64", "c_ssize_t": "i64",
    "c_float": "f32", "c_double": "f64",
    "c_char": "char", "c_bool": "u8",
}

_COMMENT_RE = re.compile(r"//[^\n]*|/\*.*?\*/", re.DOTALL)
_TYPEDEF_RE = re.compile(r"typedef\s+([A-Za-z_][\w\s]*?)\s+(\w+)\s*;")


class CType:
    """(kind, pointer depth). ``kind='?'`` means unparseable — compared
    as compatible so an exotic type degrades to silence, not noise."""

    __slots__ = ("kind", "depth")

    def __init__(self, kind: str, depth: int = 0):
        self.kind = kind
        self.depth = depth

    def __eq__(self, other) -> bool:
        if "?" in (self.kind, other.kind):
            return True
        return self.kind == other.kind and self.depth == other.depth

    def __repr__(self) -> str:
        return self.kind + "*" * self.depth


def _strip_comments(text: str) -> str:
    # Replace with spaces/newlines preserved so offsets->lines survive.
    def blank(m: re.Match) -> str:
        return "".join("\n" if c == "\n" else " " for c in m.group(0))

    return _COMMENT_RE.sub(blank, text)


def _parse_c_type(tokens: list[str], typedefs: dict[str, str]) -> CType | None:
    depth = tokens.count("*")
    words = [t for t in tokens if t != "*"]
    words = [w for w in words if w not in ("const", "volatile", "restrict",
                                           "struct", "signed")]
    words = [typedefs.get(w, w) for w in words]
    if not words:
        return None
    base = " ".join(words)
    if base in _C_BASE:
        return CType(_C_BASE[base], depth)
    if len(words) > 1:
        # Last word may be the parameter name: retry without it.
        base = " ".join(words[:-1])
        if base in _C_BASE:
            return CType(_C_BASE[base], depth)
    return CType("?", depth)


def _parse_sig(decl: str, typedefs: dict[str, str]):
    m = re.match(r"^(.*?)\b(\w+)\s*\(\s*(.*?)\s*\)$", decl.strip(), re.DOTALL)
    if not m:
        return None
    ret_txt, name, params_txt = m.groups()
    ret_tokens = ret_txt.replace("*", " * ").split()
    if not ret_tokens or any(
        t in ("return", "if", "while", "switch", "for", "sizeof", "=")
        for t in ret_tokens
    ):
        return None
    ret = _parse_c_type(ret_tokens, typedefs)
    if ret is None:
        return None
    args: list[CType] = []
    if params_txt and params_txt != "void":
        for param in params_txt.split(","):
            t = _parse_c_type(param.replace("*", " * ").split(), typedefs)
            if t is None:
                return None
            args.append(t)
    return name, ret, args


def _signatures_in(text: str, typedefs: dict[str, str], line0: int):
    """Yields (name, ret, args, line) for function definitions/prototypes
    at brace depth 0 of ``text`` (bodies are skipped wholesale)."""
    i, buf_start, line = 0, 0, line0
    while i < len(text):
        c = text[i]
        if c == "\n":
            line += 1
        if c in "{;":
            decl = text[buf_start:i]
            sig = _parse_sig(decl, typedefs)
            if sig:
                yield (*sig, line - decl.count("\n") + decl[: max(
                    decl.find(sig[0]), 0)].count("\n"))
            if c == "{":
                depth = 1
                i += 1
                while i < len(text) and depth:
                    if text[i] == "{":
                        depth += 1
                    elif text[i] == "}":
                        depth -= 1
                    elif text[i] == "\n":
                        line += 1
                    i += 1
                buf_start = i
                continue
            buf_start = i + 1
        i += 1


def parse_extern_c(cc_path: str) -> dict[str, dict]:
    """name -> {ret, args, line} for every ``extern "C"`` function in the
    file — both the block form and per-declaration form."""
    with open(cc_path, encoding="utf-8", errors="replace") as f:
        text = _strip_comments(f.read())
    typedefs = {
        m.group(2): m.group(1).strip() for m in _TYPEDEF_RE.finditer(text)
    }
    out: dict[str, dict] = {}
    for m in re.finditer(r'extern\s*"C"', text):
        i = m.end()
        while i < len(text) and text[i].isspace():
            i += 1
        line = text[: i].count("\n") + 1
        if i < len(text) and text[i] == "{":
            depth, j = 1, i + 1
            while j < len(text) and depth:
                if text[j] == "{":
                    depth += 1
                elif text[j] == "}":
                    depth -= 1
                j += 1
            region = text[i + 1 : j - 1]
            for name, ret, args, ln in _signatures_in(
                region, typedefs, line
            ):
                out[name] = {"ret": ret, "args": args, "line": ln}
        else:
            j = i
            while j < len(text) and text[j] not in "{;":
                j += 1
            sig = _parse_sig(text[i:j], typedefs)
            if sig:
                out[sig[0]] = {"ret": sig[1], "args": sig[2], "line": line}
    return out


# ----------------------------------------------------------------------
def _ctypes_desc(node: ast.AST) -> CType | None:
    if isinstance(node, ast.Constant) and node.value is None:
        return CType("void", 0)
    if isinstance(node, ast.Call):
        name = (
            node.func.attr if isinstance(node.func, ast.Attribute)
            else node.func.id if isinstance(node.func, ast.Name) else None
        )
        if name == "POINTER" and node.args:
            inner = _ctypes_desc(node.args[0])
            if inner is None:
                return None
            return CType(inner.kind, inner.depth + 1)
        return None
    name = (
        node.attr if isinstance(node, ast.Attribute)
        else node.id if isinstance(node, ast.Name) else None
    )
    if name == "c_char_p":
        return CType("char", 1)
    if name == "c_wchar_p":
        return CType("?", 1)
    if name == "c_void_p":
        return CType("void", 1)
    if name in _CTYPES_BASE:
        return CType(_CTYPES_BASE[name], 0)
    return None


def loader_declarations(tree: ast.Module) -> dict[str, dict]:
    """name -> {argtypes: [CType]|None, restype: CType|None, line} from
    ``<lib>.<name>.argtypes = [...]`` / ``.restype = ...`` assignments."""
    out: dict[str, dict] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not (
            isinstance(t, ast.Attribute)
            and t.attr in ("argtypes", "restype")
            and isinstance(t.value, ast.Attribute)
        ):
            continue
        sym = t.value.attr
        entry = out.setdefault(
            sym, {"argtypes": None, "restype": None, "line": node.lineno}
        )
        if t.attr == "argtypes":
            elts = (
                node.value.elts
                if isinstance(node.value, (ast.List, ast.Tuple))
                else None
            )
            entry["argtypes"] = (
                [_ctypes_desc(e) or CType("?") for e in elts]
                if elts is not None else None
            )
            entry["argtypes_line"] = node.lineno
        else:
            entry["restype"] = _ctypes_desc(node.value) or CType("?")
            entry["restype_line"] = node.lineno
    return out


def discover_cc_source(py_path: str, tree: ast.Module) -> str | None:
    """The paired ``.cc`` for a loader module: it must call
    ``build_and_load`` and name exactly one ``.cc`` string constant."""
    calls_build = any(
        isinstance(n, ast.Call)
        and (
            (isinstance(n.func, ast.Name) and n.func.id == "build_and_load")
            or (
                isinstance(n.func, ast.Attribute)
                and n.func.attr == "build_and_load"
            )
        )
        for n in ast.walk(tree)
    )
    if not calls_build:
        return None
    cc_names = {
        n.value
        for n in ast.walk(tree)
        if isinstance(n, ast.Constant)
        and isinstance(n.value, str)
        and n.value.endswith(".cc")
    }
    if len(cc_names) != 1:
        return None
    return os.path.join(os.path.dirname(os.path.abspath(py_path)),
                        cc_names.pop())


def cross_check(py_path: str, tree: ast.Module) -> list[Finding]:
    """GL010-GL013 for one loader module (no-op for non-loaders)."""
    cc_path = discover_cc_source(py_path, tree)
    if cc_path is None:
        return []
    findings: list[Finding] = []
    if not os.path.exists(cc_path):
        return [
            Finding(
                "GL012", py_path, 1, 1,
                f"loader names native source {os.path.basename(cc_path)} "
                "but it does not exist next to the module",
            )
        ]
    c_sigs = parse_extern_c(cc_path)
    decls = loader_declarations(tree)
    cc_name = os.path.basename(cc_path)
    for sym, d in sorted(decls.items()):
        line = d.get("argtypes_line", d["line"])
        if sym not in c_sigs:
            findings.append(
                Finding(
                    "GL012", py_path, line, 1,
                    f"ctypes declares `{sym}` but {cc_name} exports no "
                    "such extern \"C\" symbol",
                )
            )
            continue
        sig = c_sigs[sym]
        if d["argtypes"] is not None:
            if len(d["argtypes"]) != len(sig["args"]):
                findings.append(
                    Finding(
                        "GL010", py_path, line, 1,
                        f"`{sym}` argtypes has {len(d['argtypes'])} entries "
                        f"but the extern \"C\" signature in {cc_name}:"
                        f"{sig['line']} takes {len(sig['args'])}",
                    )
                )
            else:
                for i, (py_t, c_t) in enumerate(
                    zip(d["argtypes"], sig["args"])
                ):
                    if py_t != c_t:
                        findings.append(
                            Finding(
                                "GL011", py_path, line, 1,
                                f"`{sym}` arg {i}: ctypes says {py_t!r} but "
                                f"{cc_name}:{sig['line']} says {c_t!r}",
                            )
                        )
        if d["restype"] is not None and d["restype"] != sig["ret"]:
            findings.append(
                Finding(
                    "GL011", py_path, d.get("restype_line", line), 1,
                    f"`{sym}` restype: ctypes says {d['restype']!r} but "
                    f"{cc_name}:{sig['line']} returns {sig['ret']!r}",
                )
            )
    for sym, sig in sorted(c_sigs.items()):
        if sym not in decls:
            findings.append(
                Finding(
                    "GL013", py_path, 1, 1,
                    f"extern \"C\" `{sym}` ({cc_name}:{sig['line']}) has no "
                    "argtypes declaration in this loader — calls would "
                    "default every argument to int",
                )
            )
    return findings
