"""Whole-tree project model for graftlint project mode.

One parse per file (shared with the per-file rule families via
``runner.py``), one walk per module, producing the cross-module facts
the thread rules (GL040-GL045) need:

* module index keyed by dotted name (``analyzer_tpu.sched.tier``),
* function/method index with ``@thread_role`` annotations resolved
  through each module's import table,
* attribute-write sites (``self._x = ...`` and subscript stores),
* lock-acquisition sites and their syntactic nesting,
* call sites of GIL-released native entries,
* module-global write sites.

Everything is stdlib ``ast`` — the model must build in milliseconds on
machines with no accelerator stack and never import jax/numpy.
"""

from __future__ import annotations

import ast
import dataclasses

from analyzer_tpu.lint.findings import suppressed_rules
from analyzer_tpu.lint.jaxrules import _Imports

#: Terminal with-item names treated as locks even when their
#: ``threading.Lock()`` assignment is out of view (e.g. injected).
_LOCKY = ("lock", "mutex", "cond")

_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}
_CONDITION_FACTORIES = {"threading.Condition"}


@dataclasses.dataclass
class FuncInfo:
    """One function or method: where it lives and what role it claims."""

    module: str
    qualname: str            # "ClassName.method" or "func" or "outer.inner"
    cls: str | None          # enclosing class name, if a method
    role: str | None         # thread_role(...) argument, if annotated
    node: ast.AST
    line: int
    end_line: int


@dataclasses.dataclass
class AttrWrite:
    """A ``self.X = ...`` / ``self.X[...] = ...`` / aug-assign site."""

    attr: str
    line: int
    col: int
    func: FuncInfo | None    # None for class-body / module-level writes
    subscript: bool


@dataclasses.dataclass
class LockSite:
    """One ``with <lock>:`` acquisition."""

    ident: str               # project-global lock identity (see _lock_ident)
    line: int
    col: int
    func: FuncInfo | None
    held: tuple[str, ...]    # identities already held when this acquires


@dataclasses.dataclass
class ModuleInfo:
    path: str
    name: str                # dotted module name
    tree: ast.Module
    source: str
    suppressions: dict[int, set[str]]
    imports: _Imports
    funcs: list[FuncInfo]
    attr_writes: list[AttrWrite]
    lock_sites: list[LockSite]
    #: calls made while >= 1 lock held: (held identities, call node, func)
    calls_under_lock: list[tuple[tuple[str, ...], ast.Call, FuncInfo | None]]
    #: Condition.wait() call sites: (call node, enclosing func, loop info)
    cond_waits: list[tuple[ast.Call, FuncInfo | None, "WaitContext"]]
    #: GIL-released native entry calls: (entry name, call node, func)
    native_calls: list[tuple[str, ast.Call, FuncInfo | None]]
    #: module-global write sites inside functions: (name, node, func,
    #: lock-held flag)
    global_writes: list[tuple[str, ast.AST, FuncInfo | None, bool]]
    #: names assigned a Condition() anywhere in the module (terminal
    #: attr/name text, e.g. "_cond", "cv")
    condition_names: set[str]
    #: names assigned any threading lock factory
    lock_names: set[str]
    uses_thread_role: bool
    #: method qualname -> lock identities that method acquires at its
    #: top level (for the one-level call-graph edges in GL042)
    acquires_by_func: dict[str, set[str]]


@dataclasses.dataclass
class WaitContext:
    """How a Condition.wait() call sits relative to enclosing loops."""

    in_loop: bool            # any enclosing While/For
    loop_is_while_true: bool  # nearest enclosing loop is ``while True``
    has_timeout: bool        # wait(...) was given a timeout argument


def module_name_for(path: str) -> str:
    """Dotted module name from a file path, rooted at the last
    ``analyzer_tpu`` path component (so absolute and relative paths
    agree); bare basename for files outside the package."""
    parts = path.replace("\\", "/").split("/")
    base = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
    if "analyzer_tpu" in parts[:-1]:
        i = len(parts) - 2 - parts[:-1][::-1].index("analyzer_tpu")
        pkg = parts[i:-1]
        return ".".join([*pkg, base])
    return base


def _role_of(node: ast.AST, imports: _Imports) -> str | None:
    """thread_role("...") argument from a def's decorator list, resolved
    through the import table (any alias of lint.ownership.thread_role,
    or a bare ``thread_role`` name)."""
    for deco in getattr(node, "decorator_list", ()):
        if not (isinstance(deco, ast.Call) and deco.args):
            continue
        resolved = imports.resolve(deco.func)
        if resolved is None or not resolved.endswith("thread_role"):
            continue
        arg = deco.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
    return None


class _ModuleWalker(ast.NodeVisitor):
    """Single recursive pass collecting every fact ModuleInfo holds."""

    def __init__(self, info: ModuleInfo, native_entries: frozenset[str]):
        self.info = info
        self.native_entries = native_entries
        self._class_stack: list[str] = []
        self._func_stack: list[FuncInfo] = []
        self._held: list[str] = []      # lock identities currently held
        self._loop_stack: list[ast.AST] = []

    # -- identity helpers ------------------------------------------------

    def _terminal(self, node: ast.AST) -> str | None:
        if isinstance(node, ast.Attribute):
            return node.attr
        if isinstance(node, ast.Name):
            return node.id
        return None

    def _lock_ident(self, node: ast.AST) -> str | None:
        """Project-global identity for a lock expression, or None if the
        expression is not lock-shaped.

        ``self._lock`` in class C of module M -> ``M.C._lock`` so every
        method of one class agrees; a parameter annotated with a class
        name (``staging: "ViewPublisher"``) resolves to that class's
        identity, which is how cross-instance handoffs like
        ``cutover_from`` get a comparable name. Module-level names ->
        ``M.name``. Call expressions (``with tracer.span(...)``) are
        never locks.
        """
        if isinstance(node, ast.Call):
            return None
        term = self._terminal(node)
        if term is None:
            return None
        known = (
            term in self.info.lock_names
            or term in self.info.condition_names
            or any(t in term.lower() for t in _LOCKY[:2])
            or term.lower().endswith("cond")
            or term == "cv"
        )
        if not known:
            return None
        mod = self.info.name
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name):
                if base.id == "self" and self._class_stack:
                    return f"{mod}.{self._class_stack[-1]}.{term}"
                cls = self._param_class(base.id)
                if cls is not None:
                    return f"{mod}.{cls}.{term}"
            # Unresolvable receiver: scope by the enclosing class so
            # same-class chains still collide, different ones don't.
            scope = self._class_stack[-1] if self._class_stack else "<module>"
            return f"{mod}.{scope}.<expr>.{term}"
        return f"{mod}.{term}"

    def _param_class(self, name: str) -> str | None:
        """Class a parameter is annotated with, when the annotation is a
        plain or string-literal class name (``staging: "ViewPublisher"``)."""
        for fi in reversed(self._func_stack):
            args = getattr(fi.node, "args", None)
            if args is None:
                continue
            for a in [*args.posonlyargs, *args.args, *args.kwonlyargs]:
                if a.arg != name or a.annotation is None:
                    continue
                ann = a.annotation
                if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                    return ann.value.split(".")[-1].strip("'\" ")
                if isinstance(ann, ast.Name):
                    return ann.id
        return None

    # -- structure -------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def _visit_func(self, node) -> None:
        cls = self._class_stack[-1] if self._class_stack else None
        outer = ".".join(f.qualname.split(".")[-1] for f in self._func_stack)
        parts = [p for p in (cls, outer, node.name) if p]
        fi = FuncInfo(
            module=self.info.name,
            qualname=".".join(parts),
            cls=cls,
            role=_role_of(node, self.info.imports),
            node=node,
            line=node.lineno,
            end_line=getattr(node, "end_lineno", node.lineno) or node.lineno,
        )
        self.info.funcs.append(fi)
        self._func_stack.append(fi)
        # Lock state does not leak across a def boundary: the nested
        # function runs later, on whatever thread calls it.
        saved_held, self._held = self._held, []
        saved_loops, self._loop_stack = self._loop_stack, []
        self.generic_visit(node)
        self._held = saved_held
        self._loop_stack = saved_loops
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- locks -----------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        acquired: list[str] = []
        func = self._func_stack[-1] if self._func_stack else None
        for item in node.items:
            ident = self._lock_ident(item.context_expr)
            if ident is None:
                continue
            self.info.lock_sites.append(LockSite(
                ident=ident,
                line=item.context_expr.lineno,
                col=item.context_expr.col_offset,
                func=func,
                held=tuple(self._held + acquired),
            ))
            if func is not None:
                self.info.acquires_by_func.setdefault(
                    func.qualname, set()
                ).add(ident)
            acquired.append(ident)
        self._held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        del self._held[len(self._held) - len(acquired):]

    # -- loops (for GL044 wait-in-predicate-loop) ------------------------

    def _visit_loop(self, node) -> None:
        self._loop_stack.append(node)
        self.generic_visit(node)
        self._loop_stack.pop()

    visit_While = _visit_loop
    visit_For = _visit_loop

    # -- writes ----------------------------------------------------------

    def _record_target(self, tgt: ast.AST, subscript: bool) -> None:
        func = self._func_stack[-1] if self._func_stack else None
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._record_target(el, subscript)
            return
        if isinstance(tgt, ast.Subscript):
            self._record_target(tgt.value, True)
            return
        if isinstance(tgt, ast.Attribute):
            if isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
                self.info.attr_writes.append(AttrWrite(
                    attr=tgt.attr, line=tgt.lineno, col=tgt.col_offset,
                    func=func, subscript=subscript,
                ))
            return
        if isinstance(tgt, ast.Name) and func is not None:
            # A store to a module-level name from inside a function is a
            # global write only when the name IS module-global here:
            # either declared ``global`` in this function, or (for
            # subscript stores, which don't rebind) defined at module
            # top level and not shadowed by a local/param.
            name = tgt.id
            if name in self._declared_global():
                self.info.global_writes.append(
                    (name, tgt, func, bool(self._held))
                )
            elif subscript and self._is_module_level(name, func):
                self.info.global_writes.append(
                    (name, tgt, func, bool(self._held))
                )

    def _declared_global(self) -> set[str]:
        out: set[str] = set()
        for fi in self._func_stack:
            for stmt in ast.walk(fi.node):
                if isinstance(stmt, ast.Global):
                    out.update(stmt.names)
        return out

    def _is_module_level(self, name: str, func: FuncInfo) -> bool:
        for fi in self._func_stack:
            args = getattr(fi.node, "args", None)
            if args is None:
                continue
            params = {
                a.arg for a in
                [*args.posonlyargs, *args.args, *args.kwonlyargs]
            }
            if args.vararg:
                params.add(args.vararg.arg)
            if args.kwarg:
                params.add(args.kwarg.arg)
            if name in params:
                return False
            for stmt in ast.walk(fi.node):
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name) and t.id == name:
                            return False
                elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                    t = stmt.target
                    if isinstance(t, ast.Name) and t.id == name:
                        return False
                elif isinstance(stmt, (ast.For, ast.comprehension)):
                    t = stmt.target
                    if isinstance(t, ast.Name) and t.id == name:
                        return False
        return name in _module_level_names(self.info.tree)

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._record_target(tgt, False)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_target(node.target, False)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_target(node.target, False)
        self.generic_visit(node)

    # -- calls -----------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        func = self._func_stack[-1] if self._func_stack else None
        term = self._terminal(node.func)
        if self._held:
            self.info.calls_under_lock.append(
                (tuple(self._held), node, func)
            )
        if term == "wait" and isinstance(node.func, ast.Attribute):
            recv = self._terminal(node.func.value)
            if recv is not None and (
                recv in self.info.condition_names
                or recv.lower().endswith("cond")
                or recv == "cv"
            ):
                nearest = self._loop_stack[-1] if self._loop_stack else None
                is_while_true = (
                    isinstance(nearest, ast.While)
                    and isinstance(nearest.test, ast.Constant)
                    and bool(nearest.test.value)
                )
                self.info.cond_waits.append((node, func, WaitContext(
                    in_loop=nearest is not None,
                    loop_is_while_true=is_while_true,
                    has_timeout=bool(node.args or node.keywords),
                )))
        if term in self.native_entries:
            self.info.native_calls.append((term, node, func))
        self.generic_visit(node)


def _module_level_names(tree: ast.Module) -> set[str]:
    out: set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(stmt.target, ast.Name):
                out.add(stmt.target.id)
    return out


def _collect_factory_names(tree: ast.Module, imports: _Imports,
                           factories: set[str]) -> set[str]:
    """Terminal names (attr or plain) assigned ``threading.X()`` for X in
    ``factories``, anywhere in the module."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        if imports.resolve(value.func) not in factories:
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            if isinstance(t, ast.Attribute):
                out.add(t.attr)
            elif isinstance(t, ast.Name):
                out.add(t.id)
    return out


def build_module(path: str, source: str, tree: ast.Module,
                 native_entries: frozenset[str]) -> ModuleInfo:
    imports = _Imports(tree)
    info = ModuleInfo(
        path=path,
        name=module_name_for(path),
        tree=tree,
        source=source,
        suppressions=suppressed_rules(source),
        imports=imports,
        funcs=[],
        attr_writes=[],
        lock_sites=[],
        calls_under_lock=[],
        cond_waits=[],
        native_calls=[],
        global_writes=[],
        condition_names=_collect_factory_names(
            tree, imports, _CONDITION_FACTORIES
        ),
        lock_names=_collect_factory_names(tree, imports, _LOCK_FACTORIES),
        uses_thread_role=False,
        acquires_by_func={},
    )
    _ModuleWalker(info, native_entries).visit(tree)
    info.uses_thread_role = any(f.role is not None for f in info.funcs)
    return info


class ProjectModel:
    """The cross-module fact base GL040-GL045 run against."""

    def __init__(self, native_entries: frozenset[str] | None = None):
        if native_entries is None:
            from analyzer_tpu.lint.ownership import GIL_RELEASED_ENTRIES
            native_entries = GIL_RELEASED_ENTRIES
        self.native_entries = native_entries
        self.modules: dict[str, ModuleInfo] = {}

    def add(self, path: str, source: str, tree: ast.Module) -> ModuleInfo:
        info = build_module(path, source, tree, self.native_entries)
        self.modules[info.name] = info
        return info

    @classmethod
    def from_sources(
        cls, sources: dict[str, str],
        native_entries: frozenset[str] | None = None,
    ) -> "ProjectModel":
        """Builds a model from {path: source}; raises SyntaxError on bad
        input like ``lint_source`` does."""
        model = cls(native_entries)
        for path, source in sources.items():
            model.add(path, source, ast.parse(source, filename=path))
        return model
