"""File walking and per-file orchestration for graftlint."""

from __future__ import annotations

import ast
import os

from analyzer_tpu.lint.abi import cross_check
from analyzer_tpu.lint.findings import (
    Finding,
    apply_suppressions,
    suppressed_rules,
)
from analyzer_tpu.lint.jaxrules import JaxHazards
from analyzer_tpu.lint.shellrules import ShellRules

_SKIP_DIRS = {"__pycache__", ".git", ".hypothesis", ".pytest_cache"}


def iter_py_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            out.extend(
                os.path.join(root, f) for f in sorted(files)
                if f.endswith(".py")
            )
    return out


def lint_source(source: str, path: str = "<string>") -> list[Finding]:
    """Lints one python source string. Raises SyntaxError on bad input —
    callers decide whether that is a finding (CLI) or a crash (tests)."""
    tree = ast.parse(source, filename=path)
    findings = JaxHazards(path, tree).run()
    findings += ShellRules(path, tree).run()
    findings += cross_check(path, tree)
    findings = apply_suppressions(findings, suppressed_rules(source))
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))


def lint_paths(paths: list[str]) -> tuple[list[Finding], list[str]]:
    """Lints every ``.py`` under ``paths``. Returns (findings, errors) —
    errors are unreadable/unparseable files, reported separately so a
    syntax error can't masquerade as a clean run."""
    findings: list[Finding] = []
    errors: list[str] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            errors.append(f"{path}: unreadable: {e}")
            continue
        try:
            findings.extend(lint_source(source, path))
        except SyntaxError as e:
            errors.append(f"{path}: syntax error: {e}")
    return findings, errors
