"""File walking and orchestration for graftlint.

Every file is parsed exactly ONCE: the parse feeds the ProjectModel,
and the per-file rule families (jax hazards, shell rules, ABI
cross-check) run from the model's stored trees. Project mode then runs
the cross-module thread rules (GL040-GL045) over the same model — no
second pass over the sources.
"""

from __future__ import annotations

import ast
import os
import time

from analyzer_tpu.lint.abi import cross_check
from analyzer_tpu.lint.findings import Finding
from analyzer_tpu.lint.jaxrules import JaxHazards
from analyzer_tpu.lint.project import ModuleInfo, ProjectModel
from analyzer_tpu.lint.shellrules import ShellRules
from analyzer_tpu.lint.threadrules import check_project

_SKIP_DIRS = {"__pycache__", ".git", ".hypothesis", ".pytest_cache"}


def iter_py_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            out.extend(
                os.path.join(root, f) for f in sorted(files)
                if f.endswith(".py")
            )
    return out


def _per_file_findings(
    info: ModuleInfo, timings: dict[str, float] | None = None,
) -> list[Finding]:
    t0 = time.perf_counter()
    findings = JaxHazards(info.path, info.tree).run()
    t1 = time.perf_counter()
    findings += ShellRules(info.path, info.tree).run()
    t2 = time.perf_counter()
    findings += cross_check(info.path, info.tree)
    t3 = time.perf_counter()
    if timings is not None:
        timings["jax"] = timings.get("jax", 0.0) + (t1 - t0)
        timings["shell"] = timings.get("shell", 0.0) + (t2 - t1)
        timings["abi"] = timings.get("abi", 0.0) + (t3 - t2)
    return findings


def _finish(
    model: ProjectModel,
    per_file: list[Finding],
    project: bool,
    timings: dict[str, float] | None,
) -> list[Finding]:
    findings = per_file
    if project:
        findings = findings + check_project(model, timings)
    by_path = {info.path: info.suppressions for info in model.modules.values()}
    findings = [
        f for f in findings
        if f.rule not in by_path.get(f.path, {}).get(f.line, ())
    ]
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))


def lint_source(source: str, path: str = "<string>") -> list[Finding]:
    """Lints one python source string (per-file families AND the thread
    rules over a single-module model — a partial model can only miss
    cross-module facts, never invent them). Raises SyntaxError on bad
    input — callers decide whether that is a finding (CLI) or a crash
    (tests)."""
    model = ProjectModel()
    info = model.add(path, source, ast.parse(source, filename=path))
    return _finish(model, _per_file_findings(info), True, None)


def lint_project_sources(sources: dict[str, str]) -> list[Finding]:
    """Cross-module entry for tests: lints {path: source} as one
    project (thread rules see every module at once)."""
    model = ProjectModel.from_sources(sources)
    per_file: list[Finding] = []
    for info in model.modules.values():
        per_file += _per_file_findings(info)
    return _finish(model, per_file, True, None)


def lint_paths(
    paths: list[str],
    project: bool = True,
    timings: dict[str, float] | None = None,
) -> tuple[list[Finding], list[str]]:
    """Lints every ``.py`` under ``paths``. Returns (findings, errors) —
    errors are unreadable/unparseable files, reported separately so a
    syntax error can't masquerade as a clean run. ``project=False``
    skips the cross-module thread rules (GL040-GL045); ``timings``
    (if given) collects per-stage wall seconds."""
    model = ProjectModel()
    per_file: list[Finding] = []
    errors: list[str] = []
    t_parse = 0.0
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            errors.append(f"{path}: unreadable: {e}")
            continue
        try:
            t0 = time.perf_counter()
            tree = ast.parse(source, filename=path)
            info = model.add(path, source, tree)
            t_parse += time.perf_counter() - t0
        except SyntaxError as e:
            errors.append(f"{path}: syntax error: {e}")
            continue
        per_file.extend(_per_file_findings(info, timings))
    if timings is not None:
        timings["parse"] = t_parse
    return _finish(model, per_file, project, timings), errors
