"""Declarative thread-ownership surface for graftlint project mode.

The threaded subsystems (feed ring, tier manager, migration engine,
view publisher, pipelined writer) each split their state between a
producer thread and a consumer thread; until now the split lived only
in docstrings. This module makes the contract machine-readable:

* ``thread_role("producer"|"consumer"|"any")`` stamps a function or
  method with the thread it runs on. Zero runtime cost — the decorator
  only sets ``__thread_role__`` on the function, it never wraps it.
* ``OWNED_ATTRS`` names, per class, which ``self._x`` attributes each
  role owns. GL040 checks every write site against this table.
* ``GIL_RELEASED_ENTRIES`` names the ctypes entries that drop the GIL
  while running; GL041 checks buffer lifetimes around calls to them.

This module is imported BOTH by the linted runtime modules (for the
decorator) and by the linter itself (for the tables) — it must stay
stdlib-only so the lint pass never drags in jax/numpy.
"""

from __future__ import annotations

ROLES = ("producer", "consumer", "any")


def thread_role(role: str):
    """Declares which thread a function runs on.

    ``producer`` / ``consumer`` name the two sides of a documented
    handoff; ``any`` marks entry points deliberately safe from either
    side (e.g. methods that take the instance lock, or lock-free
    readers). The linter (GL040) flags writes to role-owned attributes
    from functions with the wrong — or no — role annotation.
    """
    if role not in ROLES:
        raise ValueError(f"thread_role must be one of {ROLES}, got {role!r}")

    def mark(fn):
        fn.__thread_role__ = role
        return fn

    return mark


#: Per-class attribute ownership: dotted class path -> role -> attrs
#: that only that role's thread may write (``__init__`` excepted — the
#: constructor runs before any thread is spawned). Keep entries here
#: tied to a docstring in the owning class stating the same contract.
OWNED_ATTRS: dict[str, dict[str, frozenset[str]]] = {
    # sched/tier.py: "producer owns the page table, consumer owns
    # cold-tier writes". The feed thread plans against the page table;
    # the dispatch loop applies plans and writes the host cold tier.
    "analyzer_tpu.sched.tier.TierManager": {
        "producer": frozenset({
            "_slot_lut", "_row_of", "_dirty", "_last_use", "_free",
            "_host_version", "_seq",
        }),
        "consumer": frozenset({
            "_applied", "_pending", "_c_slot_of", "_written_pub",
            "_written_start", "_host_table",
        }),
    },
    # service/pipeline.py: the writer thread creates its own store
    # handle inside run() — no other thread may touch it (sqlite
    # handles are thread-affine).
    "analyzer_tpu.service.pipeline._Writer": {
        "consumer": frozenset({"store"}),
    },
}


#: ctypes entry points that release the GIL while running. A numpy
#: buffer passed in by pointer must stay bound (same object) until the
#: call returns — rebinding or deleting the owning name mid-call frees
#: the buffer under the native loop. GL041 keys off this set.
GIL_RELEASED_ENTRIES = frozenset({
    "assign_supersteps",
    "assign_batches_first_fit",
    "assign_ff_feed",
    "parse_stream_csv",
    "scan_query",
    "cumcount",
    "lookup",
})
