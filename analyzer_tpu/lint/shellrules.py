"""Service-shell rules (GL020-GL025): exception hygiene, mutable
defaults, raw-clock timing, network-surface containment, and
feed-serializing host syncs.

GL020-GL022 target the worker/pipeline layer's failure-policy code, where
a too-broad catch silently converts "the native extension is broken" into
"the fallback engaged" — but they hold everywhere, so those passes run on
every linted file. GL023 is PATH-SCOPED: inside
``analyzer_tpu/service/`` and ``analyzer_tpu/sched/`` a raw
``time.perf_counter()`` is a measurement the obs layer
(``analyzer_tpu/obs``: PhaseTimer histograms, tracer spans) should own —
ad-hoc clocks there produced exactly the numbers-nobody-can-find state
this repo's telemetry PR replaced. The few legitimate uses (a stats
contract that must not ride the global registry) carry a line-scoped
``# graftlint: disable=GL023`` with a reason, like every other escape.

GL024 keeps the package's network surface in KNOWN places:
``http.server``/``socketserver`` imports (a listening socket) belong in
``analyzer_tpu/obs/`` — the obsd plane and its shared ``httpd``
plumbing — or ``analyzer_tpu/serve/`` — the ratesrv query plane — and
nowhere else; and a bare ``"0.0.0.0"`` literal is flagged EVERYWHERE,
those planes included, because every endpoint must default to loopback
(an all-interfaces bind is an operator's explicit runtime decision,
never a code default).

GL025 is PATH-SCOPED to ``analyzer_tpu/sched/``, the prefetched device
feed's hot path (``docs/observability.md``): a blocking
``np.asarray(<device array>)`` or ``.block_until_ready()`` there
serializes the very overlap the feed exists for — the consumer stalls
on one chunk's result instead of dispatching the next. Chunk-boundary
syncs that are INTENTIONAL (the final fetch, a checkpoint hook's
snapshot) route through ``utils.host.fetch_tree`` /
``copy_to_host_async`` or carry a line-scoped
``# graftlint: disable=GL025`` with a reason. The linter cannot prove
an argument is a device array, so literal arguments (tuples, constants)
are exempt and everything else in the scoped layer flags —
conservative in exactly the direction the hot path wants.

GL026 keeps the Pallas surface in ONE place: ``jax.experimental.pallas``
/ ``pltpu`` imports belong in ``analyzer_tpu/core/`` — the fused window
kernel (``core/fused.py``) — and test files; a second ad-hoc kernel
home would fork the IEEE-exact-op discipline and the Mosaic workarounds
that make the fused path bit-identical to the reference. Additionally a
LITERAL ``interpret=True`` on a ``pallas_call`` flags everywhere
outside tests: interpret mode is the CPU tier-1 harness, and a
hardcoded literal left enabled ships a silently-interpreted
(hundredfold slower) kernel to the TPU. Backend selection must flow
through a variable (``core.fused`` threads ``backend=`` / the
``ANALYZER_TPU_FUSE_BACKEND`` env).

GL027 protects the tiered ratings table (``sched/tier.py``,
``docs/kernels.md``): once HBM is a managed cache, a whole-table
``jax.device_put(...)`` or ``jnp.array(...)`` of a *table* value
anywhere else silently re-materializes the full ``[P+1, 16]`` table on
device — exactly the HBM hard cap the tier manager exists to remove,
and a second device copy the page table knows nothing about. The two
sanctioned homes are the tier manager itself and the view publisher
(``serve/view.py``, whose owning-copy ``jnp.array`` is the serve-plane
double buffer). The linter flags the call when the transferred
expression mentions a table-named value (``table``, ``state.table``,
``host_table``, ...); literal arguments and test files are exempt, and
a deliberate whole-table transfer (state construction at ingest, a
bench baseline) carries a line-scoped disable with a reason.

GL028 is PATH-SCOPED to ``analyzer_tpu/loadgen/``, the closed-loop soak
harness, whose entire contract is a bit-identical artifact per
(seed, config) — which is what lets a CPU smoke soak live in tier-1.
Unseeded randomness (the stdlib ``random`` module, the legacy
``np.random`` global stream, a seedless ``np.random.default_rng()``)
and wall-clock reads (``time.time``/``monotonic``/``perf_counter``/
``sleep``, ``datetime.now``) in decision paths silently break that
contract; the few legitimate wall reads — realtime pacing sleeps, the
artifact's measured-latency block — carry line-scoped disables with
reasons, like every other escape.

GL029 is PATH-SCOPED to ``analyzer_tpu/serve/``, the sharded serving
plane (``docs/serving.md`` "Sharded plane"): once the table spans
shards, the whole point of routed per-shard microbatches is that NO
query path ever reassembles the full table on the host — a
``jax.device_get(...)``, or an ``np.asarray``/``np.array``/
``jnp.array``/``jax.device_put`` whose argument is a *table*-named
value, anywhere outside the DESIGNATED merge helpers
(``host_table`` — the oracle/acceptance reassembly, ``_stacked_tables``
— the all-gather top-k's per-device stack, ``publish_state`` — the
whole-table bootstrap ingest) silently reintroduces the per-query host
round-trip the shard plane exists to kill. Test files are exempt; a
deliberate whole-table fetch elsewhere carries a line-scoped disable
with a reason.

GL031 is PATH-SCOPED to the ingest decode hot path — the ``io/``
loaders (``csv_codec.py``, ``_native_csv.py``, ``ingest.py``) and the
feed producer (``sched/feed.py``): a ``for`` over a non-literal
``range``/``enumerate`` that stores through subscripts is the per-row
python decode shape the columnar decoder exists to kill (one native
window decode replaces ~10^4 interpreter iterations), and
``np.frombuffer``/``bytes.decode`` staging builds throwaway host
buffers where the pinned arena slab should be the decode target.
Loops over LITERAL bounds (``for team in range(2)``) are exempt —
they are unrolled constant structure, not per-row work; test files
are exempt; the csv-module fallback parser carries a line-scoped
disable with a reason (it exists precisely for bytes the fast grammar
refuses).

GL032 guards the live SLO plane (``obs/history.py`` + ``obs/slo.py``,
``docs/observability.md`` "History rings / SLO engine"). Two halves:
(1) an ``Objective(...)`` construction whose LITERAL ``metric`` /
``metric_b`` does not resolve to the pre-declared STANDARD schema
flags ANYWHERE outside tests — at runtime a typo'd metric name simply
never has history, so the objective never burns: the exact
silent-green failure an SLO engine must not have; (2) the plane's two
modules are CLOCK-INJECTED (every ``sample``/``check`` takes ``now``
from the caller — the worker's clock, which under the soak is the
VirtualClock), so any wall-clock read inside them
(``time.*``, ``datetime.now``) flags — one stray ``time.monotonic()``
would silently decouple burn windows from the injected clock and break
the soak's bit-identical-with-plane-on contract.

GL034 guards the fleet observability plane (``obs/federate.py``,
``docs/observability.md`` "Fleet plane"). Two halves: (1) the ``host``
and ``fleet`` label keys are RESERVED for the Collector's federated
merge (``obs.registry.RESERVED_LABELS``) — a ``counter()``/``gauge()``/
``histogram()`` call passing either keyword anywhere outside
``obs/federate.py`` would collide with (or spoof) the per-host series
the fleet snapshot is keyed by, so it flags; (2) like GL032's
history/SLO modules, ``obs/federate.py`` is CLOCK-INJECTED
(``scrape(now)``/``check(now)`` take the caller's timestamp), so any
wall-clock read inside it flags.

GL030 is PATH-SCOPED to ``analyzer_tpu/service/``, ``sched/`` and
``serve/``: every STRING-LITERAL metric name handed to
``counter()``/``gauge()``/``histogram()`` and every literal span name
handed to ``.span()``/``.instant()`` must resolve to the pre-declared
schema (``obs.registry.STANDARD_COUNTERS``/``STANDARD_GAUGES``/
``STANDARD_HISTOGRAMS``) or the span catalog
(``obs.registry.SPAN_CATALOG``). A typo'd name fails nothing at
runtime — it just mints a fresh series no dashboard reads and a span
no timeline joins, which is the silent failure mode of a
string-keyed telemetry surface. Computed names (f-strings, variables)
are out of scope by design; test files are exempt; a deliberately
local series carries a line-scoped disable with a reason.
"""

from __future__ import annotations

import ast

from analyzer_tpu.lint.findings import Finding
from analyzer_tpu.lint.jaxrules import _Imports

#: Directories where GL023 applies (normalized path fragments).
_GL023_DIRS = ("analyzer_tpu/service/", "analyzer_tpu/sched/")

#: Directories where GL025 applies: the scan runners + feed hot path.
_GL025_DIRS = ("analyzer_tpu/sched/",)

#: Literal argument forms GL025 exempts — a host-built literal can never
#: be a device array (e.g. the fingerprint's np.asarray((a, b), int64)).
_LITERAL_ARGS = (ast.Constant, ast.Tuple, ast.List, ast.Dict, ast.Set)

#: The sanctioned homes for a listening socket (GL024): the obsd
#: introspection plane (+ its shared httpd plumbing) and the ratesrv
#: query-serving plane.
_GL024_SOCKET_DIRS = ("analyzer_tpu/obs/", "analyzer_tpu/serve/")
_SERVER_MODULES = ("http.server", "socketserver")

#: The sanctioned home for Pallas kernels (GL026): the fused window
#: kernel module and its core/ siblings. Test files are exempt from
#: both halves of the rule (they drive interpret mode on purpose).
_GL026_PALLAS_DIRS = ("analyzer_tpu/core/",)
_PALLAS_MODULES = ("jax.experimental.pallas",)

#: The sanctioned homes for a whole-table device transfer (GL027): the
#: tier manager (hot-set promotion/demotion) and the view publisher
#: (the serve plane's owning double-buffer copy).
_GL027_TABLE_HOMES = ("analyzer_tpu/sched/tier.py", "analyzer_tpu/serve/view.py")
_GL027_TRANSFERS = ("jax.device_put", "jax.numpy.array")

#: Directories where GL028 applies: the soak harness, whose whole
#: contract is bit-identical artifacts per (seed, config).
_GL028_DIRS = ("analyzer_tpu/loadgen/",)

#: Directories where GL029 applies: the serving plane, whose sharded
#: query paths must stay per-shard microbatches (docs/serving.md).
_GL029_DIRS = ("analyzer_tpu/serve/",)

#: Functions DESIGNATED to reassemble/ingest a whole table (the merge
#: helpers GL029 exempts): host_table (oracle/acceptance + debug
#: surfaces), _stacked_tables (the all-gather top-k's per-device
#: stack), publish_state (the whole-table bootstrap publish).
_GL029_MERGE_HELPERS = ("host_table", "_stacked_tables", "publish_state")

#: Directories where GL030 applies: the layers whose runtime telemetry
#: the operator schema pre-declares (docs/observability.md catalog).
_GL030_DIRS = (
    "analyzer_tpu/service/", "analyzer_tpu/sched/", "analyzer_tpu/serve/",
)

#: Call-attribute -> which catalog the literal first argument must
#: resolve against (GL030).
_GL030_REGISTRY_KINDS = ("counter", "gauge", "histogram")
_GL030_TRACER_KINDS = ("span", "instant")

#: Host<->device transfer calls GL029 inspects for a table-named
#: argument (jax.device_get flags regardless of argument shape).
_GL029_TRANSFERS = (
    "numpy.asarray", "numpy.array", "jax.numpy.array", "jax.device_put",
)

#: Files where GL031 applies: the ingest decode hot path — the io/
#: stream loaders and the feed producer (docs/ingest.md).
_GL031_FILES = (
    "analyzer_tpu/io/csv_codec.py",
    "analyzer_tpu/io/_native_csv.py",
    "analyzer_tpu/io/ingest.py",
    "analyzer_tpu/sched/feed.py",
)

#: Unpinned staging calls GL031 flags: each builds a throwaway host
#: buffer on the decode path where an arena slab should be the target.
_GL031_STAGING = ("numpy.frombuffer",)

#: Files where GL032's wall-clock ban applies: the live SLO plane's
#: clock-injected modules (timestamps are always passed in).
_GL032_FILES = (
    "analyzer_tpu/obs/history.py",
    "analyzer_tpu/obs/slo.py",
)

#: The fleet plane's sanctioned home (GL034): the only module that may
#: mint series under the reserved host=/fleet= label keys — and, being
#: clock-injected like GL032's plane, the module where wall-clock
#: reads are banned (scrape(now) takes the caller's timestamp).
_GL034_FEDERATE_FILES = ("analyzer_tpu/obs/federate.py",)

#: Label keys reserved for the fleet merge (mirrors
#: obs.registry.RESERVED_LABELS; literal here so the linter stays
#: importable without the obs package loaded).
_GL034_RESERVED_LABELS = ("host", "fleet")

#: Instrument-minting call names GL034 inspects for reserved keywords.
_GL034_MINT_KINDS = ("counter", "gauge", "histogram")

#: Directories where GL033 applies: the migration engine — the one
#: package whose code runs a backfill NEXT TO a live serve plane
#: (docs/migration.md "Lineage protocol").
_GL033_DIRS = ("analyzer_tpu/migrate/",)

#: View-publish entry points GL033 polices: inside migrate/, each may
#: target only a staging-named lineage (the live lineage is reached
#: solely through the cutover entry).
_GL033_PUBLISH = (
    "publish_rows",
    "publish_state",
    "publish_state_patch",
    "publish_shard_patches",
    "maybe_publish_state",
    "warm_patch_buckets",
)

#: Mutable publisher internals backfill code must never touch — it
#: consumes immutable snapshots (current()) or public properties only.
_GL033_INTERNALS = ("_view", "_staging")

#: The designated cutover entry's function name: cutover_from calls are
#: legal only inside a function of this name (migrate/lineage.py).
_GL033_CUTOVER_FN = "cutover"

#: Files where GL046's wall-clock ban applies: the profile-intelligence
#: plane's pure modules — profview only reads timestamps the profiler
#: recorded, and the advisor's byte-identical-report contract forbids
#: any clock at all (same clock-injected contract as GL032/GL034).
_GL046_FILES = (
    "analyzer_tpu/obs/profview.py",
    "analyzer_tpu/obs/advisor.py",
)

#: The roofline ledger's sanctioned home (GL046, peak-literal half):
#: the only module that may carry peak-magnitude numeric literals.
_GL046_PEAK_HOME = ("analyzer_tpu/obs/hw.py",)

#: Numeric literals at or above this magnitude read as hardware peaks
#: (bytes/s, flop/s) — 1e10 sits above every time-unit conversion
#: factor (1e9 ns/s) and below the smallest peak in the table, so the
#: ban needs no allowlist of innocents.
_GL046_PEAK_MIN = 1e10  # graftlint: disable=GL046 — the rule's own threshold

#: The rating-quality plane's home (GL047): the calibration ledger
#: (``analyzer_tpu/obs/quality.py``) is CLOCK-INJECTED like the
#: history/SLO plane — the soak's ``quality`` block must be
#: byte-identical per (seed, config), so the module may never own a
#: clock (clock half), and every tunable float threshold — bin edges,
#: PSI/ECE alert floors, epsilons — must live inside the module's ONE
#: declared table (literal half): a pasted magic number elsewhere
#: silently forks the calibration verdict the live objective, the soak
#: artifact check, and benchdiff are all judged against.
_GL047_FILES = ("analyzer_tpu/obs/quality.py",)

#: The one sanctioned home for the quality plane's threshold literals:
#: float constants outside this module-level assignment's span flag.
_GL047_TABLE = "QUALITY_TABLE"

#: Float literals that are arithmetic identity/structure, not tunable
#: thresholds: 0.0 accumulator seeds, 0.5 (the Phi link's midpoint),
#: 1.0 complements, 2.0 (the erfc normalizer).
_GL047_FLOAT_OK = (0.0, 0.5, 1.0, 2.0)

#: The multi-host rate fabric (GL048): every module under
#: ``analyzer_tpu/fabric/`` is CLOCK-INJECTED (clock half) — the soak's
#: deterministic block must be bit-identical per (seed, config) at every
#: host count, so fabric decisions ride the driver's VirtualClock; the
#: subprocess liveness loop and measured remote latencies carry
#: line-scoped disables with reasons. The access half forces cross-host
#: table reads through the directory/route helpers: a direct
#: ``host_table()`` on a non-owned shard is exactly the torn-view bug
#: the version protocol exists to prevent.
_GL048_DIRS = ("analyzer_tpu/fabric/",)

#: The sanctioned homes for raw ``host_table()`` access inside the
#: fabric: route.py (the kernel-replay read path, behind the directory's
#: staleness bound) and host.py (a host reading its OWN view).
_GL048_TABLE_HOMES = (
    "analyzer_tpu/fabric/route.py",
    "analyzer_tpu/fabric/host.py",
)

#: The attribute whose bare use outside the table homes flags.
_GL048_TABLE_ATTR = "host_table"

#: Wall-clock reads GL028 bans in loadgen decision paths. Pacing and
#: measured-latency reads carry line-scoped disables with reasons.
#: (GL032 reuses the same needle set for the SLO plane's modules.)
_GL028_CLOCKS = {
    "time.time",
    "time.monotonic",
    "time.perf_counter",
    "time.process_time",
    "time.sleep",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.date.today",
}

#: The serve front door (GL049, json half): response rendering in
#: ``analyzer_tpu/serve/`` goes through ``serve/fastjson.ResponseCodec``
#: — the native zero-copy encoder whose output is byte-identical to the
#: ``json.dumps(obj, sort_keys=True)`` oracle and whose python fallback
#: is COUNTED (``frontdoor.codec_fallbacks_total``, the bench's
#: ``native`` flag, the benchdiff vanished-native gate). A stray
#: ``json.dumps`` on a hot path silently forfeits the codec's
#: throughput AND dodges every one of those tripwires.
_GL049_DIRS = ("analyzer_tpu/serve/",)

#: The codec module itself — the dumps oracle and the counted fallback
#: live here by design; the whole file is exempt.
_GL049_CODEC_HOME = ("analyzer_tpu/serve/fastjson.py",)

#: Designated cold-path helpers allowed to call ``json.dumps`` outside
#: the codec home: error bodies are rare, tiny, and must match the
#: stdlib plane's bytes exactly.
_GL049_HELPERS = frozenset({"_error_body"})

#: The resolved call the json half needles on.
_GL049_JSON = "json.dumps"

#: The front door's event loop (GL049, clock half): the accept/parse/
#: pump loop paces itself on selector readiness and the engine's
#: microbatch ticks — latency telemetry rides the engine's injected
#: timestamps, so a wall-clock read here is a pacing decision the
#: soak's VirtualClock cannot see.
_GL049_FRONTDOOR_FILES = ("analyzer_tpu/serve/frontdoor.py",)

_BROAD = {"Exception", "BaseException"}
_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                     ast.SetComp)
_MUTABLE_CALLS = {"list", "dict", "set", "bytearray", "defaultdict",
                  "OrderedDict", "Counter", "deque"}


def _handler_names(handler: ast.ExceptHandler) -> set[str]:
    t = handler.type
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = set()
    for e in elts:
        if isinstance(e, ast.Name):
            out.add(e.id)
        elif isinstance(e, ast.Attribute):
            out.add(e.attr)
    return out


def _contains_import(stmts: list[ast.stmt]) -> bool:
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                return True
    return False


class ShellRules:
    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.tree = tree
        self.imports = _Imports(tree)
        self.findings: list[Finding] = []

    def _flag(self, rule: str, node: ast.AST, msg: str) -> None:
        self.findings.append(
            Finding(rule, self.path, node.lineno, node.col_offset + 1, msg)
        )

    def run(self) -> list[Finding]:
        timed_layer = self._in_timed_layer()
        obs_layer = self._in_obs_layer()
        feed_layer = self._in_feed_layer()
        loadgen_layer = self._in_loadgen_layer()
        serve_layer = self._in_serve_layer()
        schema_layer = self._in_schema_layer()
        ingest_layer = self._in_ingest_layer()
        slo_plane_layer = self._in_slo_plane_layer()
        migrate_layer = self._in_migrate_layer()
        federate_home = self._in_federate_home()
        profile_plane = self._in_profile_plane_layer()
        peak_home = self._in_peak_home()
        quality_home = self._in_quality_home()
        quality_table_span = (
            self._quality_table_span() if quality_home else None
        )
        fabric_layer = self._in_fabric_layer()
        fabric_table_home = self._in_fabric_table_home()
        tests = self._in_tests()
        pallas_home = self._in_pallas_home()
        table_home = self._in_table_home()
        codec_home = self._in_codec_home()
        frontdoor_home = self._in_frontdoor_home()
        merge_ranges = (
            self._merge_helper_ranges() if serve_layer and not tests else ()
        )
        error_helper_ranges = (
            self._gl049_helper_ranges()
            if serve_layer and not (tests or codec_home)
            else ()
        )
        cutover_ranges = (
            self._cutover_entry_ranges() if migrate_layer and not tests
            else ()
        )
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Try):
                self._check_try(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_defaults(node)
            elif isinstance(node, ast.For):
                if ingest_layer and not tests:
                    self._check_per_row_loop(node)
            elif isinstance(node, ast.Call):
                if timed_layer:
                    self._check_raw_clock(node)
                if feed_layer:
                    self._check_device_sync(node)
                if loadgen_layer:
                    self._check_soak_determinism(node)
                if serve_layer and not tests:
                    self._check_cross_shard_gather(node, merge_ranges)
                    if not codec_home:
                        self._check_serve_json(node, error_helper_ranges)
                    if frontdoor_home:
                        self._check_frontdoor_clock(node)
                if schema_layer and not tests:
                    self._check_schema_name(node)
                if ingest_layer and not tests:
                    self._check_unpinned_staging(node)
                if slo_plane_layer:
                    self._check_slo_plane_clock(node)
                if profile_plane:
                    self._check_profile_plane_clock(node)
                if quality_home:
                    self._check_quality_plane_clock(node)
                if fabric_layer:
                    self._check_fabric_clock(node)
                if federate_home:
                    self._check_federate_clock(node)
                elif not tests:
                    self._check_reserved_labels(node)
                if migrate_layer and not tests:
                    self._check_lineage_publish(node, cutover_ranges)
                if not tests:
                    self._check_objective_metric(node)
                    self._check_interpret_literal(node)
                if not (tests or table_home):
                    self._check_table_transfer(node)
            elif isinstance(node, ast.Attribute):
                if (
                    migrate_layer
                    and not tests
                    and node.attr in _GL033_INTERNALS
                ):
                    self._flag(
                        "GL033", node,
                        f"read of mutable publisher internal `.{node.attr}` "
                        "in backfill code — a torn migration is a silent "
                        "correctness bug; consume the immutable current() "
                        "snapshot or the public version property instead",
                    )
                elif (
                    fabric_layer
                    and not (tests or fabric_table_home)
                    and node.attr == _GL048_TABLE_ATTR
                ):
                    self._flag(
                        "GL048", node,
                        f"direct `.{_GL048_TABLE_ATTR}()` access in fabric "
                        "code outside route.py/host.py — a raw table read "
                        "of a non-owned shard is the torn cross-host view "
                        "the version protocol exists to prevent; go "
                        "through FabricRouter / the directory's staleness-"
                        "bounded client helpers instead",
                    )
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                if not obs_layer:
                    self._check_server_import(node)
                if not (tests or pallas_home):
                    self._check_pallas_import(node)
            elif isinstance(node, ast.Constant):
                # graftlint: disable=GL024 — the rule's own needle
                if node.value == "0.0.0.0":
                    self._flag(
                        "GL024", node,
                        'bare "0.0.0.0" bind — the introspection plane '
                        "must default to localhost; widening to all "
                        "interfaces is an operator's explicit runtime "
                        "choice, not a code default",
                    )
                elif (
                    not (tests or peak_home)
                    and isinstance(node.value, (int, float))
                    and not isinstance(node.value, bool)
                    and abs(node.value) >= _GL046_PEAK_MIN
                ):
                    self._flag(
                        "GL046", node,
                        f"peak-magnitude numeric literal {node.value!r} "
                        "outside obs/hw.py — a pasted bandwidth/flops "
                        "number silently forks the roof every roofline "
                        "verdict is judged against; import it from "
                        "analyzer_tpu.obs.hw (PEAKS / peaks_for) instead",
                    )
                elif (
                    quality_home
                    and isinstance(node.value, float)
                    and node.value not in _GL047_FLOAT_OK
                    and not (
                        quality_table_span is not None
                        and quality_table_span[0]
                        <= node.lineno
                        <= quality_table_span[1]
                    )
                ):
                    self._flag(
                        "GL047", node,
                        f"float threshold literal {node.value!r} outside "
                        f"{_GL047_TABLE} — the quality plane's bin edges "
                        "and alert floors have ONE home; a magic number "
                        "here silently forks the calibration verdict the "
                        "live objective, the soak artifact check, and "
                        "benchdiff are all judged against",
                    )
        return self.findings

    def _in_timed_layer(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(frag in path for frag in _GL023_DIRS)

    def _in_obs_layer(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(frag in path for frag in _GL024_SOCKET_DIRS)

    def _in_feed_layer(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(frag in path for frag in _GL025_DIRS)

    def _in_pallas_home(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(frag in path for frag in _GL026_PALLAS_DIRS)

    def _in_table_home(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(frag in path for frag in _GL027_TABLE_HOMES)

    def _in_loadgen_layer(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(frag in path for frag in _GL028_DIRS)

    def _in_serve_layer(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(frag in path for frag in _GL029_DIRS)

    def _in_schema_layer(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(frag in path for frag in _GL030_DIRS)

    def _in_ingest_layer(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(path.endswith(frag) for frag in _GL031_FILES)

    def _in_slo_plane_layer(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(path.endswith(frag) for frag in _GL032_FILES)

    def _in_migrate_layer(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(frag in path for frag in _GL033_DIRS)

    def _in_federate_home(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(path.endswith(frag) for frag in _GL034_FEDERATE_FILES)

    def _in_quality_home(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(path.endswith(frag) for frag in _GL047_FILES)

    def _in_fabric_layer(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(frag in path for frag in _GL048_DIRS)

    def _in_fabric_table_home(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(path.endswith(frag) for frag in _GL048_TABLE_HOMES)

    def _quality_table_span(self) -> tuple[int, int] | None:
        """The module-level ``QUALITY_TABLE = {...}`` assignment's line
        span — the one sanctioned home for the quality plane's float
        threshold literals. ``None`` (table missing or renamed) makes
        EVERY non-exempt float flag: deleting the table must not
        silently disarm the rule."""
        for stmt in self.tree.body:
            targets: tuple = ()
            if isinstance(stmt, ast.Assign):
                targets = tuple(stmt.targets)
            elif isinstance(stmt, ast.AnnAssign):
                targets = (stmt.target,)
            for t in targets:
                if isinstance(t, ast.Name) and t.id == _GL047_TABLE:
                    return (stmt.lineno, stmt.end_lineno or stmt.lineno)
        return None

    def _in_profile_plane_layer(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(path.endswith(frag) for frag in _GL046_FILES)

    def _in_peak_home(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(path.endswith(frag) for frag in _GL046_PEAK_HOME)

    def _cutover_entry_ranges(self) -> tuple:
        """(start, end) line spans of functions named ``cutover`` — the
        designated dual-lineage cutover entries, the only places in
        migrate/ sanctioned to call ``cutover_from`` on a live
        publisher (GL033)."""
        out = []
        for node in ast.walk(self.tree):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == _GL033_CUTOVER_FN
            ):
                out.append((node.lineno, node.end_lineno or node.lineno))
        return tuple(out)

    def _merge_helper_ranges(self) -> tuple:
        """(start, end) line spans of the designated merge helpers —
        the only functions in serve/ sanctioned to move a whole table
        across the host/device boundary (GL029)."""
        out = []
        for node in ast.walk(self.tree):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in _GL029_MERGE_HELPERS
            ):
                out.append((node.lineno, node.end_lineno or node.lineno))
        return tuple(out)

    def _in_codec_home(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(path.endswith(frag) for frag in _GL049_CODEC_HOME)

    def _in_frontdoor_home(self) -> bool:
        path = self.path.replace("\\", "/")
        return any(path.endswith(frag) for frag in _GL049_FRONTDOOR_FILES)

    def _gl049_helper_ranges(self) -> tuple:
        """(start, end) line spans of the designated error-body helpers
        — the only functions in serve/ (outside the codec module)
        sanctioned to call ``json.dumps`` (GL049)."""
        out = []
        for node in ast.walk(self.tree):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in _GL049_HELPERS
            ):
                out.append((node.lineno, node.end_lineno or node.lineno))
        return tuple(out)

    def _in_tests(self) -> bool:
        path = self.path.replace("\\", "/")
        return "tests/" in path or path.rsplit("/", 1)[-1].startswith("test_")

    def _check_server_import(self, node) -> None:
        """GL024: a listening-socket module imported outside
        ``analyzer_tpu/obs/`` + ``analyzer_tpu/serve/`` — the shared
        httpd plumbing (``obs/httpd.py``) is the sanctioned network
        surface; a second ad-hoc endpoint fragments auth/bind policy
        and the operator's mental model."""
        if isinstance(node, ast.Import):
            names = [a.name for a in node.names]
        else:  # ImportFrom
            names = [node.module] if node.module else []
        for name in names:
            if any(
                name == mod or name.startswith(mod + ".")
                for mod in _SERVER_MODULES
            ):
                self._flag(
                    "GL024", node,
                    f"`{name}` imported outside analyzer_tpu/obs/ and "
                    "analyzer_tpu/serve/ — listening sockets live in "
                    "the obsd/ratesrv planes (obs/httpd.py); build on "
                    "the shared plumbing instead of opening an ad-hoc "
                    "server",
                )

    def _check_pallas_import(self, node) -> None:
        """GL026 (import half): ``jax.experimental.pallas``/``pltpu``
        imported outside ``analyzer_tpu/core/`` — Pallas kernels live
        next to the fused window kernel (``core/fused.py``) so the
        IEEE-exact-op discipline and Mosaic workarounds have one home."""
        if isinstance(node, ast.Import):
            names = [a.name for a in node.names]
        else:  # ImportFrom
            names = [node.module] if node.module else []
            if node.module == "jax.experimental":
                names += [
                    f"jax.experimental.{a.name}" for a in node.names
                ]
        for name in names:
            if any(
                name == mod or name.startswith(mod + ".")
                for mod in _PALLAS_MODULES
            ):
                self._flag(
                    "GL026", node,
                    f"`{name}` imported outside analyzer_tpu/core/ — "
                    "Pallas kernels live with the fused window kernel "
                    "(core/fused.py); a second kernel home forks the "
                    "bit-identity discipline (docs/kernels.md)",
                )
                return

    def _check_per_row_loop(self, node: ast.For) -> None:
        """GL031 (loop half): a ``for`` over a non-literal ``range``/
        ``enumerate`` whose body stores through subscripts is per-row
        python decode work on the ingest hot path — the shape the native
        columnar window decoder replaces wholesale. Literal bounds
        (``for team in range(2)``) are constant structure, exempt."""
        it = node.iter
        if not isinstance(it, ast.Call) or not isinstance(it.func, ast.Name):
            return
        if it.func.id not in ("range", "enumerate"):
            return
        if it.args and all(isinstance(a, ast.Constant) for a in it.args):
            return  # literal bounds: unrolled structure, not per-row work
        for sub in ast.walk(node):
            targets = ()
            if isinstance(sub, ast.Assign):
                targets = sub.targets
            elif isinstance(sub, ast.AugAssign):
                targets = (sub.target,)
            if any(isinstance(t, ast.Subscript) for t in targets):
                self._flag(
                    "GL031", node,
                    "per-row Python loop in the ingest decode hot path — "
                    "one native window decode (io/ingest.py "
                    "ColumnarDecoder) replaces thousands of interpreter "
                    "iterations; keep per-row work out of the wire path",
                )
                return

    def _check_unpinned_staging(self, node: ast.Call) -> None:
        """GL031 (staging half): ``np.frombuffer`` or a ``.decode()``
        method call on the ingest hot path builds a throwaway host
        buffer/str where the pinned arena slab should be the decode
        target (sched/feed.py PinnedArena)."""
        resolved = self.imports.resolve(node.func)
        if resolved in _GL031_STAGING:
            self._flag(
                "GL031", node,
                f"`{resolved}` staging in the ingest decode hot path — "
                "unpinned throwaway buffers; decode into a PinnedArena "
                "slab (sched/feed.py) the H2D edge commits directly",
            )
            return
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "decode"
            and not isinstance(f.value, ast.Constant)
        ):
            self._flag(
                "GL031", node,
                "bytes .decode() staging in the ingest decode hot path — "
                "per-message str materialization; route ids/columns "
                "through the columnar decoder's typed slabs instead",
            )

    def _check_interpret_literal(self, node: ast.Call) -> None:
        """GL026 (interpret half): a LITERAL ``interpret=True`` on a
        ``pallas_call`` outside tests ships an interpreted (hundredfold
        slower) kernel to production; backend selection must flow
        through a variable (``core.fused`` threads ``backend=``)."""
        f = node.func
        name = (
            f.attr if isinstance(f, ast.Attribute)
            else f.id if isinstance(f, ast.Name) else None
        )
        if name != "pallas_call":
            return
        for kw in node.keywords:
            if (
                kw.arg == "interpret"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
            ):
                self._flag(
                    "GL026", kw.value,
                    "literal interpret=True on a pallas_call outside "
                    "tests runs the kernel interpreted in production; "
                    "thread the flag through a variable "
                    "(core.fused backend=) so only tests pin it",
                )

    def _check_table_transfer(self, node: ast.Call) -> None:
        """GL027: a whole-table device transfer outside the tier manager
        and the view publisher. ``jax.device_put`` / ``jnp.array``
        (resolved through the module's imports) flag when the
        transferred expression mentions a table-named value — the
        conservative needle for "the whole ratings table is about to be
        re-materialized on device behind the page table's back"."""
        resolved = self.imports.resolve(node.func)
        if resolved not in _GL027_TRANSFERS or not node.args:
            return
        arg = node.args[0]
        if isinstance(arg, _LITERAL_ARGS):
            return
        names = [
            n.id.lower() for n in ast.walk(arg) if isinstance(n, ast.Name)
        ] + [
            n.attr.lower() for n in ast.walk(arg)
            if isinstance(n, ast.Attribute)
        ]
        if not any("table" in name for name in names):
            return
        self._flag(
            "GL027", node,
            f"whole-table `{resolved.split('.')[-1]}` outside "
            "sched/tier.py and serve/view.py bypasses the tier manager: "
            "the full [P+1, 16] table lands in HBM behind the page "
            "table's back, re-imposing the memory cap tiering removed; "
            "route the transfer through the tier manager / view "
            "publisher, or disable with a reason for a deliberate "
            "whole-table load (ingest, bench baseline)",
        )

    def _check_cross_shard_gather(self, node: ast.Call, merge_ranges) -> None:
        """GL029: a whole-table host round-trip in the serving plane
        outside the designated merge helpers. ``jax.device_get`` flags
        on sight (it exists to fetch whole arrays); the transfer calls
        in :data:`_GL029_TRANSFERS` flag when their first argument IS a
        table-named value (``<x>.table`` or a name containing
        ``table``) — the conservative needle for "a view's full table
        is about to cross the boundary per query"."""
        resolved = self.imports.resolve(node.func)
        if resolved is None:
            return
        in_helper = any(
            lo <= node.lineno <= hi for lo, hi in merge_ranges
        )
        if resolved == "jax.device_get":
            if in_helper:
                return
            self._flag(
                "GL029", node,
                "jax.device_get in the serving plane fetches a whole "
                "(possibly sharded) array to host per call; route "
                "cross-shard reads through the designated merge helpers "
                "(host_table / _stacked_tables), or disable with a "
                "reason for a deliberate whole-table fetch",
            )
            return
        if resolved not in _GL029_TRANSFERS or not node.args or in_helper:
            return
        arg = node.args[0]
        table_named = (
            isinstance(arg, ast.Attribute) and arg.attr == "table"
        ) or (
            isinstance(arg, ast.Name) and "table" in arg.id.lower()
        )
        if not table_named:
            return
        self._flag(
            "GL029", node,
            f"whole-table `{resolved.split('.')[-1]}` on a table value "
            "in the serving plane outside the designated merge helpers "
            "— per-query host round-trips are exactly what the routed "
            "per-shard microbatches exist to kill (docs/serving.md "
            '"Sharded plane"); use the merge helpers or disable with a '
            "reason",
        )

    def _check_schema_name(self, node: ast.Call) -> None:
        """GL030: a string-literal metric/span name in the service/
        sched/serve layers that does not resolve to the pre-declared
        schema. The catalogs are the ONE owner (``obs/registry.py``):
        ``counter()``/``gauge()``/``histogram()`` literals must be in
        STANDARD_COUNTERS/GAUGES/HISTOGRAMS, ``.span()``/``.instant()``
        literals in SPAN_CATALOG — a typo'd name mints a series no
        dashboard reads and a span no timeline joins, silently.
        Computed names are out of scope (string-literal check only)."""
        f = node.func
        if not isinstance(f, ast.Attribute) or not node.args:
            return
        kind = f.attr
        if kind not in _GL030_REGISTRY_KINDS and kind not in _GL030_TRACER_KINDS:
            return
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            return
        name = arg.value
        # Lazy import: the catalogs live with the schema (stdlib-only
        # module), not duplicated into the linter.
        from analyzer_tpu.obs.registry import (
            SPAN_CATALOG,
            STANDARD_COUNTERS,
            STANDARD_GAUGES,
            STANDARD_HISTOGRAMS,
        )

        if kind in _GL030_TRACER_KINDS:
            if name in SPAN_CATALOG:
                return
            self._flag(
                "GL030", node,
                f'span name "{name}" is not in the span catalog '
                "(obs.registry.SPAN_CATALOG) — a mistyped span vanishes "
                "from every reconstructed timeline; add it to the "
                "catalog (and docs/observability.md) or fix the typo",
            )
            return
        allowed = {
            "counter": STANDARD_COUNTERS,
            "gauge": STANDARD_GAUGES,
            "histogram": STANDARD_HISTOGRAMS,
        }[kind]
        if name in allowed:
            return
        self._flag(
            "GL030", node,
            f'{kind} name "{name}" is not in the pre-declared schema '
            f"(obs.registry.STANDARD_{kind.upper()}S) — a mistyped "
            "metric mints a series no dashboard reads; declare it in "
            "the schema (and docs/observability.md) or fix the typo",
        )

    def _check_slo_plane_clock(self, node: ast.Call) -> None:
        """GL032 (clock half): a wall-clock read inside the SLO plane's
        clock-injected modules (obs/history.py, obs/slo.py) — every
        timestamp there is passed in by the caller, so a stray
        ``time.monotonic()`` would silently decouple burn windows from
        the injected clock (and break the soak's bit-identity-with-
        plane-on contract)."""
        resolved = self.imports.resolve(node.func)
        if resolved in _GL028_CLOCKS:
            self._flag(
                "GL032", node,
                f"wall-clock read `{resolved}` in the clock-injected SLO "
                "plane (obs/history.py, obs/slo.py) — take `now` from "
                "the caller (the worker's clock / the soak's "
                "VirtualClock); this module must never own a clock",
            )

    def _check_profile_plane_clock(self, node: ast.Call) -> None:
        """GL046 (clock half): a wall-clock read inside the
        profile-intelligence plane's pure modules (obs/profview.py,
        obs/advisor.py). Attribution only divides timestamps the
        profiler recorded, and the advisor's contract is a
        byte-identical report for identical inputs — a stray
        ``time.time()`` would break determinism silently (the report
        still looks plausible, it just stops being diffable)."""
        resolved = self.imports.resolve(node.func)
        if resolved in _GL028_CLOCKS:
            self._flag(
                "GL046", node,
                f"wall-clock read `{resolved}` in the pure profile-"
                "intelligence plane (obs/profview.py, obs/advisor.py) — "
                "these modules analyze recorded artifacts and must be "
                "deterministic; timestamps come from the capture, never "
                "from a clock",
            )

    def _check_quality_plane_clock(self, node: ast.Call) -> None:
        """GL047 (clock half): a wall-clock read inside the rating-
        quality plane (obs/quality.py). The calibration ledger is
        clock-injected like the history/SLO plane — ``observe_population
        (now=...)`` takes the caller's timestamp (the worker's clock,
        under the soak the VirtualClock) — so the soak's ``quality``
        block stays byte-identical per (seed, config); one stray
        ``time.monotonic()`` would silently break that contract."""
        resolved = self.imports.resolve(node.func)
        if resolved in _GL028_CLOCKS:
            self._flag(
                "GL047", node,
                f"wall-clock read `{resolved}` in the clock-injected "
                "rating-quality plane (obs/quality.py) — take `now` "
                "from the caller (the worker's clock / the soak's "
                "VirtualClock); this module must never own a clock",
            )

    def _check_fabric_clock(self, node: ast.Call) -> None:
        """GL048 (clock half): a wall-clock read inside the multi-host
        rate fabric (``analyzer_tpu/fabric/``). The fabric's headline
        contract is a deterministic soak block that is bit-identical per
        (seed, config) at every host count — so every DECISION
        (matchmaking, drain barriers, staleness checks, burn windows)
        rides the driver's injected VirtualClock. A stray
        ``time.time()`` would fork behavior per topology silently. The
        genuinely wall-shaped reads (subprocess liveness deadlines,
        measured remote-call latency) carry line-scoped disables with
        reasons."""
        resolved = self.imports.resolve(node.func)
        if resolved in _GL028_CLOCKS:
            self._flag(
                "GL048", node,
                f"wall-clock read `{resolved}` in the clock-injected "
                "fabric (analyzer_tpu/fabric/) — take `now` from the "
                "caller (the soak driver's VirtualClock); a decision on "
                "wall time forks the deterministic block per host count",
            )

    def _check_serve_json(self, node: ast.Call, helper_ranges) -> None:
        """GL049 (json half): a ``json.dumps`` call in serve/ outside
        the codec module and the designated ``_error_body`` helpers —
        responses render through ``serve/fastjson.ResponseCodec``, whose
        python fallback is counted (``frontdoor.codec_fallbacks_total``,
        the bench's ``native`` flag, benchdiff's vanished-native gate);
        a stray dumps walk forfeits the native throughput and dodges
        every tripwire that would have reported the route flip."""
        resolved = self.imports.resolve(node.func)
        if resolved != _GL049_JSON:
            return
        if any(lo <= node.lineno <= hi for lo, hi in helper_ranges):
            return
        self._flag(
            "GL049", node,
            "`json.dumps` in a serve/ hot path — render through "
            "serve/fastjson.ResponseCodec (byte-identical to the dumps "
            "oracle, fallback counted) or move the cold-path bytes into "
            "a designated _error_body helper; a stray dumps walk "
            "silently dodges the vanished-native benchdiff gate",
        )

    def _check_frontdoor_clock(self, node: ast.Call) -> None:
        """GL049 (clock half): a wall-clock read inside the front
        door's event loop (serve/frontdoor.py). The loop paces itself on
        selector readiness and the engine's microbatch ticks; request
        latency telemetry rides the engine's injected timestamps. A
        stray ``time.monotonic()`` is a pacing decision the soak's
        VirtualClock cannot see — the HTTP-mode deterministic block
        must stay bit-identical to the in-process one."""
        resolved = self.imports.resolve(node.func)
        if resolved in _GL028_CLOCKS:
            self._flag(
                "GL049", node,
                f"wall-clock read `{resolved}` in the front door "
                "(serve/frontdoor.py) — pace on selector readiness and "
                "engine ticks; latency timestamps come from the "
                "engine's pendings, never from a clock here",
            )

    def _check_federate_clock(self, node: ast.Call) -> None:
        """GL034 (clock half): a wall-clock read inside the fleet
        Collector's module (obs/federate.py) — like the history/SLO
        plane (GL032), the Collector is clock-injected: ``scrape(now)``
        / ``check(now)`` take the caller's timestamp, so fleet burn
        windows are exactly as deterministic as their driver."""
        resolved = self.imports.resolve(node.func)
        if resolved in _GL028_CLOCKS:
            self._flag(
                "GL034", node,
                f"wall-clock read `{resolved}` in the clock-injected "
                "fleet plane (obs/federate.py) — take `now` from the "
                "caller (cli fleet's loop, a test's synthetic clock); "
                "this module must never own a clock",
            )

    def _check_reserved_labels(self, node: ast.Call) -> None:
        """GL034 (reserved-label half): a counter()/gauge()/histogram()
        call passing a ``host=``/``fleet=`` label keyword outside
        obs/federate.py. The Collector merges every scraped worker's
        series into the fleet snapshot under ``host=<target>``
        (obs.registry.RESERVED_LABELS) — a worker minting its own
        host-labeled series would collide with, or spoof, the federated
        view the fleet plane serves."""
        f = node.func
        if not isinstance(f, ast.Attribute) or f.attr not in _GL034_MINT_KINDS:
            return
        for kw in node.keywords:
            if kw.arg in _GL034_RESERVED_LABELS:
                self._flag(
                    "GL034", node,
                    f"`{kw.arg}=` label on a {f.attr}() mint outside "
                    "obs/federate.py — host/fleet are RESERVED for the "
                    "fleet Collector's federated merge "
                    "(obs.registry.RESERVED_LABELS); pick another label "
                    "key, or route the series through the fleet plane",
                )
                return

    def _check_objective_metric(self, node: ast.Call) -> None:
        """GL032 (schema half): an ``Objective(...)`` construction whose
        LITERAL metric name is not in the pre-declared STANDARD schema.
        A typo'd metric fails nothing at runtime — the objective simply
        never has history to burn on, the silent-green failure mode an
        SLO engine exists to prevent. Positional arg 3 (``metric``) and
        the ``metric``/``metric_b`` keywords are checked; computed
        names are out of scope, like GL030."""
        f = node.func
        name = (
            f.attr if isinstance(f, ast.Attribute)
            else f.id if isinstance(f, ast.Name) else None
        )
        if name != "Objective":
            return
        candidates = []
        if len(node.args) >= 3:
            candidates.append(node.args[2])
        for kw in node.keywords:
            if kw.arg in ("metric", "metric_b"):
                candidates.append(kw.value)
        from analyzer_tpu.obs.registry import (
            STANDARD_COUNTERS,
            STANDARD_GAUGES,
            STANDARD_HISTOGRAMS,
        )

        schema = set(STANDARD_COUNTERS) | set(STANDARD_GAUGES) | set(
            STANDARD_HISTOGRAMS
        )
        for arg in candidates:
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            metric = arg.value
            if not metric or metric in schema:
                continue
            self._flag(
                "GL032", arg,
                f'SLO objective metric "{metric}" is not in the '
                "pre-declared STANDARD schema (obs.registry) — a typo'd "
                "metric has no history rings and the objective silently "
                "never burns; declare the series or fix the name",
            )

    def _check_lineage_publish(self, node: ast.Call, cutover_ranges) -> None:
        """GL033 (publish + cutover halves): inside migrate/, a view-
        publish call must target a STAGING-named lineage (any name in the
        receiver chain containing ``staging`` or ``backfill``), and
        ``cutover_from`` may be called only inside the designated
        ``cutover`` entry — the structural guarantee that backfill code
        cannot displace the views live traffic is served from except
        through the one atomic, audited swap."""
        f = node.func
        if not isinstance(f, ast.Attribute):
            return
        if f.attr == "cutover_from":
            if any(lo <= node.lineno <= hi for lo, hi in cutover_ranges):
                return
            self._flag(
                "GL033", node,
                "cutover_from called outside the designated cutover "
                "entry — the live lineage swap must go through "
                "migrate.lineage.cutover so it is counted, measured and "
                "single-sited",
            )
            return
        if f.attr not in _GL033_PUBLISH:
            return
        names = [
            n.id.lower() for n in ast.walk(f.value)
            if isinstance(n, ast.Name)
        ] + [
            n.attr.lower() for n in ast.walk(f.value)
            if isinstance(n, ast.Attribute)
        ]
        if any("staging" in n or "backfill" in n for n in names):
            return
        self._flag(
            "GL033", node,
            f"`{f.attr}` on a non-staging lineage in backfill code — "
            "migrate/ may publish only into the staging lineage; the "
            "live lineage is reached through migrate.lineage.cutover "
            "(the atomic swap), never by direct publish",
        )

    def _check_soak_determinism(self, node: ast.Call) -> None:
        """GL028: unseeded randomness or wall-clock reads inside
        ``analyzer_tpu/loadgen/`` — the soak harness's contract is a
        bit-identical artifact per (seed, config), so every decision
        must flow from a seeded ``np.random.default_rng`` stream or the
        virtual clock. Flags:

          * any call into the stdlib ``random`` module (one hidden
            process-global stream, seeded or not — callers can't tell);
          * ``np.random.default_rng()`` with NO seed argument (OS
            entropy), and the legacy global-stream ``np.random.<fn>()``
            functions (lowercase module-level draws); constructing
            ``Generator``/``SeedSequence``/bit generators with explicit
            state stays legal;
          * the wall clocks in :data:`_GL028_CLOCKS` — pacing sleeps
            and measured-latency reads are legitimate and carry
            line-scoped disables with reasons.
        """
        resolved = self.imports.resolve(node.func)
        if resolved is None:
            return
        if resolved in _GL028_CLOCKS:
            self._flag(
                "GL028", node,
                f"wall-clock read `{resolved}` in the soak harness's "
                "decision path — pace and decide on the driver's "
                "VirtualClock so the soak replays bit-identically per "
                "seed; a realtime pacing sleep or measured-latency "
                "read carries a line-scoped disable with a reason",
            )
            return
        if resolved == "random" or resolved.startswith("random."):
            self._flag(
                "GL028", node,
                "stdlib `random` in the soak harness draws from one "
                "hidden process-global stream — use a seeded "
                "np.random.default_rng(...) stream owned by the caller",
            )
            return
        if resolved == "numpy.random.default_rng":
            if not node.args and not node.keywords:
                self._flag(
                    "GL028", node,
                    "np.random.default_rng() with no seed pulls OS "
                    "entropy — the soak must be deterministic per "
                    "seed; pass the seed (or a SeedSequence) in",
                )
            return
        if resolved.startswith("numpy.random."):
            tail = resolved.rsplit(".", 1)[-1]
            if tail and tail[0].islower():
                self._flag(
                    "GL028", node,
                    f"global-stream `np.random.{tail}` in the soak "
                    "harness shares (and mutates) one hidden process "
                    "RNG — draw from a seeded default_rng(...) "
                    "generator instead",
                )

    def _check_raw_clock(self, node: ast.Call) -> None:
        """GL023: ``time.perf_counter()`` (or a bare imported
        ``perf_counter()``) in the service/sched layers — timing there
        belongs on the obs registry/tracer so it lands in snapshots."""
        f = node.func
        named = (
            (isinstance(f, ast.Attribute) and f.attr == "perf_counter")
            or (isinstance(f, ast.Name) and f.id == "perf_counter")
        )
        if named:
            self._flag(
                "GL023", node,
                "raw time.perf_counter() timing in the service/sched "
                "layer is invisible to metrics snapshots; use "
                "analyzer_tpu.obs (PhaseTimer / tracer spans), or "
                "disable with a reason if the clock feeds a non-metrics "
                "contract",
            )

    def _check_device_sync(self, node: ast.Call) -> None:
        """GL025: a blocking host sync in the sched feed/runner hot path.

        ``x.block_until_ready()`` always flags; ``np.asarray``/
        ``np.array`` (resolved through the module's imports) flags when
        the first argument is not an obvious host literal — in this
        layer the non-literal argument is a (potential) device array and
        the call a serializing D2H fetch. The sanctioned patterns are
        ``utils.host.fetch_tree`` (async-started tree fetch) and
        ``copy_to_host_async`` at chunk boundaries; a deliberate sync
        carries a line-scoped disable with a reason."""
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "block_until_ready":
            self._flag(
                "GL025", node,
                ".block_until_ready() in the sched hot path serializes "
                "the prefetched feed (the consumer stalls instead of "
                "dispatching the next chunk); let the data dependency "
                "synchronize, or disable with a reason at an intentional "
                "chunk-boundary sync",
            )
            return
        resolved = self.imports.resolve(f)
        if (
            resolved in ("numpy.asarray", "numpy.array")
            and node.args
            and not isinstance(node.args[0], _LITERAL_ARGS)
        ):
            self._flag(
                "GL025", node,
                "np.asarray/np.array on a (potential) device array in "
                "the sched hot path is a blocking D2H fetch that "
                "serializes the prefetched feed; use "
                "utils.host.fetch_tree / copy_to_host_async at chunk "
                "boundaries, or disable with a reason for an "
                "intentional sync",
            )

    def _check_try(self, node: ast.Try) -> None:
        body_imports = _contains_import(node.body)
        for handler in node.handlers:
            if handler.type is None:
                self._flag(
                    "GL020", handler,
                    "bare `except:` also swallows SystemExit/"
                    "KeyboardInterrupt; catch Exception (or narrower) "
                    "and say why",
                )
                if body_imports:
                    self._flag(
                        "GL021", handler,
                        "import fallback guarded by a bare except — a "
                        "broken module (SyntaxError, bad native build) "
                        "silently engages the fallback; catch ImportError",
                    )
            elif body_imports and _handler_names(handler) & _BROAD:
                self._flag(
                    "GL021", handler,
                    "import fallback guarded by `except "
                    f"{'/'.join(sorted(_handler_names(handler) & _BROAD))}` "
                    "— a broken module (SyntaxError, bad native build) "
                    "silently engages the fallback; catch ImportError",
                )

    def _check_defaults(self, fn) -> None:
        params = [*fn.args.posonlyargs, *fn.args.args]
        pairs = list(
            zip(params[len(params) - len(fn.args.defaults):], fn.args.defaults)
        )
        pairs += [
            (p, d)
            for p, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults)
            if d is not None
        ]
        for param, default in pairs:
            mutable = isinstance(default, _MUTABLE_LITERALS) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in _MUTABLE_CALLS
                and not default.args
                and not default.keywords
            )
            if mutable:
                self._flag(
                    "GL022", default,
                    f"mutable default for `{param.arg}` is shared across "
                    "calls; default to None and allocate inside",
                )
