"""graftlint — the repo's JAX-hazard + native-ABI static analysis pass.

Run ``python -m analyzer_tpu.lint [paths]`` (or ``python -m
analyzer_tpu.cli lint``). Rule catalog and suppression syntax:
``docs/lint.md``. Pure stdlib ``ast`` — importing this package never
imports jax/numpy, so it lints in milliseconds anywhere.
"""

from analyzer_tpu.lint.findings import RULES, Finding
from analyzer_tpu.lint.runner import lint_paths, lint_source

__all__ = ["Finding", "RULES", "lint_paths", "lint_source"]
