"""JAX hazard rules (GL001-GL009): host-device sync points inside jitted
code, PRNG key hygiene, and retrace storms.

Everything here is stdlib ``ast`` — the linter never imports jax (it must
stay lint-fast and runnable on machines with no accelerator stack). The
analysis is deliberately conservative:

* "Jitted context" = a function decorated with ``jax.jit`` /
  ``partial(jax.jit, ...)``, a def wrapped by name anywhere in the module
  (``f2 = jax.jit(f)``), or any def nested inside one (scan/cond bodies).
  Functions merely *called from* jitted code are not chased — that would
  need whole-program analysis and the callee is usually jitted (or
  jit-safe) in its own right.
* "Traced" = the jitted function's parameters minus its
  ``static_argnames``/``static_argnums``, propagated through simple
  assignments. Shape/dtype attribute reads (``x.shape``, ``x.ndim``,
  ...) and ``len(x)`` are static under trace and do not taint.

False negatives are acceptable; false positives are bugs (the clean-tree
test pins zero findings over the package, so every spurious rule firing
breaks CI).
"""

from __future__ import annotations

import ast

from analyzer_tpu.lint.findings import Finding

#: Attribute reads on a traced array that are static under trace.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "sharding"}

#: Builtins whose result over a traced array is static (rank/type info).
_STATIC_CALLS = {"len", "isinstance", "hasattr", "getattr", "type"}

_KEY_PRODUCERS = {"jax.random.PRNGKey", "jax.random.key", "jax.random.split",
                  "jax.random.fold_in", "jax.random.wrap_key_data"}
#: Consuming a key through these is the sanctioned terminal use.
_KEY_MINTERS = {"jax.random.PRNGKey", "jax.random.key"}

_DEBUG_CALLS = {"jax.debug.print", "jax.debug.breakpoint", "jax.debug.callback",
                "jax.debug.visualize_array_sharding"}

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                     ast.SetComp)


class _Imports:
    """Local-name -> dotted-path resolution from the module's imports."""

    def __init__(self, tree: ast.Module):
        self.table: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        self.table[alias.asname] = alias.name
                    else:
                        head = alias.name.split(".")[0]
                        self.table[head] = head
            elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
                for alias in node.names:
                    self.table[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}"
                    )

    def resolve(self, node: ast.AST) -> str | None:
        """Dotted path of a Name/Attribute chain, e.g. ``jnp.pad`` ->
        ``jax.numpy.pad``; None for anything not a plain chain."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        head = self.table.get(node.id, node.id)
        return ".".join([head, *reversed(parts)])


def _jit_spec(imports: _Imports, call_or_deco: ast.AST):
    """(is_jit, static_names, static_nums) for a decorator/call expression.

    Recognizes ``jax.jit``, bare ``jit`` imported from jax, and
    ``partial(jax.jit, ...)`` (functools.partial by any alias)."""
    node = call_or_deco
    kwargs: list[ast.keyword] = []
    if isinstance(node, ast.Call):
        resolved = imports.resolve(node.func)
        if resolved == "functools.partial" and node.args:
            inner = imports.resolve(node.args[0])
            if inner != "jax.jit":
                return False, set(), set()
            kwargs = node.keywords
        elif resolved == "jax.jit":
            kwargs = node.keywords
        else:
            return False, set(), set()
    elif imports.resolve(node) != "jax.jit":
        return False, set(), set()
    names: set[str] = set()
    nums: set[int] = set()
    for kw in kwargs:
        vals = (
            kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List))
            else [kw.value]
        )
        consts = [v.value for v in vals if isinstance(v, ast.Constant)]
        if kw.arg == "static_argnames":
            names.update(c for c in consts if isinstance(c, str))
        elif kw.arg == "static_argnums":
            nums.update(c for c in consts if isinstance(c, int))
    return True, names, nums


def _positional_params(fn: ast.FunctionDef) -> list[str]:
    return [a.arg for a in (*fn.args.posonlyargs, *fn.args.args)]


def _all_params(fn: ast.FunctionDef) -> list[str]:
    out = _positional_params(fn) + [a.arg for a in fn.args.kwonlyargs]
    for v in (fn.args.vararg, fn.args.kwarg):
        if v is not None:
            out.append(v.arg)
    return out


def _mentions_traced(node: ast.AST, traced: set[str]) -> bool:
    """Whether evaluating ``node`` touches a traced value — with the
    static escape hatches (``x.shape``, ``len(x)``, ...) excluded."""
    if isinstance(node, ast.Name):
        return node.id in traced
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
        return False
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id in _STATIC_CALLS:
            return False
        return any(
            _mentions_traced(c, traced) for c in ast.iter_child_nodes(node)
        )
    return any(_mentions_traced(c, traced) for c in ast.iter_child_nodes(node))


def _traced_bool_test(test: ast.AST, traced: set[str]) -> bool:
    """Whether an if/while test would force a traced value to a Python
    bool. ``x is None`` comparisons are host-side and fine."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _traced_bool_test(test.operand, traced)
    if isinstance(test, ast.BoolOp):
        return any(_traced_bool_test(v, traced) for v in test.values)
    if isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
    ):
        return False
    return _mentions_traced(test, traced)


class _JittedBody(ast.NodeVisitor):
    """Flags GL001-GL004 inside one jitted function body."""

    def __init__(self, module: "JaxHazards", traced: set[str]):
        self.m = module
        self.traced = traced

    def _flag(self, rule: str, node: ast.AST, msg: str) -> None:
        self.m.flag(rule, node, msg)

    def _taint_targets(self, targets, value) -> None:
        if value is None or not _mentions_traced(value, self.traced):
            return
        for t in targets:
            for leaf in ast.walk(t):
                if isinstance(leaf, ast.Name):
                    self.traced.add(leaf.id)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        self._taint_targets(node.targets, node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self.visit(node.value)
            self._taint_targets([node.target], node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        self._taint_targets([node.target], node.value)

    def visit_For(self, node: ast.For) -> None:
        self.visit(node.iter)
        self._taint_targets([node.target], node.iter)
        for stmt in (*node.body, *node.orelse):
            self.visit(stmt)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # A def nested in jitted code (scan/cond body) traces its params.
        inner = _JittedBody(self.m, self.traced | set(_all_params(node)))
        for stmt in node.body:
            inner.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_If(self, node: ast.If) -> None:
        if _traced_bool_test(node.test, self.traced):
            self._flag(
                "GL004", node,
                "Python `if` on a traced value inside jitted code — this "
                "either crashes at trace time or bakes one branch in; use "
                "jnp.where / lax.cond",
            )
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        if _traced_bool_test(node.test, self.traced):
            self._flag(
                "GL004", node,
                "Python `while` on a traced value inside jitted code — use "
                "lax.while_loop",
            )
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        if _traced_bool_test(node.test, self.traced):
            self._flag(
                "GL004", node,
                "ternary on a traced value inside jitted code — use "
                "jnp.where",
            )
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        if _traced_bool_test(node.test, self.traced):
            self._flag(
                "GL004", node,
                "assert on a traced value inside jitted code — use "
                "checkify or a host-side precondition",
            )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr in ("item", "tolist")
            and _mentions_traced(func.value, self.traced)
        ):
            self._flag(
                "GL001", node,
                f".{func.attr}() on a traced value inside jitted code "
                "forces a host-device sync (or fails to trace)",
            )
        if (
            isinstance(func, ast.Name)
            and func.id in ("float", "int", "bool", "complex")
            and node.args
            and any(_mentions_traced(a, self.traced) for a in node.args)
        ):
            self._flag(
                "GL002", node,
                f"{func.id}() on a traced value inside jitted code forces "
                "a host-device sync (or fails to trace); keep it an array "
                "or make the argument static",
            )
        resolved = self.m.imports.resolve(func)
        if (
            resolved in ("numpy.asarray", "numpy.array")
            and any(_mentions_traced(a, self.traced) for a in node.args)
        ):
            self._flag(
                "GL003", node,
                "np.asarray/np.array on a traced value inside jitted code "
                "pulls the array to host; use jnp.asarray",
            )
        self.generic_visit(node)


class JaxHazards:
    """One module's GL001-GL009 pass. ``run`` returns raw findings
    (suppressions are applied by the runner)."""

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.tree = tree
        self.imports = _Imports(tree)
        self.findings: list[Finding] = []
        self._seen: set[tuple] = set()

    def flag(self, rule: str, node: ast.AST, msg: str) -> None:
        key = (rule, node.lineno, node.col_offset)
        if key not in self._seen:
            self._seen.add(key)
            self.findings.append(
                Finding(rule, self.path, node.lineno, node.col_offset + 1, msg)
            )

    # ------------------------------------------------------------------
    def run(self) -> list[Finding]:
        defs: dict[str, list[ast.FunctionDef]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)

        jitted: list[tuple[ast.FunctionDef, set[str], set[int]]] = []
        for name, fns in defs.items():
            for fn in fns:
                for deco in fn.decorator_list:
                    is_jit, names, nums = _jit_spec(self.imports, deco)
                    if is_jit:
                        jitted.append((fn, names, nums))
                        self._check_static_defaults(fn, names, nums)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            is_jit, names, nums = _jit_spec(self.imports, node)
            if not is_jit or isinstance(node.func, ast.Name):
                # partial(jax.jit, ...) used as decorator lands here too
                # when scanned as a bare Call; only wrap-by-name counts.
                pass
            if is_jit and isinstance(node.args[0], ast.Name):
                for fn in defs.get(node.args[0].id, []):
                    jitted.append((fn, names, nums))
                    self._check_static_defaults(fn, names, nums)

        analyzed: set[int] = set()
        for fn, names, nums in jitted:
            if id(fn) in analyzed:
                continue
            analyzed.add(id(fn))
            pos = _positional_params(fn)
            static = set(names)
            static.update(pos[i] for i in nums if i < len(pos))
            traced = {p for p in _all_params(fn) if p not in static}
            traced.discard("self")
            body = _JittedBody(self, traced)
            for stmt in fn.body:
                body.visit(stmt)

        self._check_loops_and_debug()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_keys(node)
        return self.findings

    # ------------------------------------------------------------------
    def _check_static_defaults(self, fn, names: set[str], nums: set[int]):
        """GL008: a static arg default that is unhashable retraces (or
        crashes) on every call that relies on it."""
        pos = _positional_params(fn)
        static = set(names) | {pos[i] for i in nums if i < len(pos)}
        params = [*fn.args.posonlyargs, *fn.args.args]
        defaults = fn.args.defaults
        for param, default in zip(params[len(params) - len(defaults):], defaults):
            if param.arg in static and isinstance(default, _MUTABLE_LITERALS):
                self.flag(
                    "GL008", default,
                    f"static arg `{param.arg}` has a mutable (unhashable) "
                    "default — jit requires hashable statics; use a tuple "
                    "or None-sentinel",
                )
        for param, default in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
            if (
                default is not None
                and param.arg in static
                and isinstance(default, _MUTABLE_LITERALS)
            ):
                self.flag(
                    "GL008", default,
                    f"static arg `{param.arg}` has a mutable (unhashable) "
                    "default — jit requires hashable statics; use a tuple "
                    "or None-sentinel",
                )

    # ------------------------------------------------------------------
    def _check_loops_and_debug(self) -> None:
        """GL007 (jit built inside a loop body) and GL009 (jax.debug.*).

        Loop context resets at nested def boundaries: a function defined
        inside a loop runs elsewhere, but its *decorators* evaluate in
        the loop, so a jit-decorated def inside a loop still flags."""

        hazards = self

        class V(ast.NodeVisitor):
            def __init__(self, loop_depth: int = 0):
                self.loop_depth = loop_depth

            def _loop(self, node):
                inner = V(self.loop_depth + 1)
                for child in ast.iter_child_nodes(node):
                    inner.visit(child)

            visit_For = visit_While = visit_AsyncFor = _loop

            def visit_FunctionDef(self, node):
                for deco in node.decorator_list:
                    if self.loop_depth and _jit_spec(hazards.imports, deco)[0]:
                        hazards.flag(
                            "GL007", deco,
                            "jit-decorated function built inside a loop "
                            "body — every iteration mints a fresh jit "
                            "cache (retrace storm); hoist the jit",
                        )
                body = V(0)
                for child in node.body:
                    body.visit(child)

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Call(self, node):
                if self.loop_depth and _jit_spec(hazards.imports, node)[0]:
                    hazards.flag(
                        "GL007", node,
                        "jax.jit(...) called inside a loop body — every "
                        "iteration mints a fresh jit cache (retrace "
                        "storm); hoist the jit",
                    )
                if hazards.imports.resolve(node.func) in _DEBUG_CALLS:
                    hazards.flag(
                        "GL009", node,
                        "leftover jax.debug.* call — host callbacks "
                        "serialize the device stream; remove before "
                        "shipping",
                    )
                self.generic_visit(node)

        V().visit(self.tree)

    # ------------------------------------------------------------------
    def _is_key_producer(self, node: ast.AST) -> str | None:
        if isinstance(node, ast.Call):
            resolved = self.imports.resolve(node.func)
            if resolved in _KEY_PRODUCERS:
                return resolved
        return None

    def _check_keys(self, fn: ast.FunctionDef) -> None:
        """GL005/GL006 for one function scope, statements in order.

        Key names are bound by ``k = PRNGKey(...)`` / ``a, b = split(k)``;
        every later plain-Name use consumes the key. Two consumptions of
        the same binding (or one consumption inside a loop the binding is
        outside of) is reuse — identical randomness at both sites.
        Subscript reads (``keys[i]``) are exempt: elements of a split are
        distinct keys."""
        literal_defaults = self._literal_default_params(fn)
        bindings: dict[str, dict] = {}

        def note_mint(call: ast.Call) -> None:
            resolved = self.imports.resolve(call.func)
            if resolved in _KEY_MINTERS and call.args:
                seed = call.args[0]
                if isinstance(seed, ast.Constant) and isinstance(seed.value, int):
                    self.flag(
                        "GL006", call,
                        "PRNG key minted from a literal seed in library "
                        "code — every call site gets the same stream; "
                        "take the seed (or a key) from the caller",
                    )
                elif (
                    isinstance(seed, ast.Name) and seed.id in literal_defaults
                ):
                    self.flag(
                        "GL006", call,
                        f"PRNG key minted from `{seed.id}` whose default "
                        f"is the literal {literal_defaults[seed.id]!r} — "
                        "callers that omit it silently share one stream; "
                        "make the seed required at the mint site",
                    )

        def consume(
            name_node: ast.Name, loop_depth: int, rebinding: set[str] = frozenset()
        ) -> None:
            b = bindings.get(name_node.id)
            if b is None:
                return
            # `key, sub = split(key)` in a loop rebinds the name every
            # iteration — the split consumption never repeats on the
            # same binding, so it must not take the in-loop weight.
            in_loop = loop_depth > b["loop_depth"] and (
                name_node.id not in rebinding
            )
            weight = 2 if in_loop else 1
            b["uses"] += weight
            if b["uses"] >= 2 and not b["flagged"]:
                b["flagged"] = True
                self.flag(
                    "GL005", name_node,
                    f"PRNG key `{name_node.id}` reused without an "
                    "interposing split — both consumers draw identical "
                    "randomness; jax.random.split it first",
                )

        def walk_expr(
            node: ast.AST, loop_depth: int, skip: set[int],
            rebinding: set[str] = frozenset(),
        ) -> None:
            for sub in ast.walk(node):
                if id(sub) in skip:
                    continue
                if isinstance(sub, ast.Call):
                    note_mint(sub)
                if isinstance(sub, ast.Subscript):
                    # keys[i]: element reads are distinct keys
                    skip.update(id(x) for x in ast.walk(sub.value))
                if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                    consume(sub, loop_depth, rebinding)

        def bind_targets(targets, value, loop_depth: int) -> None:
            produced = self._is_key_producer(value)
            names: list[str] = []
            for t in targets:
                if isinstance(t, ast.Name):
                    names.append(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    names.extend(
                        e.id for e in t.elts if isinstance(e, ast.Name)
                    )
            for n in names:
                if produced:
                    bindings[n] = {
                        "uses": 0, "flagged": False, "loop_depth": loop_depth
                    }
                else:
                    bindings.pop(n, None)  # rebound to a non-key

        def walk_stmt(stmt: ast.stmt, loop_depth: int) -> None:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return  # own scope, checked separately
            if isinstance(stmt, ast.Assign):
                rebinding = (
                    {
                        leaf.id
                        for t in stmt.targets
                        for leaf in ast.walk(t)
                        if isinstance(leaf, ast.Name)
                    }
                    if self._is_key_producer(stmt.value)
                    else frozenset()
                )
                walk_expr(stmt.value, loop_depth, set(), rebinding)
                bind_targets(stmt.targets, stmt.value, loop_depth)
                return
            if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                walk_expr(stmt.value, loop_depth, set())
                bind_targets([stmt.target], stmt.value, loop_depth)
                return
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                walk_expr(stmt.iter, loop_depth, set())
                for s in (*stmt.body, *stmt.orelse):
                    walk_stmt(s, loop_depth + 1)
                return
            if isinstance(stmt, ast.While):
                walk_expr(stmt.test, loop_depth + 1, set())
                for s in (*stmt.body, *stmt.orelse):
                    walk_stmt(s, loop_depth + 1)
                return
            if isinstance(stmt, (ast.If, ast.With, ast.Try)):
                for expr in ast.iter_child_nodes(stmt):
                    if isinstance(expr, ast.expr):
                        walk_expr(expr, loop_depth, set())
                for field in ("body", "orelse", "finalbody", "handlers", "items"):
                    for s in getattr(stmt, field, []):
                        if isinstance(s, ast.stmt):
                            walk_stmt(s, loop_depth)
                        elif isinstance(s, ast.ExceptHandler):
                            for inner in s.body:
                                walk_stmt(inner, loop_depth)
                return
            walk_expr(stmt, loop_depth, set())

        for stmt in fn.body:
            walk_stmt(stmt, 0)

    @staticmethod
    def _literal_default_params(fn: ast.FunctionDef) -> dict[str, int]:
        """Param name -> literal-int default, for the GL006 defaulted-seed
        check."""
        out: dict[str, int] = {}
        params = [*fn.args.posonlyargs, *fn.args.args]
        defaults = fn.args.defaults
        for param, default in zip(params[len(params) - len(defaults):], defaults):
            if isinstance(default, ast.Constant) and isinstance(default.value, int):
                out[param.arg] = default.value
        for param, default in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
            if (
                default is not None
                and isinstance(default, ast.Constant)
                and isinstance(default.value, int)
            ):
                out[param.arg] = default.value
        return out
