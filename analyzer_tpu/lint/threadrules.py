"""Thread-ownership rules GL040-GL045, run against the ProjectModel.

These are the cross-module rules per-file AST cannot express: attribute
ownership (who writes what from which thread), buffer lifetime across
GIL-released native calls, lock-order cycles over the whole tree,
callbacks fired under locks, Condition.wait predicate loops, and
module-global writes from threaded modules.

Conservatism contract matches the per-file families: false negatives
are acceptable, false positives break the clean-tree test and must be
fixed in the rule.
"""

from __future__ import annotations

import ast
import time

from analyzer_tpu.lint.findings import Finding
from analyzer_tpu.lint.ownership import OWNED_ATTRS
from analyzer_tpu.lint.project import FuncInfo, ModuleInfo, ProjectModel

#: Callback-shaped terminal callee names for GL043. ``notify_progress``
#: and Condition.notify* are excluded on purpose: notifying under the
#: lock is the documented Condition idiom.
_HOOK_SUFFIXES = ("_hook", "_callback")


def _callbacky(name: str) -> bool:
    if name.startswith("on_") and len(name) > 3:
        return True
    if name.endswith(_HOOK_SUFFIXES):
        return True
    return name == "callback"


def _owner_of(cls_path: str, attr: str) -> str | None:
    roles = OWNED_ATTRS.get(cls_path, {})
    for role, attrs in roles.items():
        if attr in attrs:
            return role
    return None


# ---------------------------------------------------------------- GL040


def _check_gl040(model: ProjectModel) -> list[Finding]:
    out: list[Finding] = []
    for mod in model.modules.values():
        for w in mod.attr_writes:
            if w.func is None or w.func.cls is None:
                continue
            cls_path = f"{mod.name}.{w.func.cls}"
            owner = _owner_of(cls_path, w.attr)
            if owner is None:
                continue
            method = w.func.qualname.split(".")[-1]
            if method == "__init__":
                continue  # constructor runs before any thread is spawned
            if w.func.role == owner:
                continue
            claimed = (
                f"role {w.func.role!r}" if w.func.role else "no thread_role"
            )
            out.append(Finding(
                "GL040", mod.path, w.line, w.col,
                f"self.{w.attr} is owned by the {owner} thread "
                f"(OWNED_ATTRS[{cls_path!r}]) but {w.func.qualname} "
                f"claims {claimed}; annotate the method with "
                f"@thread_role({owner!r}) or move the write",
            ))
    return out


# ---------------------------------------------------------------- GL041


def _check_gl041(model: ProjectModel) -> list[Finding]:
    out: list[Finding] = []
    for mod in model.modules.values():
        reassigned = _self_attrs_reassigned_outside_init(mod)
        for entry, call, func in mod.native_calls:
            for arg in call.args:
                # (a) self.X passed by pointer where some OTHER method
                # of the class plainly rebinds self.X — the binding can
                # change (freeing the old buffer) while the GIL-released
                # loop still writes through the stale pointer.
                if (
                    isinstance(arg, ast.Attribute)
                    and isinstance(arg.value, ast.Name)
                    and arg.value.id == "self"
                    and func is not None and func.cls is not None
                    and arg.attr in reassigned.get(func.cls, set())
                ):
                    out.append(Finding(
                        "GL041", mod.path, call.lineno, call.col_offset,
                        f"self.{arg.attr} is passed into GIL-released "
                        f"native entry {entry}() but is reassigned "
                        f"outside __init__ elsewhere in {func.cls}; the "
                        f"old buffer can be freed while the native loop "
                        f"still writes through it — make the binding "
                        f"immutable after __init__ or copy before the "
                        f"call",
                    ))
        out.extend(_gl041_stale_pointers(mod))
    return out


def _self_attrs_reassigned_outside_init(
    mod: ModuleInfo,
) -> dict[str, set[str]]:
    """class name -> self attrs rebound (plain Assign, not subscript)
    outside __init__."""
    out: dict[str, set[str]] = {}
    for w in mod.attr_writes:
        if w.subscript or w.func is None or w.func.cls is None:
            continue
        if w.func.qualname.split(".")[-1] == "__init__":
            continue
        out.setdefault(w.func.cls, set()).add(w.attr)
    return out


def _gl041_stale_pointers(mod: ModuleInfo) -> list[Finding]:
    """Local flavor: ``p = x.ctypes.data_as(...)`` (or ``.ctypes.data``)
    followed by a rebind or ``del`` of ``x`` before a later call using
    ``p`` — the pointer outlives the array that backs it. Linear
    statement-order scan per function body."""
    out: list[Finding] = []
    for fi in mod.funcs:
        body = getattr(fi.node, "body", None)
        if not body:
            continue
        ptr_of: dict[str, str] = {}      # pointer var -> source array var
        dead: set[str] = set()           # array vars rebound/deleted
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    for arg in node.args:
                        if (
                            isinstance(arg, ast.Name)
                            and arg.id in ptr_of
                            and ptr_of[arg.id] in dead
                        ):
                            out.append(Finding(
                                "GL041", mod.path, node.lineno,
                                node.col_offset,
                                f"pointer {arg.id} was taken from "
                                f"{ptr_of[arg.id]}.ctypes but "
                                f"{ptr_of[arg.id]} was rebound or "
                                f"deleted before this call; the buffer "
                                f"behind the pointer may already be "
                                f"freed",
                            ))
            if isinstance(stmt, ast.Assign):
                src = _ctypes_pointer_source(stmt.value)
                for t in stmt.targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if src is not None:
                        ptr_of[t.id] = src
                        continue
                    if t.id in ptr_of:
                        del ptr_of[t.id]
                    if t.id in {a for a in ptr_of.values()}:
                        dead.add(t.id)
            elif isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        dead.add(t.id)
    return out


def _ctypes_pointer_source(value: ast.AST) -> str | None:
    """Array name behind ``x.ctypes.data_as(...)`` / ``x.ctypes.data``."""
    node = value
    if isinstance(node, ast.Call):
        node = node.func
    # walk: data_as -> ctypes -> x  /  data -> ctypes -> x
    if isinstance(node, ast.Attribute) and node.attr in ("data_as", "data"):
        inner = node.value
        if isinstance(inner, ast.Attribute) and inner.attr == "ctypes":
            if isinstance(inner.value, ast.Name):
                return inner.value.id
    return None


# ---------------------------------------------------------------- GL042


def _check_gl042(model: ProjectModel) -> list[Finding]:
    # Edge set: (from lock, to lock) -> first (path, line, col) seen.
    edges: dict[tuple[str, str], tuple[str, int, int]] = {}
    for mod in model.modules.values():
        for site in mod.lock_sites:
            for held in site.held:
                if held != site.ident:
                    edges.setdefault(
                        (held, site.ident), (mod.path, site.line, site.col)
                    )
        # One-level call graph: while holding L, calling a same-class
        # method (self.m()) or an imports-resolved module function that
        # acquires M at its top level adds L -> M.
        for held_stack, call, func in mod.calls_under_lock:
            for target in _resolved_acquisitions(model, mod, call, func):
                for held in held_stack:
                    if held != target:
                        edges.setdefault(
                            (held, target),
                            (mod.path, call.lineno, call.col_offset),
                        )
    # Cycle detection over the edge graph; report each edge that sits on
    # a cycle, at the site that created it.
    adj: dict[str, set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    out: list[Finding] = []
    seen: set[tuple[str, int]] = set()
    for (a, b), (path, line, col) in sorted(edges.items()):
        if _reaches(adj, b, a):
            if (path, line) in seen:
                continue
            seen.add((path, line))
            out.append(Finding(
                "GL042", path, line, col,
                f"lock-order cycle: {a} is held while acquiring {b}, "
                f"but elsewhere {b} is held while (transitively) "
                f"acquiring {a}; two threads taking the locks in "
                f"opposite orders deadlock — pick one global order",
            ))
    return out


def _resolved_acquisitions(
    model: ProjectModel, mod: ModuleInfo, call: ast.Call,
    func: FuncInfo | None,
) -> set[str]:
    callee = call.func
    # self.method() -> same class, same module.
    if (
        isinstance(callee, ast.Attribute)
        and isinstance(callee.value, ast.Name)
        and callee.value.id == "self"
        and func is not None and func.cls is not None
    ):
        return set(
            mod.acquires_by_func.get(f"{func.cls}.{callee.attr}", ())
        )
    # module.func() via the import table -> that module's top level.
    resolved = mod.imports.resolve(callee)
    if resolved and "." in resolved:
        target_mod, target_fn = resolved.rsplit(".", 1)
        other = model.modules.get(target_mod)
        if other is not None:
            return set(other.acquires_by_func.get(target_fn, ()))
    return set()


def _reaches(adj: dict[str, set[str]], src: str, dst: str) -> bool:
    stack, seen = [src], {src}
    while stack:
        cur = stack.pop()
        if cur == dst:
            return True
        for nxt in adj.get(cur, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


# ---------------------------------------------------------------- GL043


def _check_gl043(model: ProjectModel) -> list[Finding]:
    out: list[Finding] = []
    for mod in model.modules.values():
        for held, call, _func in mod.calls_under_lock:
            callee = call.func
            name = (
                callee.attr if isinstance(callee, ast.Attribute)
                else callee.id if isinstance(callee, ast.Name) else None
            )
            if name is None or not _callbacky(name):
                continue
            out.append(Finding(
                "GL043", mod.path, call.lineno, call.col_offset,
                f"user callback {name}() invoked while holding "
                f"{', '.join(held)}; a callback that blocks or "
                f"re-enters the lock deadlocks the owner — snapshot "
                f"under the lock, invoke after releasing it",
            ))
    return out


# ---------------------------------------------------------------- GL044


def _check_gl044(model: ProjectModel) -> list[Finding]:
    out: list[Finding] = []
    for mod in model.modules.values():
        for call, _func, ctx in mod.cond_waits:
            if ctx.in_loop and not ctx.loop_is_while_true:
                continue  # predicate loop: while <pred>: cv.wait(...)
            if ctx.in_loop and ctx.loop_is_while_true and ctx.has_timeout:
                continue  # timed poll inside an explicit forever-loop
            shape = (
                "inside `while True:` without a timeout"
                if ctx.in_loop else "outside any loop"
            )
            out.append(Finding(
                "GL044", mod.path, call.lineno, call.col_offset,
                f"Condition.wait() {shape}; spurious wakeups and "
                f"stolen notifications are legal, so wait must sit in "
                f"`while <predicate>: cond.wait()` (or carry a timeout "
                f"inside an explicit poll loop)",
            ))
    return out


# ---------------------------------------------------------------- GL045


def _check_gl045(model: ProjectModel) -> list[Finding]:
    out: list[Finding] = []
    for mod in model.modules.values():
        if not mod.uses_thread_role:
            continue
        for name, node, func, lock_held in mod.global_writes:
            if lock_held:
                continue
            out.append(Finding(
                "GL045", mod.path, node.lineno, node.col_offset,
                f"module-global {name!r} written from "
                f"{func.qualname if func else '<module>'} without a "
                f"lock, in a thread-role-annotated module; any thread "
                f"may call in — guard the write with a module lock "
                f"(see sched.feed.get_arena) or move the state onto an "
                f"instance",
            ))
    return out


_CHECKS = [
    ("GL040", _check_gl040),
    ("GL041", _check_gl041),
    ("GL042", _check_gl042),
    ("GL043", _check_gl043),
    ("GL044", _check_gl044),
    ("GL045", _check_gl045),
]


def check_project(
    model: ProjectModel, timings: dict[str, float] | None = None,
) -> list[Finding]:
    """Runs every thread rule over the model. ``timings`` (if given)
    collects per-rule wall seconds for the CLI's --json output."""
    out: list[Finding] = []
    for rule_id, check in _CHECKS:
        t0 = time.perf_counter()
        out.extend(check(model))
        if timings is not None:
            timings[rule_id] = time.perf_counter() - t0
    return out
