"""Host-side device-transfer helpers shared by the runners, the
checkpoint writer, and anything else that pulls device state back."""

from __future__ import annotations

import jax
import numpy as np


def fetch_tree(tree):
    """D2H fetch of a pytree with every leaf's host copy started FIRST
    (``copy_to_host_async``), so N leaves cost ~one link round trip
    instead of N sequential ones. On the tunneled dev chip a blocking
    ``np.asarray`` pays ~100 ms of latency PER ARRAY; the service loop
    fetched a 9-leaf output tree per 500-match batch, which made the
    sequential version the dominant per-batch cost (measured ~0.9 s of
    1.4 s). Non-jax leaves (numpy, scalars) pass through unchanged."""
    for x in jax.tree.leaves(tree):
        if hasattr(x, "copy_to_host_async"):
            x.copy_to_host_async()
    return jax.tree.map(np.asarray, tree)
