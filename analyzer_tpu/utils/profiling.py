"""Observability: phase timing, throughput counters, XLA profiler traces.

The reference imports ``time`` and never uses it (``worker.py:4``); its
only observability is debug logging (SURVEY.md section 5.1/5.5). Here the
pipeline's phases — generate/ingest, schedule packing, host->device
transfer, device compute — are first-class measurements, because on TPU
the balance between them IS the performance model (host packing and
transfer overlap device compute in a well-fed pipeline).

Since the obs subsystem landed (``analyzer_tpu/obs``), these classes are
THIN VIEWS over the process-wide registry/tracer: ``PhaseTimer.phase``
keeps its local totals (the CLI stats lines read them) and ALSO records a
``phase_seconds{phase=...}`` histogram observation plus a ``phase.<name>``
span, so a ``--metrics-out`` snapshot carries the same numbers without
any caller changing. ``Counters.add`` mirrors into registry counters the
same way.

``trace`` wraps ``jax.profiler`` so a full XLA trace (viewable in
TensorBoard / Perfetto) can be captured around any history run with one
line; it no-ops gracefully where the backend can't profile.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from collections import defaultdict

from analyzer_tpu.obs import get_registry, get_tracer


@dataclasses.dataclass
class PhaseTimer:
    """Accumulating wall-clock phase timer.

    >>> t = PhaseTimer()
    >>> with t.phase("pack"):
    ...     do_packing()
    >>> t.report()   # {'pack': 1.23}
    """

    totals: dict = dataclasses.field(default_factory=lambda: defaultdict(float))
    counts: dict = dataclasses.field(default_factory=lambda: defaultdict(int))

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        with get_tracer().span(f"phase.{name}", cat="phase"):
            try:
                yield
            finally:
                dt = time.perf_counter() - t0
                self.totals[name] += dt
                self.counts[name] += 1
                get_registry().histogram(
                    "phase_seconds", phase=name
                ).observe(dt)

    def report(self) -> dict[str, float]:
        return dict(self.totals)

    def summary(self) -> str:
        total = sum(self.totals.values()) or 1.0
        parts = [
            f"{k}={v:.3f}s({100 * v / total:.0f}%)"
            for k, v in sorted(self.totals.items(), key=lambda kv: -kv[1])
        ]
        return " ".join(parts)


@dataclasses.dataclass
class Counters:
    """Monotonic counters with rate computation — the matches/sec/chip
    number BASELINE.json tracks, generalized. Mirrors every add into the
    process-wide registry (``app.<name>_total``).

    ``rate`` is anchored at the FIRST ``add`` of each counter, not at
    object construction: a long-lived worker whose counter starts moving
    an hour in reports the rate over its active window, not a number
    decaying toward zero from a stale epoch. ``reset`` re-arms the
    anchors."""

    values: dict = dataclasses.field(default_factory=lambda: defaultdict(int))
    _first_at: dict = dataclasses.field(
        default_factory=dict, repr=False
    )

    def add(self, name: str, n: int = 1) -> None:
        if name not in self._first_at:
            self._first_at[name] = time.perf_counter()
        self.values[name] += n
        get_registry().counter(f"app.{name}_total").add(n)

    def rate(self, name: str) -> float:
        t0 = self._first_at.get(name)
        if t0 is None:
            return 0.0
        dt = time.perf_counter() - t0
        return self.values[name] / dt if dt > 0 else 0.0

    def reset(self) -> None:
        """Clears values and rate anchors (a new measurement window).
        The registry mirrors are monotonic by contract and keep running."""
        self.values.clear()
        self._first_at.clear()

    def report(self) -> dict[str, int]:
        return dict(self.values)


@contextlib.contextmanager
def trace(log_dir: str | None):
    """XLA profiler trace around a block; None disables, and backends
    that can't profile degrade to a no-op instead of failing the run.

    Only the profiler start/stop are guarded: an exception raised by the
    BODY always propagates. (The old form re-``yield``ed inside an
    ``except Exception:`` around the whole ``with`` — so a body error
    surfaced as ``RuntimeError: generator didn't stop after throw()``,
    masking the real traceback.)"""
    if not log_dir:
        yield
        return
    import jax

    try:
        jax.profiler.start_trace(log_dir)
    except Exception:  # noqa: BLE001 — observability must not kill the run
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — ditto; never mask the body error
            pass
