"""Observability: phase timing, throughput counters, XLA profiler traces.

The reference imports ``time`` and never uses it (``worker.py:4``); its
only observability is debug logging (SURVEY.md section 5.1/5.5). Here the
pipeline's phases — generate/ingest, schedule packing, host->device
transfer, device compute — are first-class measurements, because on TPU
the balance between them IS the performance model (host packing and
transfer overlap device compute in a well-fed pipeline).

``trace`` wraps ``jax.profiler.trace`` so a full XLA trace (viewable in
TensorBoard / Perfetto) can be captured around any history run with one
line; it no-ops gracefully where the backend can't profile.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from collections import defaultdict


@dataclasses.dataclass
class PhaseTimer:
    """Accumulating wall-clock phase timer.

    >>> t = PhaseTimer()
    >>> with t.phase("pack"):
    ...     do_packing()
    >>> t.report()   # {'pack': 1.23}
    """

    totals: dict = dataclasses.field(default_factory=lambda: defaultdict(float))
    counts: dict = dataclasses.field(default_factory=lambda: defaultdict(int))

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def report(self) -> dict[str, float]:
        return dict(self.totals)

    def summary(self) -> str:
        total = sum(self.totals.values()) or 1.0
        parts = [
            f"{k}={v:.3f}s({100 * v / total:.0f}%)"
            for k, v in sorted(self.totals.items(), key=lambda kv: -kv[1])
        ]
        return " ".join(parts)


@dataclasses.dataclass
class Counters:
    """Monotonic counters with rate computation — the matches/sec/chip
    number BASELINE.json tracks, generalized."""

    values: dict = dataclasses.field(default_factory=lambda: defaultdict(int))
    _t0: float = dataclasses.field(default_factory=time.perf_counter)

    def add(self, name: str, n: int = 1) -> None:
        self.values[name] += n

    def rate(self, name: str) -> float:
        dt = time.perf_counter() - self._t0
        return self.values[name] / dt if dt > 0 else 0.0

    def report(self) -> dict[str, int]:
        return dict(self.values)


@contextlib.contextmanager
def trace(log_dir: str | None):
    """XLA profiler trace around a block; None disables, and backends that
    can't profile degrade to a no-op instead of failing the run."""
    if not log_dir:
        yield
        return
    import jax

    try:
        with jax.profiler.trace(log_dir):
            yield
    except Exception:  # noqa: BLE001 — observability must not kill the run
        yield
