"""Cross-cutting utilities: observability (phase timers, counters,
profiler traces) that the reference lacks entirely (SURVEY.md section 5.1:
no profiler hooks, no timing, no metrics — only debug logs)."""

from analyzer_tpu.utils.host import fetch_tree
from analyzer_tpu.utils.profiling import PhaseTimer, Counters, trace

__all__ = ["PhaseTimer", "Counters", "trace", "fetch_tree"]
