"""analyzer_tpu — a TPU-native match-rating framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
``vainglorygame/analyzer`` (reference: ``rater.py``, ``worker.py``,
``worker_test.py``): per-match TrueSkill skill updates, match-quality scoring,
and win-probability models as jit-compiled pure functions over HBM-resident
match/player tensors, scaled over a TPU mesh with XLA collectives instead of
RabbitMQ competing consumers.

Layers (bottom up):
  ops       closed-form rating kernels (TrueSkill two-team, quality, win prob)
  core      packed player-state table + SoA match batches + the superstep kernel
  sched     chronology-respecting conflict-free superstep scheduler + scan runner
  parallel  device-mesh data parallelism (shard_map, all_gather over ICI)
  models    Elo rater + win-probability heads (logistic, MLP) trained with optax
  io        synthetic/CSV match streams, checkpoint/resume
  service   broker/store/worker shell mirroring the reference service
  rater     reference-compatible object API (get_trueskill_seed, rate_match)
"""

from analyzer_tpu.config import RatingConfig, ServiceConfig

__version__ = "0.1.0"

__all__ = ["RatingConfig", "ServiceConfig", "__version__"]
