"""Device-mesh data parallelism for the rating pipeline.

The reference scales out with AMQP competing consumers racing on a shared
MySQL table (``worker.py:91-92``; SURVEY.md section 2.5) — workers never
talk to each other and last-commit-wins on conflicts. The TPU design keeps
the throughput model (data parallelism over matches) but makes the shared
state exact: the player table is **replicated** across the mesh, each
superstep's batch is **sharded** over the ``data`` axis, and the per-match
posterior writes ride ICI through one small ``all_gather`` so every replica
applies the identical scatter. Conflict-freedom within a superstep (the
scheduler's invariant) makes the combine exact — no last-commit-wins races.
"""

from analyzer_tpu.parallel.mesh import (
    make_mesh,
    rate_history_sharded,
    sharded_step_fn,
)
from analyzer_tpu.parallel.multihost import initialize_distributed, process_slice

__all__ = [
    "make_mesh",
    "rate_history_sharded",
    "sharded_step_fn",
    "initialize_distributed",
    "process_slice",
]
