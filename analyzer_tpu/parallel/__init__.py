"""Device-mesh data parallelism for the rating pipeline.

The reference scales out with AMQP competing consumers racing on a shared
MySQL table (``worker.py:91-92``; SURVEY.md section 2.5) — workers never
talk to each other and last-commit-wins on conflicts. The TPU design keeps
the throughput model (data parallelism over matches) but makes the shared
state exact AND shards the dominant cost: the player table is **sharded**
across the mesh (each chip owns a contiguous row block), priors are
assembled with one batch-shaped ``psum`` of disjoint per-shard
contributions riding ICI, compute is replicated (cheap, bit-identical),
and each chip scatters only its own rows' updates via host-precomputed
compacted routing — dividing the ~370 us/superstep scatter (the v5e
bottleneck, core/update.py) by the mesh size. Conflict-freedom within a
superstep (the scheduler's invariant) makes the combine exact — no
last-commit-wins races. Full design + scaling model: mesh.py docstring.
"""

from analyzer_tpu.parallel.mesh import (
    Routing,
    build_routing,
    make_mesh,
    rate_history_sharded,
    sharded_step_fn,
)
from analyzer_tpu.parallel.multihost import (
    assert_processes_agree,
    initialize_distributed,
    process_slice,
)

__all__ = [
    "Routing",
    "build_routing",
    "make_mesh",
    "rate_history_sharded",
    "sharded_step_fn",
    "assert_processes_agree",
    "initialize_distributed",
    "process_slice",
]
