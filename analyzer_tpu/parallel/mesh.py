"""shard_map data parallelism: sharded batches, replicated state.

Design (SURVEY.md section 7, step 5):

  * The player table (a few M rows x 7 (mu, sigma) pairs ~ tens of MB) is far
    below per-chip HBM, so it is replicated; sharding it would turn every
    prior gather into an all_to_all.
  * Each superstep's ``[B, ...]`` batch is sharded over the ``data`` mesh
    axis: every chip gathers priors and runs the closed-form update for its
    ``B/D`` matches only.
  * The posterior writes are exchanged with one ``all_gather`` of the
    batch-shaped update tensors (KBs — not the table), and every replica
    applies the identical full-batch scatter. Because a superstep is
    conflict-free *globally*, replicas stay bit-identical with no
    last-write ambiguity (the reference instead let AMQP workers race on
    MySQL, last-commit-wins — SURVEY.md section 2.5).
  * The scan over supersteps lives inside one jitted computation per chunk,
    so ICI transfers overlap with compute and the table stays in HBM.

Multi-host runs use the same code: ``jax.distributed.initialize()`` +
a global mesh makes ``all_gather`` ride ICI within a slice and DCN across
slices; the host feed stays sharded by process.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core.state import MatchBatch, PlayerState
from analyzer_tpu.core.update import rate_batch, scatter_rows
from analyzer_tpu.sched.superstep import PackedSchedule

DATA_AXIS = "data"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D ``data`` mesh over the first ``n_devices`` local devices.
    Raises when fewer devices exist than asked for — silently truncating
    would run at lower parallelism than the caller sized the batch for."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"asked for a {n_devices}-device mesh but only "
                    f"{len(devices)} devices are available"
                )
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


_step_fn_cache: dict = {}


def sharded_step_fn(mesh: Mesh, cfg: RatingConfig):
    """Builds (and memoizes — jit cache can't see through fresh closures)
    the jitted, shard_map'd chunk runner.

    Returns ``run(state, pidx, mask, winner, mode, afk) -> state`` scanning
    over the leading superstep axis; the batch axis (second) is sharded over
    ``data``, state is replicated and donated.
    """
    key = (tuple(d.id for d in mesh.devices.flat), cfg)
    cached = _step_fn_cache.get(key)
    if cached is not None:
        return cached

    def scan_chunk(state: PlayerState, pidx, mask, winner, mode, afk):
        def step(st, xs):
            lp, lm, lw, lmo, la = xs  # local [B/D, ...] shard
            local = MatchBatch(
                player_idx=lp, slot_mask=lm, winner=lw, mode_id=lmo, afk=la
            )
            out = rate_batch(st, local, cfg)
            # One ICI exchange of the batch-shaped updates; then every
            # replica applies the same scatter, staying bit-identical.
            g = jax.tree.map(
                lambda x: jax.lax.all_gather(x, DATA_AXIS, axis=0, tiled=True),
                (lp, lm, out.updated, out.new_rows),
            )
            return scatter_rows(st, *g), None

        state, _ = jax.lax.scan(step, state, (pidx, mask, winner, mode, afk))
        return state

    bspec = P(None, DATA_AXIS)  # [S, B, ...]: shard the batch axis
    # check_vma=False: the varying-manual-axes checker can't see that the
    # post-all_gather scatter keeps state bit-identical across replicas
    # (it types all_gather outputs as varying); replication is guaranteed
    # by construction here and asserted in tests/test_parallel.py.
    shmapped = jax.shard_map(
        scan_chunk,
        mesh=mesh,
        in_specs=(P(), bspec, bspec, bspec, bspec, bspec),
        out_specs=P(),
        check_vma=False,
    )
    fn = jax.jit(shmapped, donate_argnums=(0,))
    _step_fn_cache[key] = fn
    return fn


def rate_history_sharded(
    state: PlayerState,
    sched: PackedSchedule,
    cfg: RatingConfig,
    mesh: Mesh | None = None,
    steps_per_chunk: int = 1024,
) -> PlayerState:
    """Full-history re-rate, data-parallel over the mesh. Returns final state.

    ``sched.batch_size`` must be divisible by the mesh size (pack with
    ``batch_size = k * n_devices``).
    """
    mesh = mesh or make_mesh()
    n_dev = mesh.devices.size
    if sched.batch_size % n_dev:
        raise ValueError(
            f"batch_size {sched.batch_size} not divisible by mesh size {n_dev}"
        )
    step_fn = sharded_step_fn(mesh, cfg)

    replicated = NamedSharding(mesh, P())
    # Copy before resharding: device_put is a no-op alias when the input
    # already matches, and the donated step would then free the CALLER's
    # buffers (same guard as sched.runner.rate_history).
    state = jax.device_put(jax.tree.map(jnp.copy, state), replicated)
    batch_sharding = NamedSharding(mesh, P(None, DATA_AXIS))

    for start in range(0, sched.n_steps, steps_per_chunk):
        sl = slice(start, min(start + steps_per_chunk, sched.n_steps))
        arrays = (
            jax.device_put(sched.player_idx[sl], batch_sharding),
            jax.device_put(sched.slot_mask[sl], batch_sharding),
            jax.device_put(sched.winner[sl], batch_sharding),
            jax.device_put(sched.mode_id[sl], batch_sharding),
            jax.device_put(sched.afk[sl], batch_sharding),
        )
        state = step_fn(state, *arrays)
    return state
