"""shard_map data parallelism: sharded batches, replicated state.

Design (SURVEY.md section 7, step 5):

  * The player table (a few M rows x 7 (mu, sigma) pairs ~ tens of MB) is far
    below per-chip HBM, so it is replicated; sharding it would turn every
    prior gather into an all_to_all.
  * Each superstep's ``[B, ...]`` batch is sharded over the ``data`` mesh
    axis: every chip gathers priors and runs the closed-form update for its
    ``B/D`` matches only.
  * The posterior writes are exchanged with one ``all_gather`` of the
    batch-shaped update tensors (KBs — not the table), and every replica
    applies the identical full-batch scatter. Because a superstep is
    conflict-free *globally*, replicas stay bit-identical with no
    last-write ambiguity (the reference instead let AMQP workers race on
    MySQL, last-commit-wins — SURVEY.md section 2.5).
  * The scan over supersteps lives inside one jitted computation per chunk,
    so ICI transfers overlap with compute and the table stays in HBM.

Multi-host runs use the same code: ``jax.distributed.initialize()`` +
a global mesh makes ``all_gather`` ride ICI within a slice and DCN across
slices; the host feed stays sharded by process.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core.state import MatchBatch, PlayerState
from analyzer_tpu.core.update import rate_batch
from analyzer_tpu.sched.superstep import PackedSchedule

DATA_AXIS = "data"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D ``data`` mesh over the first ``n_devices`` local devices."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def _scatter_rows(
    state: PlayerState,
    player_idx: jnp.ndarray,
    slot_mask: jnp.ndarray,
    updated: jnp.ndarray,
    new_rows: jnp.ndarray,
) -> PlayerState:
    """Applies a full batch of row writes (identical on each replica)."""
    do = updated[:, None, None] & slot_mask
    idx = jnp.where(do, player_idx, state.pad_row)
    return dataclasses.replace(state, table=state.table.at[idx].set(new_rows))


def sharded_step_fn(mesh: Mesh, cfg: RatingConfig):
    """Builds the jitted, shard_map'd chunk runner.

    Returns ``run(state, pidx, mask, winner, mode, afk) -> state`` scanning
    over the leading superstep axis; the batch axis (second) is sharded over
    ``data``, state is replicated and donated.
    """

    def scan_chunk(state: PlayerState, pidx, mask, winner, mode, afk):
        def step(st, xs):
            lp, lm, lw, lmo, la = xs  # local [B/D, ...] shard
            local = MatchBatch(
                player_idx=lp, slot_mask=lm, winner=lw, mode_id=lmo, afk=la
            )
            out = rate_batch(st, local, cfg)
            # One ICI exchange of the batch-shaped updates; then every
            # replica applies the same scatter, staying bit-identical.
            g = jax.tree.map(
                lambda x: jax.lax.all_gather(x, DATA_AXIS, axis=0, tiled=True),
                (lp, lm, out.updated, out.new_rows),
            )
            return _scatter_rows(st, *g), None

        state, _ = jax.lax.scan(step, state, (pidx, mask, winner, mode, afk))
        return state

    bspec = P(None, DATA_AXIS)  # [S, B, ...]: shard the batch axis
    # check_vma=False: the varying-manual-axes checker can't see that the
    # post-all_gather scatter keeps state bit-identical across replicas
    # (it types all_gather outputs as varying); replication is guaranteed
    # by construction here and asserted in tests/test_parallel.py.
    shmapped = jax.shard_map(
        scan_chunk,
        mesh=mesh,
        in_specs=(P(), bspec, bspec, bspec, bspec, bspec),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(shmapped, donate_argnums=(0,))


def rate_history_sharded(
    state: PlayerState,
    sched: PackedSchedule,
    cfg: RatingConfig,
    mesh: Mesh | None = None,
    steps_per_chunk: int = 1024,
) -> PlayerState:
    """Full-history re-rate, data-parallel over the mesh. Returns final state.

    ``sched.batch_size`` must be divisible by the mesh size (pack with
    ``batch_size = k * n_devices``).
    """
    mesh = mesh or make_mesh()
    n_dev = mesh.devices.size
    if sched.batch_size % n_dev:
        raise ValueError(
            f"batch_size {sched.batch_size} not divisible by mesh size {n_dev}"
        )
    step_fn = sharded_step_fn(mesh, cfg)

    replicated = NamedSharding(mesh, P())
    state = jax.device_put(state, replicated)  # reshards without host detour
    batch_sharding = NamedSharding(mesh, P(None, DATA_AXIS))

    for start in range(0, sched.n_steps, steps_per_chunk):
        sl = slice(start, min(start + steps_per_chunk, sched.n_steps))
        arrays = (
            jax.device_put(sched.player_idx[sl], batch_sharding),
            jax.device_put(sched.slot_mask[sl], batch_sharding),
            jax.device_put(sched.winner[sl], batch_sharding),
            jax.device_put(sched.mode_id[sl], batch_sharding),
            jax.device_put(sched.afk[sl], batch_sharding),
        )
        state = step_fn(state, *arrays)
    return state
